"""Benchmark: placement-decision throughput, TPU kernel vs naive Python.

Prints ONE JSON line (the LAST line of stdout is authoritative):
  {"metric": ..., "value": N, "unit": "decisions/sec", "vs_baseline": N, ...}

One exception to "one line": when a run falls back to CPU because the
accelerator tunnel was dead at start but the end-of-run re-probe finds it
alive, the process re-executes on the TPU and prints a second, TPU-backed
line after the CPU one — the superseding record.  Consumers must parse
the final JSON line, not the whole stream; as a belt-and-braces guard for
stream parsers that don't, any non-final line carries
``"superseded": true`` (and if the tunnel dies again before the re-measure,
the CPU line is re-printed WITHOUT the marker as the final word).

The measured quantity is the north-star hot loop (BASELINE.md): the
cost-aware (PIVOT) placement decision over a ready-task × host batch —
fit mask + score + argmin with greedy within-tick state updates.

  * baseline — the reference-faithful naive Python policy
    (``CostAwarePolicy(mode='naive')``, mirroring
    ``scheduler/cost_aware.py:99-127``) on one T×H batch.
  * device   — the fused ``cost_aware_kernel`` (``lax.scan`` + masked
    argmin) vmapped over a Monte-Carlo ensemble of R perturbed replicas,
    i.e. R×T decisions per call — the workload class the reference cannot
    express at all (it fans out OS processes per run instead,
    ``alibaba/sim.py:187-195``).

Scale: T=2048 ready tasks, H=512 hosts, R=1024 replicas — the
BASELINE.json ensemble configuration (1024 vmapped Monte-Carlo replicas);
R=1024 also maps the vmapped replica axis exactly onto the TPU's (8, 128)
vector registers, which roughly 4×es per-replica throughput vs R=64.

Round 6 — absolute accounting (VERDICT r05 gap #2): every measured row
carries a ``roofline`` block (estimated FLOPs / HBM bytes from the row's
shape, achieved GFLOP/s and GB/s, %-of-peak for both, and the binding
regime) against per-backend peaks — CPU peaks measured in-process by a
STREAM-style probe, TPU peaks from the v5e spec
(``pivot_tpu/infra/roofline.py``).  A ``two_phase`` row measures the
round-6 kernel restructure at its acceptance shape (T=600 real tasks in
the 2048 bucket, H=1024, single dispatch): the retained scan oracle vs
the two-phase kernel, plus a serialized-step model (per-step wall probed
at the same H) that explains the scan's figure when neither roofline
bound does.

A watchdog falls back to the CPU backend if accelerator initialization
stalls (single-tenant tunnel), so the driver always gets its JSON line.

Round 15 — continuous-bench plumbing (``tools/bench_history.py``):
``--json PATH`` additionally writes the authoritative final JSON line
to PATH (so the history appender never has to scrape stdout), and
``--rows a,b,c`` restricts the run to the named optional rows (row
names: headline, two_phase, grid_batched, fused_tick, serve_stream,
serve_tiers, shard_place, spot_survival, obs_overhead,
profiler_overhead, cost_attribution, saturated) — the baseline
generator measures the history-tracked rows without paying for the
whole artifact.  No arguments = the driver's exact historical
behavior.  Two new rows: ``profiler_overhead`` (the round-15
acceptance gate — sampled dispatch profiling on the fused-tick DEVICE
path costs <3% and leaves the meter bit-identical) and
``cost_attribution`` (every jitmap entry point has an XLA cost row or
an explicit flag — the register-or-flag coverage gate).
"""

from __future__ import annotations

import json
import os
import sys
import time

#: --rows subset (None = all rows) and --json sink, set by main().
_ROWS = None
_JSON_PATH = None


def _row_on(name: str) -> bool:
    return _ROWS is None or name in _ROWS


def _emit(line: dict) -> None:
    """Print an authoritative final JSON line (and mirror it to the
    --json sink when one was requested)."""
    print(json.dumps(line), flush=True)
    if _JSON_PATH:
        try:
            with open(_JSON_PATH, "w") as f:
                json.dump(line, f)
                f.write("\n")
        except OSError:
            pass  # the printed line is the authoritative record


def _timed_calls(call, fetch, n: int = 3) -> "tuple[float, object]":
    """(seconds per call, last output) over ``n`` serialized device
    calls, forced complete by a scalar value fetch of the LAST output.

    ``jax.block_until_ready`` can under-wait over this image's tunnel
    backend: measured in a fresh process, a ~1 s 256-replica rollout
    "blocks" in 0.7 ms while an actual value fetch takes the full
    second (RESULTS.md, round-2 "measurement integrity" note) — so a
    value fetch is the only trustworthy completion barrier.  Batching
    ``n`` calls and fetching once amortizes the ~70 ms link RTT out of
    the per-call figure; a single TPU core executes programs serially,
    so total/n is an honest per-call wall time.
    """
    fetch(call())  # warm: compile + settle the dispatch queue
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = call()
    fetch(out)
    return (time.perf_counter() - t0) / n, out


def _build_batch(n_hosts: int, n_tasks: int, seed: int):
    """Realistic tick batch from the framework's own infra + trace stats."""
    import numpy as np

    from pivot_tpu.des import Environment
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched import GlobalScheduler, TickContext
    from pivot_tpu.sched.policies import CostAwarePolicy
    from pivot_tpu.workload import Application, TaskGroup

    rng = np.random.default_rng(seed)
    meta = ResourceMetadata(seed=seed)
    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=seed,
    )
    cluster = gen.generate(n_hosts)
    # Alibaba-trace-like demands: cpus ∈ {0.5, 1, 2, 4}, mem fractional.
    groups = []
    remaining = n_tasks
    gi = 0
    while remaining > 0:
        inst = int(min(remaining, rng.integers(1, 64)))
        groups.append(
            TaskGroup(
                str(gi),
                cpus=float(rng.choice([0.5, 1.0, 2.0, 4.0])),
                mem=float(rng.uniform(0.05, 0.9)) * 7864.32,
                runtime=float(rng.integers(1, 300)),
                output_size=float(rng.uniform(0, 0.9)) * 1000,
                instances=inst,
            )
        )
        remaining -= inst
        gi += 1
    app = Application("bench", groups)
    tasks = [t for g in app.groups for t in g.materialize_tasks()]
    # Partially loaded cluster: consume a random slice of each host.
    for h in cluster.hosts:
        r = h.resource
        frac = rng.uniform(0, 0.7)
        r.cpus -= frac * r.t_cpus
        r.mem -= frac * r.t_mem
    scheduler = GlobalScheduler(
        cluster.env, cluster, CostAwarePolicy(mode="naive"), seed=seed
    )
    ctx = TickContext(scheduler, tasks, tick_seq=0)
    return ctx


def _bench_naive(ctx, repeats: int = 3) -> float:
    """Decisions/sec of the reference-faithful Python loop."""
    from pivot_tpu.sched.policies import CostAwarePolicy

    best = float("inf")
    for _ in range(repeats):
        policy = CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="naive")
        avail0 = ctx.avail.copy()
        t0 = time.perf_counter()
        policy.place(ctx)
        best = min(best, time.perf_counter() - t0)
        ctx.avail[:] = avail0  # restore for the next round
    return ctx.n_tasks / best


def _bench_numpy_modes(ctx, repeats: int = 3) -> dict:
    """Fallback-record enrichment (VERDICT r04 item 8): decisions/sec of
    the vectorized numpy policy twins at the bench shape, so a CPU
    fallback record exercises the cross-policy surface rather than the
    scan kernel alone.  Same protocol as ``_bench_naive`` (best of
    ``repeats``, availability restored between rounds)."""
    from pivot_tpu.sched.policies import (
        BestFitPolicy,
        CostAwarePolicy,
        FirstFitPolicy,
        OpportunisticPolicy,
    )

    rows = {}
    for name, mk in (
        ("cost_aware_numpy",
         lambda: CostAwarePolicy(sort_tasks=True, sort_hosts=True,
                                 mode="numpy")),
        ("first_fit_numpy", lambda: FirstFitPolicy(mode="numpy")),
        ("best_fit_numpy", lambda: BestFitPolicy(mode="numpy")),
        ("opportunistic_numpy", lambda: OpportunisticPolicy(mode="numpy")),
    ):
        best = float("inf")
        for _ in range(repeats):
            policy = mk()
            avail0 = ctx.avail.copy()
            t0 = time.perf_counter()
            policy.place(ctx)
            best = min(best, time.perf_counter() - t0)
            ctx.avail[:] = avail0
        rows[name] = ctx.n_tasks / best
    return rows


def _cost_aware_tick_args(ctx, rng_seed: int = 0):
    """Host-staged cost-aware tick payload for ``ctx``: ``(topo, dem
    [B,4], valid [B], ng [B], az [B])`` with the task axis padded to its
    bucket — the exact per-tick kernel feed ``TpuCostAwarePolicy``
    builds, shared by the single-run device bench and the
    ``grid_batched`` dispatch-amortization row."""
    import numpy as np

    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import DeviceTopology
    from pivot_tpu.sched.policies import CostAwarePolicy
    from pivot_tpu.sched.tpu import pad_bucket

    topo = DeviceTopology.from_cluster(ctx.cluster, jnp.float32)
    T = ctx.n_tasks
    B = pad_bucket(T)

    grouper = CostAwarePolicy(sort_tasks=True, sort_hosts=True)
    groups = grouper.group_tasks(ctx)
    order, anchor_zone, new_group = [], [], []
    storage_zones = ctx.cluster.storage_zone_vector()
    rng = np.random.default_rng(rng_seed)
    for anchor, idxs in groups.items():
        az = (
            ctx.meta.zone_index[anchor.locality]
            if hasattr(anchor, "locality")
            else int(rng.choice(storage_zones))
        )
        for j, i in enumerate(idxs):
            order.append(i)
            anchor_zone.append(az)
            new_group.append(j == 0)

    dem = np.zeros((B, 4), dtype=np.float32)
    dem[:T] = ctx.demands[order]
    valid = np.zeros(B, dtype=bool)
    valid[:T] = True
    az_arr = np.zeros(B, dtype=np.int32)
    az_arr[:T] = anchor_zone
    ng_arr = np.zeros(B, dtype=bool)
    ng_arr[:T] = new_group
    return topo, dem, valid, ng_arr, az_arr


def _scan_step_probe(args, mode, n_lo: int = 64, n_hi: int = 256) -> float:
    """Per-step wall of the scan oracle at the target H: two-point
    difference over short task axes — ``(wall(n_hi) − wall(n_lo)) /
    (n_hi − n_lo)`` — so the fixed per-call cost (dispatch, staging,
    fetch) cancels and only the marginal serialized step is priced."""
    import numpy as np

    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import cost_aware_kernel_ref

    avail, dem, valid, ng, az, cost, bw, hz, counts = args

    def wall(n):
        short = (
            avail, dem[:n], valid[:n], ng[:n], az[:n], cost, bw, hz, counts,
        )
        per_call, _ = _timed_calls(
            lambda: cost_aware_kernel_ref(*short, **mode)[0],
            lambda p: int(np.asarray(jnp.sum(p))),
            n=5,
        )
        return per_call

    return max(wall(n_hi) - wall(n_lo), 1e-9) / (n_hi - n_lo)


def _bench_two_phase(n_tasks: int = 600, n_hosts: int = 1024,
                     repeats: int = 5) -> dict:
    """Round-6 acceptance row: single-dispatch decisions/sec of the
    two-phase cost-aware kernel vs the retained scan oracle at T=600
    real tasks (padded to the 2048 bucket), H=1024 — the shape where the
    serialized-scan floor dominates.  Also times the speculative
    chunk-commit form (C=64) and reports rooflines + the serial model
    for all three.  Placement parity across the variants is checked
    in-row: a mismatch becomes a row-level ``error`` and forces
    ``meets_2x`` false, so a parity break can never bank a speedup.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from pivot_tpu.infra import roofline
    from pivot_tpu.ops.kernels import cost_aware_kernel, cost_aware_kernel_ref

    ctx = _build_batch(n_hosts, n_tasks, seed=13)
    topo, dem, valid, ng, az = _cost_aware_tick_args(ctx, rng_seed=13)
    B = dem.shape[0]
    args = (
        jnp.asarray(ctx.avail, dtype=jnp.float32),
        jnp.asarray(dem), jnp.asarray(valid), jnp.asarray(ng),
        jnp.asarray(az), topo.cost, topo.bw, topo.host_zone,
        jnp.zeros(n_hosts, dtype=jnp.int32),
    )
    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    n_groups = int(np.asarray(ng).sum())
    backend = jax.default_backend()
    peaks = roofline.backend_peaks(backend)
    dtype_bytes = 4

    def timed(fn):
        per_call, out = _timed_calls(
            fn, lambda p: int(np.asarray(jnp.sum(p))), n=repeats
        )
        return per_call, np.asarray(out)

    t_scan, p_scan = timed(lambda: cost_aware_kernel_ref(*args, **mode)[0])
    t_auto, p_auto = timed(
        lambda: cost_aware_kernel(*args, **mode, totals=topo.totals)[0]
    )
    t_chunk, p_chunk = timed(
        lambda: cost_aware_kernel(
            *args, **mode, totals=topo.totals, phase2=64
        )[0]
    )
    parity = bool(
        np.array_equal(p_scan, p_auto) and np.array_equal(p_scan, p_chunk)
    )
    step_s = _scan_step_probe(args, mode)
    serial = roofline.serial_model(B, step_s)
    row = {
        # A parity failure poisons every ratio below: surface it as a
        # row-level error (meets_2x forced false) instead of burying a
        # parity:false flag under a healthy-looking speedup.
        **(
            {"error": "two_phase/chunked placements != scan oracle"}
            if not parity else {}
        ),
        "t": n_tasks,
        "bucket": B,
        "h": n_hosts,
        "n_groups": n_groups,
        "backend": backend,
        "parity": parity,
        "scan_ref_dps": round(n_tasks / t_scan, 1),
        "two_phase_dps": round(n_tasks / t_auto, 1),
        "chunked64_dps": round(n_tasks / t_chunk, 1),
        "speedup_vs_scan": round(t_scan / t_auto, 2),
        "chunked64_speedup_vs_scan": round(t_scan / t_chunk, 2),
        "meets_2x": bool(parity and t_scan / t_auto >= 2.0),
        "scan_serial_model": {
            **serial,
            # within-2x when the serialized chain explains the scan wall
            "measured_s": round(t_scan, 6),
            "model_over_measured": round(serial["predicted_s"] / t_scan, 3),
        },
        "roofline": {
            "scan_ref": roofline.annotate(
                t_scan, "scan", B, n_hosts, backend=backend,
                dtype_bytes=dtype_bytes, n_groups=n_groups, peaks=peaks,
            ),
            "two_phase": roofline.annotate(
                t_auto, "slim" if backend == "cpu" else "scan",
                n_tasks if backend == "cpu" else B, n_hosts,
                backend=backend, dtype_bytes=dtype_bytes,
                n_groups=n_groups, peaks=peaks,
            ),
            "chunked64": roofline.annotate(
                t_chunk, "chunked", n_tasks, n_hosts, backend=backend,
                dtype_bytes=dtype_bytes, n_groups=n_groups, peaks=peaks,
            ),
        },
    }
    return row


def _bench_fused_tick(
    n_hosts: int = 64,
    cohort: int = 8,
    k_sweep=(1, 2, 4, 8, 16, 32),
    repeats: int = 30,
) -> dict:
    """Round-8 acceptance row: the device-resident multi-tick loop
    (``ops/tickloop.py``) vs the per-tick dispatch path, K ticks per
    span.

    Shape: one ``cohort``-task wave arrives every tick onto a roomy
    cluster (every wave places in full), so each span tick does real
    placement work and the carry genuinely folds tick to tick.  The
    sequential baseline is :func:`reference_tick_run` — the exact
    per-tick protocol (one jitted kernel dispatch + host wait-queue
    algebra per tick) the fused driver replaces.  ``overhead_per_tick``
    isolates the dispatch floor by subtracting the marginal per-tick
    device cost (two-point difference over the largest two K, where the
    floor cancels — the ``_scan_step_probe`` idiom); the acceptance bar
    is that overhead at K=16 amortized ≥5× below K=1.  Roofline's
    ``fused_loop_model`` supplies the predicted-vs-measured column from
    the probed dispatch floor alone.  Per-tick placements are checked
    fused-vs-sequential in-row: a parity break becomes a row-level
    ``error`` and forces ``meets_5x`` false.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from pivot_tpu.infra import roofline
    from pivot_tpu.ops.tickloop import fused_tick_run, reference_tick_run
    from pivot_tpu.sched.tpu import _probe_device_floor, pad_bucket

    rng = np.random.default_rng(11)
    backend = jax.default_backend()
    floor_s = _probe_device_floor()
    k_max = max(k_sweep)
    dem_all = rng.uniform(0.3, 2.0, (cohort * k_max, 4))
    # ONE slot bucket for the whole sweep: per-tick compute must be
    # constant across K for the two-point overhead isolation below (the
    # slim pass early-exits at the live batch, so pad slots are free,
    # but a K-dependent bucket would still change sort/gather widths).
    B = pad_bucket(cohort * k_max)
    rows = {}
    walls = {}
    parity = True
    for K in k_sweep:
        S = cohort * K
        dem = np.zeros((B, 4))
        dem[:S] = dem_all[:S]
        arrive = np.full(B, k_max + 1, np.int32)
        arrive[:S] = np.repeat(np.arange(K, dtype=np.int32), cohort)
        # Roomy cluster: every wave fits, so all K ticks place `cohort`
        # tasks each — the maximal-work span shape.
        avail = np.full((n_hosts, 4), 4.0 * cohort * k_max / n_hosts + 8.0)
        kw = dict(policy="first-fit", strict=False)

        def fused_call():
            return fused_tick_run(
                jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
                jnp.asarray(K, jnp.int32), n_ticks=K, **kw,
            )

        # Best-of-N single-call walls (value-fetch completion barrier):
        # these spans run in the hundreds of microseconds on CPU, where
        # a mean soaks up scheduler/GC jitter that the min rejects.
        res = fused_call()
        int(np.asarray(res.placements).sum())  # warm: compile + settle
        t_fused = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = fused_call()
            int(np.asarray(out.placements).sum())
            t_fused = min(t_fused, time.perf_counter() - t0)
        ref = reference_tick_run(avail, dem, arrive, K, **kw)
        p_parity = bool(
            np.array_equal(np.asarray(res.placements), ref[0])
            and np.array_equal(np.asarray(res.avail), ref[3])
        )
        parity = parity and p_parity
        t_seq = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            reference_tick_run(avail, dem, arrive, K, **kw)
            t_seq = min(t_seq, time.perf_counter() - t0)
        walls[K] = t_fused
        rows[K] = {
            "span_s": round(t_fused, 6),
            "per_tick_fused_s": round(t_fused / K, 6),
            "per_tick_sequential_s": round(t_seq / K, 6),
            "sequential_span_s": round(t_seq, 6),
            "speedup_vs_sequential": round(t_seq / t_fused, 2),
            "parity": p_parity,
        }
    # Marginal per-tick device cost: the floor cancels in the two-point
    # difference over the two largest spans.
    k_hi, k_lo = k_sweep[-1], k_sweep[-2]
    tick_s = max((walls[k_hi] - walls[k_lo]) / (k_hi - k_lo), 1e-9)
    # The fused program's own per-call floor (staging + dispatch + fetch
    # of its operand set) from the smallest span's intercept — the
    # trivial-kernel probe ``floor_s`` bounds it from below but misses
    # the operand staging, exactly the cost being amortized.
    k1 = k_sweep[0]
    floor_fused = max(walls[k1] - k1 * tick_s, floor_s)
    for K in k_sweep:
        overhead = max(walls[K] / K - tick_s, 0.0)
        model = roofline.fused_loop_model(K, tick_s, floor_fused)
        rows[K]["overhead_per_tick_us"] = round(overhead * 1e6, 3)
        rows[K]["fused_loop_model"] = {
            **model,
            "measured_s": round(walls[K], 6),
            "model_over_measured": round(
                model["predicted_s"] / walls[K], 3
            ),
        }
    ov1 = rows[k_sweep[0]]["overhead_per_tick_us"]
    ov16 = rows[16]["overhead_per_tick_us"] if 16 in rows else None
    # A zero K=16 overhead means the floor amortized below measurement
    # resolution — better than any finite ratio, but the ratio itself is
    # undefined; emit null (an inf would make the record line invalid
    # strict JSON) and record the full-amortization fact explicitly.
    fully_amortized = ov16 == 0.0 and ov1 > 0.0
    amort = (
        round(ov1 / ov16, 2)
        if ov16 not in (None, 0.0) else None
    )
    return {
        **(
            {"error": "fused span placements != sequential ticking"}
            if not parity else {}
        ),
        "h": n_hosts,
        "cohort_per_tick": cohort,
        "backend": backend,
        "policy": "first-fit",
        "parity": parity,
        "dispatch_floor_us": round(floor_s * 1e6, 3),
        "fused_call_floor_us": round(floor_fused * 1e6, 3),
        "marginal_tick_us": round(tick_s * 1e6, 3),
        "per_k": {str(k): rows[k] for k in k_sweep},
        "overhead_amortization_k16_vs_k1": amort,
        "overhead_fully_amortized_at_k16": fully_amortized,
        "meets_5x": bool(
            parity
            and ov16 is not None
            and (fully_amortized or (amort is not None and amort >= 5.0))
        ),
    }


def _bracketed_overhead(once, repeats: int) -> dict:
    """The bracketed-pair measurement protocol shared by the
    ``obs_overhead`` and ``profiler_overhead`` rows — ONE
    implementation so a fix to the noise model can never apply to one
    gate and not the other.

    ``once(on: bool) -> (wall_s, summary)`` runs the identical seeded
    workload with the instrumented arm on/off.  Protocol (the design
    that survives a noisy shared CPU — see the obs_overhead docstring
    for the measured reasoning): one unmeasured warmup, then per round
    the ON run BRACKETED between two OFF runs (order alternating),
    scored as on / min(off, off2); the MEDIAN across rounds rejects
    rounds a scheduler hiccup poisoned, and the off/off gap is the
    row's own noise estimate.  ``parity`` compares the three summaries
    with the wall-clock field excluded."""
    from statistics import median

    once(True)  # unmeasured warmup: trace-file load, compiles, caches
    on_ratios: list = []
    noise_ratios: list = []
    summaries = {}
    walls = {"off": float("inf"), "on": float("inf")}
    for r in range(repeats):
        order = ("off", "on", "off2") if r % 2 else ("off2", "on", "off")
        round_walls = {}
        for key in order:
            wall, summary = once(key == "on")
            round_walls[key] = wall
            summaries[key] = summary
        base_r = min(round_walls["off"], round_walls["off2"])
        walls["off"] = min(walls["off"], base_r)
        walls["on"] = min(walls["on"], round_walls["on"])
        on_ratios.append(round_walls["on"] / base_r)
        noise_ratios.append(
            abs(round_walls["off"] - round_walls["off2"]) / base_r
        )

    def sim_view(s: dict) -> dict:
        return {k: v for k, v in s.items() if k not in ("wall_clock",)}

    parity = (
        sim_view(summaries["on"])
        == sim_view(summaries["off"])
        == sim_view(summaries["off2"])
    )
    return {
        "wall_off_s": round(walls["off"], 6),
        "wall_on_s": round(walls["on"], 6),
        "overhead_pct": round((median(on_ratios) - 1.0) * 100.0, 3),
        "off_noise_pct": round(median(noise_ratios) * 100.0, 3),
        "parity": parity,
    }


def _bench_policy_search(
    n_hosts: int = 12,
    seed: int = 5,
    n_apps: int = 6,
    popsize: int = 8,
    n_replicas: int = 16,
    generations: int = 2,
) -> dict:
    """Policy-search row (round 16, ``pivot_tpu/search/``): search
    throughput at population scale — a CEM run over the seeded
    spot-market fitness environment where every generation's candidate
    population (``popsize × replicas`` rows) is one fused vmapped-
    rollout dispatch.  Columns: generations/s and rollouts/s over the
    timed generations (a warm-up search compiles the draw + population
    programs first, so the row measures steady state), plus the search
    outcome sanity (``improved``: the best evaluated vector is never
    worse than the incumbent's generation-0 score).  Pure estimator
    row — runs on any backend; ``rollouts_per_sec`` is tracked by
    ``tools/bench_history.py``.
    """
    from pivot_tpu.search.cem import cem_search
    from pivot_tpu.search.fitness import make_search_env

    env = make_search_env(
        n_hosts=n_hosts, seed=seed, n_apps=n_apps, horizon=400.0,
        n_replicas=n_replicas,
    )
    # Warm-up: compiles the draw program and the population program.
    cem_search(env, generations=1, popsize=popsize, seed=seed)
    t0 = time.perf_counter()
    res = cem_search(env, generations=generations, popsize=popsize, seed=seed)
    wall = time.perf_counter() - t0
    rollouts = generations * popsize * n_replicas
    return {
        "popsize": popsize,
        "replicas": n_replicas,
        "generations": generations,
        "rows_per_generation": popsize * n_replicas,
        "n_tasks": env.n_tasks,
        "n_preemptions": env.n_preemptions,
        "wall_s": round(wall, 3),
        "generations_per_sec": round(generations / wall, 4),
        "rollouts_per_sec": round(rollouts / wall, 2),
        "best_score": res.best_score,
        "init_score": res.init_score,
        "improved": bool(res.best_score <= res.init_score),
    }


def _bench_obs_overhead(n_apps: int = 16, repeats: int = 9) -> dict:
    """Round-14 acceptance row: the observability plane's hot-path cost.

    Three measurements over the IDENTICAL seeded DES run on the
    fused-tick path (``fuse_spans=True``, the default — fast-forward +
    fused-span machinery engaged, which is where per-tick tracer hooks
    would hurt most):

      * ``off`` — a disabled ``Tracer`` (the shipped default: every
        recording call short-circuits on ``enabled`` before touching a
        clock or lock);
      * ``off_again`` — the same arm re-measured, so the row carries
        its own noise floor (``off_noise_pct``) — "tracer-off at noise
        level" is then a statement against a measured noise, not a
        hand-wave;
      * ``on`` — full tracing (tick spans, task instants, causal
        stages).

    Gates: ``meets_3pct`` (tracer-on overhead < 3% of the untraced
    wall) and ``parity`` (the traced run's meter summary — wall clock
    excluded — and avg_runtime are bit-identical to the untraced run:
    observation must not perturb the system).  Walls are best-of-N:
    these runs are hundreds of ms, where the min rejects scheduler/GC
    jitter a mean would soak up.
    """
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import CostAwarePolicy

    from pivot_tpu.des import Environment

    trace_file = "data/jobs/jobs-5000-200-86400-172800.npz"

    def build():
        meta = ResourceMetadata(seed=0)
        gen = RandomClusterGenerator(
            Environment(), (16, 16), (128 * 1024,) * 2, (100, 100),
            (1, 1), meta=meta, seed=0,
        )
        return gen.generate(24)

    cluster = build()

    state = {"trace_events": 0}

    def once(trace_events: bool):
        import gc

        run = ExperimentRun(
            "obs", cluster, CostAwarePolicy(mode="numpy"),
            trace_file, n_apps=n_apps, seed=3, fuse_spans=True,
            trace_events=trace_events,
        )
        # GC pauses landing mid-run are 10-40% of the wall at this
        # scale (measured) — collect up front and pause the collector
        # so the row measures the tracer, not the allocator.
        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            summary = run.run()
            wall = time.perf_counter() - t0
        finally:
            gc.enable()
        if trace_events:
            state["trace_events"] = len(run.tracer.events)
        return wall, summary

    # Bracketed-pair median (the shared ``_bracketed_overhead``
    # protocol).  On a shared, noisy CPU the wall of one run wobbles
    # far more than the tracer costs, so neither absolute floors nor
    # single pairs resolve a 3% gate; what does (measured): pin the GC
    # (done in ``once`` — its pauses alone are 10-40% of the wall),
    # BRACKET each traced run between two untraced runs in the same
    # round (machine state maximally shared), score the round as
    # on / min(off, off2), and take the MEDIAN across rounds — the
    # median rejects the rounds a scheduler hiccup poisoned, and the
    # off/off gap inside each round is the row's own noise estimate,
    # so "tracer-off at noise level" is a measured statement.
    r = _bracketed_overhead(once, repeats)
    return {
        **({} if r["parity"] else {
            "error": "traced run diverged from untraced (meter/runtime)"
        }),
        "n_apps": n_apps,
        "rounds": repeats,
        "fused_tick_path": True,
        "wall_off_s": r["wall_off_s"],
        "wall_on_s": r["wall_on_s"],
        "trace_events": state["trace_events"],
        "tracer_on_overhead_pct": r["overhead_pct"],
        "tracer_off_noise_pct": r["off_noise_pct"],
        "parity": r["parity"],
        "meets_3pct": bool(r["parity"] and r["overhead_pct"] < 3.0),
    }


def _bench_profiler_overhead(n_apps: int = 16, n_hosts: int = 16,
                             repeats: int = 7) -> dict:
    """Round-15 acceptance row: the sampled dispatch profiler's cost.

    Same bracketed-pair protocol as ``obs_overhead`` (see that row's
    docstring for the noise reasoning), but over a DEVICE-policy
    fused-tick run — the profiler hooks at the ``_call_kernel`` /
    ``place_span`` dispatch boundaries, so a numpy-policy run would
    measure nothing.  Aggressive 1-in-4 sampling (4× the shipped
    default cadence), so the gate bounds a *harsher* configuration
    than production.

    Gates: ``meets_3pct`` (profiler-on overhead < 3% of the unprofiled
    wall, or below the round's own measured off/off noise — on a box
    whose run-to-run wobble exceeds 3%, "indistinguishable from the
    noise" is the strongest statement the protocol can make), ``parity``
    (meter summary and avg_runtime bit-identical — the profiler times
    dispatches, it must never perturb one), and ``sampled > 0`` (an
    unexercised profiler would make the other two gates vacuous).
    """
    import gc

    from pivot_tpu.des import Environment
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.obs import DispatchProfiler
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    trace_file = "data/jobs/jobs-5000-200-86400-172800.npz"

    def build():
        meta = ResourceMetadata(seed=0)
        gen = RandomClusterGenerator(
            Environment(), (16, 16), (128 * 1024,) * 2, (100, 100),
            (1, 1), meta=meta, seed=0,
        )
        return gen.generate(n_hosts)

    cluster = build()
    state = {"sampled": 0, "families": None}

    def once(profile: bool):
        policy = TpuCostAwarePolicy(
            bin_pack="first-fit", sort_tasks=True, sort_hosts=True,
            adaptive=False,
        )
        prof = None
        if profile:
            prof = DispatchProfiler(sample_every=4, seed=0)
            policy.enable_profiler(prof)
        run = ExperimentRun(
            "prof_overhead", cluster, policy, trace_file,
            n_apps=n_apps, seed=3, fuse_spans=True,
        )
        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            summary = run.run()
            wall = time.perf_counter() - t0
        finally:
            gc.enable()
        if prof is not None:
            s = prof.summary()
            state["sampled"] = sum(
                fam["sampled"] for fam in s["families"].values()
            )
            state["families"] = s["families"]
        return wall, summary

    # Shared bracketed-pair protocol (``_bracketed_overhead``); the
    # warmup run also pays the XLA compiles and the profiler's
    # one-shot floor probe.
    r = _bracketed_overhead(once, repeats)
    return {
        **({} if r["parity"] else {
            "error": "profiled run diverged from unprofiled (meter)"
        }),
        "n_apps": n_apps,
        "h": n_hosts,
        "rounds": repeats,
        "fused_tick_path": True,
        "sample_every": 4,
        "wall_off_s": r["wall_off_s"],
        "wall_on_s": r["wall_on_s"],
        "sampled_dispatches": state["sampled"],
        "families": state["families"],
        "profiler_on_overhead_pct": r["overhead_pct"],
        "profiler_off_noise_pct": r["off_noise_pct"],
        "parity": r["parity"],
        "meets_3pct": bool(
            r["parity"]
            and r["overhead_pct"] < max(3.0, r["off_noise_pct"])
            and state["sampled"] > 0
        ),
    }


def _bench_cost_attribution() -> dict:
    """Round-15 coverage row: every jitmap-registered XLA entry point
    carries a cost-attribution row — measured
    ``lowered.compile().cost_analysis()`` FLOPs/bytes joined against
    the analytic roofline model, or an explicit flag naming where its
    cost story lives (register-or-flag, ``pivot_tpu/obs/costattr.py``).
    ``complete`` is the gate: a new jit site without a manifest entry
    fails it."""
    from pivot_tpu.obs.costattr import cost_attribution

    return cost_attribution()


def _bench_device(ctx, n_replicas: int, repeats: int = 5):
    """Decisions/sec of the vmapped fused kernel over a perturbed ensemble."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from pivot_tpu.infra import roofline
    from pivot_tpu.ops.kernels import cost_aware_kernel, cost_aware_kernel_ref
    from pivot_tpu.ops.pallas_kernels import (
        cost_aware_pallas,
        cost_aware_pallas_batched,
    )

    T, H, R = ctx.n_tasks, ctx.n_hosts, n_replicas
    topo, dem, valid, ng_arr, az_arr = _cost_aware_tick_args(ctx)

    # Monte-Carlo ensemble: perturb availability ±10% per replica.
    repl_rng = np.random.default_rng(1)
    avail_r = (
        ctx.avail[None, :, :] * repl_rng.uniform(0.9, 1.1, size=(R, H, 1))
    ).astype(np.float32)

    # One shared argument pack for every kernel variant — scan, Pallas,
    # and batched Pallas must time the identical policy configuration or
    # the winner comparison is meaningless.
    kernel_args = (
        jnp.asarray(dem),
        jnp.asarray(valid),
        jnp.asarray(ng_arr),
        jnp.asarray(az_arr),
        topo.cost,
        topo.bw,
        topo.host_zone,
        jnp.zeros(H, dtype=jnp.int32),
    )
    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)

    def make(base_kernel):
        return jax.jit(jax.vmap(lambda a: base_kernel(a, *kernel_args, **mode)))

    avail_dev = jnp.asarray(avail_r)
    # Race the two device implementations — the lax.scan kernel and the
    # Pallas VMEM-resident greedy kernel — and report the winner.  A
    # variant that fails to compile or run must not kill the benchmark
    # (the Pallas kernel has only ever been validated in interpret mode
    # when the real chip was unreachable; a Mosaic lowering failure on
    # first hardware contact should cost that variant, not the artifact).
    # "two_phase" is the production kernel (round-6 restructure; on CPU it
    # resolves to the slim early-exit pass, on TPU to the scan form);
    # "scan_ref" is the retained oracle, kept in the race so the record
    # always carries the before/after pair on the same backend.
    variants = {
        "two_phase": make(
            lambda a, *rest, **kw: cost_aware_kernel(
                a, *rest, **kw, totals=topo.totals
            )
        ),
        "scan_ref": make(cost_aware_kernel_ref),
    }
    if jax.default_backend() == "tpu":
        variants["pallas"] = make(cost_aware_pallas)
        # Replica-batched Pallas: takes the whole [R, H, 4] ensemble in
        # one kernel (replicas ride the sublane axis, block size chosen
        # by the kernel — see pallas_kernels.cost_aware_pallas_batched);
        # measured 76.5 M decisions/s vs the scan's 12.9 M at the bench
        # shape on the v5e.
        variants["pallas_rb"] = jax.jit(
            lambda a: cost_aware_pallas_batched(a, *kernel_args, **mode)
        )
    results, outputs, errors, times = {}, {}, {}, {}
    for name, kernel in variants.items():
        try:
            per_call, placements = _timed_calls(
                lambda: kernel(avail_dev)[0],
                lambda p: int(np.asarray(jnp.sum(p))),
                n=repeats,
            )
        except Exception as exc:  # noqa: BLE001 — variant-level isolation
            errors[name] = f"{type(exc).__name__}: {exc}"[:300]
            if not results and name == "scan_ref":
                raise  # no viable device path left; let the watchdog act
            continue
        results[name] = (R * T) / per_call
        outputs[name] = placements
        times[name] = per_call
    winner = max(results, key=results.get)
    if "two_phase" in outputs and "scan_ref" in outputs and not np.array_equal(
        np.asarray(outputs["two_phase"]), np.asarray(outputs["scan_ref"])
    ):
        errors["two_phase_parity"] = "two_phase != scan_ref placements"
    # Roofline columns per timed variant (VERDICT r05 gap #2).
    backend = jax.default_backend()
    peaks = roofline.backend_peaks(backend)
    B = dem.shape[0]
    n_groups = int(np.asarray(ng_arr).sum())
    kind_of = {
        "two_phase": "slim" if backend == "cpu" else "scan",
        "scan_ref": "scan",
        "pallas": "pallas_rb",
        "pallas_rb": "pallas_rb",
    }
    rooflines = {
        name: roofline.annotate(
            secs, kind_of[name], B, H, R=R, backend=backend, dtype_bytes=4,
            n_groups=n_groups, peaks=peaks,
        )
        for name, secs in times.items()
    }
    return results[winner], outputs[winner], winner, results, errors, rooflines


def _bench_ensemble(ctx, n_replicas: int = 256, repeats: int = 3) -> float:
    """Replica rollouts/sec of the full on-device Monte-Carlo simulator
    (readiness + anchor votes + placement scan + timing, 128 ticks) — the
    flagship workload class the reference can only express as one OS
    process per scenario."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import DeviceTopology
    from pivot_tpu.parallel.ensemble import EnsembleWorkload, rollout
    from pivot_tpu.workload import Application, TaskGroup

    rng = np.random.default_rng(11)
    groups = []
    for i in range(24):
        deps = [str(i - 1)] if i % 3 and i else []
        groups.append(
            TaskGroup(
                str(i),
                cpus=float(rng.choice([0.5, 1.0, 2.0])),
                mem=float(rng.uniform(64, 2048)),
                runtime=float(rng.integers(5, 120)),
                output_size=float(rng.uniform(0, 500)),
                instances=int(rng.integers(1, 24)),
                dependencies=deps,
            )
        )
    workload = EnsembleWorkload.from_applications([Application("bench", groups)])
    topo = DeviceTopology.from_cluster(ctx.cluster, jnp.float32)
    avail0 = jnp.asarray(ctx.cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(ctx.cluster.storage_zone_vector())
    kw = dict(n_replicas=n_replicas, tick=5.0, max_ticks=128, perturb=0.1)

    per_call, _ = _timed_calls(
        lambda: rollout(jax.random.PRNGKey(0), avail0, workload, topo, sz, **kw),
        lambda res: float(np.asarray(jnp.sum(res.makespan))),
        n=repeats,
    )
    # Roofline, nominal model: T × max_ticks full placement steps per
    # replica.  The real rollout both does more (readiness, anchors,
    # transfer timing) and less (the place loop early-exits at the
    # eligible count; the tick loop stops when all tasks finish), so
    # this is a same-order estimate, good for the bound verdict only.
    from pivot_tpu.infra import roofline

    rl = roofline.annotate(
        per_call, "scan", workload.n_tasks * kw["max_ticks"],
        ctx.n_hosts, R=n_replicas, backend=jax.default_backend(),
        dtype_bytes=4,
    )
    rl["model"] = "nominal T x max_ticks placement steps; see docstring"
    return n_replicas / per_call, rl


def _bench_grid_batched(
    n_runs: int = 8, n_tasks: int = 32, n_hosts: int = 64, repeats: int = 5
) -> dict:
    """Dispatch-floor amortization row: G grid runs' per-tick cost-aware
    dispatches as ONE [G]-vmapped device call (the ``DispatchBatcher``
    program behind ``--batch-runs``) vs the same G ticks as sequential
    single-run dispatches — G×T×H decisions per dispatch instead of T×H.

    Small-tick shape on purpose: this is the regime the DES grid driver
    lives in, where the fixed per-dispatch cost (host staging + call +
    result fetch; 76–86 ms of tunnel RTT on the remote backend,
    ~0.1–0.3 ms of jit/transfer overhead even on CPU) dominates the
    kernel's compute and the reference's only recourse is one OS process
    per run.  The sequential arm reproduces the single-run policy's
    dispatch exactly (``sched/tpu.py``): bind-time topology stays
    device-resident, the six per-tick arrays are staged with explicit
    ``jnp.asarray`` like ``_padded``/``_device_place`` do, and each
    run's placements are fetched separately.  The batched arm is the
    ``DispatchBatcher`` program: one staging, one call, one fetch for
    the whole grid.
    """
    import numpy as np

    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import cost_aware_kernel
    from pivot_tpu.sched.batch import batch_execute

    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    reqs = []  # per-run host-staged tick payloads (the batcher's feed)
    seq_args = []  # same ticks: (numpy per-tick arrays, device topology)
    for g in range(n_runs):
        ctx = _build_batch(n_hosts, n_tasks, seed=g)
        topo, dem, valid, ng, az = _cost_aware_tick_args(ctx, rng_seed=g)
        counts = np.zeros(n_hosts, dtype=np.int32)
        per_tick = (
            ctx.avail.astype(np.float32), dem, valid, ng, az, counts,
        )
        topo_np = tuple(
            np.asarray(a) for a in (topo.cost, topo.bw, topo.host_zone)
        )
        reqs.append((per_tick[:5] + topo_np + (counts,), {}))
        seq_args.append((per_tick, (topo.cost, topo.bw, topo.host_zone)))

    def sequential():
        out = []
        for (avail, dem, valid, ng, az, counts), (cost, bw, hz) in seq_args:
            p, _ = cost_aware_kernel(
                jnp.asarray(avail),  # the policy's per-tick device staging
                jnp.asarray(dem),
                jnp.asarray(valid),
                jnp.asarray(ng),
                jnp.asarray(az),
                cost, bw, hz,
                jnp.asarray(counts),
                **mode,
            )
            out.append(np.asarray(p))  # per-run fetch — the dispatch floor
        return out

    def batched():
        return [p for p, _ in batch_execute(cost_aware_kernel, reqs, mode)]

    seq_out = sequential()  # warm (compile both programs)
    bat_out = batched()
    parity = all(np.array_equal(a, b) for a, b in zip(seq_out, bat_out))

    def best(fn):
        b = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            b = min(b, time.perf_counter() - t0)
        return b

    seq_wall, bat_wall = best(sequential), best(batched)
    decisions = n_runs * n_tasks
    import jax

    from pivot_tpu.infra import roofline

    backend = jax.default_backend()
    kind = "slim" if backend == "cpu" else "scan"
    B = reqs[0][0][1].shape[0]  # padded bucket of the per-tick demands
    rl = {
        arm: roofline.annotate(
            wall, kind, B if kind == "scan" else n_tasks, n_hosts,
            R=n_runs, backend=backend, dtype_bytes=4,
        )
        for arm, wall in (("sequential", seq_wall), ("batched", bat_wall))
    }
    return {
        "g": n_runs,
        "t": n_tasks,
        "h": n_hosts,
        "decisions_per_dispatch": n_runs * n_tasks,
        "sequential_dps": round(decisions / seq_wall, 1),
        "batched_dps": round(decisions / bat_wall, 1),
        "amortization": round(seq_wall / bat_wall, 2),
        "parity": bool(parity),
        "roofline": rl,
    }


def _bench_serve_stream(
    n_sessions: int = 2,
    n_jobs: int = 24,
    rate: float = 0.25,
    n_hosts: int = 16,
    queue_depth: int = 16,
    flush_after: float = 0.02,
    seed: int = 0,
) -> dict:
    """Online-serving row (``pivot_tpu.serve``): sustained placement
    decisions/sec and decision-latency percentiles while a Poisson
    arrival stream flows through ``n_sessions`` always-on scheduling
    sessions sharing one batched device dispatch.

    The measured regime is the serving hot path: per-tick dispatches of
    a handful of ready tasks, where the fixed per-call cost dominates —
    the batcher amortizes it across sessions exactly as ``grid_batched``
    does across grid runs, but under *streaming* arrivals with the
    deadline flush armed.  Replay pacing (as fast as the sessions can
    schedule) so the figure is throughput, not sleep time.
    """
    from pivot_tpu.serve import ServeDriver, ServeSession, poisson_arrivals
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    pcfg = PolicyConfig(
        name="cost-aware", device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )
    sessions = [
        ServeSession(
            f"bench-{g}",
            build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed)),
            make_policy(pcfg),
            seed=seed,
        )
        for g in range(n_sessions)
    ]
    driver = ServeDriver(
        sessions, queue_depth=queue_depth, backpressure="shed",
        flush_after=flush_after,
    )
    t0 = time.perf_counter()
    report = driver.run(poisson_arrivals(rate, n_jobs, seed=seed))
    wall = time.perf_counter() - t0
    slo = report["slo"]
    lat = slo["decision_latency_s"]
    import jax

    from pivot_tpu.infra import roofline

    backend = jax.default_backend()
    decisions = slo["counters"]["decisions"]
    # Aggregate roofline over the stream: per-decision placement work at
    # this host count (slim model, one group per decision — serving
    # dispatches are singleton-job batches), over the measured wall.
    rl = roofline.annotate(
        max(wall, 1e-9), "slim" if backend == "cpu" else "scan",
        max(decisions, 1), n_hosts, backend=backend, dtype_bytes=4,
        n_groups=max(decisions, 1),
    )
    return {
        "sessions": n_sessions,
        "jobs": n_jobs,
        "arrival_rate": rate,
        "h": n_hosts,
        "completed": slo["counters"]["completed"],
        "shed": slo["counters"]["shed"],
        "decisions": slo["counters"]["decisions"],
        "decisions_per_sec": round(slo["counters"]["decisions"] / wall, 1),
        "p50_decision_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "p99_decision_ms": round(lat.get("p99", 0.0) * 1e3, 3),
        "batcher": report["batcher"],
        "wall_s": round(wall, 3),
        "roofline": rl,
    }


def _bench_serve_tiers(
    n_jobs: int = 40,
    rate: float = 2.5,
    n_hosts: int = 16,
    queue_depth: int = 12,
    seed: int = 0,
    fixed_sessions: int = 2,
    g_min: int = 1,
    g_max: int = 4,
    slo_p99_s: float = 0.25,
) -> dict:
    """Multi-tenant serving row (round 9): a mixed-tier Poisson stream
    (25 % serving / 35 % batch / 40 % best-effort) at 10× the
    ``serve_stream`` row's arrival rate, against a queue too small for
    it — tier reservations + per-tier policies + in-queue preemption
    keep tier 0 lossless while the lower tiers absorb the pressure.

    Two arms over identical arrivals: a FIXED pool of
    ``fixed_sessions``, and the SLO-driven autoscaler free to resize in
    [g_min, g_max] against the tier-0 p99 decision-latency target.
    Each arm reports sustained decisions/s and per-tier p50/p95/p99
    decision latency; the autoscaler arm adds its scaling-event log.
    Runnable on CPU under ``JAX_PLATFORMS=cpu`` like every row.

    Caveat for cross-arm latency reads: both arms share one process, so
    the FIRST (fixed) arm pays jit tracing/compilation inside its early
    decision latencies while the second starts warm — compare tiers
    *within* an arm, and pool/shed/preemption trajectories across arms.
    """
    from pivot_tpu.serve import (
        AutoscaleConfig,
        ServeDriver,
        ServeSession,
        mixed_tier_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    pcfg = PolicyConfig(
        name="cost-aware", device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )

    def make_session(label):
        return ServeSession(
            label,
            build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed)),
            make_policy(pcfg),
            seed=seed,
        )

    def one_arm(label, n_sessions, autoscale):
        reset_ids()
        sessions = [
            make_session(f"{label}-{g}") for g in range(n_sessions)
        ]
        driver = ServeDriver(
            sessions,
            queue_depth=queue_depth,
            backpressure="shed",
            flush_after=0.02,
            tier_reserve=(0, 2, 4),
            tier_policies=("spill", "shed", "shed"),
            routing="least_loaded",
            preempt=True,
            session_factory=make_session,
            autoscale=autoscale,
        )
        stream = mixed_tier_arrivals(
            rate, n_jobs, weights=(0.25, 0.35, 0.40), seed=seed,
            make_app=synthetic_app_factory(seed=seed),
        )
        t0 = time.perf_counter()
        report = driver.run(stream)
        wall = time.perf_counter() - t0
        driver.audit(context=f"serve_tiers bench ({label})")
        snap = report["slo"]
        tiers = {}
        for tier, tsnap in snap["tiers"].items():
            lat = tsnap["decision_latency_s"]
            tiers[tier] = {
                "p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
                "p95_ms": round(lat.get("p95", 0.0) * 1e3, 3),
                "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
                "admitted": tsnap["counters"]["admitted"],
                "completed": tsnap["counters"]["completed"],
                "shed": tsnap["counters"]["shed"],
                "preempted": tsnap["counters"]["preempted"],
            }
        arm = {
            "wall_s": round(wall, 3),
            "decisions": snap["counters"]["decisions"],
            "decisions_per_sec": round(
                snap["counters"]["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["counters"]["completed"],
            "shed": snap["counters"]["shed"],
            "preempted": snap["counters"]["preempted"],
            "pool_final": report["pool"]["final"],
            "dispatch": snap["dispatch"],
            "tiers": tiers,
        }
        if report["autoscaler"] is not None:
            arm["scale_events"] = report["autoscaler"]["events"]
        return arm

    return {
        "jobs": n_jobs,
        "arrival_rate": rate,
        "h": n_hosts,
        "queue_depth": queue_depth,
        "tier_mix": [0.25, 0.35, 0.40],
        "slo_p99_ms": slo_p99_s * 1e3,
        "fixed_pool": one_arm("fix", fixed_sessions, None),
        "autoscaled": one_arm(
            "auto", g_min,
            AutoscaleConfig(
                g_min=g_min, g_max=g_max, slo_p99_s=slo_p99_s,
                check_interval_s=0.05,
            ),
        ),
    }


def _bench_spot_survival(
    n_hosts: int = 12,
    seed: int = 3,
    n_apps: int = 10,
    risk_weight: float = 1.0,
    rework_cost: float = 50.0,
) -> dict:
    """Spot-market survival row (round 11, ``infra/market.py``): the
    same seeded :class:`MarketSchedule` — discounted-but-hazardous spot
    zones next to calm on-demand ones, piecewise-constant prices and
    hazards — played by three arms of the cost-aware scheduler over the
    IDENTICAL hazard-drawn preemption plan:

      * ``hazard_blind`` — risk_weight 0, reactive recovery only (the
        pre-market scheduler: packs onto the cheap/evictable pool);
      * ``proactive_only`` — still hazard-blind at placement, but the
        preemption warning triggers drain → migrate → restart
        (``GlobalScheduler.on_preempt_warning``): isolates what the
        survival machinery alone buys;
      * ``risk_aware`` — risk term in every score AND proactive drain
        (the Bamboo/SpotServe shape).

    Headline columns: cost per completed task (price-trace-integrated
    instance cost + egress over completions), dead-letter rate, wasted
    rework seconds.  ``meets_survival`` asserts the acceptance
    inequality — risk_aware strictly below hazard_blind on BOTH
    headline metrics.  Pure-DES row: runs identically on any backend.
    """
    from pivot_tpu.experiments.spot import run_spot_arm, spot_market

    market = spot_market(n_hosts, seed=seed)
    kw = dict(n_hosts=n_hosts, seed=seed, n_apps=n_apps)
    n_preemptions = {}

    def arm(label, **extra):
        r = run_spot_arm(market, **kw, **extra)
        n_preemptions[label] = r["n_preemptions"]
        cpt = r["cost_per_completed_task"]  # None when nothing completed
        return {
            "cost_per_completed_task": (
                round(cpt, 6) if cpt is not None else None
            ),
            "dead_letter_rate": round(r["dead_letter_rate"], 4),
            "completed": r["n_completed_tasks"],
            "tasks": r["n_tasks"],
            "rework_seconds": round(r["rework_seconds"], 1),
            "instance_cost": round(r["instance_cost"], 5),
            "egress_cost": round(r["egress_cost"], 5),
            "n_migrated": r["n_migrated"],
            "n_proactive_restarts": r["n_proactive_restarts"],
            "audit_violations": r["audit_violations"],
        }

    blind = arm("hazard_blind")
    proactive = arm("proactive_only", proactive=True)
    aware = arm(
        "risk_aware", risk_weight=risk_weight, rework_cost=rework_cost,
        proactive=True,
    )
    # Identical across arms by construction (the plan is a pure function
    # of topology × market × seed); a divergence makes the three-way
    # comparison unattributable, so it fails meets_survival outright.
    plans_identical = len(set(n_preemptions.values())) == 1
    return {
        "h": n_hosts,
        "apps": n_apps,
        "plans_identical": plans_identical,
        "n_preemptions_planned": (
            n_preemptions["hazard_blind"]
            if plans_identical
            else n_preemptions
        ),
        "hot_zones": len(market.meta.get("hot_zones", [])),
        "risk_weight": risk_weight,
        "rework_cost": rework_cost,
        "hazard_blind": blind,
        "proactive_only": proactive,
        "risk_aware": aware,
        "meets_survival": bool(
            plans_identical
            and aware["cost_per_completed_task"] is not None
            and blind["cost_per_completed_task"] is not None
            and aware["cost_per_completed_task"]
            < blind["cost_per_completed_task"]
            and aware["dead_letter_rate"] < blind["dead_letter_rate"]
        ),
        "audits_clean": not (
            blind["audit_violations"]
            or proactive["audit_violations"]
            or aware["audit_violations"]
        ),
    }


def _child_backend_setup():
    """Shared child preamble: apply the parent's ``PIVOT_BENCH_BACKEND``
    override explicitly (ignoring it would silently contradict the
    parent — ADVICE.md) and warm the persistent compile cache.  Returns
    the configured ``jax`` module."""
    import jax

    from pivot_tpu.utils import enable_compilation_cache

    override = os.environ.get("PIVOT_BENCH_BACKEND")
    if override:
        jax.config.update("jax_platforms", override)
    enable_compilation_cache()
    return jax


def _run_row_in_child(env_flag: str, timeout_s: int,
                      error_base: dict = None) -> dict:
    """Shared parent side of every child-isolated bench row: spawn this
    file as a disposable child with ``env_flag=1``, bound it, parse its
    one-JSON-line row.  Failures — nonzero exit, hang, dead backend —
    become a recorded error row carrying the child's stdout/stderr tail
    (tracebacks and libtpu diagnostics land on stderr; an empty stdout
    tail would record "rc=N:" with no content — ADVICE.md).  Stderr is
    routed through ``filter_xla_aot_noise`` first: the XLA:CPU AOT
    cache-portability warning wall otherwise IS the recorded tail,
    burying the real traceback (round-15 satellite)."""
    import subprocess

    from pivot_tpu.utils import filter_xla_aot_noise

    base = error_base or {}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**os.environ, env_flag: "1"},
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        if proc.returncode != 0:
            out_lines = [
                ln for ln in proc.stdout.strip().splitlines() if ln.strip()
            ]
            err_lines = [
                ln for ln in
                filter_xla_aot_noise(proc.stderr).strip().splitlines()
                if ln.strip()
            ]
            tail = (out_lines or err_lines or [""])[-1][:300]
            return {**base, "error": f"child rc={proc.returncode}: {tail}"}
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:  # noqa: BLE001 — row-level isolation
        return {**base, "error": f"{type(exc).__name__}: {exc}"[:300]}


def _serve_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_CHILD=1``): run the
    serve_stream row and print ONE JSON line.  A child for the same two
    reasons as the saturated row: a wedged tunnel RPC can hang where
    SIGALRM cannot reach, and on the single-tenant backend the child
    must be the only PJRT client alive."""
    jax = _child_backend_setup()
    row = _bench_serve_stream()
    row["backend"] = jax.default_backend()
    print(json.dumps(row), flush=True)


def _bench_serve_in_child(timeout_s: int = 420) -> dict:
    """Parent side of the serve_stream row — see ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_CHILD", timeout_s)


def _serve_tiers_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_TIERS_CHILD=1``): run the
    serve_tiers row and print ONE JSON line.  Child-isolated for the
    same reasons as serve_stream (wedged-tunnel hangs; single-tenant
    backend wants one PJRT client alive)."""
    jax = _child_backend_setup()
    row = _bench_serve_tiers()
    row["backend"] = jax.default_backend()
    print(json.dumps(row), flush=True)


def _bench_serve_tiers_in_child(timeout_s: int = 420) -> dict:
    """Parent side of the serve_tiers row — see ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_TIERS_CHILD", timeout_s)


def _bench_serve_sharded(
    n_jobs: int = 40,
    rate: float = 25.0,
    n_hosts: int = 16,
    queue_depth: int = 12,
    seed: int = 0,
    n_sessions: int = 3,
) -> dict:
    """2-D mesh serving row (round 17): the SAME mixed-tier stream at
    100× the PR-2 ``serve_stream`` rate served by three stacks —

      * ``batch_1d``  — cross-run batching only (the pre-round-17
        serving stack: vmapped coalesced flushes, single device);
      * ``shard_1d``  — host sharding only (sessions run free, each
        dispatch host-sharded over the 8-device mesh, no coalescing);
      * ``mesh_2d``   — batching × sharding composed on the
        ``replica × host`` mesh + ``fuse_spans="slo"`` (the 100×
        stack: coalesced 2-D flushes, fused spans between SLO
        checkpoints).

    Per arm: sustained decisions/s, per-tier p99 decision latency, the
    dispatch mix, and span stats.  Runs on the forced-8-device CPU mesh
    (the child pins the flag); same warm-start caveat as serve_tiers —
    the FIRST arm pays jit compiles, so compare tiers within an arm and
    dispatch mixes across arms.  Tracked as ``serve_sharded_dps``
    (the ``mesh_2d`` arm) in ``tools/bench_history.py``, phase-in:
    note-not-gate until the committed baseline carries rows."""
    from pivot_tpu.parallel.mesh import build_hybrid_mesh
    from pivot_tpu.serve import (
        ServeDriver,
        ServeSession,
        mixed_tier_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    mesh2d = build_hybrid_mesh(host_parallel=2)
    pcfg = PolicyConfig(
        name="cost-aware", device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )

    def one_arm(label, sharded, fuse, mesh):
        reset_ids()

        def make_session(slabel):
            policy = make_policy(pcfg)
            if sharded:
                policy.enable_sharding(mesh2d)
            return ServeSession(
                slabel,
                build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed)),
                policy,
                seed=seed,
                fuse_spans=fuse,
            )

        sessions = [
            make_session(f"{label}-{g}") for g in range(n_sessions)
        ]
        driver = ServeDriver(
            sessions,
            queue_depth=queue_depth,
            backpressure="shed",
            flush_after=0.02,
            mesh=mesh,
            tier_reserve=(0, 2, 4),
            tier_policies=("spill", "shed", "shed"),
        )
        stream = mixed_tier_arrivals(
            rate, n_jobs, weights=(0.25, 0.35, 0.40), seed=seed,
            make_app=synthetic_app_factory(seed=seed),
        )
        t0 = time.perf_counter()
        report = driver.run(stream)
        wall = time.perf_counter() - t0
        driver.audit(context=f"serve_sharded bench ({label})")
        snap = report["slo"]
        tiers = {
            tier: {
                "p99_ms": round(
                    tsnap["decision_latency_s"].get("p99", 0.0) * 1e3, 3
                ),
                "completed": tsnap["counters"]["completed"],
                "shed": tsnap["counters"]["shed"],
            }
            for tier, tsnap in snap["tiers"].items()
        }
        span_stats = {
            k: sum(
                s.summary()["span_stats"][k]
                for s in driver.sessions + driver._retired
            )
            for k in ("fused_spans", "fused_ticks", "ff_ticks",
                      "span_aborts")
        }
        return {
            "wall_s": round(wall, 3),
            "decisions": snap["counters"]["decisions"],
            "decisions_per_sec": round(
                snap["counters"]["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["counters"]["completed"],
            "shed": snap["counters"]["shed"],
            "span_dispatches": snap["counters"]["span_dispatches"],
            "dispatch": snap["dispatch"],
            "span_stats": span_stats,
            "tiers": tiers,
            "mesh": report["mesh"],
        }

    return {
        "jobs": n_jobs,
        "arrival_rate": rate,
        "rate_vs_pr2": round(rate / 0.25, 1),
        "h": n_hosts,
        "sessions": n_sessions,
        "tier_mix": [0.25, 0.35, 0.40],
        "batch_1d": one_arm("b1", sharded=False, fuse=False, mesh=None),
        "shard_1d": one_arm("s1", sharded=True, fuse=False, mesh=None),
        "mesh_2d": one_arm("m2", sharded=True, fuse="slo", mesh=mesh2d),
    }


def _serve_sharded_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_SHARDED_CHILD=1``): pin the
    forced-8-device CPU mesh BEFORE the first jax import (XLA reads the
    flag once per process — the shard_place arms' pattern), run the
    serve_sharded row, print ONE JSON line."""
    os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    jax = _child_backend_setup()
    row = _bench_serve_sharded()
    row["backend"] = jax.default_backend()
    row["n_devices"] = len(jax.devices())
    print(json.dumps(row), flush=True)


def _bench_serve_sharded_in_child(timeout_s: int = 420) -> dict:
    """Parent side of the serve_sharded row — see ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_SHARDED_CHILD", timeout_s)


def _bench_serve_ragged(
    n_jobs: int = 40,
    rate: float = 25.0,
    n_hosts: int = 16,
    queue_depth: int = 12,
    seed: int = 0,
    n_sessions: int = 3,
    dense_jobs: int = 160,
    dense_sessions: int = 4,
) -> dict:
    """Ragged continuous batching row (round 18): the serve_sharded
    mixed-tier stream (100× the PR-2 rate) on the full ``mesh_2d``
    stack, with the dispatch batcher's ragged mode ON vs OFF —

      * ``same_shape`` — the PR-15 path (``ragged=False``): co-pending
        spans coalesce only on exact shape match, so mixed-horizon
        groups fragment into serial flushes and mesh fallbacks;
      * ``ragged``     — mixed-horizon spans padded to a shared
        (K-bucket, B-bucket) and served as ONE device program, trimmed
        per request (bit-identical by the repack parity suite).

    Two blocks, because the acceptance properties live at different
    densities.  The SPARSE block (``n_jobs`` jobs) is deterministic end
    to end — admission and routing settle identically run to run — so
    it carries the exact assertions: bit-identical final placements
    across ragged / same-shape / per-tick-referee arms (``parity_ok``)
    and zero recompiles on the measured ragged pass after a warmup pass
    of the same stream (``count_compiles``).  The DENSE block
    (``dense_jobs`` jobs, ``dense_sessions`` sessions) actually
    produces co-pending mixed-horizon spans — that is where
    ``throughput_ratio`` (ragged vs same-shape decisions/s) and
    ``fallbacks_lower`` (ragged kills the mixed-shape mesh fallbacks)
    are measured; its placements are covered by the repack parity
    suite, not re-asserted here, because wall-clock routing at 100×
    density is legitimately racy across arms.  Tracked as
    ``serve_ragged`` in ``tools/bench_history.py``, phase-in:
    note-not-gate until the committed baseline carries rows."""
    from pivot_tpu.parallel.mesh import build_hybrid_mesh
    from pivot_tpu.serve import (
        ServeDriver,
        ServeSession,
        mixed_tier_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.compile_counter import count_compiles
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    mesh2d = build_hybrid_mesh(host_parallel=2)
    pcfg = PolicyConfig(
        name="cost-aware", device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )

    def final_placements(sessions):
        out = []
        for s in sessions:
            for app in s._injected:
                for group in app.groups:
                    for task in group.tasks:
                        out.append((app.id, task.id, task.placement))
        return sorted(out)

    def one_arm(label, sharded, fuse, mesh, ragged,
                jobs=n_jobs, pool_n=n_sessions):
        reset_ids()

        def make_session(slabel):
            policy = make_policy(pcfg)
            if sharded:
                policy.enable_sharding(mesh2d)
            return ServeSession(
                slabel,
                build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed)),
                policy,
                seed=seed,
                fuse_spans=fuse,
            )

        sessions = [
            make_session(f"{label}-{g}") for g in range(pool_n)
        ]
        driver = ServeDriver(
            sessions,
            queue_depth=queue_depth,
            backpressure="shed",
            flush_after=0.02,
            mesh=mesh,
            tier_reserve=(0, 2, 4),
            tier_policies=("spill", "shed", "shed"),
            ragged=ragged,
        )
        stream = mixed_tier_arrivals(
            rate, jobs, weights=(0.25, 0.35, 0.40), seed=seed,
            make_app=synthetic_app_factory(seed=seed),
        )
        t0 = time.perf_counter()
        report = driver.run(stream)
        wall = time.perf_counter() - t0
        driver.audit(context=f"serve_ragged bench ({label})")
        snap = report["slo"]
        batcher = report["batcher"] or {}
        pool = driver.sessions + driver._retired
        span_stats = {
            k: sum(s.summary()["span_stats"][k] for s in pool)
            for k in ("fused_spans", "fused_ticks", "ff_ticks",
                      "span_aborts", "span_ticks_max")
        }
        coalesced = max(int(batcher.get("dispatches", 0)), 1)
        return {
            "wall_s": round(wall, 3),
            "decisions": snap["counters"]["decisions"],
            "decisions_per_sec": round(
                snap["counters"]["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["counters"]["completed"],
            "shed": snap["counters"]["shed"],
            "device_calls": int(batcher.get("device_calls", 0)),
            "mesh_dispatches": int(batcher.get("mesh_dispatches", 0)),
            "mesh_fallbacks": int(batcher.get("mesh_fallbacks", 0)),
            "fallback_causes": {
                k: int(batcher.get(f"mesh_fallback_{k}", 0))
                for k in ("unshardable", "mixed_shapes", "indivisible")
            },
            "ragged_merges": int(batcher.get("ragged_merges", 0)),
            "ragged_rows": int(batcher.get("ragged_rows", 0)),
            "ragged_pad_cells": int(batcher.get("ragged_pad_cells", 0)),
            "ragged_frac": round(
                int(batcher.get("ragged_rows", 0)) / coalesced, 3
            ),
            "span_stats": span_stats,
        }, final_placements(pool)

    # -- sparse block: exact assertions on a deterministic stream -----
    # Warmup pass: both mesh arms serve the full stream once so every
    # (policy, shape) program is compiled before measurement.
    one_arm("w0", sharded=True, fuse="slo", mesh=mesh2d, ragged=False)
    one_arm("w1", sharded=True, fuse="slo", mesh=mesh2d, ragged=True)

    sp_same, p_same = one_arm(
        "ss", sharded=True, fuse="slo", mesh=mesh2d, ragged=False
    )
    with count_compiles() as counter:
        sp_ragged, p_ragged = one_arm(
            "rg", sharded=True, fuse="slo", mesh=mesh2d, ragged=True
        )
    sp_referee, p_ref = one_arm(
        "pt", sharded=False, fuse=False, mesh=None, ragged=False
    )

    # -- dense block: co-pending mixed horizons, throughput + fallbacks
    # Best-of-3 measured passes per arm: span shapes at this density
    # are timing-dependent, so a pass can hit a shape the warmup never
    # saw — one compile on a ~0.2 s wall would swamp the ratio, but it
    # can only poison the pass that first meets the shape.
    one_arm("dw0", sharded=True, fuse="slo", mesh=mesh2d, ragged=False,
            jobs=dense_jobs, pool_n=dense_sessions)
    one_arm("dw1", sharded=True, fuse="slo", mesh=mesh2d, ragged=True,
            jobs=dense_jobs, pool_n=dense_sessions)

    def dense_arm(label, ragged):
        passes = [
            one_arm(f"{label}{i}", sharded=True, fuse="slo",
                    mesh=mesh2d, ragged=ragged,
                    jobs=dense_jobs, pool_n=dense_sessions)[0]
            for i in range(3)
        ]
        best = max(passes, key=lambda a: a["decisions_per_sec"])
        best["pass_walls_s"] = [a["wall_s"] for a in passes]
        return best

    dn_same = dense_arm("dss", ragged=False)
    with count_compiles() as dense_counter:
        dn_ragged = dense_arm("drg", ragged=True)
    return {
        "jobs": n_jobs,
        "dense_jobs": dense_jobs,
        "arrival_rate": rate,
        "rate_vs_pr2": round(rate / 0.25, 1),
        "h": n_hosts,
        "sessions": n_sessions,
        "dense_sessions": dense_sessions,
        "tier_mix": [0.25, 0.35, 0.40],
        "sparse": {
            "same_shape": sp_same,
            "ragged": sp_ragged,
            "referee": sp_referee,
        },
        "same_shape": dn_same,
        "ragged": dn_ragged,
        "throughput_ratio": round(
            dn_ragged["decisions_per_sec"]
            / max(dn_same["decisions_per_sec"], 1e-9), 3
        ),
        "fallbacks_lower": (
            dn_ragged["mesh_fallbacks"] < dn_same["mesh_fallbacks"]
        ),
        "recompiles_after_warmup": int(counter.compiles),
        "retraces_after_warmup": int(counter.traces),
        # Informational at dense density: timing-dependent span shapes
        # can straddle the warmup pass (the assertion lives in the
        # deterministic sparse block above).
        "dense_recompiles": int(dense_counter.compiles),
        "parity_ok": bool(p_ragged == p_same == p_ref),
    }


def _serve_ragged_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_RAGGED_CHILD=1``): pin the
    forced-8-device CPU mesh BEFORE the first jax import (XLA reads the
    flag once per process), run the serve_ragged row, print ONE JSON
    line."""
    os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    jax = _child_backend_setup()
    row = _bench_serve_ragged()
    row["backend"] = jax.default_backend()
    row["n_devices"] = len(jax.devices())
    print(json.dumps(row), flush=True)


def _bench_serve_ragged_in_child(timeout_s: int = 540) -> dict:
    """Parent side of the serve_ragged row — see ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_RAGGED_CHILD", timeout_s)


def _bench_serve_mpc(
    n_jobs: int = 120,
    rate: float = 0.4,
    n_hosts: int = 8,
    queue_depth: int = 24,
    seed: int = 7,
    pace: float = 120.0,
) -> dict:
    """Model-predictive serving row (round 19): the same seeded
    mixed-tier chaos+market stream through a reactive fixed-pool driver
    and an MPC-supervised one (``pivot_tpu/mpc/``: forecaster →
    shape-pinned shadow rollouts → five-slot planner → background CEM
    tuner → staged weight rollout).

    What the row records:

      * ``mpc.decisions_per_sec`` — serving throughput WITH the
        controller, forecaster tap, and tuner thread attached (the
        overhead question: the tracked metric in
        ``tools/bench_history.py``, phase-in note-not-gate until the
        committed baseline carries the row);
      * ``overhead_ratio`` — mpc vs reactive decisions/s on the
        identical stream;
      * ``tuned_vs_default`` — cost-per-task of the best regret-gated
        tuner vector relative to ``DEFAULT_WEIGHTS``, re-scored on a
        FRESH scenario key (< 1.0 means the live tuner found a cheaper
        scoring vector than the reactive incumbent — the subsystem's
        headline);
      * ``recompiles_after_warmup`` — the planner AND tuner dispatches
        are compile-counted across the whole MPC arm after one warmup
        of each program: shape-pinned rendering means every window's
        variation (forecast rates, tier masks, scenario keys) enters
        as data, so the count must be zero;
      * ``tier0_lossless`` / ``parity`` — tier 0 sheds nothing in
        either arm, and the MPC arm's admission outcome stays within a
        whisker of the reactive baseline on the identical stream.
    """
    import jax

    from pivot_tpu.infra.market import MarketSchedule
    from pivot_tpu.mpc import MpcConfig
    from pivot_tpu.mpc.forecast import TierForecast, render_env
    from pivot_tpu.mpc.planner import enumerate_actions, plan
    from pivot_tpu.mpc.tuner import tune_once
    from pivot_tpu.sched.policies import CostAwarePolicy
    from pivot_tpu.search.fitness import evaluate_rows
    from pivot_tpu.search.weights import DEFAULT_WEIGHTS, PolicyWeights
    from pivot_tpu.serve import (
        ServeDriver,
        ServeSession,
        mixed_tier_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.compile_counter import count_compiles
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    reset_ids()
    template = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=11))
    market = MarketSchedule.generate(template.meta, seed=11, horizon=240.0)
    cfg = MpcConfig(
        check_interval_s=0.02, horizon=200.0, tick=5.0, n_replicas=2,
        env_apps=4, seed=5, min_observations=3, cooldown_s=0.0,
        latency_weight=0.05, referee_every=4, g_min=1, g_max=3,
        n_tiers=3, bucket_s=10.0,
        tune=True, tune_interval_s=0.05, tune_generations=1,
        tune_popsize=4, cluster=template, market=market,
    )

    # Warm BOTH compiled programs outside the counter — the planner's
    # fused 5-slot dispatch and the tuner's CEM population dispatch —
    # on the same template and pinned shapes the controller renders
    # every window.
    mix = (0.4, 0.3, 0.3)
    warm_fc = TierForecast(
        rates=tuple(rate * m for m in mix), mix=mix,
        n_observed=12, window=60.0,
    )
    env, _, task_tiers = render_env(
        warm_fc, cluster=template, market=market, horizon=cfg.horizon,
        seed=cfg.seed, n_replicas=cfg.n_replicas, tick=cfg.tick,
        n_apps=cfg.env_apps, redraw_faults=cfg.redraw_faults,
    )
    warm_menu = enumerate_actions(
        1, g_min=cfg.g_min, g_max=cfg.g_max, incumbent=DEFAULT_WEIGHTS,
        shed_tier=2,
    )
    plan(warm_menu, env, task_tiers, 1,
         latency_weight=cfg.latency_weight,
         key=jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0))
    tune_once(env, incumbent=DEFAULT_WEIGHTS, seed=cfg.seed,
              generations=cfg.tune_generations, popsize=cfg.tune_popsize)

    def arm(label, mpc):
        reset_ids()
        make_app = synthetic_app_factory(
            seed=seed, runtime=(60.0, 120.0), n_nodes=(2, 3),
        )

        def make_session(slabel):
            return ServeSession(
                slabel,
                build_cluster(ClusterConfig(n_hosts=n_hosts, seed=1)),
                CostAwarePolicy(),
                seed=1,
            )

        driver = ServeDriver(
            [make_session(f"{label}-0")],
            queue_depth=queue_depth,
            backpressure="shed",
            tier_policies=("spill", "shed", "shed"),
            preempt=True,
            session_factory=make_session if mpc is not None else None,
            mpc=mpc,
        )
        stream = mixed_tier_arrivals(
            rate, n_jobs, mix, seed=seed, make_app=make_app,
        )
        t0 = time.perf_counter()
        report = driver.run(stream, pace=pace)
        wall = time.perf_counter() - t0
        driver.audit(context=f"serve_mpc bench ({label})")
        snap = report["slo"]
        row = {
            "wall_s": round(wall, 3),
            "decisions": snap["counters"]["decisions"],
            "decisions_per_sec": round(
                snap["counters"]["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["counters"]["completed"],
            "shed": snap["counters"]["shed"],
            "tier0_shed": snap["tiers"]["0"]["counters"]["shed"],
            "pool_final": len(driver.sessions),
        }
        return driver, report, row

    _, report_r, reactive = arm("re", None)
    with count_compiles() as counter:
        driver_m, report_m, mpc_row = arm("mp", cfg)

    mpc = report_m["mpc"] or {}
    mpc_row.update(
        {
            "rounds": int(mpc.get("rounds", 0)),
            "plans": int(mpc.get("plans", 0)),
            "disabled": bool(mpc.get("disabled", False)),
            "n_observed": int(
                (mpc.get("forecast") or {}).get("n_observed", 0)
            ),
            "actions": _count_mpc_actions(mpc.get("events") or []),
            "tuner": mpc.get("tuner"),
            "rollout": {
                k: (mpc.get("rollout") or {}).get(k)
                for k in ("promotions", "rollbacks", "stage")
            },
        }
    )

    # The headline: the soak's own tuner output vs the reactive
    # incumbent, re-scored on a fresh scenario key neither the tuner
    # nor the planner ever drew.
    tuned_vs_default = None
    results = list(driver_m._mpc.tuner.results) if driver_m._mpc else []
    eligible = [r.weights for r in results if r.eligible]
    if eligible:
        W = PolicyWeights.stack(eligible + [DEFAULT_WEIGHTS])
        scores, _ = evaluate_rows(
            W, env, key=jax.random.PRNGKey(1234), backend="rollout",
        )
        scores = [float(s) for s in scores]
        tuned_vs_default = round(
            min(scores[:-1]) / max(scores[-1], 1e-9), 4
        )

    c_r = report_r["slo"]["counters"]
    c_m = report_m["slo"]["counters"]
    return {
        "jobs": n_jobs,
        "arrival_rate": rate,
        "h": n_hosts,
        "pace": pace,
        "tier_mix": list(mix),
        "reactive": reactive,
        "mpc": mpc_row,
        "overhead_ratio": round(
            mpc_row["decisions_per_sec"]
            / max(reactive["decisions_per_sec"], 1e-9), 3
        ),
        "tuned_vs_default": tuned_vs_default,
        "tuned_beats_default": (
            tuned_vs_default is not None and tuned_vs_default < 1.0
        ),
        "tier0_lossless": (
            reactive["tier0_shed"] == 0 and mpc_row["tier0_shed"] == 0
        ),
        "parity": (
            abs(c_m["completed"] - c_r["completed"]) <= 4
            and c_m["shed"] <= c_r["shed"] + 4
        ),
        "recompiles_after_warmup": int(counter.compiles),
        "retraces_after_warmup": int(counter.traces),
    }


def _count_mpc_actions(events) -> dict:
    counts: dict = {}
    for evt in events:
        a = evt.get("action", "?")
        counts[a] = counts.get(a, 0) + 1
    return counts


def _serve_mpc_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_MPC_CHILD=1``): run the
    serve_mpc row and print ONE JSON line.  Child-isolated like every
    serve row — the MPC arm starts controller and tuner threads that
    must never share a PJRT client with the parent's headline pass."""
    jax = _child_backend_setup()
    row = _bench_serve_mpc()
    row["backend"] = jax.default_backend()
    print(json.dumps(row), flush=True)


def _bench_serve_mpc_in_child(timeout_s: int = 540) -> dict:
    """Parent side of the serve_mpc row — see ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_MPC_CHILD", timeout_s)


def _bench_serve_resident(
    n_hosts: int = 4,
    n_apps: int = 6,
    micro_hosts: tuple = (4096, 32768, 100_000),
    micro_b: int = 32,
    micro_k: int = 8,
    micro_spans: int = 30,
    n_jobs: int = 80,
    rate: float = 50.0,
    seed: int = 0,
) -> dict:
    """Resident-carry serving row (round 20): device-persistent span
    state with donated buffers vs the re-staged span path.

    Three blocks:

      * ``serve`` — a deterministic DES run (cost-aware policy, chain
        apps) with the profiler attached: resident vs re-staged arms
        must produce bit-identical placements AND meter totals, the
        resident arm must take ZERO recompiles on a second identical
        pass after warmup, and the profiler's census-grade per-family
        transfer counters give honest h2d bytes/span for both arms at
        serving scale.
      * ``scaling`` — kernel-level micro arms at H up to 100k hosts
        with every per-span input the real serve paths pay engaged
        (live mask, resident task counts, market risk): the re-staged
        arm renders + stages [K, H] risk rows, [H, 4] availability,
        counts, and live every span; the resident arm mirror-diffs
        against the carry and ships only the [B]-sized operands plus a
        [K] segment row against the once-staged [P, H] table.
        ``throughput_ratio`` (≥1.2x) and ``h2d_ratio`` (≥5x) are
        measured at the largest H, with bit parity asserted per H.
      * ``splice_soak`` — mid-span arrivals at staggered DES instants
        joined into the RUNNING span, each run verified bit-identical
        against the per-tick (``fuse_spans=False``) referee, plus a
        streamed ServeDriver pass with ``resident=True`` and the
        splice tier gate open (``driver`` — serve-level decisions/s;
        its slo-bounded spans end at the admission window, so driver
        streams report splices only when an in-DES submission lands
        mid-span).

    Tracked as ``serve_resident`` in ``tools/bench_history.py``
    (phase-in: note-not-gate until the committed baseline carries
    rows)."""
    import gc

    import jax.numpy as jnp
    import numpy as np

    from pivot_tpu.des import Environment
    from pivot_tpu.infra import Cluster, Host, Storage
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.infra.meter import Meter
    from pivot_tpu.obs import DispatchProfiler
    from pivot_tpu.ops.tickloop import (
        fused_tick_run,
        resident_carry_init,
        resident_span_run,
    )
    from pivot_tpu.sched import GlobalScheduler
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.compile_counter import count_compiles
    from pivot_tpu.workload import Application, TaskGroup

    # -- serve block: bit parity + meter parity + h2d census ----------
    def build_cluster_des(env, meter):
        meta = ResourceMetadata(seed=seed)
        zones = meta.zones
        hosts = [
            Host(env, 4.0, 1024, 100, 1, locality=zones[i % 2],
                 meter=meter, id=f"h{i}")
            for i in range(n_hosts)
        ]
        storage = [
            Storage(env, z)
            for z in dict.fromkeys(h.locality for h in hosts)
        ]
        return Cluster(
            env, hosts=hosts, storage=storage, meta=meta, meter=meter,
            route_mode="meta", seed=seed, executor_backend="fast",
        )

    def chain_apps():
        return [
            Application(f"app{i}", [
                TaskGroup("a", cpus=1, mem=64, runtime=17.0,
                          output_size=400, instances=10),
                TaskGroup("b", cpus=2, mem=64, runtime=9.0,
                          dependencies=["a"], instances=6),
                TaskGroup("c", cpus=1, mem=32, runtime=5.0,
                          dependencies=["b"], instances=8),
            ])
            for i in range(n_apps)
        ]

    def serve_arm(resident):
        reset_ids()
        env = Environment()
        meta = ResourceMetadata(seed=seed)
        meter = Meter(env, meta)
        cluster = build_cluster_des(env, meter)
        policy = TpuCostAwarePolicy(
            bin_pack="first-fit", sort_tasks=True, sort_hosts=True,
            adaptive=False,
        )
        prof = DispatchProfiler(sample_every=4, seed=0)
        policy.enable_profiler(prof)
        if resident:
            policy.enable_resident(splice=False)
        sched = GlobalScheduler(
            env, cluster, policy, seed=3, meter=meter, fuse_spans=True,
        )
        cluster.start()
        sched.start()
        apps = chain_apps()
        for a in apps:
            sched.submit(a)
        sched.stop()
        gc.collect()
        t0 = time.perf_counter()
        env.run()
        wall = time.perf_counter() - t0
        placements = sorted(
            (t.id, t.placement)
            for a in apps for g in a.groups for t in g.tasks
        )
        fam = prof.summary()["families"].get(
            "resident_span_run" if resident else "fused_tick_run", {},
        )
        return {
            "wall_s": round(wall, 3),
            "spans": int(fam.get("calls", 0)),
            "h2d_bytes_total": int(fam.get("h2d_bytes_total", 0)),
            "h2d_bytes_per_span": round(
                fam.get("h2d_bytes_per_call", 0.0), 1
            ),
            "meter_ops": meter.total_scheduling_ops,
            "span_stats": dict(sched.span_stats),
        }, placements

    serve_re, p_re = serve_arm(resident=False)
    serve_res, p_res = serve_arm(resident=True)
    with count_compiles() as counter:
        serve_res2, p_res2 = serve_arm(resident=True)
    serve_parity = bool(
        p_re == p_res == p_res2
        and serve_re["meter_ops"] == serve_res["meter_ops"]
    )

    # -- scaling block: kernel-level arms at H up to 100k -------------
    P = 24  # market segments in the synthetic risk table

    def micro(H):
        rng = np.random.default_rng(seed)
        avail0 = rng.uniform(4.0, 8.0, (H, 4)).astype(np.float32)
        counts0 = np.zeros(H, np.int32)
        live0 = np.ones(H, bool)
        dems = rng.uniform(
            0.05, 0.3, (micro_spans, micro_b, 4)
        ).astype(np.float32)
        arrive = np.zeros(micro_b, np.int32)
        hz = rng.integers(0, 4, H).astype(np.int32)
        hazard = rng.uniform(0.0, 0.2, (P, 4))
        w = 0.5
        table = (w * hazard[:, hz]).astype(np.float32)  # [P, H]
        segs = rng.integers(0, P, (micro_spans, micro_k)).astype(
            np.int32
        )
        kw = dict(policy="first-fit", n_ticks=micro_k, strict=False)

        def restaged():
            host_avail = avail0.copy()
            counts = counts0.copy()
            pls = []
            for i in range(micro_spans):
                rows = (w * hazard[:, hz])[segs[i]].astype(np.float32)
                res = fused_tick_run(
                    jnp.asarray(host_avail), jnp.asarray(dems[i]),
                    jnp.asarray(arrive), jnp.int32(micro_k),
                    base_task_counts=jnp.asarray(counts),
                    live=jnp.asarray(live0),
                    risk_rows=jnp.asarray(rows), **kw,
                )
                host_avail = np.asarray(res.avail)
                pl = np.asarray(res.placements)
                np.add.at(counts, pl[pl >= 0], 1)
                pls.append(pl)
            return pls

        def resident():
            carry = resident_carry_init(
                jnp.asarray(avail0), jnp.asarray(counts0),
                jnp.asarray(live0),
            )
            tdev = jnp.asarray(table)
            host_avail = avail0.copy()
            counts = counts0.copy()
            pls = []
            for i in range(micro_spans):
                # The mirror-diff the serve path pays every span (reads
                # are D2H — free of the h2d budget this row gates on).
                diff = (
                    (np.asarray(carry.avail) != host_avail).any(axis=1)
                    | (np.asarray(carry.counts) != counts)
                    | (np.asarray(carry.live) != live0)
                )
                assert not diff.any()
                res, carry = resident_span_run(
                    carry, jnp.asarray(dems[i]), jnp.asarray(arrive),
                    jnp.int32(micro_k), risk_table=tdev,
                    risk_seg=jnp.asarray(segs[i]), **kw,
                )
                host_avail = np.asarray(res.avail)
                pl = np.asarray(res.placements)
                np.add.at(counts, pl[pl >= 0], 1)
                pls.append(pl)
            return pls

        restaged(), resident()  # warmup: every program compiled
        gc.collect()
        t0 = time.perf_counter()
        p0 = restaged()
        t_re = time.perf_counter() - t0
        t0 = time.perf_counter()
        p1 = resident()
        t_res = time.perf_counter() - t0
        parity = all(np.array_equal(a, b) for a, b in zip(p0, p1))
        decisions = sum(int((p >= 0).sum()) for p in p0)
        dem_b = int(dems[0].nbytes)
        arr_b = int(arrive.nbytes)
        h2d_re = (
            int(avail0.nbytes) + int(counts0.nbytes) + int(live0.nbytes)
            + micro_k * H * 4 + dem_b + arr_b
        )
        h2d_res = dem_b + arr_b + int(segs[0].nbytes)
        return {
            "h": H,
            "restaged": {
                "ms_per_span": round(t_re * 1e3 / micro_spans, 3),
                "decisions_per_sec": round(decisions / t_re, 1),
                "h2d_bytes_per_span": h2d_re,
            },
            "resident": {
                "ms_per_span": round(t_res * 1e3 / micro_spans, 3),
                "decisions_per_sec": round(decisions / t_res, 1),
                "h2d_bytes_per_span": h2d_res,
                "first_span_h2d_bytes": h2d_res + int(avail0.nbytes)
                + int(counts0.nbytes) + int(live0.nbytes)
                + int(table.nbytes),
            },
            "throughput_ratio": round(t_re / t_res, 3),
            "h2d_ratio": round(h2d_re / h2d_res, 1),
            "parity_ok": parity,
        }

    scaling = [micro(H) for H in micro_hosts]
    top = scaling[-1]

    # -- splice soak: mid-span arrivals vs the per-tick referee -------
    def splice_arm(late_at, resident):
        reset_ids()
        env = Environment()
        meta = ResourceMetadata(seed=seed)
        meter = Meter(env, meta)
        cluster = build_cluster_des(env, meter)
        policy = TpuCostAwarePolicy(
            bin_pack="first-fit", sort_tasks=True, sort_hosts=True,
            adaptive=False,
        )
        if resident:
            policy.enable_resident(splice=True)
        sched = GlobalScheduler(
            env, cluster, policy, seed=3, meter=meter,
            fuse_spans=resident,
        )
        cluster.start()
        sched.start()
        apps = chain_apps()
        for a in apps:
            sched.submit(a)
        env.run(until=late_at)
        late = Application("late", [
            TaskGroup("z", cpus=1, mem=32, runtime=4.0, instances=3),
        ])
        sched.submit(late)
        apps.append(late)
        sched.stop()
        env.run()
        placements = sorted(
            (t.id, t.placement)
            for a in apps for g in a.groups for t in g.tasks
        )
        return placements, dict(sched.span_stats)

    def splice_soak():
        splices = 0
        parity = True
        for t in (18.0, 22.0, 27.0, 33.0, 38.0):
            ref, _ = splice_arm(t, resident=False)
            res, stats = splice_arm(t, resident=True)
            parity = parity and ref == res
            splices += stats["span_splices"]
        return {"splices": splices, "referee_parity_ok": bool(parity)}

    # -- driver pass: the serve stack with the splice tier gate open --
    def driver_soak():
        from pivot_tpu.serve import (
            ServeDriver,
            ServeSession,
            mixed_tier_arrivals,
            synthetic_app_factory,
        )
        from pivot_tpu.utils.config import (
            ClusterConfig,
            PolicyConfig,
            build_cluster,
            make_policy,
        )

        reset_ids()
        pcfg = PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )
        sessions = [
            ServeSession(
                f"res-{g}",
                build_cluster(ClusterConfig(n_hosts=16, seed=seed)),
                make_policy(pcfg),
                seed=seed,
                fuse_spans="slo",
            )
            for g in range(3)
        ]
        driver = ServeDriver(
            sessions,
            queue_depth=32,
            backpressure="shed",
            flush_after=0.02,
            resident=True,
            splice_tier=2,
        )
        stream = mixed_tier_arrivals(
            rate, n_jobs, weights=(0.25, 0.35, 0.40), seed=seed,
            make_app=synthetic_app_factory(seed=seed),
        )
        t0 = time.perf_counter()
        report = driver.run(stream)
        wall = time.perf_counter() - t0
        driver.audit(context="serve_resident bench (splice soak)")
        pool = driver.sessions + driver._retired
        stats = {
            k: sum(s.summary()["span_stats"].get(k, 0) for s in pool)
            for k in ("fused_spans", "span_splices", "span_aborts")
        }
        snap = report["slo"]
        return {
            "wall_s": round(wall, 3),
            "decisions": snap["counters"]["decisions"],
            "decisions_per_sec": round(
                snap["counters"]["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["counters"]["completed"],
            **stats,
        }

    soak = splice_soak()
    try:
        soak["driver"] = driver_soak()
    except Exception as exc:  # noqa: BLE001 — block-level isolation
        soak["driver"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    return {
        "h_top": int(micro_hosts[-1]),
        "b": micro_b,
        "k": micro_k,
        "spans": micro_spans,
        "serve": {
            "restaged": serve_re,
            "resident": serve_res,
            "h2d_ratio": round(
                serve_re["h2d_bytes_per_span"]
                / max(serve_res["h2d_bytes_per_span"], 1e-9), 1
            ),
        },
        "scaling": scaling,
        "restaged": top["restaged"],
        "resident": top["resident"],
        "throughput_ratio": top["throughput_ratio"],
        "throughput_1p2x_ok": bool(top["throughput_ratio"] >= 1.2),
        "h2d_ratio": top["h2d_ratio"],
        "h2d_5x_ok": bool(top["h2d_ratio"] >= 5.0),
        "splice_soak": soak,
        "recompiles_after_warmup": int(counter.compiles),
        "retraces_after_warmup": int(counter.traces),
        "parity_ok": bool(
            serve_parity
            and all(s["parity_ok"] for s in scaling)
            and soak["referee_parity_ok"]
        ),
    }


def _serve_resident_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_RESIDENT_CHILD=1``): run
    the serve_resident row and print ONE JSON line.  Child-isolated
    like every serve row (single-tenant backend; a wedged RPC must
    never hang the parent)."""
    os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
    jax = _child_backend_setup()
    row = _bench_serve_resident()
    row["backend"] = jax.default_backend()
    print(json.dumps(row), flush=True)


def _bench_serve_resident_in_child(timeout_s: int = 540) -> dict:
    """Parent side of the serve_resident row — see
    ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_RESIDENT_CHILD", timeout_s)


# -- serve_recovery row: crash-safe serving overhead (round 21) -------------


def _bench_serve_recovery(
    n_jobs: int = 150,
    rate: float = 20.0,
    repeats: int = 3,
    seed: int = 0,
) -> dict:
    """Recovery-plane overhead row (round 21, ``pivot_tpu.recover``).

    The same resident serve soak as the ``serve_resident`` driver block,
    A/B'd: ``recovery=None`` (the PR-18 stack) vs a recovery-armed
    driver (write-ahead journal on every admission/flush/span, fsync
    every 32 records, device carry cloned to the host-side snapshot
    worker every 2 spans).  The contract under test: the journal costs
    ≤5% serve throughput (``overhead_5pct_ok``) and changes NOTHING
    (``parity_ok`` — bit-identical placements).  A second, untimed
    span-forming soak proves the snapshot path live
    (``snapshots.written`` ≥ 1 → ``snapshot_path_ok``).

    Tracked as ``serve_recovery_dps`` in ``tools/bench_history.py``
    (phase-in: note-not-gate until the committed baseline carries
    rows)."""
    import shutil
    import tempfile

    from pivot_tpu.utils import reset_ids
    from pivot_tpu.serve import (
        JobArrival,
        RecoveryConfig,
        ServeDriver,
        ServeSession,
        mixed_tier_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    from pivot_tpu.workload import Application, TaskGroup

    pcfg = PolicyConfig(
        name="cost-aware", device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )

    def soak(recovery, nj=None, weights=(0.25, 0.35, 0.40),
             mix_seed=None, app_seed=None):
        reset_ids()
        arrs = list(
            mixed_tier_arrivals(
                rate, nj if nj is not None else n_jobs,
                weights=weights,
                seed=seed if mix_seed is None else mix_seed,
                make_app=synthetic_app_factory(
                    seed=seed if app_seed is None else app_seed
                ),
            )
        )
        # A far-future straggler releases the stream frontier past the
        # whole burst the moment it is admitted, so the "slo" fuser
        # forms real multi-tick spans (the snapshot hook's feedstock)
        # instead of serving the burst per-tick behind the frontier.
        arrs.append(JobArrival(
            ts=10_000.0,
            app=Application("bench-straggler", [
                TaskGroup("s", cpus=1, mem=32, runtime=2.0, instances=1),
            ]),
        ))
        # One session on a small cluster: span formation needs a deep
        # per-session dependency backlog (the "slo" fuser requires armed
        # pump deliveries inside the scan window), and splitting the
        # burst three ways starves every session of one.
        sessions = [
            ServeSession(
                "rec-0",
                build_cluster(ClusterConfig(n_hosts=8, seed=seed)),
                make_policy(pcfg),
                seed=seed,
                fuse_spans="slo",
            )
        ]
        # Queue must hold the whole burst: a shed job never arms its
        # pump, and the "slo" fuser only forms spans (the snapshot
        # hook's feedstock) over in-window armed deliveries.
        driver = ServeDriver(
            sessions, queue_depth=256, backpressure="shed",
            flush_after=0.02, resident=True, splice_tier=2,
            recovery=recovery,
        )
        t0 = time.perf_counter()
        report = driver.run(iter(arrs))
        wall = time.perf_counter() - t0
        placements = sorted(
            (t.id, t.placement)
            for a in (x.app for x in arrs)
            for g in a.groups
            for t in g.tasks
        )
        snap = report["slo"]["counters"]
        return {
            "wall_s": round(wall, 3),
            "decisions": snap["decisions"],
            "decisions_per_sec": round(
                snap["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["completed"],
        }, placements, report

    def best_of(recovery):
        """Best-of-N walls: serve soaks are thread-scheduling noisy at
        sub-second walls, and the A/B difference under test (journal
        appends + a clone every 8 spans) is a per-dispatch constant —
        the fastest pass of each arm is the cleanest comparison."""
        best = pl = rep = None
        for _ in range(repeats):
            row, pl, rep = soak(recovery)
            if best is None or row["wall_s"] < best["wall_s"]:
                best = row
        best["decisions_per_sec"] = round(
            best["decisions"] / max(best["wall_s"], 1e-9), 1
        )
        return best, pl, rep

    # Warmup compiles outside both timed arms, then baseline vs armed.
    soak(None)
    base, base_pl, _ = best_of(None)
    tmp = tempfile.mkdtemp(prefix="pivot-bench-recovery-")
    try:
        armed, armed_pl, armed_rep = best_of(
            RecoveryConfig(directory=tmp, snapshot_every=2,
                           fsync_every=32)
        )
        rec = armed_rep["recovery"]
        journal = {
            "records": rec["journal"]["records"],
            "fsyncs": rec["journal"]["fsyncs"],
        }
        # Snapshot probe (untimed): the deep timed burst saturates the
        # SLO fuser's scan window (quarantine deadlines crowd out the
        # grid), so resident spans — the snapshot trigger — only form
        # in a shallower mix.  Run the span-forming soak once so the
        # row also proves the clone+write snapshot path live.
        _, _, probe_rep = soak(
            RecoveryConfig(directory=tmp, snapshot_every=2,
                           fsync_every=32),
            nj=24, weights=(0.5, 0.3, 0.2), mix_seed=7, app_seed=11,
        )
        psnap = probe_rep["recovery"]["snapshots"]
        snapshots = {
            "written": psnap["written"],
            "dropped": psnap["dropped"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    overhead_pct = round(
        100.0
        * (base["decisions_per_sec"] - armed["decisions_per_sec"])
        / max(base["decisions_per_sec"], 1e-9),
        1,
    )
    return {
        "n_jobs": n_jobs,
        "rate": rate,
        "repeats": repeats,
        "baseline": base,
        "recovery": armed,
        "journal": journal,
        "snapshots": snapshots,
        "overhead_pct": overhead_pct,
        "overhead_5pct_ok": bool(overhead_pct <= 5.0),
        "snapshot_path_ok": bool(snapshots["written"] >= 1),
        "parity_ok": bool(base_pl == armed_pl),
    }


def _serve_recovery_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_RECOVERY_CHILD=1``): run
    the serve_recovery row and print ONE JSON line.  Child-isolated
    like every serve row (single-tenant backend; a wedged RPC must
    never hang the parent)."""
    os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
    jax = _child_backend_setup()
    row = _bench_serve_recovery()
    row["backend"] = jax.default_backend()
    print(json.dumps(row), flush=True)


def _bench_serve_recovery_in_child(timeout_s: int = 540) -> dict:
    """Parent side of the serve_recovery row — see
    ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_RECOVERY_CHILD", timeout_s)


# -- serve_elastic row: elastic mesh serving under device loss (round 22) ----


def _bench_serve_elastic(
    n_jobs: int = 18,
    rate: float = 20.0,
    seed: int = 0,
) -> dict:
    """Elastic-mesh serving row (round 22, ``pivot_tpu/serve/elastic``).

    The same seeded mixed-tier chaos+market sharded resident soak as
    the elastic referee (``tests/test_elastic.py``), served twice on
    the forced-8-device CPU mesh:

      * **healthy** — an armed ``ElasticMeshManager`` with an EMPTY
        fault plan (the gate runs on every dispatch, pure overhead,
        full mesh end to end);
      * **kill_one_shard** — a seeded ``fail_device`` window drops
        shard 3 mid-soak: the session crashes at the gate, the
        supervisor requeues its work, the replacement reshards onto the
        4-rung of the divisor ladder and keeps serving, and the
        far-future straggler dispatch lands after the restore and
        regrows the full mesh through a passing shadow probe.

    Per arm: decisions/s, per-tier p99, completions; the kill arm adds
    ``recovery_latency_ms`` — wall clock from the device-loss raise to
    the first dispatch served by the shrunk mesh (the requeue + reshard
    + re-warm window) — plus shrink/regrow/probe counts and tier-0
    losslessness (``tier0_lossless_ok``).  Tracked as
    ``serve_elastic_dps`` (the kill arm — the headline is throughput
    *while surviving*) in ``tools/bench_history.py``, phase-in."""
    from pivot_tpu.infra.faults import (
        ChaosEvent,
        ChaosSchedule,
        FaultInjector,
    )
    from pivot_tpu.infra.market import MarketSchedule
    from pivot_tpu.parallel.mesh import host_sharded_mesh
    from pivot_tpu.serve import (
        ElasticMeshManager,
        JobArrival,
        ServeDriver,
        ServeSession,
        mixed_tier_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.serve.elastic import ElasticConfig
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )
    from pivot_tpu.workload import Application, TaskGroup

    mesh = host_sharded_mesh(8)
    pcfg = PolicyConfig(
        name="cost-aware", device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )

    class _TimedElastic(ElasticMeshManager):
        """Bench instrumentation: wall-stamp the first device-loss
        raise and the first dispatch the shrunk mesh serves — their
        difference is the row's recovery latency (requeue + reshard +
        replacement warmup, the price of surviving)."""

        def __init__(self, config=None):
            super().__init__(config)
            self.loss_wall = None
            self.resume_wall = None

        def note_loss(self, exc, label):
            if self.loss_wall is None:
                self.loss_wall = time.perf_counter()
            super().note_loss(exc, label)

        def _gate_for(self, policy):
            gate = super()._gate_for(policy)

            def timed_gate(now):
                gate(now)
                if (
                    self.resume_wall is None
                    and self.loss_wall is not None
                    and self.shrinks >= 1
                ):
                    self.resume_wall = time.perf_counter()

            return timed_gate

    def arrivals():
        reset_ids()
        arrs = list(
            mixed_tier_arrivals(
                rate=rate, n_jobs=n_jobs, weights=(0.5, 0.3, 0.2),
                seed=7, make_app=synthetic_app_factory(seed=11),
            )
        )
        # The far-future straggler dispatches past the restore window —
        # the regrow arm's feedstock (frontier-judged promotion).
        arrs.append(JobArrival(
            ts=10_000.0,
            app=Application("bench-straggler", [
                TaskGroup("s", cpus=1, mem=32, runtime=2.0, instances=1),
            ]),
            tier=0,
        ))
        return arrs

    def soak(manager):
        arrs = arrivals()

        def factory(label):
            s = ServeSession(
                label, build_cluster(ClusterConfig(n_hosts=8, seed=seed)),
                make_policy(pcfg), seed=seed, fuse_spans="slo",
            )
            s.policy.enable_sharding(mesh)
            FaultInjector(s.cluster, seed=seed).preempt_host(
                s.cluster.hosts[2].id, at=8.0, lead=6.0, outage=25.0,
            )
            s.scheduler.market = MarketSchedule.generate(
                s.cluster.meta, seed=5, horizon=400.0, n_segments=4,
                hot_fraction=0.3, hot_hazard=1e-2, base_hazard=1e-4,
            )
            return s

        driver = ServeDriver(
            [factory("el-0")], queue_depth=64, backpressure="shed",
            flush_after=0.02, resident=True, splice_tier=2,
            session_factory=factory, max_restarts=4, elastic=manager,
        )
        t0 = time.perf_counter()
        report = driver.run(iter(arrs))
        wall = time.perf_counter() - t0
        snap = report["slo"]
        tiers = {}
        for tier, tsnap in snap["tiers"].items():
            lat = tsnap["decision_latency_s"]
            tiers[tier] = {
                "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
                "admitted": tsnap["counters"]["admitted"],
                "completed": tsnap["counters"]["completed"],
            }
        return {
            "wall_s": round(wall, 3),
            "decisions": snap["counters"]["decisions"],
            "decisions_per_sec": round(
                snap["counters"]["decisions"] / max(wall, 1e-9), 1
            ),
            "completed": snap["counters"]["completed"],
            "failed": snap["counters"].get("failed_jobs", 0),
            "tiers": tiers,
        }, report

    # Warmup compiles outside both timed arms — one healthy pass (the
    # full-mesh program family) and one kill pass (the 4-rung family),
    # so neither timed wall pays a trace.
    soak(_TimedElastic())
    soak(_TimedElastic(ElasticConfig(schedule=ChaosSchedule(
        seed=13, events=[ChaosEvent(
            kind="device_fault", at=6.0, target="device:3",
            duration=200.0,
        )],
    ))))

    healthy, _ = soak(_TimedElastic())

    kill_mgr = _TimedElastic(ElasticConfig(schedule=ChaosSchedule(
        seed=13, events=[ChaosEvent(
            kind="device_fault", at=6.0, target="device:3",
            duration=200.0,
        )],
    )))
    kill, kill_report = soak(kill_mgr)
    recovery_ms = (
        round((kill_mgr.resume_wall - kill_mgr.loss_wall) * 1e3, 1)
        if kill_mgr.resume_wall is not None
        and kill_mgr.loss_wall is not None
        else None
    )
    tier0 = kill["tiers"].get(0) or kill["tiers"].get("0") or {}
    return {
        "n_jobs": n_jobs,
        "rate": rate,
        "ladder": list(kill_mgr.ladder),
        "healthy": healthy,
        "kill_one_shard": {
            **kill,
            "recovery_latency_ms": recovery_ms,
            "shrinks": kill_mgr.shrinks,
            "regrows": kill_mgr.regrows,
            "probes": kill_mgr.probes,
            "probe_failures": kill_mgr.probe_failures,
            "device_losses": kill_report["slo"]["counters"].get(
                "device_losses", 0
            ),
            "session_restarts": kill_report["slo"]["counters"].get(
                "session_restarts", 0
            ),
        },
        "survived_ok": bool(
            kill_mgr.shrinks >= 1
            and kill["failed"] == 0
            and kill["completed"] == n_jobs + 1
        ),
        "regrow_ok": bool(
            kill_mgr.regrows >= 1 and kill_mgr.probe_failures == 0
        ),
        "tier0_lossless_ok": bool(
            tier0.get("completed", 0) == tier0.get("admitted", -1)
        ),
    }


def _serve_elastic_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SERVE_ELASTIC_CHILD=1``): pin the
    forced-8-device CPU mesh BEFORE the first jax import (XLA reads the
    flag once per process), run the serve_elastic row, print ONE JSON
    line."""
    os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    jax = _child_backend_setup()
    row = _bench_serve_elastic()
    row["backend"] = jax.default_backend()
    row["n_devices"] = len(jax.devices())
    print(json.dumps(row), flush=True)


def _bench_serve_elastic_in_child(timeout_s: int = 540) -> dict:
    """Parent side of the serve_elastic row — see
    ``_run_row_in_child``."""
    return _run_row_in_child("PIVOT_BENCH_SERVE_ELASTIC_CHILD", timeout_s)


# -- shard_place row: pod-scale host-sharded placement (ops/shard.py) -------
#
# Weak-scaling protocol: per-shard host count H0 held fixed while the
# shard count S grows, so per-device work is constant and the wall-clock
# ratio wall_1(H0) / wall_S(S*H0) is the weak-scaling efficiency.  Every
# arm runs in its OWN child process because the CPU mesh only exists via
# --xla_force_host_platform_device_count, which XLA reads once per
# process, before the first jax import (the serve rows' child-isolation
# pattern, plus per-arm device-count pinning).
#
# On a shared-bus VM the raw ratio conflates two causes: the machine's
# parallel capacity (two timesharing cores contending on one memory bus
# — probed by the REFEREE arm: S independent single-device kernels in S
# processes, zero communication) and the actual cost of the mesh
# collectives (the two-stage argmin rendezvous every placement step).
# The row reports the full decomposition —
#
#   raw_weak_eff     = idle wall / sharded wall
#   hw_parallel_eff  = idle wall / referee wall   (the box, not the code)
#   collective_eff   = referee wall / sharded wall (the code, not the box)
#
# and gates on collective_eff: it is the only one of the three the
# sharding design answers for, and on real per-device-memory hardware
# (one HBM per chip) referee == idle, so the definitions coincide.

_SHARD_T = 256              #: ready tasks per placement call (fixed T)
_SHARD_H0 = 98304           #: per-shard hosts for the weak-scaling pair
_SHARD_SWEEP_H0 = (32768, 65536, 98304)  #: S-fixed scale curve (H = S*H0)
_SHARD_CPU_FLAGS = "--xla_cpu_multi_thread_eigen=false"


def _shard_arm_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SHARD_ARM=<json>``): time ONE
    (S, H0) best-fit placement arm and print ONE JSON line.  S=1 runs
    the single-device slim kernel (the oracle the parity suite pins the
    sharded pass to); S>1 runs ``best_fit_kernel_sharded`` on a
    host-only mesh.  Best-of-7 walls, per-call scalar-fetch barrier."""
    cfg = json.loads(os.environ["PIVOT_BENCH_SHARD_ARM"])
    s = int(cfg["s"])
    h0 = int(cfg["h0"])
    t = int(cfg.get("t", _SHARD_T))
    if cfg.get("force_devices"):
        # Must land before the first jax import: XLA reads the forced
        # device count exactly once per process.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={s} "
            + _SHARD_CPU_FLAGS
        )
    jax = _child_backend_setup()
    import numpy as np

    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import best_fit_kernel
    from pivot_tpu.ops.shard import best_fit_kernel_sharded
    from pivot_tpu.parallel.mesh import host_sharded_mesh

    n_dev = len(jax.devices())
    if s > n_dev:
        print(json.dumps({
            "error": f"need {s} devices, backend has {n_dev}",
            "n_devices": n_dev, "backend": jax.default_backend(),
        }), flush=True)
        return
    rng = np.random.default_rng(0)
    B = ((t + 63) // 64) * 64
    H = s * h0
    avail = jnp.asarray(rng.uniform(2.0, 16.0, (H, 4)).astype(np.float32))
    dem = jnp.asarray(rng.uniform(0.1, 1.0, (B, 4)).astype(np.float32))
    valid = jnp.asarray(np.arange(B) < t)
    if s == 1:
        call = lambda: best_fit_kernel(avail, dem, valid, phase2="slim")[0]
    else:
        mesh = host_sharded_mesh(s)
        call = lambda: best_fit_kernel_sharded(mesh, avail, dem, valid)[0]
    fetch = lambda p: int(np.asarray(p).sum())
    fetch(call())  # compile + warm
    best = float("inf")
    for _ in range(7):
        t0 = time.perf_counter()
        fetch(call())
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({
        "s": s, "h": H, "t": t, "wall_s": round(best, 5),
        "decisions_per_s": round(t / best, 1),
        "hostrows_per_s_per_device": round(t * h0 / best, 1),
        "backend": jax.default_backend(), "n_devices": n_dev,
    }), flush=True)


def _spawn_shard_arm(cfg: dict):
    import subprocess

    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env={**os.environ, "PIVOT_BENCH_SHARD_ARM": json.dumps(cfg)},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _collect_shard_arm(proc, timeout_s: int = 300) -> dict:
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except Exception as exc:  # noqa: BLE001 — arm-level isolation
        proc.kill()
        proc.communicate()
        return {"error": f"{type(exc).__name__}: {exc}"[:300]}
    if proc.returncode != 0:
        from pivot_tpu.utils import filter_xla_aot_noise

        # AOT cache-portability noise would otherwise BE the recorded
        # stderr tail (round-15 satellite — same filter as the
        # multichip capture artifacts).
        lines = [
            ln for ln in (
                out.strip().splitlines()
                + filter_xla_aot_noise(err).strip().splitlines()
            )
            if ln.strip()
        ]
        return {"error": f"arm rc={proc.returncode}: {(lines or [''])[-1][:300]}"}
    try:
        return json.loads(out.strip().splitlines()[-1])
    except Exception as exc:  # noqa: BLE001 — arm-level isolation
        return {"error": f"unparseable arm output: {exc}"[:300]}


def _best_of(run_once, launches: int) -> dict:
    """Best-of-``launches`` runs of a thunk returning ``{"wall_s": ...}``
    or ``{"error": ...}``: keep the minimum wall, or the first error if
    no launch succeeds.  Whole launches are the repeat unit because
    thread/core placement is decided per process on this box — a single
    launch can land unlucky for its entire life (bimodal walls), which
    within-process repeats cannot average away."""
    best = None
    for _ in range(launches):
        row = run_once()
        if "error" in row:
            best = best if best is not None else row
            continue
        if best is None or "error" in best or row["wall_s"] < best["wall_s"]:
            best = row
    return best if best is not None else {"error": "no launches"}


def _run_shard_arm(cfg: dict, launches: int = 1, timeout_s: int = 300) -> dict:
    """Best-of-``launches`` child runs of one arm (see ``_best_of``)."""
    return _best_of(
        lambda: _collect_shard_arm(_spawn_shard_arm(cfg), timeout_s),
        launches,
    )


def _bench_shard_place() -> dict:
    """The pod-scale sharded-placement row (header comment above)."""
    t = _SHARD_T
    cpu_mode = os.environ.get("PIVOT_BENCH_BACKEND", "") == "cpu"
    if cpu_mode:
        n_shards, force = 2, True
    else:
        # Accelerator path: a cheap probe arm reports the real device
        # count; a single-device backend (the usual tunnel shape) cannot
        # run a ≥2-shard mesh at all, which is exactly the point of the
        # CPU-mesh arms — record why and bail.
        probe = _run_shard_arm(
            dict(s=1, h0=4096, t=t, force_devices=False), timeout_s=240
        )
        if "error" in probe:
            return {"policy": "best-fit", "error": probe["error"]}
        n_dev = int(probe.get("n_devices", 1))
        if n_dev < 2:
            return {
                "policy": "best-fit", "t": t,
                "backend": probe.get("backend"),
                "skipped": (
                    f"single-device backend (n_devices={n_dev}); the "
                    "CPU-mesh arms run under PIVOT_BENCH_BACKEND=cpu"
                ),
            }
        n_shards, force = min(n_dev, 8), False
    h0 = _SHARD_H0
    # Best-of-2 launches for every arm that feeds an efficiency column —
    # one unlucky core placement would otherwise skew the whole row.
    idle = _run_shard_arm(
        dict(s=1, h0=h0, t=t, force_devices=force), launches=2
    )
    sharded = _run_shard_arm(
        dict(s=n_shards, h0=h0, t=t, force_devices=force), launches=2
    )
    row = {
        "policy": "best-fit",
        "phase2": "slim step, two-stage sharded reduce",
        "t": t, "h0_per_shard": h0, "n_shards": n_shards,
        "flags": _SHARD_CPU_FLAGS if force else "",
        "idle_baseline": idle, "sharded": sharded,
        "eff_definition": (
            "collective_eff = referee/sharded walls; referee = S "
            "independent single-device kernels in S processes (joint "
            "completion) — the zero-communication ceiling of this "
            "shared-bus box.  hw_parallel_eff = idle/referee is the "
            "box, not the code; on per-device-memory hardware "
            "referee == idle and collective_eff == raw_weak_eff."
        ),
    }
    if "error" in idle or "error" in sharded:
        row["error"] = idle.get("error") or sharded.get("error")
        return row
    row["raw_weak_eff"] = round(idle["wall_s"] / sharded["wall_s"], 3)
    if cpu_mode:

        def referee_once():
            procs = [
                _spawn_shard_arm(dict(s=1, h0=h0, t=t, force_devices=True))
                for _ in range(n_shards)
            ]
            rows = [_collect_shard_arm(p) for p in procs]
            errs = [r for r in rows if "error" in r]
            if errs:
                return errs[0]
            return {"wall_s": max(r["wall_s"] for r in rows)}  # joint

        referee = _best_of(referee_once, launches=2)
        if "error" in referee:
            row["referee_error"] = referee["error"]
            row["weak_scaling_eff"] = row["raw_weak_eff"]
        else:
            row["referee_wall_s"] = referee["wall_s"]
            row["hw_parallel_eff"] = round(
                idle["wall_s"] / referee["wall_s"], 3
            )
            row["collective_eff"] = round(
                referee["wall_s"] / sharded["wall_s"], 3
            )
            row["weak_scaling_eff"] = row["collective_eff"]
    else:
        # Real multi-device backend: per-device memory, no shared bus —
        # the raw ratio already isolates the collectives.
        row["collective_eff"] = row["raw_weak_eff"]
        row["weak_scaling_eff"] = row["raw_weak_eff"]
    row["meets_70pct"] = bool(row["weak_scaling_eff"] >= 0.70)
    # S-fixed scale curve: the absolute-H ladder (64k–196k hosts on the
    # 2-shard CPU mesh) the single-device arm never climbs in-tree.
    sweep = []
    for h0s in _SHARD_SWEEP_H0:
        if h0s == h0:
            sweep.append({k: sharded[k] for k in (
                "s", "h", "wall_s", "decisions_per_s",
                "hostrows_per_s_per_device",
            ) if k in sharded})
            continue
        r = _run_shard_arm(dict(s=n_shards, h0=h0s, t=t, force_devices=force))
        sweep.append(r if "error" in r else {k: r[k] for k in (
            "s", "h", "wall_s", "decisions_per_s",
            "hostrows_per_s_per_device",
        ) if k in r})
    row["h_sweep"] = sweep
    return row


# (probe timeout s, sleep-before s): ~7 min worst-case total. A wedged
# single-tenant tunnel recovers on operator timescales, so one 150 s shot
# (round 1) under-samples it; spreading attempts across the bench runtime
# costs nothing when the first probe succeeds (the common case).
_PROBE_SCHEDULE = ((90, 0), (120, 20), (150, 45))


def _probe_with_backoff(history: list) -> bool:
    """Repeated child-process liveness probes; appends to ``history``."""
    from pivot_tpu.utils import probe_backend_alive

    for timeout, sleep_before in _PROBE_SCHEDULE:
        if sleep_before:
            time.sleep(sleep_before)
        t0 = time.time()
        alive = probe_backend_alive(timeout)
        history.append(
            {
                "timeout_s": timeout,
                "wall_s": round(time.time() - t0, 1),
                "alive": alive,
            }
        )
        if alive:
            return True
    return False


def _write_tpu_record(line: dict, probe_history: list) -> None:
    """Refresh the canonical hardware-bench artifact ``BENCH_TPU.json``.

    The driver's ``BENCH_r{N}.json`` records whatever backend answered at
    driver time — two rounds running, that was a dead tunnel and a CPU
    fallback even though the chip was reached (and measured) in-session
    both times.  This file is the tunnel-proof record: every TPU-backed
    ``bench.py`` run rewrites it with the JSON line verbatim plus an ISO
    timestamp, the git revision, and the probe history, so a dead-tunnel
    driver round still leaves a dated, machine-readable hardware figure
    in the tree (VERDICT r02 item 2).
    """
    import datetime
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        rev = subprocess.run(
            ["git", "-C", here, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — the record matters more than the rev
        rev = "unknown"
    rec = {
        "recorded_at_utc": datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "git_rev": rev,
        "bench_line": line,
        "probe_history": probe_history,
        "note": (
            "Latest live-tunnel bench.py line, refreshed automatically by "
            "every TPU-backed run; see RESULTS.md for the measurement "
            "methodology (batch-fetch timing)."
        ),
    }
    path = os.path.join(here, "BENCH_TPU.json")
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        # A read-only checkout must not turn a successful (and scarce)
        # hardware measurement into a nonzero exit — the authoritative
        # JSON line has already printed.
        pass


def _saturated_child() -> None:
    """Child-mode entry (``PIVOT_BENCH_SATURATED_CHILD=1``): measure the
    R=1024 saturated-dispatch ensemble row and print ONE JSON line.

    Runs as a disposable child because that is the file's only hang-proof
    isolation: SIGALRM cannot interrupt a wedged tunnel RPC (it only
    fires between Python bytecodes), but the parent can always kill a
    child process no matter where it blocks.
    """
    jax = _child_backend_setup()
    if jax.default_backend() != "tpu":
        print(json.dumps({"error": f"child backend {jax.default_backend()}"}))
        sys.exit(3)
    ctx = _build_batch(512, 2048, seed=7)
    rps, rl = _bench_ensemble(ctx, n_replicas=1024)
    print(
        json.dumps(
            {
                "n_replicas": 1024,
                "rollouts_per_sec": round(rps, 2),
                "roofline": rl,
            }
        ),
        flush=True,
    )


def _bench_saturated_in_child(timeout_s: int = 420) -> dict:
    """Parent side of the saturated row — see ``_run_row_in_child``."""
    return _run_row_in_child(
        "PIVOT_BENCH_SATURATED_CHILD", timeout_s, {"n_replicas": 1024}
    )


def main() -> None:
    global _ROWS, _JSON_PATH
    import argparse

    parser = argparse.ArgumentParser(
        prog="bench",
        description="placement-decision throughput benchmark; prints "
        "ONE JSON line (the LAST line of stdout is authoritative)",
    )
    parser.add_argument(
        "--json", default="", metavar="PATH",
        help="also write the authoritative final JSON line to PATH "
        "(the tools/bench_history.py feed)",
    )
    parser.add_argument(
        "--rows", default="", metavar="a,b,c",
        help="run only the named optional rows (headline, two_phase, "
        "grid_batched, fused_tick, serve_stream, serve_tiers, "
        "shard_place, spot_survival, obs_overhead, profiler_overhead, "
        "cost_attribution, saturated); default: all",
    )
    # parse_known_args: tests drive main() in-process under pytest,
    # whose argv this parser must not choke on; unknown args are the
    # host harness's business.
    args, _unknown = parser.parse_known_args()
    if args.json:
        _JSON_PATH = os.path.abspath(args.json)
    if args.rows:
        known_rows = {
            "headline", "two_phase", "grid_batched", "fused_tick",
            "serve_stream", "serve_tiers", "serve_sharded",
            "serve_ragged", "serve_mpc", "serve_resident", "serve_recovery",
            "serve_elastic",
            "shard_place",
            "spot_survival", "policy_search", "obs_overhead",
            "profiler_overhead", "cost_attribution", "saturated",
        }
        _ROWS = {r.strip() for r in args.rows.split(",") if r.strip()}
        unknown_rows = _ROWS - known_rows
        if unknown_rows:
            # A typo'd subset would silently run nothing and emit an
            # artifact with no tracked metrics — fail loudly instead.
            parser.error(
                f"unknown row(s) {sorted(unknown_rows)}; "
                f"valid: {sorted(known_rows)}"
            )
    if os.environ.get("PIVOT_BENCH_SHARD_ARM"):
        _shard_arm_child()
        return
    if os.environ.get("PIVOT_BENCH_SATURATED_CHILD"):
        _saturated_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_CHILD"):
        _serve_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_TIERS_CHILD"):
        _serve_tiers_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_SHARDED_CHILD"):
        _serve_sharded_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_RAGGED_CHILD"):
        _serve_ragged_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_MPC_CHILD"):
        _serve_mpc_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_RESIDENT_CHILD"):
        _serve_resident_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_RECOVERY_CHILD"):
        _serve_recovery_child()
        return
    if os.environ.get("PIVOT_BENCH_SERVE_ELASTIC_CHILD"):
        _serve_elastic_child()
        return
    backend_override = os.environ.get("PIVOT_BENCH_BACKEND")
    # Probe breadcrumbs survive the watchdog re-exec via the environment,
    # so a CPU-fallback JSON line is always self-explaining.
    probe_history = json.loads(os.environ.get("PIVOT_BENCH_PROBES", "[]"))
    tpu_attempted = os.environ.get("PIVOT_BENCH_TPU_ATTEMPTED") == "1"

    # Watchdog: if accelerator init stalls (wedged tunnel), restart on CPU;
    # if even the CPU run stalls, emit an error line rather than dying mute.
    import signal

    def _stall(_sig, _frm):
        if os.environ.get("PIVOT_BENCH_BACKEND"):
            print(
                json.dumps(
                    {
                        "metric": "cost-aware placement decisions/sec",
                        "value": 0,
                        "unit": "decisions/sec",
                        "vs_baseline": 0,
                        "error": "benchmark timed out",
                        "tpu_attempted": tpu_attempted,
                        "probe_history": probe_history,
                    }
                ),
                flush=True,
            )
            os._exit(1)
        os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
        os.environ["PIVOT_BENCH_AUTOFALLBACK"] = "1"
        os.environ["PIVOT_BENCH_PROBES"] = json.dumps(probe_history)
        os.environ["PIVOT_BENCH_TPU_ATTEMPTED"] = "1" if tpu_attempted else "0"
        os.execv(sys.executable, [sys.executable] + sys.argv)

    if hasattr(signal, "SIGALRM"):
        signal.signal(signal.SIGALRM, _stall)

    # SIGALRM only fires between Python bytecodes — a PJRT client init
    # hanging inside a blocking C++ RPC would never return to let the
    # handler run.  Probe accelerator liveness in disposable child
    # processes first (killable regardless of where they block); only a
    # fully failed backoff schedule falls back to CPU.
    ens_saturated = None
    if not backend_override:
        if _probe_with_backoff(probe_history):
            tpu_attempted = True
            # Saturated-dispatch row FIRST, while this process has no
            # PJRT client of its own: the tunnel backend is single-tenant,
            # so a child spawned after the parent's device work begins is
            # a concurrent co-acquisition that typically cannot get the
            # chip (ADVICE.md).  Serialized here, the child is the only
            # client alive; the parent acquires the device after it exits.
            if _row_on("saturated"):
                ens_saturated = _bench_saturated_in_child()
            if hasattr(signal, "SIGALRM"):
                # Armed only now, so the parent's own init gets the full
                # budget — neither the probes nor the saturated child eat
                # into it.
                signal.alarm(240)
        elif os.environ.get("PIVOT_BENCH_POSTPROBE"):
            # This process exists only because a post-run re-probe saw
            # the tunnel alive; it has died again before the start
            # probes (the flappy-tunnel case).  The already-printed CPU
            # line carries ``"superseded": true`` (marked optimistically
            # before the re-exec), so it must not be left as the last
            # word: re-print it un-superseded as the final authoritative
            # line — re-measuring the whole CPU bench would add minutes
            # for an identical figure.
            stashed = os.environ.get("PIVOT_BENCH_SUPERSEDED_LINE")
            if stashed:
                line = json.loads(stashed)
                line.pop("superseded", None)
                line["postprobe"] = "tunnel died again before re-measure"
                # Refresh the attempt telemetry: the stashed line was
                # serialized before the re-exec, so it predates this
                # child's failed start probes (in ``probe_history`` via
                # the env) and says tpu_attempted: false.
                line["tpu_attempted"] = True
                line["probe_history"] = probe_history
                _emit(line)
            sys.exit(0)
        else:
            os.environ["PIVOT_BENCH_BACKEND"] = "cpu"
            # Our fallback, not a user request: the end-of-run re-probe
            # may still promote this run back to the TPU (see main tail).
            os.environ["PIVOT_BENCH_AUTOFALLBACK"] = "1"
            backend_override = "cpu"
    elif backend_override == "tpu" and _row_on("saturated"):
        # Explicit TPU request: same single-tenant serialization — the
        # saturated child runs before this process touches the device.
        ens_saturated = _bench_saturated_in_child()

    # Online-serving row, also child-isolated and serialized BEFORE this
    # process creates its own PJRT client (single-tenant co-acquisition
    # guard, ADVICE.md).  The child inherits PIVOT_BENCH_BACKEND — set
    # above on every fallback/override path — so the row always measures
    # the same backend the headline metrics will; a crash, hang, or dead
    # backend costs this one row (recorded error + stderr tail), never
    # the record.
    skipped = {"skipped": "--rows subset"}
    serve_stream = (
        _bench_serve_in_child() if _row_on("serve_stream") else skipped
    )
    serve_tiers = (
        _bench_serve_tiers_in_child() if _row_on("serve_tiers")
        else skipped
    )
    serve_sharded = (
        _bench_serve_sharded_in_child() if _row_on("serve_sharded")
        else skipped
    )
    serve_ragged = (
        _bench_serve_ragged_in_child() if _row_on("serve_ragged")
        else skipped
    )
    serve_mpc = (
        _bench_serve_mpc_in_child() if _row_on("serve_mpc")
        else skipped
    )
    serve_resident = (
        _bench_serve_resident_in_child() if _row_on("serve_resident")
        else skipped
    )
    serve_recovery = (
        _bench_serve_recovery_in_child() if _row_on("serve_recovery")
        else skipped
    )
    serve_elastic = (
        _bench_serve_elastic_in_child() if _row_on("serve_elastic")
        else skipped
    )
    # Pod-scale sharded placement, also all-children (each arm pins its
    # own forced device count) and serialized before this process's PJRT
    # client exists.
    if _row_on("shard_place"):
        try:
            shard_place = _bench_shard_place()
        except Exception as exc:  # noqa: BLE001 — row-level isolation
            shard_place = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    else:
        shard_place = skipped

    import jax

    if backend_override:
        jax.config.update("jax_platforms", backend_override)

    from pivot_tpu.utils import enable_compilation_cache

    # Persistent-cache warmup: kernels compiled by earlier runs (or the
    # test suite) load from disk, shrinking the window in which a flaky
    # tunnel can stall a compile RPC.
    enable_compilation_cache()

    backend = jax.default_backend()
    from pivot_tpu.infra import roofline

    # Per-backend peak table for the roofline columns: CPU measured by a
    # one-shot STREAM-style probe in this process, TPU from the v5e spec.
    peaks = roofline.backend_peaks(backend)
    if hasattr(signal, "SIGALRM"):
        signal.alarm(600)

    H, T, R = 512, 2048, 1024
    if _row_on("headline"):
        ctx = _build_batch(H, T, seed=7)
        naive_dps = _bench_naive(ctx)
        device_dps, _, winner, results, kernel_errors, kernel_rooflines = (
            _bench_device(ctx, R)
        )
        ens_rps, ens_roofline = _bench_ensemble(ctx)
    else:
        # --rows subset without the headline metric: keep the schema
        # (nullable) so history tooling parses every artifact the same.
        ctx = None
        naive_dps = device_dps = ens_rps = None
        winner, results, kernel_errors = None, {}, {}
        kernel_rooflines, ens_roofline = {}, None
    def _row(name: str, fn) -> dict:
        """Row-level isolation + --rows gating for the in-process rows:
        a crash costs that one row, never the record."""
        if not _row_on(name):
            return dict(skipped)
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — row-level isolation
            return {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # Round-6 acceptance row: two-phase vs the scan oracle at the
    # serialization-bound shape, single dispatch, with rooflines and the
    # serialized-step model.  Row-level isolation like grid_batched.
    two_phase = _row("two_phase", _bench_two_phase)
    # Dispatch-floor amortization: G concurrent grid runs' ticks as one
    # vmapped dispatch vs G sequential single-run dispatches (the
    # --batch-runs execution model; ≥5× on CPU is the tracked bar —
    # without any tunnel RTT to amortize, the win is pure host-side
    # staging + dispatch overhead).  Row-level isolation like the
    # saturated row: the headline metrics are already banked above, so a
    # failure here must cost this one row, never the record.
    grid_batched = _row("grid_batched", _bench_grid_batched)
    # Round-8 acceptance row: K simulator ticks fused into one device
    # program (ops/tickloop.py) vs K per-tick dispatches, with the
    # fused-loop roofline model's predicted-vs-measured columns.
    fused_tick = _row("fused_tick", _bench_fused_tick)
    # Round-11 acceptance row: the spot-market survival game — pure DES
    # (CPU policies, no device dispatch), so it measures the same thing
    # on every backend.
    spot_survival = _row("spot_survival", _bench_spot_survival)
    # Round-16 acceptance row: policy-search throughput — candidate
    # populations scored as one fused ensemble dispatch per generation
    # (pivot_tpu/search/).  Pure estimator row, any backend.
    policy_search = _row("policy_search", _bench_policy_search)
    # Round-14 acceptance row: the observability plane must be free
    # when off and <3% when on, on the fused-tick DES path, without
    # perturbing a single meter bit.  Pure DES (numpy policy) — same
    # measurement on every backend.
    obs_overhead = _row("obs_overhead", _bench_obs_overhead)
    # Round-15 acceptance rows: the sampled dispatch profiler's cost
    # gate (device-policy fused-tick path, <3%, bit-parity) and the
    # XLA cost-attribution coverage gate (register-or-flag over every
    # jitmap entry point).
    profiler_overhead = _row(
        "profiler_overhead", _bench_profiler_overhead
    )
    cost_attribution = _row("cost_attribution", _bench_cost_attribution)
    if backend != "tpu" and ctx is not None:
        # The Pallas variants cannot run on the fallback backend, so the
        # official record would otherwise exercise one kernel (VERDICT
        # r04 item 8); carry the numpy policy twins + the naive loop as
        # additional per_kernel rows.  ``winner``/``value`` stay the
        # device-kernel figures — these rows are breadth, not the metric.
        results = dict(results, naive=naive_dps, **_bench_numpy_modes(ctx))
    if hasattr(signal, "SIGALRM"):
        signal.alarm(0)

    # Saturated-dispatch row (round-5 live-window finding, RESULTS.md
    # "rollout throughput anatomy"): the R=256 metric is bound by the
    # tunnel's ~0.1 s per-dispatch RTT, not by compute (~0.65 ms/tick)
    # — batching 4× the replicas into ONE device call amortizes the
    # RTT, which is the TPU-first shape for Monte-Carlo ensembles.
    # Measured ABOVE, before this process created its PJRT client
    # (single-tenant co-acquisition guard, ADVICE.md), in a disposable
    # timeout-killed child: a wedged tunnel RPC during the fresh 4×
    # compile can hang in C++ where neither SIGALRM nor try/except can
    # reach — a hang or crash must cost that one row, never the record.
    # The row is dropped from a CPU-fallback line: there was no RTT to
    # amortize and the child errored (or never ran) anyway.
    if backend != "tpu":
        ens_saturated = None

    tpu_record = None
    if backend != "tpu":
        # A fallback line must carry the pointer to the canonical
        # hardware record, so a dead-tunnel round's artifact is
        # self-explaining (VERDICT r02 item 2).
        try:
            with open(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_TPU.json",
                )
            ) as f:
                rec = json.load(f)
            tpu_record = {
                "recorded_at_utc": rec.get("recorded_at_utc"),
                "value": rec.get("bench_line", {}).get("value"),
                "kernel": rec.get("bench_line", {}).get("kernel"),
                "ensemble_replica_rollouts_per_sec": rec.get(
                    "bench_line", {}
                ).get("ensemble_replica_rollouts_per_sec"),
                **(
                    {
                        "ensemble_saturated": rec["bench_line"][
                            "ensemble_saturated"
                        ]
                    }
                    if rec.get("bench_line", {}).get("ensemble_saturated")
                    else {}
                ),
                "see": "BENCH_TPU.json",
            }
        except Exception:  # noqa: BLE001 — the pointer is best-effort
            pass
    line = {
        "metric": (
            "cost-aware placement decisions/sec "
            f"(T={T} tasks x H={H} hosts, {R}-replica vmapped ensemble)"
        ),
        "value": round(device_dps, 1) if device_dps else None,
        "unit": "decisions/sec",
        "vs_baseline": (
            round(device_dps / naive_dps, 2)
            if device_dps and naive_dps else None
        ),
        "baseline_decisions_per_sec": (
            round(naive_dps, 1) if naive_dps else None
        ),
        "backend": backend,
        "kernel": winner,
        "per_kernel": {k: round(v, 1) for k, v in results.items()},
        "kernel_rooflines": kernel_rooflines,
        "peaks": peaks,
        **({"kernel_errors": kernel_errors} if kernel_errors else {}),
        "ensemble_replica_rollouts_per_sec": (
            round(ens_rps, 2) if ens_rps else None
        ),
        "ensemble_roofline": ens_roofline,
        "two_phase": two_phase,
        "grid_batched": grid_batched,
        "fused_tick": fused_tick,
        "serve_stream": serve_stream,
        "serve_tiers": serve_tiers,
        "serve_sharded": serve_sharded,
        "serve_ragged": serve_ragged,
        "serve_mpc": serve_mpc,
        "serve_resident": serve_resident,
        "serve_recovery": serve_recovery,
        "serve_elastic": serve_elastic,
        "shard_place": shard_place,
        "spot_survival": spot_survival,
        "policy_search": policy_search,
        "obs_overhead": obs_overhead,
        "profiler_overhead": profiler_overhead,
        "cost_attribution": cost_attribution,
        **(
            {"ensemble_saturated": ens_saturated} if ens_saturated else {}
        ),
        "tpu_attempted": tpu_attempted,
        "probe_history": probe_history,
        **({"tpu_record": tpu_record} if tpu_record else {}),
        **({"rows": sorted(_ROWS)} if _ROWS is not None else {}),
    }
    if backend == "tpu":
        _emit(line)
        _write_tpu_record(line, probe_history)
    elif (
        os.environ.get("PIVOT_BENCH_AUTOFALLBACK") == "1"
        and not os.environ.get("PIVOT_BENCH_POSTPROBE")
    ):
        # End-of-run re-probe (VERDICT r02 item 2): tunnels recover on
        # operator timescales, so a run that STARTED against a dead
        # tunnel can end against a live one — several minutes have
        # passed.  If it answers now, re-exec to measure on the chip;
        # the TPU line prints after (and therefore supersedes) the CPU
        # line).  The probe runs BEFORE the CPU line prints so a line
        # about to be superseded is marked ``"superseded": true`` —
        # stream parsers that read the first JSON line cannot silently
        # record the stale CPU figure (the authoritative line is the
        # LAST one either way).  One shot only (PIVOT_BENCH_POSTPROBE)
        # so a tunnel that dies again mid-rerun cannot loop the process.
        from pivot_tpu.utils import probe_backend_alive

        t0 = time.time()
        alive = probe_backend_alive(120)
        probe_history.append(
            {
                "timeout_s": 120,
                "wall_s": round(time.time() - t0, 1),
                "alive": alive,
                "phase": "post-run",
            }
        )
        if alive:
            print(json.dumps(dict(line, superseded=True)), flush=True)
            os.environ.pop("PIVOT_BENCH_BACKEND", None)
            os.environ.pop("PIVOT_BENCH_AUTOFALLBACK", None)
            os.environ["PIVOT_BENCH_POSTPROBE"] = "1"
            os.environ["PIVOT_BENCH_PROBES"] = json.dumps(probe_history)
            os.environ["PIVOT_BENCH_TPU_ATTEMPTED"] = "1"
            # The flappy-tunnel path (re-exec'd child finds the link dead
            # again) re-prints this line un-superseded as the final
            # authoritative record — see the POSTPROBE early-exit above.
            os.environ["PIVOT_BENCH_SUPERSEDED_LINE"] = json.dumps(line)
            try:
                os.execv(sys.executable, [sys.executable] + sys.argv)
            except OSError:
                # execv failure (e.g. ENOMEM) must not leave the only
                # measurement falsely marked superseded: re-print it as
                # the authoritative final line.  (A child that crashes
                # AFTER a successful execv is out of our hands — but it
                # re-runs this whole program, whose every exit path
                # prints a final line.)
                _emit(line)
        else:
            _emit(line)
    else:
        _emit(line)


if __name__ == "__main__":
    main()
