"""Experiment runner: replay a trace into a scheduler over a cloned cluster.

Capability parity with the reference's ``ExperimentRun`` +
``TraceBasedApplicationGenerator`` (``alibaba/runner.py:13-136``): each run
gets a fresh event loop and meter, a cluster clone, a scheduler wired to a
policy, and a submission process that replays trace jobs with their
inter-arrival gaps, then stops the scheduler; the run executes to event
exhaustion and writes the meter's JSON output plus ``avg_runtime``.

Runs are plain callables — the grid driver in ``experiments.cli`` executes
them sequentially, via ``multiprocessing`` (the reference always forks; on
a single-core host sequential is faster), or — for device-backed policies —
tick-synchronously through one cross-run :class:`DispatchBatcher`
(:func:`run_grid_lockstep`, the ``--batch-runs`` path): G runs advance in
lock-step and their per-tick placement dispatches coalesce into single
``[G]``-vmapped device calls, amortizing the per-call dispatch floor the
reference pays once per OS process.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler, Policy
from pivot_tpu.utils import LogMixin, get_logger
from pivot_tpu.utils.trace import Tracer
from pivot_tpu.workload.trace import TraceSchedule, load_trace_jobs

__all__ = [
    "ExperimentRun",
    "replay_schedule",
    "run_grid_lockstep",
    "sentinel_path",
]


def sentinel_path(data_dir: str, label: str) -> str:
    """Completion-sentinel location for a run — the single definition shared
    by the writer (``ExperimentRun.run``) and the resume check
    (``experiments.cli``)."""
    return os.path.join(data_dir, label, "complete.json")


def replay_schedule(
    env: Environment,
    scheduler: GlobalScheduler,
    schedule: TraceSchedule,
    n_apps: Optional[int] = None,
):
    """Generator process: submit apps at trace inter-arrival gaps, then stop
    the scheduler (ref ``alibaba/runner.py:104-119``)."""
    last_ts = None
    counter = 0
    done = False
    for ts, apps in schedule.bins:
        if last_ts is not None:
            yield env.timeout(ts - last_ts)
        for app in apps:
            scheduler.submit(app)
            counter += 1
            if n_apps and counter == n_apps:
                done = True
                break
        if done:
            break
        last_ts = ts
    scheduler.stop()


def run_grid_lockstep(runs, stats_out: Optional[dict] = None,
                      mesh=None) -> list:
    """Advance several :class:`ExperimentRun`\\ s tick-synchronously through
    one cross-run dispatch batcher (``pivot_tpu.sched.batch``).

    Each run executes its full DES event loop in its own thread; a
    device-policy placement call parks the thread at its tick boundary,
    and the coordinator (this thread) flushes whenever every live run is
    parked — co-pending same-shape ticks become ONE vmapped device
    dispatch.  All runs share the tick grid (the global scheduler ticks
    at ``start + k·interval`` from sim time 0), so runs of one grid stay
    aligned until their workloads drain; a run with no co-pending
    partner (desynchronized or last alive) falls back to plain
    sequential kernel calls.

    Correctness bar (``tests/test_batch_dispatch.py``): per-run
    placements, meter output, and artifacts are **bit-identical** to the
    same runs executed sequentially — the kernels are pure per-tick
    functions, per-run Philox streams are stateless, and vmap preserves
    each row's op sequence.

    Runs whose policy is not device-backed (or is adaptive — its routing
    is timing-dependent) execute sequentially first, then the batchable
    runs execute in lock-step.  Returns per-run summaries in input
    order; ``stats_out`` (optional dict) receives the batcher's
    coalescing counters — the documented key set (asserted by
    ``tests/test_batch_dispatch.py``, described in
    ``docs/ARCHITECTURE.md``):

      * ``runs`` — lock-step slots (0 when nothing was batchable, in
        which case no other key is written);
      * ``dispatches`` — kernel calls requested by the runs;
      * ``device_calls`` — actual device dispatches issued (< dispatches
        when coalescing worked);
      * ``coalesced`` — requests served inside a >1-run batch;
      * ``max_group`` — largest batch assembled;
      * ``deadline_flushes`` — partial flushes forced by a flush
        deadline (always 0 here: the grid driver runs quiescence-only;
        the serving layer's batcher sets a deadline).

    ``mesh`` (``parallel.mesh.replica_mesh``) shards each coalesced
    flush's stacked [G] axis over the mesh's ``replica`` axis, so
    co-pending runs execute on distinct devices — bit-identical results
    (``sched/batch.py``); ``stats_out['mesh_dispatches']`` counts the
    flushes that actually sharded and ``stats_out['mesh_fallbacks']``
    the coalesced flushes that DROPPED the mesh because their padded
    bucket did not divide the replica axis (single-device fallbacks —
    bit-identical, but a mesh deployment should watch the count; the
    first is also logged).  A 2-D ``replica × host`` mesh
    (``build_hybrid_mesh``) additionally host-shards each row through
    the registered ``*_kernel_sharded_batched`` programs (round 17).
    """
    import threading

    import jax

    from pivot_tpu.sched.batch import DispatchBatcher
    from pivot_tpu.sched.tpu import _DevicePolicyBase

    logger = get_logger("runner")
    batchable, sequential = [], []
    for i, run in enumerate(runs):
        if isinstance(run.policy, _DevicePolicyBase) and not run.policy.adaptive:
            batchable.append((i, run))
        else:
            sequential.append((i, run))
    results: list = [None] * len(runs)
    if sequential:
        logger.info(
            "lockstep grid: %d run(s) not batchable (non-device or "
            "adaptive policy) — executing sequentially", len(sequential),
        )
        for i, run in sequential:
            results[i] = run.run()
    if len(batchable) == 1:
        # A batch of one is the sequential program with extra threads.
        i, run = batchable[0]
        results[i] = run.run()
        batchable = []
    if not batchable:
        if stats_out is not None:
            stats_out.update(runs=0)
        return results

    # Initialize the backend once, here, before any run thread touches
    # jax — concurrent first-touch PJRT client creation is not safe.
    jax.default_backend()
    batcher = DispatchBatcher(len(batchable), mesh=mesh)
    errors: list = [None] * len(batchable)

    def work(slot, idx, run, client):
        try:
            run.policy.enable_batching(client)
            results[idx] = run.run()
        except BaseException as exc:  # noqa: BLE001 — joined below
            errors[slot] = exc
        finally:
            client.close()

    threads = [
        threading.Thread(
            target=work, args=(slot, idx, run, batcher.client()),
            name=f"lockstep-{run.label}", daemon=True,
        )
        for slot, (idx, run) in enumerate(batchable)
    ]
    for t in threads:
        t.start()
    batcher.serve()
    for t in threads:
        t.join()
    failed = [e for e in errors if e is not None]
    if failed:
        raise failed[0]
    if stats_out is not None:
        stats_out.update(batcher.stats)
    logger.info(
        "lockstep grid: %d runs, %d kernel dispatches in %d device calls "
        "(%d coalesced, max batch %d)",
        len(batchable), batcher.stats["dispatches"],
        batcher.stats["device_calls"], batcher.stats["coalesced"],
        batcher.stats["max_group"],
    )
    return results


class ExperimentRun(LogMixin):
    """One (policy × trace) simulation run."""

    def __init__(
        self,
        label: str,
        cluster: Cluster,
        policy: Policy,
        trace_file: Optional[str] = None,
        output_size_scale_factor: float = 1000.0,
        n_apps: Optional[int] = None,
        data_dir: Optional[str] = None,
        seed: Optional[int] = None,
        interval: float = 5,
        fuse_spans: bool = True,
        trace_events: bool = False,
        identity: Optional[dict] = None,
        audit: bool = False,
        schedule: Optional[TraceSchedule] = None,
        market=None,
    ):
        self.label = label
        self.cluster = cluster
        self.policy = policy
        self.trace_file = trace_file
        # In-memory submission schedule: bypasses the trace-file load —
        # the serving parity harness (tests/test_serve.py) compares a
        # served job subset against exactly this run.
        self._schedule = schedule
        if trace_file is None and schedule is None:
            raise ValueError("ExperimentRun needs a trace_file or schedule")
        self.output_size_scale_factor = output_size_scale_factor
        self.n_apps = n_apps
        self.data_dir = data_dir
        self.seed = seed
        self.interval = interval
        #: Pure-tick-run fusion (round 8): fast-forward provably no-op
        #: ticks and serve pump-delivery windows as fused device spans.
        #: Bit-identical outputs either way; off only for harnesses that
        #: compare per-tick policy-call logs (tests/test_serve.py).
        self.fuse_spans = fuse_spans
        # Structured event tracing (utils.trace); written next to the
        # meter's JSON when data_dir is set, kept on .tracer otherwise.
        self.trace_events = trace_events
        self.tracer: Optional[Tracer] = None
        self.identity = identity
        self.audit = audit
        #: Optional spot-market environment (``infra/market.py``):
        #: attached to the scheduler so placement scores with the
        #: time-varying cost matrix and — for risk-aware policies — the
        #: per-tick hazard vector.  None keeps the static world.
        self.market = market

    def run_identity(self) -> dict:
        """What makes this run *this* run — compared on grid resume.

        The grid driver passes the full spec identity (cluster config,
        policy config including device/adaptive, flags) via ``identity``;
        the fallback fields cover direct ``ExperimentRun`` users."""
        if self.identity is not None:
            return self.identity
        return {
            "label": self.label,
            "trace_file": (
                os.path.abspath(self.trace_file) if self.trace_file else None
            ),
            "n_apps": self.n_apps,
            "seed": self.seed,
            "scale_factor": self.output_size_scale_factor,
            # Content digest, not object identity: a market changes
            # placements and costs, so a market-free and a market run of
            # the same label must not compare as the same run.
            "market": (
                hashlib.sha256(self.market.dumps().encode()).hexdigest()
                if self.market is not None else None
            ),
        }

    def run(self) -> dict:
        env = Environment()
        self.tracer = Tracer(enabled=self.trace_events)
        # One injected obs clock per run: the meter's wall snapshot and
        # the tracer's wall timestamps share an epoch (round 14).
        meter = Meter(env, self.cluster.meta, clock=self.tracer.clock)
        cluster = self.cluster.clone(env, meter)
        if self.market is not None:
            # Price-regime changes land on the same timeline as ticks
            # and task events (no-op when tracing is disabled).
            self.market.emit_timeline(self.tracer)
        scheduler = GlobalScheduler(
            env,
            cluster,
            self.policy,
            interval=self.interval,
            seed=self.seed,
            meter=meter,
            tracer=self.tracer,
            fuse_spans=self.fuse_spans,
            market=self.market,
        )
        if self._schedule is not None:
            schedule = self._schedule
        else:
            schedule = load_trace_jobs(
                self.trace_file, self.output_size_scale_factor
            )
        if self.n_apps:
            schedule = schedule.take(self.n_apps)
        # Kept for post-run inspection (app start/end times carry the
        # simulated timestamps) — the calibration harness reads these.
        self.schedule = schedule

        cluster.start()
        scheduler.start()
        if self.audit:
            from pivot_tpu.infra.audit import start_periodic_audit

            start_periodic_audit(cluster, period=self.interval)
        env.process(replay_schedule(env, scheduler, schedule, self.n_apps))

        self.logger.info("running %s on %s", self.label, self.trace_file)
        env.run()
        if self.audit:
            # The periodic observer throttles to one audit per interval;
            # a final full check closes the last window so corruption
            # arising near event exhaustion cannot ship silently.
            from pivot_tpu.infra import audit

            audit.check(cluster, f"final state after {self.label}")

        apps = schedule.apps
        runtimes = [a.end_time - a.start_time for a in apps]
        avg_runtime = sum(runtimes) / len(runtimes) if runtimes else 0.0
        summary = meter.summary()
        summary["avg_runtime"] = avg_runtime
        summary["n_apps"] = len(apps)
        summary["label"] = self.label

        if self.data_dir:
            out = os.path.join(self.data_dir, self.label)
            meter.save(out)
            general_path = os.path.join(out, "general.json")
            with open(general_path) as f:
                general = json.load(f)
            general["avg_runtime"] = avg_runtime
            with open(general_path, "w") as f:
                json.dump(general, f)
            if self.trace_events:
                self.tracer.save_jsonl(os.path.join(out, "events.jsonl"))
                self.tracer.save_chrome(os.path.join(out, "events.chrome.json"))
                self.tracer.save_perfetto(
                    os.path.join(out, "events.perfetto.json")
                )
            # Completion sentinel — written LAST and atomically (a truncated
            # sentinel after a mid-write kill must read as "incomplete", not
            # crash the resumed sweep), carrying the run identity so grid
            # resume can (a) trust every other artifact exists and (b)
            # refuse to skip when the spec behind this dir changed.
            marker = sentinel_path(self.data_dir, self.label)
            tmp = marker + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.run_identity(), f)
            os.replace(tmp, marker)
        self.logger.info(
            "finished %s: avg_runtime=%.1f egress=$%.2f wall=%.2fs",
            self.label,
            avg_runtime,
            summary["egress_cost"],
            summary["wall_clock"],
        )
        return summary
