"""Experiment runner: replay a trace into a scheduler over a cloned cluster.

Capability parity with the reference's ``ExperimentRun`` +
``TraceBasedApplicationGenerator`` (``alibaba/runner.py:13-136``): each run
gets a fresh event loop and meter, a cluster clone, a scheduler wired to a
policy, and a submission process that replays trace jobs with their
inter-arrival gaps, then stops the scheduler; the run executes to event
exhaustion and writes the meter's JSON output plus ``avg_runtime``.

Runs are plain callables — the grid driver in ``experiments.cli`` executes
them sequentially or via ``multiprocessing`` (the reference always forks;
on a single-core host sequential is faster).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler, Policy
from pivot_tpu.utils import LogMixin
from pivot_tpu.utils.trace import Tracer
from pivot_tpu.workload.trace import TraceSchedule, load_trace_jobs

__all__ = ["ExperimentRun", "replay_schedule", "sentinel_path"]


def sentinel_path(data_dir: str, label: str) -> str:
    """Completion-sentinel location for a run — the single definition shared
    by the writer (``ExperimentRun.run``) and the resume check
    (``experiments.cli``)."""
    return os.path.join(data_dir, label, "complete.json")


def replay_schedule(
    env: Environment,
    scheduler: GlobalScheduler,
    schedule: TraceSchedule,
    n_apps: Optional[int] = None,
):
    """Generator process: submit apps at trace inter-arrival gaps, then stop
    the scheduler (ref ``alibaba/runner.py:104-119``)."""
    last_ts = None
    counter = 0
    done = False
    for ts, apps in schedule.bins:
        if last_ts is not None:
            yield env.timeout(ts - last_ts)
        for app in apps:
            scheduler.submit(app)
            counter += 1
            if n_apps and counter == n_apps:
                done = True
                break
        if done:
            break
        last_ts = ts
    scheduler.stop()


class ExperimentRun(LogMixin):
    """One (policy × trace) simulation run."""

    def __init__(
        self,
        label: str,
        cluster: Cluster,
        policy: Policy,
        trace_file: str,
        output_size_scale_factor: float = 1000.0,
        n_apps: Optional[int] = None,
        data_dir: Optional[str] = None,
        seed: Optional[int] = None,
        interval: float = 5,
        trace_events: bool = False,
        identity: Optional[dict] = None,
        audit: bool = False,
    ):
        self.label = label
        self.cluster = cluster
        self.policy = policy
        self.trace_file = trace_file
        self.output_size_scale_factor = output_size_scale_factor
        self.n_apps = n_apps
        self.data_dir = data_dir
        self.seed = seed
        self.interval = interval
        # Structured event tracing (utils.trace); written next to the
        # meter's JSON when data_dir is set, kept on .tracer otherwise.
        self.trace_events = trace_events
        self.tracer: Optional[Tracer] = None
        self.identity = identity
        self.audit = audit

    def run_identity(self) -> dict:
        """What makes this run *this* run — compared on grid resume.

        The grid driver passes the full spec identity (cluster config,
        policy config including device/adaptive, flags) via ``identity``;
        the fallback fields cover direct ``ExperimentRun`` users."""
        if self.identity is not None:
            return self.identity
        return {
            "label": self.label,
            "trace_file": os.path.abspath(self.trace_file),
            "n_apps": self.n_apps,
            "seed": self.seed,
            "scale_factor": self.output_size_scale_factor,
        }

    def run(self) -> dict:
        env = Environment()
        meter = Meter(env, self.cluster.meta)
        cluster = self.cluster.clone(env, meter)
        self.tracer = Tracer(enabled=self.trace_events)
        scheduler = GlobalScheduler(
            env,
            cluster,
            self.policy,
            interval=self.interval,
            seed=self.seed,
            meter=meter,
            tracer=self.tracer,
        )
        schedule = load_trace_jobs(self.trace_file, self.output_size_scale_factor)
        if self.n_apps:
            schedule = schedule.take(self.n_apps)
        # Kept for post-run inspection (app start/end times carry the
        # simulated timestamps) — the calibration harness reads these.
        self.schedule = schedule

        cluster.start()
        scheduler.start()
        if self.audit:
            from pivot_tpu.infra.audit import start_periodic_audit

            start_periodic_audit(cluster, period=self.interval)
        env.process(replay_schedule(env, scheduler, schedule, self.n_apps))

        self.logger.info("running %s on %s", self.label, self.trace_file)
        env.run()
        if self.audit:
            # The periodic observer throttles to one audit per interval;
            # a final full check closes the last window so corruption
            # arising near event exhaustion cannot ship silently.
            from pivot_tpu.infra import audit

            audit.check(cluster, f"final state after {self.label}")

        apps = schedule.apps
        runtimes = [a.end_time - a.start_time for a in apps]
        avg_runtime = sum(runtimes) / len(runtimes) if runtimes else 0.0
        summary = meter.summary()
        summary["avg_runtime"] = avg_runtime
        summary["n_apps"] = len(apps)
        summary["label"] = self.label

        if self.data_dir:
            out = os.path.join(self.data_dir, self.label)
            meter.save(out)
            general_path = os.path.join(out, "general.json")
            with open(general_path) as f:
                general = json.load(f)
            general["avg_runtime"] = avg_runtime
            with open(general_path, "w") as f:
                json.dump(general, f)
            if self.trace_events:
                self.tracer.save_jsonl(os.path.join(out, "events.jsonl"))
                self.tracer.save_chrome(os.path.join(out, "events.chrome.json"))
            # Completion sentinel — written LAST and atomically (a truncated
            # sentinel after a mid-write kill must read as "incomplete", not
            # crash the resumed sweep), carrying the run identity so grid
            # resume can (a) trust every other artifact exists and (b)
            # refuse to skip when the spec behind this dir changed.
            marker = sentinel_path(self.data_dir, self.label)
            tmp = marker + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.run_identity(), f)
            os.replace(tmp, marker)
        self.logger.info(
            "finished %s: avg_runtime=%.1f egress=$%.2f wall=%.2fs",
            self.label,
            avg_runtime,
            summary["egress_cost"],
            summary["wall_clock"],
        )
        return summary
