"""Calibration harness: quantify the ensemble estimator against the DES.

The device-resident Monte-Carlo rollout (``pivot_tpu.parallel.ensemble``)
deliberately simplifies the ground-truth discrete-event simulation —
fixed-tick time, zone-level transfer estimates, optional backlog-pipe
congestion instead of per-route packet service.  This module measures how
much those simplifications cost: it runs the SAME (trace, cluster, policy)
through both engines and reports side-by-side metrics with relative
errors, for the static and congestion-aware transfer models.

The reference has no analog — it has exactly one engine and no way to ask
"how faithful is my cheap estimator?" (its only estimator-like code path,
``Application.estimate_local_runtime``, is never called;
``application/__init__.py:115-126``).

Usage:
  python -m pivot_tpu.experiments.cli calibrate --num-apps 50
or programmatically::

  from pivot_tpu.experiments.calibrate import calibrate
  report = calibrate("data/jobs/jobs-....npz", n_hosts=100, n_apps=50)
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pivot_tpu.utils import get_logger

__all__ = ["calibrate", "ensemble_inputs_from_schedule"]

logger = get_logger("calibrate")


def ensemble_inputs_from_schedule(schedule, cluster, dtype=None):
    """(workload, app_slices, arrivals, topo, avail0, storage_zones) for an
    ensemble rollout of ``schedule`` on ``cluster`` — the single
    trace→device-inputs bridge shared by the ``ensemble`` and
    ``calibrate`` CLI paths.

    ``app_slices[i]`` is the ``slice`` of task rows owned by app ``i`` in
    the flattened workload (``EnsembleWorkload.from_applications`` lays
    instances out app by app, group by group).

    Rebasing to the first submission is phase-exact, not an
    approximation: the DES trace replay submits its first bin at env time
    0 (``TraceBasedApplicationGenerator`` waits only *inter*-arrival
    gaps), so the live scheduler's tick grid — absolute multiples of the
    interval — hits the first submission exactly at a grid point, and the
    rollout's clock-from-0 grid matches it tick for tick.
    """
    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import DeviceTopology
    from pivot_tpu.parallel.ensemble import EnsembleWorkload

    apps = schedule.apps
    arrivals = [ts for ts, bin_apps in schedule.bins for _ in bin_apps]
    t0 = arrivals[0] if arrivals else 0.0
    arrivals = [a - t0 for a in arrivals]  # rollout clock starts at 0
    workload = EnsembleWorkload.from_applications(apps, arrivals=arrivals)

    app_slices: List[slice] = []
    offset = 0
    for app in apps:
        n = sum(g.instances for g in app.groups)
        app_slices.append(slice(offset, offset + n))
        offset += n

    dtype = jnp.float32 if dtype is None else dtype
    topo = DeviceTopology.from_cluster(cluster, dtype)
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=dtype)
    storage_zones = jnp.asarray(cluster.storage_zone_vector())
    return workload, app_slices, arrivals, topo, avail0, storage_zones


def des_metrics(summary: dict, schedule) -> dict:
    """The four comparison metrics from a finished DES run — the ONE
    definition shared by the calibration harness and the sensitivity
    experiment (``cli.py run_sensitivity``), so their numbers cannot
    silently diverge.  Makespan runs first submission → last app
    completion (the rollout clock starts at the first submission)."""
    apps = schedule.apps
    t0 = min(a.start_time for a in apps)
    return {
        "avg_runtime": summary["avg_runtime"],
        "egress_cost": summary["egress_cost"],
        "instance_hours": summary["cum_instance_hours"],
        "makespan": max(a.end_time for a in apps) - t0,
    }


def _des_ground_truth(cluster, policy_name, trace_file, n_apps, scale_factor,
                      seed, interval, realtime=False):
    """Run the exact simulation; return its metric dict."""
    import dataclasses

    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.utils.config import (
        PolicyConfig,
        make_policy,
        reference_policy_set,
    )

    # The canonical arms come from the ONE definition the experiments use
    # (reference_policy_set) so the calibration target cannot drift from
    # what `overall`/`num-apps` actually run; best-fit has no canonical
    # arm and falls back to defaults.
    pc = next(
        (c for c in reference_policy_set("numpy") if c.name == policy_name),
        PolicyConfig(name=policy_name, device="numpy"),
    )
    if realtime:
        pc = dataclasses.replace(pc, realtime_bw=True)
    run = ExperimentRun(
        f"calibrate-{policy_name}", cluster, make_policy(pc), trace_file,
        output_size_scale_factor=scale_factor, n_apps=n_apps, seed=seed,
        interval=interval,
    )
    summary = run.run()
    # Timestamps live on the runner's schedule, whose apps went through
    # the simulation.
    schedule = run.schedule
    return des_metrics(summary, schedule), schedule


def _estimate(workload, app_slices, arrivals, topo, avail0, storage_zones,
              policy_name, seed, tick, max_ticks, replicas, perturb,
              congestion, realtime_scoring=False, tick_order="fifo"):
    """One ensemble rollout → metric dict (means over replicas)."""
    import jax

    from pivot_tpu.parallel.ensemble import rollout

    res = rollout(
        jax.random.PRNGKey(seed), avail0, workload, topo, storage_zones,
        n_replicas=replicas, tick=tick, max_ticks=max_ticks,
        perturb=perturb, policy=policy_name, congestion=congestion,
        realtime_scoring=realtime_scoring, tick_order=tick_order,
    )
    finish = np.asarray(res.finish_time)  # [R, T]
    app_runtimes = np.stack(
        [finish[:, s].max(axis=1) - a for s, a in zip(app_slices, arrivals)],
        axis=1,
    )  # [R, A]
    return {
        "avg_runtime": float(app_runtimes.mean()),
        "egress_cost": float(np.asarray(res.egress_cost).mean()),
        "instance_hours": float(np.asarray(res.instance_hours).mean()),
        "makespan": float(np.asarray(res.makespan).mean()),
        "unfinished_max": int(np.asarray(res.n_unfinished).max()),
    }


def _with_errors(est: dict, des: dict) -> dict:
    """Attach signed relative errors vs the DES (None where DES is ~0).

    A truncated rollout (tasks unfinished at the horizon) cannot produce
    honest fidelity numbers — avg_runtime is infinite and makespan
    understates — so the whole estimate is flagged, non-finite metrics are
    nulled (inf is not valid JSON), and every rel_err is None.
    """
    out = dict(est)
    if est["unfinished_max"] > 0:
        logger.warning(
            "%d tasks unfinished at the rollout horizon — fidelity numbers "
            "are invalid; raise --max-ticks", est["unfinished_max"],
        )
        out["horizon_exceeded"] = True
        for k in ("avg_runtime", "egress_cost", "instance_hours", "makespan"):
            if not np.isfinite(out[k]):
                out[k] = None
        out["rel_err"] = {
            k: None
            for k in ("avg_runtime", "egress_cost", "instance_hours",
                      "makespan")
        }
        return out
    out["rel_err"] = {
        k: (None if abs(des[k]) < 1e-12 else (est[k] - des[k]) / des[k])
        for k in ("avg_runtime", "egress_cost", "instance_hours", "makespan")
    }
    return out


def calibrate(
    trace_file: str,
    cluster=None,
    n_hosts: int = 100,
    n_apps: Optional[int] = 50,
    policy: str = "cost-aware",
    scale_factor: float = 1000.0,
    seed: int = 0,
    tick: float = 5.0,
    max_ticks: int = 4096,
    replicas: int = 1,
    perturb: float = 0.0,
    modes: Optional[Sequence[str]] = None,
    realtime: bool = False,
    x64: bool = False,
    des_seeds: int = 1,
    cluster_seeds: int = 1,
    cluster_config=None,
    tick_order: str = "lifo",
) -> dict:
    """DES ground truth vs ensemble estimates for one (trace, policy) pair.

    With the default ``replicas=1, perturb=0.0`` the estimator runs the
    nominal scenario; larger replica counts with perturbation report the
    Monte-Carlo mean instead.  With ``realtime`` (cost-aware only), BOTH
    engines switch to their bandwidth-aware variants — the DES scores on
    live route queues (``realtime_bw``) and the estimator on the
    backlog-discounted pipes (``congestion + realtime_scoring``) — and
    the single reported mode is ``"realtime"``.  Returns::

      {"des": {...}, "static": {..., "rel_err": {...}},
       "congested": {..., "rel_err": {...}}, ...config keys...}

    ``des_seeds > 1`` runs the DES at ``des_seeds`` consecutive policy
    seeds on the same (trace, cluster) and calibrates against the seed
    **mean**, attaching ``des_per_seed``/``des_spread`` to the report.
    Measured (100×50, live chip): this matters for the RNG-bearing arms
    — cost-aware's DES egress spans 0.117–0.269 (±43%) across 3 policy
    seeds via its root-anchor draws, and the 8-replica estimator mean
    lands −4.5% from the seed mean — while the packing arms are exactly
    policy-seed-deterministic (spread 0.000): their variability lives in
    the *environment*, which ``cluster_seeds`` addresses.  Pair with
    ``replicas > 1`` so the estimator side is a mean too.

    ``cluster_seeds > 1`` repeats the whole paired comparison on that
    many independently generated clusters (seed+i: fresh zone layout and
    ±5% bandwidth jitter), returning ``{"clusters": [per-cluster
    reports], "cluster_summary": {mode: {metric: mean/std rel err}}}`` —
    the distributional fidelity claim for the deterministic packing
    arms: mean rel err is estimator *bias*, std is environment *chaos*.
    Incompatible with a prebuilt ``cluster``.

    ``x64`` runs the estimator in float64 like the DES (JAX x64 is
    enabled only for the scope of this calibration run and restored on
    return — calibration is a CPU-side harness, where f64 is native).
    Measured effect: the *static* packing arms track the
    DES markedly closer (best-fit egress +70% → +35% at 100×50, seed 0 —
    strict-fit boundaries and residual-norm near-ties stop flipping on
    f32 rounding), the cost-aware arm is unchanged, and the congested
    arms can move either way (the backlog model's sample path shifts);
    see RESULTS.md.

    ``tick_order`` defaults to ``"lifo"`` here — the DES-faithful
    within-tick batch order (the reference drains its ready/wait dicts
    with ``popitem()``, ref ``scheduler/__init__.py:93-94,187``; see
    ``_rollout_segment``).  The round-3 bias diagnosis
    (``tools/bias_diagnose.py``, artifacts ``figures/bias_diagnose_*``)
    pinned the packing arms' consistent-sign egress bias to exactly this
    order plus f32 scoring: at 80×30 across 5 clusters, best-fit mean
    egress error fell +54% → +1.7% (±19) and first-fit +24% → +7.7%
    (±7.5) under ``lifo`` + ``x64``, with per-wave placement assignments
    matching the DES exactly until the transfer-timing model shifts a
    completion across a tick boundary.  ``"fifo"`` (task-index order)
    remains the throughput default of the raw :func:`rollout` entry.
    """
    from pivot_tpu.utils import enable_compilation_cache, ensure_live_backend
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    ensure_live_backend()  # degrade to CPU on a wedged tunnel, never hang
    enable_compilation_cache()

    if realtime and policy != "cost-aware":
        raise ValueError("realtime calibration applies to the cost-aware "
                         "arm only")
    if realtime and modes is not None:
        raise ValueError("realtime=True fixes the mode to ('realtime',) — "
                         "don't pass modes explicitly")
    if not realtime and modes is not None and "realtime" in modes:
        raise ValueError("mode 'realtime' needs realtime=True (otherwise "
                         "the DES side would not be the realtime_bw arm — "
                         "a mismatched comparison)")
    modes = (
        ("realtime",) if realtime
        else ("static", "congested") if modes is None else tuple(modes)
    )
    unknown = set(modes) - {"static", "congested", "realtime", "pairs"}
    if unknown:
        # A typo'd mode would otherwise run as a second static arm under
        # the wrong label — and could even be crowned recommended_mode.
        raise ValueError(f"unknown calibration mode(s): {sorted(unknown)}")
    if cluster_seeds > 1:
        if cluster is not None:
            raise ValueError("cluster_seeds > 1 generates its own clusters "
                             "— pass n_hosts or cluster_config, not a "
                             "prebuilt cluster")
        import dataclasses

        base_cfg = cluster_config or ClusterConfig(n_hosts=n_hosts, seed=seed)
        runs = []
        for ci in range(cluster_seeds):
            cl = build_cluster(dataclasses.replace(base_cfg, seed=seed + ci))
            runs.append(_calibrate_one(
                trace_file, cl, n_apps, policy, scale_factor,
                seed + ci, tick, max_ticks, replicas, perturb, modes,
                realtime, x64, des_seeds, tick_order=tick_order,
            ))
        summary = {}
        for mode in modes:
            summary[mode] = {}
            for k in _METRICS:
                errs = [r[mode]["rel_err"][k] for r in runs]
                errs = [e for e in errs if e is not None]
                summary[mode][k] = {
                    "mean_rel_err": float(np.mean(errs)) if errs else None,
                    "std_rel_err": float(np.std(errs)) if errs else None,
                    "n": len(errs),
                }
        # Measured per-arm mode recommendation (docs/ARCHITECTURE.md "Per-
        # arm transfer-model recommendation"): the congested model can
        # WORSEN an arm (best-fit: its global argmin chain amplifies the
        # zone-pipe's overstated contention), so the right mode is an
        # empirical property of the arm — picked here by smallest |mean
        # egress error| over the measured clusters, the metric the packing
        # arms diverge on.
        candidates = [
            (abs(summary[m]["egress_cost"]["mean_rel_err"]), m)
            for m in modes
            if summary[m]["egress_cost"]["mean_rel_err"] is not None
        ]
        recommended = min(candidates)[1] if candidates else None
        return {
            "trace": trace_file,
            "n_hosts": base_cfg.n_hosts,
            "policy": policy,
            "replicas": replicas,
            "perturb": perturb,
            "realtime_variant": realtime,
            "x64": x64,
            "cluster_seeds": cluster_seeds,
            "clusters": runs,
            "cluster_summary": summary,
            "recommended_mode": recommended,
        }
    if cluster is not None and cluster_config is not None:
        raise ValueError("pass cluster or cluster_config, not both")
    if cluster is None:
        cluster = build_cluster(
            cluster_config or ClusterConfig(n_hosts=n_hosts, seed=seed)
        )
    return _calibrate_one(
        trace_file, cluster, n_apps, policy, scale_factor, seed, tick,
        max_ticks, replicas, perturb, modes, realtime, x64, des_seeds,
        tick_order=tick_order,
    )


_METRICS = ("avg_runtime", "egress_cost", "instance_hours", "makespan")


def _calibrate_one(trace_file, cluster, n_apps, policy, scale_factor, seed,
                   tick, max_ticks, replicas, perturb, modes, realtime, x64,
                   des_seeds, tick_order="fifo"):
    """One (cluster, seed) paired DES↔estimator comparison (the body of
    :func:`calibrate`; see its docstring for the distributional modes)."""
    # Distributional mode (des_seeds > 1): a single-path comparison
    # conflates estimator bias with the DES's own RNG noise.  Running the
    # DES at several policy seeds and comparing the estimator's
    # replica-mean against the DES seed-mean (with the DES's own spread on
    # record) separates the two: bias is the mean gap, noise is the
    # spread.  The workload schedule (apps, arrival bins) is trace-driven
    # and identical across seeds — only policy RNG and tie-breaking vary.
    per_seed = []
    schedule = None
    for i in range(max(des_seeds, 1)):
        d, s = _des_ground_truth(
            cluster, policy, trace_file, n_apps, scale_factor, seed + i,
            tick, realtime=realtime,
        )
        per_seed.append(d)
        if schedule is None:
            schedule = s
    des = {k: float(np.mean([d[k] for d in per_seed])) for k in _METRICS}
    import contextlib

    import jax
    import jax.numpy as jnp

    # Scoped: jax_enable_x64 is process-global, so restore the caller's
    # value on exit — otherwise a later calibrate(x64=False) in the same
    # process would silently run f64 while reporting "x64": False.
    # (jax.enable_x64 was removed from the top-level namespace; the
    # context manager lives in jax.experimental.)
    from jax.experimental import enable_x64 as _enable_x64

    x64_scope = _enable_x64(True) if x64 else contextlib.nullcontext()
    with x64_scope:
        inputs = ensemble_inputs_from_schedule(
            schedule, cluster, dtype=jnp.float64 if x64 else None
        )
        report = _calibrate_modes(
            inputs, des, schedule, trace_file, cluster, policy, replicas,
            perturb, realtime, x64, modes, seed, tick, max_ticks,
            tick_order=tick_order,
        )
    if des_seeds > 1:
        report["des_seeds"] = des_seeds
        report["des_per_seed"] = per_seed
        report["des_spread"] = {
            k: {
                "std": float(np.std([d[k] for d in per_seed])),
                "min": float(min(d[k] for d in per_seed)),
                "max": float(max(d[k] for d in per_seed)),
            }
            for k in _METRICS
        }
    return report


def _calibrate_modes(inputs, des, schedule, trace_file, cluster, policy,
                     replicas, perturb, realtime, x64, modes, seed, tick,
                     max_ticks, tick_order="fifo"):

    report = {
        "trace": trace_file,
        "n_hosts": len(cluster.hosts),
        "n_apps": len(schedule.apps),
        "n_tasks": inputs[0].n_tasks,
        "policy": policy,
        "replicas": replicas,
        "perturb": perturb,
        "realtime_variant": realtime,
        "x64": x64,
        "tick_order": tick_order,
        "des": des,
    }
    for mode in modes:
        est = _estimate(
            *inputs, policy, seed, tick, max_ticks, replicas, perturb,
            congestion=(
                "pairs" if mode == "pairs"
                else mode in ("congested", "realtime")
            ),
            realtime_scoring=(mode == "realtime"), tick_order=tick_order,
        )
        report[mode] = _with_errors(est, des)
        if report[mode].get("horizon_exceeded"):
            continue
        logger.info(
            "%s/%s: makespan %.0f vs DES %.0f (%+.0f%%), egress $%.2f vs "
            "$%.2f, inst-h %.1f vs %.1f",
            policy, mode, est["makespan"], des["makespan"],
            100 * (est["makespan"] / max(des["makespan"], 1e-9) - 1),
            est["egress_cost"], des["egress_cost"],
            est["instance_hours"], des["instance_hours"],
        )
    return report
