"""Experiment CLI — the user-facing entry point.

Capability parity with the reference's ``alibaba/sim.py`` (argparse flags
``:20-52``, experiment drivers ``:168-230``): the ``overall`` and
``num-apps`` subcommands run the three reference scheduler arms
(Opportunistic / VBP / Cost-Aware) over every trace file in the job
directory, write the per-run JSON metric layout, and render the matching
plots.  Additions: ``--device {naive,numpy,tpu}`` selects the policy
backend, ``--trace-limit`` bounds the grid, and runs execute sequentially
by default (fork with ``--workers N`` like the reference's unconditional
``multiprocessing`` fan-out, ``alibaba/sim.py:187-195``, or — device
backend only — advance ``--batch-runs G`` runs tick-synchronously in one
process with their per-tick placement dispatches coalesced into single
vmapped device calls, bit-identical to sequential execution).

Usage:
  python -m pivot_tpu.experiments.cli --num-hosts 100 overall --num-apps 100
  python -m pivot_tpu.experiments.cli num-apps --num-apps-list 100 500 1000
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import time
from typing import List

from pivot_tpu.utils import get_logger
from pivot_tpu.utils.config import (
    ClusterConfig,
    HostShape,
    PolicyConfig,
    build_cluster,
    make_policy,
    reference_policy_set,
)

logger = get_logger("cli")


@dataclasses.dataclass(frozen=True)
class RunSpec:
    """One (policy × trace) run, fully described by picklable values so it
    can cross a multiprocessing boundary under any start method (the run
    rebuilds its cluster from the seeded config — cheap with lazy routes,
    and deterministic, so every run sees the identical fabric)."""

    cluster: ClusterConfig
    policy: PolicyConfig
    trace: str
    data_dir: str
    n_apps: int
    scale_factor: float
    seed: int
    trace_events: bool = False
    profile_dir: str = ""
    audit: bool = False


def _spec_identity(spec: RunSpec) -> dict:
    """The full identity of a run, derived from the spec alone — EVERY knob
    that can change results is included (cluster shape, policy backend and
    hyperparameters, flags), so ``--resume`` re-runs rather than silently
    inheriting a directory produced under different configuration."""
    return {
        "label": spec.policy.display_label,
        "trace_file": os.path.abspath(spec.trace),
        "n_apps": spec.n_apps,
        "seed": spec.seed,
        "scale_factor": spec.scale_factor,
        "cluster": {
            k: v
            for k, v in dataclasses.asdict(spec.cluster).items()
            # --executor is result-neutral (bit-identical trajectories,
            # tests/test_executor.py), so like --audit it must not
            # invalidate completed runs — nor may its absence from
            # sentinels written before the knob existed.
            if k != "executor"
        },
        "policy": dataclasses.asdict(spec.policy),
        "trace_events": spec.trace_events,
        # --audit is deliberately NOT part of the identity: it is a pure
        # observer (asserted by test_audit_does_not_perturb_metrics) with
        # no artifacts, so toggling it must not invalidate completed runs.
    }


def _is_complete(spec: RunSpec) -> bool:
    """True iff the run's completion sentinel — written atomically as its
    LAST artifact — exists, parses, and describes this exact run.  An
    unreadable/truncated sentinel counts as incomplete."""
    import json

    from pivot_tpu.experiments.runner import sentinel_path

    marker = sentinel_path(spec.data_dir, spec.policy.display_label)
    if not os.path.exists(marker):
        return False
    try:
        with open(marker) as f:
            recorded = json.load(f)
    except (json.JSONDecodeError, OSError):
        return False
    if isinstance(recorded.get("cluster"), dict):
        # Sentinels written while the executor knob briefly lived in the
        # identity carry it; strip before comparing (it is result-neutral).
        recorded["cluster"].pop("executor", None)
    if recorded == _spec_identity(spec):
        return True
    logger.warning("stale results in %s (different run spec) — rerunning",
                   spec.data_dir)
    return False


def _build_run(spec: RunSpec):
    """Materialize a spec: cluster + policy + ExperimentRun (not executed)."""
    from pivot_tpu.experiments.runner import ExperimentRun

    cluster = build_cluster(spec.cluster)
    return ExperimentRun(
        spec.policy.display_label,
        cluster,
        make_policy(spec.policy),
        spec.trace,
        output_size_scale_factor=spec.scale_factor,
        n_apps=spec.n_apps,
        data_dir=spec.data_dir,
        seed=spec.seed,
        trace_events=spec.trace_events,
        identity=_spec_identity(spec),
        audit=spec.audit,
    )


def _execute_run(spec: RunSpec) -> None:
    from pivot_tpu.utils.trace import device_profile

    # Grid-level resume.  _run_grid also pre-filters in the parent (so a
    # worker process is never forked for a skip); this in-run check covers
    # sequential execution and direct callers, before any construction.
    if _is_complete(spec):
        logger.info("skipping completed run %s (%s)",
                    spec.policy.display_label, spec.data_dir)
        return

    run = _build_run(spec)
    # Per-run profile dir: jax.profiler names sessions by wall-clock second
    # and hostname, so concurrent/sub-second runs sharing one dir collide.
    # Reuse the run's unique data-dir tail (".../data/<...>/<i>") as the key.
    profile_dir = ""
    if spec.profile_dir:
        tail = spec.data_dir.split(os.sep + "data" + os.sep, 1)[-1]
        profile_dir = os.path.join(
            spec.profile_dir, tail, spec.policy.display_label
        )
    with device_profile(profile_dir):
        run.run()


def _add_tick_order(sub_parser, default="fifo"):
    """The ONE definition of the --tick-order flag (five estimator
    subcommands carry it): 'fifo' is the bit-stable throughput order,
    'lifo' the DES-faithful popitem-queue emulation (~1.5x per-tick
    cost; the calibrate default — the round-3 bias fix)."""
    sub_parser.add_argument(
        "--tick-order", default=default, choices=["fifo", "lifo"],
        help="within-tick batch order: 'fifo' (task-index, bit-stable "
             "throughput default) or 'lifo' (exact DES popitem-queue "
             f"emulation, ~1.5x per-tick cost; default: {default})",
    )


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="Run cost-aware scheduling simulations on Alibaba traces"
    )
    parser.add_argument("--num-hosts", type=int, dest="n_hosts", default=600)
    parser.add_argument("--cpus", type=int, default=16)
    parser.add_argument("--mem", type=int, default=128 * 1024, help="MB per host")
    parser.add_argument("--disk", type=int, default=100, help="GB per host")
    parser.add_argument("--gpus", type=int, default=1)
    parser.add_argument(
        "--job-dir", default=os.environ.get("JOB_DIR", "./data/jobs")
    )
    parser.add_argument(
        "--output-dir", default=os.environ.get("OUTPUT_DIR", "./output")
    )
    parser.add_argument(
        "--task-output-scale-factor", type=float, dest="scale_factor", default=1000
    )
    parser.add_argument(
        "--device",
        choices=["naive", "numpy", "tpu"],
        default="numpy",
        help="policy backend",
    )
    parser.add_argument(
        "--no-adaptive",
        action="store_false",
        dest="adaptive",
        help="tpu backend: always call the device, even for ticks too small "
             "to amortize the per-call link latency (default: adaptive "
             "routing between device and in-process numpy twin)",
    )
    parser.add_argument(
        "--executor",
        choices=["fast", "process"],
        default="fast",
        help="task executor: 'fast' callback executor (default) or the "
             "reference-shaped one-process-per-execution 'process'; "
             "bit-identical trajectories",
    )
    parser.add_argument(
        "--network",
        choices=["python", "native"],
        default="python",
        help="network fabric backend (native = C++ co-simulator)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--audit", action="store_true",
                        help="audit simulation-state invariants (resource "
                             "accounting, down-host emptiness, route "
                             "consistency) every tick; abort on violation")
    parser.add_argument("--trace-events", action="store_true",
                        help="write structured event traces (events.jsonl + "
                             "Chrome/Perfetto events.chrome.json) per run")
    parser.add_argument("--profile-dir", default="",
                        help="capture a jax.profiler device trace into this "
                             "directory (TensorBoard-loadable)")
    parser.add_argument("--workers", type=int, default=1,
                        help="process-parallel runs (1 = sequential)")
    parser.add_argument("--batch-runs", type=int, default=1, metavar="G",
                        help="advance G grid runs tick-synchronously and "
                             "coalesce their per-tick placement dispatches "
                             "into one vmapped device call (amortizes the "
                             "per-call dispatch floor; --device tpu only, "
                             "implies --no-adaptive; bit-identical to "
                             "sequential execution)")
    parser.add_argument("--resume", default=None, metavar="EXP_DIR",
                        help="resume an interrupted sweep: reuse this "
                             "experiment directory and skip completed runs")
    parser.add_argument("--trace-limit", type=int, default=None,
                        help="use only the first N trace files")
    sub = parser.add_subparsers(dest="command")
    overall = sub.add_parser("overall", help="overall comparison experiment")
    overall.add_argument("--num-apps", type=int, dest="num_apps", default=None)
    napps = sub.add_parser("num-apps", help="cost vs number of applications")
    napps.add_argument("--host-hourly-rate", type=float, default=0.932)
    napps.add_argument("--num-apps-list", nargs="+", type=int, required=True)
    ens = sub.add_parser(
        "ensemble",
        help="device-resident Monte-Carlo ensemble: the full scheduling "
             "rollout vmapped over perturbed replicas (BASELINE config 5; "
             "the reference can only fork one OS process per scenario)",
    )
    ens.add_argument("--num-apps", type=int, dest="num_apps", default=50)
    ens.add_argument("--replicas", type=int, default=1024)
    ens.add_argument("--policy", default="cost-aware",
                     choices=["cost-aware", "first-fit", "best-fit",
                              "opportunistic"],
                     help="placement arm simulated by the rollout (the "
                          "reference's three comparison arms + cost-aware)")
    ens.add_argument("--perturb", type=float, default=0.1,
                     help="± multiplicative jitter on task runtimes and "
                          "arrival times per replica")
    _add_tick_order(ens)
    ens.add_argument("--tick", type=float, default=5.0)
    ens.add_argument("--max-ticks", type=int, default=2048)
    ens.add_argument("--checkpoint", default=None, metavar="NPZ",
                     help="segmented rollout with mid-flight "
                          "checkpoint/resume at this path")
    ens.add_argument("--replica-chunk", type=int, default=0, metavar="R",
                     help="run the ensemble in replica chunks of R per "
                          "device call (0 = off) — the pressure valve "
                          "for replica counts beyond HBM's comfortable "
                          "working set, and a way to keep each device "
                          "call short on remote transports (RESULTS.md "
                          "scaling tables).  Ignored (with a warning) on "
                          "a multi-chip mesh where the sharded path "
                          "already splits replicas.  Without "
                          "--checkpoint each chunk is one monolithic "
                          "device call; add --checkpoint to bound calls "
                          "per 64-tick segment.  Opt-in because chunking "
                          "draws a different (equally i.i.d.) "
                          "Monte-Carlo sample set than one monolithic "
                          "call")
    ens.add_argument("--faults", type=int, default=0, metavar="N",
                     help="per-replica random host crashes: each replica "
                          "draws an independent N-crash schedule "
                          "(resilience what-if ensemble)")
    ens.add_argument("--fault-horizon", type=float, default=None,
                     help="crash times drawn uniform in [0, horizon) "
                          "(default: tick x max-ticks)")
    ens.add_argument("--fault-mttr", type=float, default=None,
                     help="mean outage duration (Exp-distributed); "
                          "omit for permanent crashes")
    ens.add_argument("--congestion", action="store_true",
                     help="tick-resolution link-contention model: transfer "
                          "delays account for queued backlog on each "
                          "(src zone → dst host) pipe instead of assuming "
                          "uncontended bandwidth")
    ens.add_argument("--realtime-score", action="store_true",
                     dest="realtime_scoring",
                     help="cost-aware scoring reads the backlog-discounted "
                          "inbound bandwidth (the DES realtime_bw arm's "
                          "estimator analog; implies --congestion)")
    cal = sub.add_parser(
        "calibrate",
        help="quantify the ensemble estimator against DES ground truth: "
             "same (trace, cluster, policy) through both engines, "
             "side-by-side metrics with relative errors for the static "
             "and congestion-aware transfer models",
    )
    cal.add_argument("--num-apps", type=int, dest="num_apps", default=50)
    cal.add_argument("--policy", default="cost-aware",
                     choices=["cost-aware", "first-fit", "best-fit",
                              "opportunistic"])
    cal.add_argument("--replicas", type=int, default=1,
                     help="ensemble replicas (1 + --perturb 0 = nominal "
                          "scenario; more = Monte-Carlo mean)")
    cal.add_argument("--perturb", type=float, default=0.0)
    cal.add_argument("--tick", type=float, default=5.0)
    cal.add_argument("--max-ticks", type=int, default=4096)
    cal.add_argument("--des-seeds", type=int, default=1,
                     help="run the DES ground truth at this many "
                          "consecutive seeds and calibrate against the "
                          "seed MEAN (records des_per_seed/des_spread; "
                          "the right comparison for the order-chaotic "
                          "packing arms — pair with --replicas > 1)")
    cal.add_argument("--cluster-seeds", type=int, default=1,
                     help="repeat the paired DES-vs-estimator comparison "
                          "on this many independently generated clusters "
                          "(fresh zone layout + bandwidth jitter) and "
                          "report mean/std rel err per metric — the "
                          "distributional fidelity mode for the "
                          "policy-seed-deterministic packing arms")
    cal.add_argument("--x64", action="store_true",
                     help="run the estimator in float64 like the DES "
                          "(CPU-side harness; tightens the static packing "
                          "arms' fidelity — see RESULTS.md)")
    _add_tick_order(cal, default="lifo")
    cal.add_argument("--realtime", action="store_true",
                     help="calibrate the bandwidth-aware variants against "
                          "each other: DES realtime_bw arm vs estimator "
                          "congestion + realtime scoring (cost-aware only)")
    at = sub.add_parser(
        "autotune",
        help="on-device scheduler-hyperparameter search: sweep the "
             "cost-aware score exponents cost^a / (norm^c × bw^b) over a "
             "grid, every candidate × Monte-Carlo replica in ONE device "
             "program with paired draws (the reference would need one OS "
             "process per cell)",
    )
    at.add_argument("--num-apps", type=int, dest="num_apps", default=50)
    at.add_argument("--replicas", type=int, default=32,
                    help="Monte-Carlo replicas per candidate")
    at.add_argument("--perturb", type=float, default=0.1)
    _add_tick_order(at)
    at.add_argument("--tick", type=float, default=5.0)
    at.add_argument("--max-ticks", type=int, default=2048)
    at.add_argument("--exponents", nargs="+", type=float,
                    default=[0.5, 1.0, 2.0],
                    help="candidate values for each of the three "
                         "exponents; the grid is their cube (default "
                         "3^3 = 27 candidates) plus the reference shape "
                         "(1,1,1) if absent")
    at.add_argument("--objective", choices=["makespan", "egress"],
                    default="makespan",
                    help="winner selection: mean makespan or mean egress")
    at.add_argument("--congestion", action="store_true",
                    help="score candidates under the link-contention "
                         "transfer model")
    cap = sub.add_parser(
        "capacity",
        help="on-device capacity planning: roll the workload out on K "
             "candidate cluster sizes × R Monte-Carlo replicas in ONE "
             "device program (paired draws) and report the cost/makespan "
             "trade-off per size — the reference re-forks a full "
             "experiment per cluster configuration",
    )
    cap.add_argument("--num-apps", type=int, dest="num_apps", default=50)
    cap.add_argument("--host-counts", nargs="+", type=int, required=True,
                     help="candidate cluster sizes (each ≤ --num-hosts); "
                          "prefixes of the generated cluster, so zone "
                          "balance is preserved")
    cap.add_argument("--replicas", type=int, default=32)
    cap.add_argument("--perturb", type=float, default=0.1)
    _add_tick_order(cap)
    cap.add_argument("--tick", type=float, default=5.0)
    cap.add_argument("--max-ticks", type=int, default=2048)
    cap.add_argument("--host-hourly-rate", type=float, default=0.932,
                     help="$/host-hour for the total-cost column (ref "
                          "alibaba/sim.py:44-45)")
    cap.add_argument("--slo-makespan", type=float, default=None,
                     help="pick the cheapest size whose MEAN makespan "
                          "meets this bound (default: cheapest that "
                          "finishes the workload)")
    cap.add_argument("--policy", default="cost-aware",
                     choices=["cost-aware", "first-fit", "best-fit",
                              "opportunistic"])
    cap.add_argument("--congestion", action="store_true",
                     help="roll out under the link-contention model")
    cap.add_argument("--realtime-score", action="store_true",
                     dest="realtime_scoring",
                     help="cost-aware scoring reads the backlog-discounted "
                          "inbound bandwidth (implies --congestion; "
                          "cost-aware arm only)")
    cap.add_argument("--faults", type=int, default=0, metavar="N",
                     help="resilience-aware sizing: each replica draws an "
                          "independent N-crash schedule, applied as the "
                          "SAME physical failure trace to every candidate "
                          "size (a crash on a host a small candidate "
                          "doesn't have is a no-op there)")
    cap.add_argument("--fault-horizon", type=float, default=None,
                     help="crash times drawn uniform in [0, horizon) "
                          "(default: tick x max-ticks)")
    cap.add_argument("--fault-mttr", type=float, default=None,
                     help="mean outage duration (Exp-distributed); "
                          "omit for permanent crashes")
    aps = sub.add_parser(
        "apps",
        help="on-device num-apps sweep: cost vs workload size for the "
             "three reference policy arms, each arm one device program "
             "over K app-counts × R Monte-Carlo replicas (paired draws) — "
             "the estimator analog of the DES num-apps experiment",
    )
    aps.add_argument("--app-counts", nargs="+", type=int, required=True,
                     help="candidate workload sizes (first N apps of the "
                          "trace, in submission order)")
    aps.add_argument("--replicas", type=int, default=32)
    aps.add_argument("--perturb", type=float, default=0.1)
    _add_tick_order(aps)
    aps.add_argument("--tick", type=float, default=5.0)
    aps.add_argument("--max-ticks", type=int, default=4096)
    aps.add_argument("--host-hourly-rate", type=float, default=0.932)
    aps.add_argument("--policies", nargs="+",
                     default=["opportunistic", "first-fit", "cost-aware"],
                     choices=["cost-aware", "first-fit", "best-fit",
                              "opportunistic"],
                     help="arms to sweep (default: the reference's three)")
    aps.add_argument("--congestion", action="store_true",
                     help="roll out under the link-contention model")
    sens = sub.add_parser(
        "sensitivity",
        help="sensitivity-gated dispatch experiment: score every tick's "
             "cost-aware decision against R availability-noise replicas "
             "(one batched kernel call — replica 0 IS the production "
             "decision), hold placements below a stability threshold for "
             "one tick, and report the egress/runtime/makespan deltas vs "
             "the identical un-gated arm on the same (trace, cluster, "
             "seed)s",
    )
    sens.add_argument("--num-apps", type=int, dest="num_apps", default=30)
    sens.add_argument("--policy", default="cost-aware",
                      choices=["cost-aware", "vbp", "best-fit"],
                      help="arm to gate: the canonical cost-aware policy, "
                           "the VBP arm (first-fit decreasing — the arm "
                           "whose egress headroom is 100x larger at "
                           "scale, VERDICT r04 item 2), or best-fit "
                           "decreasing")
    sens.add_argument("--replicas", type=int, default=256,
                      help="noise replicas per tick (the batched kernel's "
                           "native axis)")
    sens.add_argument("--perturb", type=float, default=0.05,
                      help="± multiplicative noise on the availability "
                           "snapshot")
    sens.add_argument("--threshold", type=float, default=0.7,
                      help="hold a placed task whose replica agreement is "
                           "below this fraction")
    sens.add_argument("--max-holds", type=int, default=1,
                      help="per-task hold budget; after this many holds "
                           "the nominal decision goes through")
    sens.add_argument("--des-seeds", type=int, default=5,
                      help="paired (gated vs baseline) DES runs at this "
                           "many consecutive seeds")
    sens.add_argument("--market", default=None, metavar="FILE",
                      help="attach a saved MarketSchedule "
                           "(tools/market_replay.py generate): both arms "
                           "score egress against the time-varying "
                           "price-scaled cost tensor — the round-11 "
                           "environment axis for the gate's "
                           "sign-stability")
    sch = sub.add_parser(
        "search",
        help="policy search at ensemble scale (pivot_tpu/search/): learn "
             "scoring weights (fit/egress/bw exponents + the risk pair) "
             "with CEM/ES — every generation's candidate population is "
             "one fused vmapped-rollout dispatch under the seeded "
             "market + preemption environment — then score learned vs "
             "hand-tuned on held-out seeds and report regret against "
             "the exact branch-and-bound oracle; prints the report JSON",
    )
    sch.add_argument("--method", default="cem", choices=["cem", "es"])
    sch.add_argument("--generations", type=int, default=6)
    sch.add_argument("--popsize", type=int, default=12,
                     help="candidate weight vectors per generation")
    sch.add_argument("--replicas", type=int, default=8,
                     help="Monte-Carlo rollouts per candidate (the "
                          "population dispatch is popsize x replicas "
                          "rows)")
    sch.add_argument("--hosts", type=int, default=12)
    sch.add_argument("--num-apps", type=int, dest="num_apps", default=8)
    sch.add_argument("--horizon", type=float, default=600.0)
    sch.add_argument("--seed", type=int, default=5)
    sch.add_argument("--holdout", type=int, default=2,
                     help="held-out environment seeds for the "
                          "learned-vs-hand-tuned comparison")
    sch.add_argument("--backend", default="rollout",
                     choices=["rollout", "sharded_rollout"],
                     help="fitness backend: single-device rows, or rows "
                          "host-sharded over the replica mesh "
                          "(bit-identical scores; the 10k+-row shape)")
    sch.add_argument("--bad-init", action="store_true",
                     help="start from the deliberately-bad vector (the "
                          "smoke gate's shape) instead of the hand-tuned "
                          "default")
    sch.add_argument("--no-oracle", action="store_true",
                     help="skip the small-instance regret section")
    sch.add_argument("--des-validate", action="store_true",
                     help="also play learned vs hand-tuned through the "
                          "exact DES under the first held-out market")
    sch.add_argument("--config", default=None, metavar="FILE",
                     help="JSON config overriding the flags above (the "
                          "smoke lane replays data/search/ci_seed.json)")
    sch.add_argument("--out", default=None, metavar="FILE",
                     help="write the report JSON here as well")
    srv = sub.add_parser(
        "serve",
        help="online serving layer: stream Poisson/trace job arrivals "
             "through G always-on scheduling sessions — bounded admission "
             "queue with backpressure, shared batched device dispatch "
             "(--device tpu), SLO-metered (p50/p95/p99 decision latency, "
             "queue depth, shed counts); prints the service report JSON",
    )
    srv.add_argument("--sessions", type=int, default=2, metavar="G",
                     help="concurrent scheduling sessions multiplexed "
                          "onto one batched dispatch")
    srv.add_argument("--jobs", type=int, default=50,
                     help="jobs to serve before shutdown")
    srv.add_argument("--arrival-rate", type=float, default=0.2,
                     help="Poisson arrivals per sim-second (with "
                          "--source trace, 0 replays the recorded "
                          "submit times instead)")
    srv.add_argument("--source", choices=["poisson", "trace"],
                     default="poisson",
                     help="'poisson': synthetic chain-DAG jobs at "
                          "exponential gaps; 'trace': the first Alibaba "
                          "trace window in --job-dir, re-timed onto a "
                          "Poisson process at --arrival-rate")
    srv.add_argument("--queue-depth", type=int, default=64,
                     help="admission queue bound (admitted-but-"
                          "incomplete jobs)")
    srv.add_argument("--backpressure",
                     choices=["block", "shed", "spill"], default="shed",
                     help="policy when the admission queue is full: "
                          "block the stream, shed with a recorded "
                          "reason, or spill to the next scheduler tick")
    srv.add_argument("--flush-after-us", type=float, default=5000.0,
                     help="dispatch-batcher deadline flush in "
                          "microseconds (0 = quiescence-only, the batch "
                          "grid driver's behavior)")
    srv.add_argument("--closed-loop", type=int, default=0, metavar="C",
                     help="closed-loop load generator: keep C jobs in "
                          "flight (each completion injects the next) "
                          "instead of the open-loop arrival stream")
    srv.add_argument("--pace", type=float, default=0.0,
                     help="wall pacing in sim-seconds per wall-second "
                          "(0 = replay as fast as the sessions can "
                          "schedule)")
    srv.add_argument("--policy", default="cost-aware",
                     choices=["cost-aware", "first-fit", "best-fit",
                              "opportunistic"],
                     help="placement arm every session runs")
    srv.add_argument("--shard-hosts", type=int, default=0, metavar="S",
                     help="2-D mesh serving (round 17): shard every "
                          "session's host axis over S mesh shards AND "
                          "coalesce co-pending dispatches over the "
                          "remaining devices' replica axis — one "
                          "shard_map(vmap) program per flush "
                          "(build_hybrid_mesh(host_parallel=S); needs "
                          "a device-backed policy and n_hosts "
                          "divisible by S).  0 = off")
    srv.add_argument("--fuse-spans", choices=["off", "slo"],
                     default="off",
                     help="serve-span mode: 'off' keeps per-tick "
                          "dispatch (the bit-parity default); 'slo' "
                          "fuses multi-tick spans between SLO "
                          "checkpoints — spans bounded by the "
                          "admission window, ONE decision latency "
                          "per span with span lengths in the snapshot")
    srv.add_argument("--no-ragged", action="store_true",
                     help="disable ragged continuous batching (round "
                          "18): by default co-pending mixed-horizon "
                          "spans are padded into a shared power-of-two "
                          "K-bucket and served as ONE device program "
                          "(trimmed per request, bit-identical); this "
                          "flag pins the round-17 same-shape-only "
                          "coalescing for A/B runs")
    srv.add_argument("--resident", action=argparse.BooleanOptionalAction,
                     default=False,
                     help="resident span carries (round 20): keep each "
                          "session's span state (availability, counts, "
                          "live mask) device-persistent between spans, "
                          "donated forward span to span, shipping only "
                          "sparse mirror-diff deltas instead of the "
                          "full O(H) re-staging (bit-identical "
                          "placements; the serve_resident bench row is "
                          "the A/B).  Skipped for policies without the "
                          "tier (numpy backends).  --no-resident pins "
                          "the re-staged path for A/B runs")
    srv.add_argument("--splice-tier", type=int, default=0,
                     help="with --resident: arrivals at or below this "
                          "priority tier may join a RUNNING span via "
                          "the checkpoint splice (re-run from the span-"
                          "entry carry clone, prefix bitwise-verified); "
                          "higher tiers wait for the flush boundary")
    srv.add_argument("--tenant-quota", type=float, default=0.0,
                     help="DRF tenant fairness within a tier: cap each "
                          "tenant's dominant-resource occupancy at "
                          "this share (0 < q <= 1) of its tier's "
                          "total, shedding/spilling over-quota "
                          "arrivals with reason 'tenant_quota'.  "
                          "0 = off")
    srv.add_argument("--tier-mix", default="",
                     help="multi-tenant arrival mix: comma-separated "
                          "tier weights, index = priority tier (0 = "
                          "serving, most important), e.g. "
                          "'0.25,0.35,0.40'.  Empty = single-tenant "
                          "tier-0 stream (the bit-parity default)")
    srv.add_argument("--tier-reserve", default="",
                     help="per-tier depth reservations, e.g. '0,2,4': "
                          "reserve[t] queue slots are off-limits to "
                          "tier t, so low tiers run out of queue first")
    srv.add_argument("--tier-policies", default="",
                     help="per-tier backpressure override, e.g. "
                          "'spill,shed,shed' (tier 0 lossless, lower "
                          "tiers shed).  Empty = --backpressure for all")
    srv.add_argument("--routing", choices=["rr", "least-loaded"],
                     default="rr",
                     help="job routing: deterministic round-robin (the "
                          "bit-parity default) or least-loaded over "
                          "inbox depth + recent decision latency")
    srv.add_argument("--preempt", action="store_true",
                     help="in-queue preemption: a high-tier arrival "
                          "meeting a full queue cancels an admitted-"
                          "but-unplaced lower-tier job (requeued to "
                          "the spill buffer) instead of degrading")
    srv.add_argument("--autoscale", default="", metavar="GMIN:GMAX",
                     help="SLO-driven session-pool autoscaling between "
                          "GMIN and GMAX (e.g. '1:8'): grow on p99 "
                          "decision-latency breach, drain-then-retire "
                          "on calm.  Empty = fixed pool")
    srv.add_argument("--slo-p99-ms", type=float, default=50.0,
                     help="tier-0 p99 decision-latency target (ms) the "
                          "autoscaler sizes the pool against")
    srv.add_argument("--mpc", action="store_true",
                     help="model-predictive serving (pivot_tpu/mpc): a "
                          "control thread forecasts the arrival stream, "
                          "scores hold/grow/drain/shed/weight actions "
                          "with seeded shadow rollouts of the predicted "
                          "horizon (ONE fused dispatch per window), "
                          "executes the predicted best, and promotes "
                          "tuned PolicyWeights through a canary→fleet "
                          "rollout with automatic SLO rollback")
    srv.add_argument("--mpc-pool", default="", metavar="GMIN:GMAX",
                     help="pool bounds the MPC planner moves between "
                          "(e.g. '1:8'); empty pins the pool at the "
                          "launch size (plan actions limited to "
                          "hold/shed/weights)")
    srv.add_argument("--mpc-horizon", type=float, default=300.0,
                     help="shadow-rollout horizon (sim seconds) each "
                          "decision window predicts over")
    srv.add_argument("--mpc-interval-ms", type=float, default=50.0,
                     help="wall milliseconds between MPC decision "
                          "windows")
    srv.add_argument("--mpc-replicas", type=int, default=4,
                     help="seeded shadow rollouts per candidate action")
    srv.add_argument("--mpc-max-regret", type=float, default=1.0,
                     help="oracle regret gate ($ from the proven "
                          "optimum) a tuned weight vector must pass "
                          "before canary eligibility")
    srv.add_argument("--mpc-dry-run", action="store_true",
                     help="score and record every MPC window but never "
                          "actuate — the observe-only A/B arm")
    srv.add_argument("--mpc-no-tune", action="store_true",
                     help="disable the background CEM weight tuner "
                          "(plan pool/shed actions only)")
    srv.add_argument("--trace-out", default="", metavar="PATH",
                     help="write the service's causal trace timeline "
                          "(every job's arrival→completion chain, "
                          "dispatch spans, autoscaler actions) as "
                          "Perfetto/Chrome trace_event JSON to PATH "
                          "(plus PATH.jsonl raw events); render with "
                          "tools/obs_report.py or load in ui.perfetto.dev")
    srv.add_argument("--metrics-out", default="", metavar="PATH",
                     help="export the unified metrics registry "
                          "(SLO counters, latency summaries, dispatch "
                          "mix, autoscaler actions) as Prometheus text "
                          "exposition to PATH (plus PATH.json)")
    srv.add_argument("--metrics-port", type=int, default=0, metavar="N",
                     help="serve the registry's Prometheus text "
                          "exposition LIVE at http://127.0.0.1:N"
                          "/metrics (stdlib HTTP thread, thread-guarded "
                          "snapshot; /metrics.json for the JSON form). "
                          "0 = off")
    srv.add_argument("--profile-dispatch", type=int, default=0,
                     metavar="N",
                     help="sampled device-dispatch profiler: time every "
                          "Nth kernel dispatch to completion "
                          "(block_until_ready) at the dispatch "
                          "boundaries, publishing per-family latency "
                          "summaries into the registry and 'device' "
                          "lane spans into --trace-out.  Placements "
                          "are bit-identical either way.  0 = off")
    sub.add_parser(
        "worker",
        help="resident what-if worker: serve repeated CLI requests from "
             "stdin in one warm process (one JSON argv array per line), "
             "amortizing JAX import, accelerator-backend init, and jit "
             "tracing across queries — see run_worker",
    )
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        parser.exit(1)
    if (
        getattr(args, "realtime_scoring", False)
        or getattr(args, "realtime", False)
    ) and args.policy != "cost-aware":
        parser.error(
            "--realtime-score/--realtime apply to the cost-aware arm only "
            "— no other policy scores on bandwidth"
        )
    if args.command == "serve" and args.tier_mix and (
        args.source == "trace" or args.closed_loop
    ):
        parser.error(
            "--tier-mix generates its own synthetic mixed-tier Poisson "
            "stream — it cannot be combined with --source trace or "
            "--closed-loop (the trace/closed-loop jobs would be "
            "silently replaced)"
        )
    if args.command == "serve":
        if args.mpc and args.autoscale:
            parser.error(
                "--mpc and --autoscale are mutually exclusive: two "
                "supervisors resizing the same pool would fight (the "
                "MPC planner subsumes the autoscaler's grow/drain)"
            )
        if not args.mpc and (
            args.mpc_pool or args.mpc_dry_run or args.mpc_no_tune
        ):
            parser.error("--mpc-* options require --mpc")
    if args.command == "serve" and args.device == "tpu":
        # Shared-dispatch serving needs deterministic routing, exactly
        # like --batch-runs: adaptive timing-based twin routing would
        # make batch membership (and, on f32 backends, placements)
        # nondeterministic.
        args.adaptive = False
    if (
        args.command == "serve"
        and getattr(args, "profile_dispatch", 0)
        and args.device != "tpu"
    ):
        # The profiler brackets DEVICE dispatches; a numpy/naive policy
        # has none, so the run would silently produce an empty census —
        # same fail-loud precedent as --batch-runs' device requirement.
        parser.error(
            "--profile-dispatch requires --device tpu (the numpy/naive "
            "policies dispatch no kernels for the profiler to bracket)"
        )
    if args.batch_runs > 1:
        if args.device != "tpu":
            parser.error(
                "--batch-runs coalesces device-kernel dispatches — it "
                "requires --device tpu"
            )
        if args.workers > 1:
            parser.error(
                "--batch-runs and --workers are mutually exclusive (the "
                "lock-step driver already runs the grid concurrently)"
            )
        # Adaptive routing is timing-dependent (it would make batch
        # membership — and, on f32 backends, placements — nondeterministic);
        # the lock-step driver needs the pure device path.
        args.adaptive = False
    if args.network == "native":
        from pivot_tpu import native

        if not native.available():
            parser.exit(
                1,
                "error: --network native needs a working g++ toolchain "
                "(native build failed); use --network python\n",
            )
    return args


def _list_traces(job_dir: str, limit=None) -> List[str]:
    if not os.path.isdir(job_dir):
        raise SystemExit(
            f"error: job directory {job_dir!r} does not exist "
            "(set --job-dir or the JOB_DIR env var)"
        )
    names = sorted(
        f for f in os.listdir(job_dir) if f.endswith((".npz", ".yaml", ".yml"))
    )
    if not names:
        raise SystemExit(f"error: no .npz/.yaml traces in {job_dir!r}")
    # Prefer npz when both forms of the same trace exist.
    stems = {}
    for n in names:
        stem = n.rsplit(".", 1)[0]
        if stem not in stems or n.endswith(".npz"):
            stems[stem] = n
    out = [os.path.join(job_dir, n) for n in sorted(stems.values())]
    return out[:limit] if limit else out


def _run_grid(specs: List[RunSpec], workers: int, batch_runs: int = 1):
    """Execute runs sequentially, across worker processes, or — with
    ``batch_runs > 1`` — in tick-synchronous lock-step chunks whose
    per-tick device dispatches coalesce (``runner.run_grid_lockstep``)."""
    if batch_runs > 1:
        _run_grid_batched(specs, batch_runs)
        return
    if workers <= 1:
        for spec in specs:
            _execute_run(spec)
        return
    # Pre-filter completed runs in the parent: forking a fresh interpreter
    # (full package + jax import) just to read one sentinel is not free.
    pending = []
    for spec in specs:
        if _is_complete(spec):
            logger.info("skipping completed run %s (%s)",
                        spec.policy.display_label, spec.data_dir)
        else:
            pending.append(spec)
    specs = pending
    import multiprocessing as mp

    def _join(procs):
        failed = []
        for q in procs:
            q.join()
            if q.exitcode != 0:
                failed.append(f"{q.name} (exit {q.exitcode})")
        if failed:
            # A worker died — e.g. an --audit invariant violation.  The
            # abort contract must hold for parallel sweeps exactly as it
            # does sequentially, not vanish into an ignored exitcode.
            raise RuntimeError("worker run(s) failed: " + ", ".join(failed))

    active = []
    for spec in specs:
        p = mp.Process(
            target=_execute_run, args=(spec,), name=spec.policy.display_label
        )
        p.start()
        active.append(p)
        if len(active) >= workers:
            _join(active)
            active = []
    _join(active)


def _run_grid_batched(specs: List[RunSpec], batch_runs: int) -> None:
    """Lock-step chunks of ≤ ``batch_runs`` same-policy runs.

    Grouped by policy label before chunking: only same-kernel, same-config
    ticks can share a vmapped dispatch, so batching across policy arms
    would park runs without ever coalescing them.  Completed runs are
    skipped up front (the same resume contract as the other drivers).
    """
    from pivot_tpu.experiments.runner import run_grid_lockstep

    pending = []
    for spec in specs:
        if _is_complete(spec):
            logger.info("skipping completed run %s (%s)",
                        spec.policy.display_label, spec.data_dir)
        else:
            pending.append(spec)
    if any(s.profile_dir for s in pending):
        logger.warning(
            "--profile-dir is ignored under --batch-runs (the device "
            "profiler is process-global; concurrent run sessions collide)"
        )
    by_label: dict = {}
    for spec in pending:
        by_label.setdefault(spec.policy.display_label, []).append(spec)
    for label, group in by_label.items():
        for i in range(0, len(group), batch_runs):
            chunk = group[i : i + batch_runs]
            stats: dict = {}
            run_grid_lockstep([_build_run(s) for s in chunk],
                              stats_out=stats)
            logger.info("lockstep chunk %s[%d:%d]: %s",
                        label, i, i + len(chunk), stats)


def _cluster_config(args) -> ClusterConfig:
    return ClusterConfig(
        n_hosts=args.n_hosts,
        shape=HostShape(args.cpus, args.mem, args.disk, args.gpus),
        seed=args.seed,
        network=args.network,
        executor=args.executor,
    )


def run_overall(args) -> str:
    exp_dir = args.resume or os.path.join(
        args.output_dir, "overall", str(int(time.time()))
    )
    os.makedirs(exp_dir, exist_ok=True)
    cluster_cfg = _cluster_config(args)
    traces = _list_traces(args.job_dir, args.trace_limit)
    policy_set = reference_policy_set(args.device, adaptive=args.adaptive)
    specs = [
        RunSpec(cluster_cfg, pc, trace, os.path.join(exp_dir, "data", str(i)),
                args.num_apps, args.scale_factor, args.seed,
                args.trace_events, args.profile_dir, args.audit)
        for i, trace in enumerate(traces)
        for pc in policy_set
    ]
    logger.info("overall: %d runs (%d traces × %d policies) → %s",
                len(specs), len(traces), len(policy_set), exp_dir)
    _run_grid(specs, args.workers, args.batch_runs)
    return exp_dir


def run_num_apps(args) -> str:
    exp_dir = args.resume or os.path.join(
        args.output_dir, "n_app", str(int(time.time()))
    )
    os.makedirs(exp_dir, exist_ok=True)
    cluster_cfg = _cluster_config(args)
    traces = _list_traces(args.job_dir, args.trace_limit)
    policy_set = reference_policy_set(args.device, adaptive=args.adaptive)
    specs = [
        RunSpec(cluster_cfg, pc, trace,
                os.path.join(exp_dir, "data", str(n), str(i)),
                n, args.scale_factor, args.seed,
                args.trace_events, args.profile_dir, args.audit)
        for n in args.num_apps_list
        for i, trace in enumerate(traces)
        for pc in policy_set
    ]
    logger.info("num-apps sweep: %d runs → %s", len(specs), exp_dir)
    _run_grid(specs, args.workers, args.batch_runs)
    return exp_dir


def _maybe_shard_sweep(sweep_fn, **static_kw):
    """Shard a what-if sweep over the devices (``ensemble.shard_sweep``),
    logging when an indivisible replica count forces the unsharded path."""
    import jax

    from pivot_tpu.parallel.ensemble import shard_sweep

    # Unsharded fallback runs in bounded 256-tick device calls (the
    # rollout_checkpointed default's rationale — remote-transport
    # friendly at +14 % over monolithic, vs +49 % for 64-tick segments);
    # shard_sweep owns — and logs — the fallback decision.
    return shard_sweep(sweep_fn, fallback_segment_ticks=256, **static_kw)


def _ensemble_setup(args):
    """(trace, schedule, workload, topo, avail0, storage_zones) — the one
    trace→device-inputs preamble shared by the ``ensemble`` and
    ``autotune`` subcommands."""
    from pivot_tpu.experiments.calibrate import ensemble_inputs_from_schedule
    from pivot_tpu.utils import enable_compilation_cache, ensure_live_backend
    from pivot_tpu.workload.trace import load_trace_jobs

    # Every caller is about to jit large ensemble programs; make compiles
    # survive the process (VERDICT r1: only the policy path cached before,
    # so each fresh CLI run repaid a full compile, e.g. the 362 s apps sweep),
    # and refuse to hang on a wedged tunnel (degrade to CPU instead).
    ensure_live_backend()
    enable_compilation_cache()

    trace = _list_traces(args.job_dir, 1)[0]
    schedule = load_trace_jobs(trace, args.scale_factor).take(args.num_apps)
    cluster = build_cluster(_cluster_config(args))
    workload, _slices, _arrivals, topo, avail0, storage_zones = (
        ensemble_inputs_from_schedule(schedule, cluster)
    )
    return trace, schedule, workload, topo, avail0, storage_zones


def run_ensemble(args) -> dict:
    """BASELINE config 5: N perturbed what-if replicas of a trace workload,
    scheduled entirely on-device, sharded over every available chip."""
    import json

    import numpy as np

    import jax

    from pivot_tpu.parallel.ensemble import rollout_chunked, sharded_rollout
    from pivot_tpu.parallel.mesh import build_mesh

    trace, schedule, workload, topo, avail0, storage_zones = (
        _ensemble_setup(args)
    )
    apps = schedule.apps
    key = jax.random.PRNGKey(args.seed)
    kw = dict(
        n_replicas=args.replicas,
        tick=args.tick,
        max_ticks=args.max_ticks,
        perturb=args.perturb,
        n_faults=args.faults,
        fault_horizon=args.fault_horizon,
        mttr=args.fault_mttr,
        policy=args.policy,
        congestion=args.congestion or args.realtime_scoring,
        realtime_scoring=args.realtime_scoring,
        tick_order=args.tick_order,
    )

    wall0 = time.perf_counter()
    single_device = (
        args.checkpoint
        or len(jax.devices()) == 1
        # Same rationale as shard_sweep's CPU fallback: a forced-host-
        # device "mesh" shares the physical cores — sharding over it
        # costs, not saves.
        or jax.default_backend() == "cpu"
    )
    replica_chunk = args.replica_chunk
    if replica_chunk and not single_device:
        # Chunking is a single-chip working-set remedy; on a real
        # multi-chip mesh the sharded path already splits the replica
        # axis across devices, and chunking would silently idle all but
        # one chip.
        logger.warning(
            "--replica-chunk ignored: %d-device mesh takes the sharded "
            "rollout path, which already splits replicas across chips",
            len(jax.devices()),
        )
        replica_chunk = 0
    if single_device:
        # Without --replica-chunk: segmented execution, one bounded
        # device call per 256 ticks (a monolithic while_loop over
        # thousands of ticks is one minutes-long execution, which remote
        # single-chip transports may kill; 256 keeps calls ~1.4 s at the
        # canonical scale at +14 % over monolithic, vs +49 % for the old
        # 64-tick segments).  With --replica-chunk and no --checkpoint:
        # one MONOLITHIC call per chunk — that execution shape is where
        # the chunking win lives (RESULTS.md), at the cost of unbounded
        # per-call duration; see the flag's help text.
        res = rollout_chunked(
            key, avail0, workload, topo, storage_zones, args.checkpoint,
            replica_chunk, **kw
        )
        jax.block_until_ready(res)
    else:
        mesh = build_mesh(len(jax.devices()), ("replica", "host"))
        res = sharded_rollout(
            mesh, key, avail0, workload, topo, storage_zones, **kw
        )
        jax.block_until_ready(res)
    wall = time.perf_counter() - wall0

    mk = np.asarray(res.makespan)
    eg = np.asarray(res.egress_cost)
    ih = np.asarray(res.instance_hours)
    summary = {
        "trace": os.path.basename(trace),
        "n_apps": len(apps),
        "n_tasks": workload.n_tasks,
        "n_hosts": args.n_hosts,
        "replicas": args.replicas,
        "replica_chunk": replica_chunk,
        "perturb": args.perturb,
        "policy": args.policy,
        "faults": args.faults,
        "fault_horizon": args.fault_horizon,
        "fault_mttr": args.fault_mttr,
        "congestion": args.congestion or args.realtime_scoring,
        "realtime_scoring": args.realtime_scoring,
        "devices": len(jax.devices()),
        "makespan_mean": float(mk.mean()),
        "makespan_p5": float(np.percentile(mk, 5)),
        "makespan_p95": float(np.percentile(mk, 95)),
        "egress_mean": float(eg.mean()),
        "egress_p95": float(np.percentile(eg, 95)),
        "instance_hours_mean": float(ih.mean()),
        "instance_hours_p95": float(np.percentile(ih, 95)),
        "unfinished_max": int(np.asarray(res.n_unfinished).max()),
        "wall_s": round(wall, 3),
        "replica_rollouts_per_sec": round(args.replicas / wall, 2),
    }
    out_dir = os.path.join(args.output_dir, "ensemble", str(int(time.time())))
    os.makedirs(out_dir, exist_ok=True)
    np.savez(
        os.path.join(out_dir, "rollout.npz"),
        makespan=mk,
        egress_cost=eg,
        instance_hours=ih,
        finish_time=np.asarray(res.finish_time),
        placement=np.asarray(res.placement),
    )
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    from pivot_tpu.experiments.plots import plot_ensemble_distribution

    plot_ensemble_distribution(out_dir)
    print(json.dumps(summary))
    return summary


def run_calibrate(args) -> dict:
    """Estimator-fidelity report: DES vs ensemble on one (trace, policy)."""
    import json

    from pivot_tpu.experiments.calibrate import calibrate

    trace = _list_traces(args.job_dir, 1)[0]
    multi_cluster = args.cluster_seeds > 1
    report = calibrate(
        trace,
        cluster=None if multi_cluster else build_cluster(_cluster_config(args)),
        cluster_config=_cluster_config(args) if multi_cluster else None,
        n_apps=args.num_apps,
        policy=args.policy,
        scale_factor=args.scale_factor,
        seed=args.seed,
        tick=args.tick,
        max_ticks=args.max_ticks,
        replicas=args.replicas,
        perturb=args.perturb,
        realtime=args.realtime,
        x64=args.x64,
        des_seeds=args.des_seeds,
        cluster_seeds=args.cluster_seeds,
        tick_order=args.tick_order,
    )
    out_dir = os.path.join(args.output_dir, "calibrate", str(int(time.time())))
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    # Plot path AFTER the JSON document — the first stdout line is the
    # report (pipe-to-jq contract, same as every other subcommand).
    if "clusters" in report or "des_per_seed" in report:
        from pivot_tpu.experiments.plots import plot_calibration_spread

        print(plot_calibration_spread(out_dir))
    return report


def run_sensitivity(args) -> dict:
    """Paired DES experiment: sensitivity-gated cost-aware dispatch vs the
    identical un-gated arm (see ``pivot_tpu.sched.sensitivity``).

    Each seed runs BOTH arms on the same (trace, cluster): the baseline
    is the same ``TpuCostAwarePolicy`` configuration the gated wrapper
    drives, so the only degree of freedom is the hold rule.  The report
    is the measured answer to "does deferring low-stability placements
    one tick help?" — mean signed deltas with per-seed detail, plus the
    gate's own telemetry (holds, stability profile).
    """
    import json

    import numpy as np

    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.sched.sensitivity import SensitivityGatedCostAware
    from pivot_tpu.sched.tpu import (
        TpuBestFitPolicy,
        TpuCostAwarePolicy,
        TpuFirstFitPolicy,
    )

    trace = _list_traces(args.job_dir, 1)[0]
    policy_name = getattr(args, "policy", "cost-aware")
    market = None
    if getattr(args, "market", None):
        from pivot_tpu.infra.market import MarketSchedule

        market = MarketSchedule.load(args.market)
    # Recorded in the report: a reader comparing against the calibrate /
    # overall arms must be able to see which packing variant ran (VBP is
    # first-fit DEcreasing per the reference, config.py:111; best-fit's
    # canonical arm is plain, decreasing=False).
    decreasing = None
    if policy_name == "vbp":
        decreasing = True

        def make_inner():
            return TpuFirstFitPolicy(decreasing=True)
    elif policy_name == "best-fit":
        decreasing = False

        def make_inner():
            return TpuBestFitPolicy(decreasing=False)
    else:
        canonical = dict(bin_pack="first-fit", sort_tasks=True,
                         sort_hosts=True)

        def make_inner():
            return TpuCostAwarePolicy(**canonical)

    def one(seed: int, gated: bool):
        cluster = build_cluster(_cluster_config(args))
        if gated:
            pol = SensitivityGatedCostAware(
                threshold=args.threshold,
                n_replicas=args.replicas,
                perturb=args.perturb,
                max_holds=args.max_holds,
                noise_seed=seed,
                inner=make_inner(),
            )
        else:
            pol = make_inner()
        run = ExperimentRun(
            f"sensitivity-{'gated' if gated else 'base'}-{seed}",
            cluster, pol, trace,
            output_size_scale_factor=args.scale_factor,
            n_apps=args.num_apps, seed=seed, interval=5.0,
            market=market,
        )
        t0 = time.perf_counter()
        summary = run.run()
        wall = time.perf_counter() - t0
        from pivot_tpu.experiments.calibrate import des_metrics

        return des_metrics(summary, run.schedule), (
            pol.summary() if gated else None
        ), round(wall, 2)

    per_seed = []
    for s in range(args.seed, args.seed + args.des_seeds):
        base, _, base_wall = one(s, gated=False)
        gated, gate_stats, gated_wall = one(s, gated=True)
        per_seed.append({
            "seed": s, "baseline": base, "gated": gated,
            "gate": gate_stats,
            # The gate's price at this scale: paired run walls plus the
            # time inside the batched sensitivity calls themselves
            # (gate.sensitivity_wall_s / _per_tick_s).
            "baseline_wall_s": base_wall,
            "gated_wall_s": gated_wall,
            "delta": {
                k: gated[k] - base[k] for k in base
            },
        })
    keys = ("avg_runtime", "egress_cost", "instance_hours", "makespan")
    deltas = {
        k: {
            "mean": float(np.mean([r["delta"][k] for r in per_seed])),
            "std": float(np.std([r["delta"][k] for r in per_seed])),
            "mean_rel": float(
                np.mean([
                    r["delta"][k] / max(abs(r["baseline"][k]), 1e-12)
                    for r in per_seed
                ])
            ),
        }
        for k in keys
    }
    report = {
        "trace": trace,
        "policy": policy_name,
        **({"market": os.path.abspath(args.market)}
           if getattr(args, "market", None) else {}),
        **({"decreasing": decreasing} if decreasing is not None else {}),
        "n_hosts": args.n_hosts,
        "n_apps": args.num_apps,
        "gate_cost": {
            "mean_baseline_wall_s": float(
                np.mean([r["baseline_wall_s"] for r in per_seed])
            ),
            "mean_gated_wall_s": float(
                np.mean([r["gated_wall_s"] for r in per_seed])
            ),
            "mean_sensitivity_wall_per_tick_s": float(np.mean([
                r["gate"]["sensitivity_wall_per_tick_s"]
                for r in per_seed
                if r["gate"] and r["gate"]["sensitivity_wall_per_tick_s"]
                is not None
            ])) if per_seed else None,
        },
        "replicas": args.replicas,
        "perturb": args.perturb,
        "threshold": args.threshold,
        "max_holds": args.max_holds,
        "des_seeds": args.des_seeds,
        "delta_gated_minus_baseline": deltas,
        "per_seed": per_seed,
    }
    out_dir = os.path.join(
        args.output_dir, "sensitivity", str(int(time.time()))
    )
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    return report


def run_autotune(args) -> dict:
    """K-candidate × R-replica scheduler-hyperparameter grid search as one
    device program (``score_param_sweep``); candidates share the same
    Monte-Carlo draws, so comparisons are paired."""
    import itertools
    import json

    import numpy as np

    import jax

    from pivot_tpu.parallel.ensemble import score_param_sweep

    trace, schedule, workload, topo, avail0, storage_zones = (
        _ensemble_setup(args)
    )
    grid = list(itertools.product(args.exponents, repeat=3))
    if (1.0, 1.0, 1.0) not in grid:
        # The reference shape is always evaluated so summary["reference"]
        # reports measured scores, never a nearest-neighbor stand-in.
        grid.append((1.0, 1.0, 1.0))
    grid = np.array(grid, dtype=np.float32)  # [K, 3] (w_cost, w_bw, w_norm)

    wall0 = time.perf_counter()
    sweep = _maybe_shard_sweep(
        score_param_sweep, n_replicas=args.replicas, tick=args.tick,
        max_ticks=args.max_ticks, perturb=args.perturb,
        congestion=args.congestion, tick_order=args.tick_order,
    )
    res = sweep(
        jax.random.PRNGKey(args.seed), avail0, workload, topo, storage_zones,
        grid,
    )
    jax.block_until_ready(res)
    wall = time.perf_counter() - wall0

    mk = np.asarray(res.makespan).mean(axis=1)  # [K]
    eg = np.asarray(res.egress_cost).mean(axis=1)
    unfinished = np.asarray(res.n_unfinished).max(axis=1)
    objective = mk if args.objective == "makespan" else eg
    # A candidate that cannot finish the workload inside the horizon is
    # not a winner no matter its (understated) objective.
    objective = np.where(unfinished > 0, np.inf, objective)
    order = np.argsort(objective, kind="stable")
    ref_idx = int(np.where((grid == 1.0).all(axis=1))[0][0])

    candidates = [
        {
            "exponents": [float(x) for x in grid[k]],
            "makespan_mean": float(mk[k]),
            "egress_mean": float(eg[k]),
            "unfinished_max": int(unfinished[k]),
        }
        for k in order
    ]
    if np.isfinite(objective).any():
        best = candidates[0]
    else:
        # Every candidate hit the horizon: the means are truncated-rollout
        # understatements and no winner exists.
        logger.warning(
            "all %d candidates left tasks unfinished at the horizon — "
            "no winner; raise --max-ticks", len(grid),
        )
        best = None
    summary = {
        "trace": os.path.basename(trace),
        "n_apps": len(schedule.apps),
        "n_tasks": workload.n_tasks,
        "n_hosts": args.n_hosts,
        "replicas": args.replicas,
        "perturb": args.perturb,
        "congestion": args.congestion,
        "objective": args.objective,
        "grid_size": len(grid),
        "rollouts": len(grid) * args.replicas,
        "wall_s": round(wall, 3),
        "best": best,
        "reference": {
            "exponents": [float(x) for x in grid[ref_idx]],
            "makespan_mean": float(mk[ref_idx]),
            "egress_mean": float(eg[ref_idx]),
            "unfinished_max": int(unfinished[ref_idx]),
        },
        "candidates": candidates,
    }
    out_dir = os.path.join(args.output_dir, "autotune", str(int(time.time())))
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    # The full table is in summary.json; print everything but it.
    print(json.dumps({k: v for k, v in summary.items() if k != "candidates"}))
    return summary


def run_capacity(args) -> dict:
    """K cluster sizes × R replicas in one device program; report the
    financial cost per size and pick the cheapest candidate that meets
    the makespan SLO (if any).

    Two cost columns: ``busy_cost_mean`` bills busy instance-hours — the
    reference's financial model (``alibaba/sim.py:132-165``), but nearly
    invariant to cluster size since busy-hours ≈ total task work — and
    ``total_cost_mean`` bills PROVISIONED capacity (hosts × makespan ×
    rate + egress), the quantity a capacity decision actually trades
    against the SLO.  Selection uses the provisioned cost.
    """
    import json

    import numpy as np

    import jax

    from pivot_tpu.parallel.ensemble import capacity_grid, capacity_sweep

    if max(args.host_counts) > args.n_hosts:
        raise SystemExit(
            f"error: --host-counts max {max(args.host_counts)} exceeds "
            f"--num-hosts {args.n_hosts}"
        )
    trace, schedule, workload, topo, avail0, storage_zones = (
        _ensemble_setup(args)
    )
    grid = capacity_grid(avail0, args.host_counts)

    wall0 = time.perf_counter()
    sweep = _maybe_shard_sweep(
        capacity_sweep,
        n_replicas=args.replicas, tick=args.tick, max_ticks=args.max_ticks,
        perturb=args.perturb, policy=args.policy,
        congestion=args.congestion or args.realtime_scoring,
        realtime_scoring=args.realtime_scoring, n_faults=args.faults,
        fault_horizon=args.fault_horizon, mttr=args.fault_mttr,
        tick_order=args.tick_order,
    )
    res = sweep(
        jax.random.PRNGKey(args.seed), grid, workload, topo, storage_zones,
    )
    jax.block_until_ready(res)
    wall = time.perf_counter() - wall0

    mk = np.asarray(res.makespan)  # [K, R]
    eg = np.asarray(res.egress_cost)
    ih = np.asarray(res.instance_hours)
    unfinished = np.asarray(res.n_unfinished).max(axis=1)
    # An unfinished candidate's makespan (max finish over DONE tasks only)
    # understates reality; clamp it to the truncation horizon so the
    # reported numbers are an honest lower bound, not an artificially
    # cheap-and-fast point.
    mk_mean = np.where(
        unfinished > 0,
        np.maximum(mk.mean(axis=1), args.tick * args.max_ticks),
        mk.mean(axis=1),
    )
    hosts = np.asarray(args.host_counts, dtype=np.float64)
    busy_cost = ih.mean(axis=1) * args.host_hourly_rate + eg.mean(axis=1)
    provisioned_hours = hosts * mk_mean / 3600.0
    total_cost = provisioned_hours * args.host_hourly_rate + eg.mean(axis=1)

    candidates = [
        {
            "hosts": int(n),
            "makespan_mean": float(mk_mean[k]),
            "makespan_p95": float(np.percentile(mk[k], 95)),
            "egress_mean": float(eg[k].mean()),
            "instance_hours_mean": float(ih[k].mean()),
            "busy_cost_mean": float(busy_cost[k]),
            "provisioned_hours_mean": float(provisioned_hours[k]),
            "total_cost_mean": float(total_cost[k]),
            "unfinished_max": int(unfinished[k]),
        }
        for k, n in enumerate(args.host_counts)
    ]
    feasible = [
        c for c in candidates
        if c["unfinished_max"] == 0
        and (args.slo_makespan is None
             or c["makespan_mean"] <= args.slo_makespan)
    ]
    best = min(feasible, key=lambda c: c["total_cost_mean"], default=None)
    if best is None:
        logger.warning(
            "no candidate size finishes the workload%s — raise "
            "--host-counts or --max-ticks",
            "" if args.slo_makespan is None else " within the SLO",
        )
    summary = {
        "trace": os.path.basename(trace),
        "n_apps": len(schedule.apps),
        "n_tasks": workload.n_tasks,
        "policy": args.policy,
        "replicas": args.replicas,
        "perturb": args.perturb,
        "congestion": args.congestion or args.realtime_scoring,
        "realtime_scoring": args.realtime_scoring,
        "faults": args.faults,
        "fault_horizon": args.fault_horizon,
        "fault_mttr": args.fault_mttr,
        "host_hourly_rate": args.host_hourly_rate,
        "slo_makespan": args.slo_makespan,
        "rollouts": len(args.host_counts) * args.replicas,
        "wall_s": round(wall, 3),
        "best": best,
        "candidates": candidates,
    }
    out_dir = os.path.join(args.output_dir, "capacity", str(int(time.time())))
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    from pivot_tpu.experiments.plots import plot_capacity_frontier

    plot_capacity_frontier(out_dir)
    print(json.dumps(summary))
    return summary


def run_apps(args) -> dict:
    """Workload-size sweep per policy arm: one device program per arm over
    K app-counts × R replicas; writes per-(arm, count) metrics and the
    financial-cost figure (the reference's num-apps analysis,
    ``alibaba/sim.py:132-165,199-230``, as an on-device estimate)."""
    import json

    import numpy as np

    import jax

    from pivot_tpu.parallel.ensemble import workload_sweep

    args.num_apps = max(args.app_counts)
    trace, schedule, workload, topo, avail0, storage_zones = (
        _ensemble_setup(args)
    )
    n_loaded = len(schedule.apps)
    # Sorted + deduped: the cost-vs-#apps lines connect points in row
    # order, so unsorted user input would zigzag the figure.
    counts = sorted({n for n in args.app_counts if n <= n_loaded})
    if len(counts) < len(set(args.app_counts)):
        logger.warning("trace has only %d apps — dropping larger counts",
                       n_loaded)

    wall0 = time.perf_counter()
    arms = {}
    for policy in args.policies:
        sweep = _maybe_shard_sweep(
            workload_sweep, n_replicas=args.replicas,
            tick=args.tick, max_ticks=args.max_ticks, perturb=args.perturb,
            policy=policy, congestion=args.congestion,
            tick_order=args.tick_order,
        )
        res = sweep(
            jax.random.PRNGKey(args.seed), avail0, workload, topo,
            storage_zones, counts,
        )
        jax.block_until_ready(res)
        eg = np.asarray(res.egress_cost)  # [K, R]
        ih = np.asarray(res.instance_hours)
        mk = np.asarray(res.makespan)
        unfinished = np.asarray(res.n_unfinished).max(axis=1)
        # Same truncation clamp as run_capacity: an arm that strands tasks
        # at the horizon reports max-finish-over-DONE only, which would
        # make the WORST arm look fastest in the cross-arm comparison.
        mk_mean = np.where(
            unfinished > 0,
            np.maximum(mk.mean(axis=1), args.tick * args.max_ticks),
            mk.mean(axis=1),
        )
        arms[policy] = [
            {
                "n_apps": int(n),
                "makespan_mean": float(mk_mean[k]),
                "egress_mean": float(eg[k].mean()),
                "instance_hours_mean": float(ih[k].mean()),
                "host_cost_mean": float(
                    ih[k].mean() * args.host_hourly_rate
                ),
                "unfinished_max": int(unfinished[k]),
            }
            for k, n in enumerate(counts)
        ]
    wall = time.perf_counter() - wall0

    summary = {
        "trace": os.path.basename(trace),
        "n_hosts": args.n_hosts,
        "app_counts": counts,
        "replicas": args.replicas,
        "perturb": args.perturb,
        "congestion": args.congestion,
        "host_hourly_rate": args.host_hourly_rate,
        "rollouts": len(counts) * args.replicas * len(args.policies),
        "wall_s": round(wall, 3),
        "arms": arms,
    }
    out_dir = os.path.join(args.output_dir, "apps", str(int(time.time())))
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    from pivot_tpu.experiments.plots import plot_apps_cost

    plot_apps_cost(out_dir)
    print(json.dumps(summary))
    return summary


def run_serve_stream(args) -> dict:
    """The online serving layer (``pivot_tpu.serve``): G always-on
    scheduling sessions fed by a streaming arrival source through a
    bounded admission queue; device-backed sessions share ONE vmapped
    placement dispatch per tick through idle-aware, deadline-flushed
    ``DispatchBatcher`` slots.  Prints (and writes) the service report:
    SLO snapshot (decision-latency percentiles, queue depth, admission /
    shed counters), batcher coalescing stats, per-session metrics."""
    import json

    from pivot_tpu.serve import (
        AutoscaleConfig,
        ServeDriver,
        ServeSession,
        closed_loop_source,
        mixed_tier_arrivals,
        poisson_arrivals,
        synthetic_app_factory,
        trace_arrivals,
    )

    arm = dict(
        name=args.policy, device=args.device, adaptive=args.adaptive,
    )
    if args.policy == "cost-aware":
        arm.update(bin_pack="first-fit", sort_tasks=True, sort_hosts=True)
    elif args.policy == "first-fit":
        arm.update(decreasing=True)  # the reference's VBP arm
    pcfg = PolicyConfig(**arm)

    # 2-D mesh serving (round 17): --shard-hosts S builds the hybrid
    # replica × host mesh once, shards every session policy's host axis
    # over it, and hands it to the driver so coalesced flushes run the
    # composed shard_map(vmap(...)) program.
    mesh = None
    if args.shard_hosts:
        if args.device != "tpu":
            raise SystemExit(
                "--shard-hosts needs a device-backed policy "
                "(--device tpu); numpy policies have no sharded form"
            )
        from pivot_tpu.parallel.mesh import build_hybrid_mesh

        mesh = build_hybrid_mesh(host_parallel=args.shard_hosts)
    fuse = "slo" if args.fuse_spans == "slo" else False

    def make_session(label):
        policy = make_policy(pcfg)
        if mesh is not None:
            policy.enable_sharding(mesh)
        return ServeSession(
            label,
            build_cluster(_cluster_config(args)),
            policy,
            seed=args.seed,
            fuse_spans=fuse,
        )

    sessions = [make_session(f"session-{g}") for g in range(args.sessions)]
    flush_after = (args.flush_after_us or 0) / 1e6 or None

    def _csv(text, cast):
        return tuple(cast(x) for x in text.split(",")) if text else None

    autoscale = None
    if args.autoscale:
        try:
            g_min, g_max = (int(x) for x in args.autoscale.split(":"))
        except ValueError:
            raise SystemExit(
                f"--autoscale wants GMIN:GMAX, got {args.autoscale!r}"
            )
        autoscale = AutoscaleConfig(
            g_min=g_min, g_max=g_max, slo_p99_s=args.slo_p99_ms / 1e3,
        )
    mpc = None
    if args.mpc:
        from pivot_tpu.mpc import MpcConfig

        if args.mpc_pool:
            try:
                mpc_min, mpc_max = (
                    int(x) for x in args.mpc_pool.split(":")
                )
            except ValueError:
                raise SystemExit(
                    f"--mpc-pool wants GMIN:GMAX, got {args.mpc_pool!r}"
                )
        else:
            mpc_min = mpc_max = args.sessions
        tier_weights = _csv(args.tier_mix, float)
        mpc = MpcConfig(
            check_interval_s=args.mpc_interval_ms / 1e3,
            horizon=args.mpc_horizon,
            n_replicas=args.mpc_replicas,
            seed=args.seed or 0,
            g_min=mpc_min,
            g_max=mpc_max,
            n_tiers=max(len(tier_weights), 1) if tier_weights else 1,
            max_regret=args.mpc_max_regret,
            dry_run=args.mpc_dry_run,
            tune=not args.mpc_no_tune,
        )
    # Observability plane (round 14): --trace-out turns on causal task
    # tracing (zero-cost otherwise), --metrics-out attaches the unified
    # registry; the report then carries the metrics snapshot inline.
    # Round 15: --profile-dispatch N samples device dispatches; a live
    # --metrics-port endpoint serves the registry mid-soak.
    from pivot_tpu.obs import DispatchProfiler, MetricsRegistry, Tracer

    tracer = Tracer() if args.trace_out else None
    registry = (
        MetricsRegistry()
        if args.metrics_out or args.metrics_port else None
    )
    profiler = (
        DispatchProfiler(
            sample_every=args.profile_dispatch, seed=args.seed or 0,
            registry=registry,
        )
        if args.profile_dispatch else None
    )
    driver = ServeDriver(
        sessions,
        queue_depth=args.queue_depth,
        backpressure=args.backpressure,
        flush_after=flush_after,
        tier_reserve=_csv(args.tier_reserve, int),
        tier_policies=_csv(args.tier_policies, str),
        routing=args.routing.replace("-", "_"),
        preempt=args.preempt,
        session_factory=(
            make_session if (autoscale or mpc) else None
        ),
        autoscale=autoscale,
        mpc=mpc,
        tracer=tracer,
        registry=registry,
        profiler=profiler,
        mesh=mesh,
        tenant_quota=args.tenant_quota or None,
        ragged=not args.no_ragged,
        resident=args.resident,
        splice_tier=args.splice_tier,
    )
    metrics_server = None
    if args.metrics_port:
        # Live scrape endpoint: every GET re-publishes the service's
        # current state into the registry (cv-snapshotted) and renders
        # the text exposition under the registry lock.
        from pivot_tpu.obs import MetricsHTTPServer

        def _render_text() -> str:
            driver.publish_metrics(registry)
            return registry.to_prometheus()

        def _render_json() -> dict:
            return driver.publish_metrics(registry) or {}

        metrics_server = MetricsHTTPServer(
            _render_text, _render_json, port=args.metrics_port
        )
        metrics_server.start()
    if args.closed_loop:
        arrivals = closed_loop_source(
            driver, synthetic_app_factory(seed=args.seed),
            args.closed_loop, args.jobs,
        )
    elif args.tier_mix:
        arrivals = mixed_tier_arrivals(
            args.arrival_rate, args.jobs,
            weights=_csv(args.tier_mix, float),
            seed=args.seed,
        )
    elif args.source == "trace":
        arrivals = trace_arrivals(
            _list_traces(args.job_dir, 1)[0],
            n_apps=args.jobs,
            scale_factor=args.scale_factor,
            rate=args.arrival_rate or None,
            seed=args.seed,
        )
    else:
        arrivals = poisson_arrivals(
            args.arrival_rate, args.jobs, seed=args.seed
        )
    wall0 = time.perf_counter()
    try:
        report = driver.run(arrivals, pace=args.pace or None)
    finally:
        if metrics_server is not None:
            metrics_server.stop()
    wall = time.perf_counter() - wall0
    report["wall_s"] = round(wall, 3)
    report["decisions_per_sec"] = round(
        report["slo"]["counters"]["decisions"] / max(wall, 1e-9), 1
    )
    out_dir = os.path.join(args.output_dir, "serve", str(int(time.time())))
    os.makedirs(out_dir, exist_ok=True)
    if tracer is not None:
        tracer.save_perfetto(args.trace_out)
        tracer.save_jsonl(args.trace_out + ".jsonl")
        report["trace_out"] = args.trace_out
        report["trace_events"] = len(tracer.events)
    if metrics_server is not None:
        report["metrics_port"] = metrics_server.port
    if registry is not None and args.metrics_out:
        driver.publish_metrics(registry)
        registry.save_prometheus(args.metrics_out)
        registry.save_json(args.metrics_out + ".json")
        report["metrics_out"] = args.metrics_out
    with open(os.path.join(out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    return report


_serving = False


def run_worker() -> None:
    """Resident what-if worker (VERDICT r02 item 7): one process serves
    many CLI requests, paying the per-process costs the persistent
    compilation cache cannot remove — JAX import, accelerator-backend
    init over the tunnel (~8–10 s measured in RESULTS.md), and jit
    tracing of the rollout programs (~2 s at the canonical scale) —
    exactly ONCE.  After the first request, repeated what-if queries run
    at device-wall speed.

    Protocol: one JSON argv array per stdin line, exactly as the
    one-shot CLI would receive it, e.g.
    ``["--num-hosts", "100", "ensemble", "--num-apps", "25"]``.  The
    request's normal JSON report prints to stdout, followed by one
    sentinel line ``{"served": n, "ok": ..., "wall_s": ...}``.  Id
    counters reset per request, so every report is bit-identical to the
    same request in a fresh process (given warm = cold programs, which
    the jit cache guarantees).  ``quit`` or EOF ends the loop.
    """
    import json
    import sys as _sys

    from pivot_tpu.utils import reset_ids

    global _serving
    if _serving:
        # A request whose parsed command is `worker` dispatches back here
        # through main(); reading stdin recursively would deadlock the
        # worker.  (Checked on the PARSED command — an argv merely
        # containing the string "worker", e.g. an --output-dir value, is
        # a legitimate request.)
        raise RuntimeError("nested worker requests are not allowed")
    _serving = True
    served = 0
    try:
        for line in _sys.stdin:
            line = line.strip()
            if line == "quit":
                break
            if not line:
                continue
            t0 = time.perf_counter()
            ok = True
            try:
                req = json.loads(line)
                if not isinstance(req, list) or not all(
                    isinstance(a, str) for a in req
                ):
                    raise ValueError(
                        "request must be a JSON array of argv strings"
                    )
                reset_ids()  # fresh-process determinism per request
                main(req)
            except SystemExit as exc:  # argparse rejection — keep serving
                ok = (exc.code or 0) == 0
            except Exception as exc:  # noqa: BLE001 — request isolation
                ok = False
                print(
                    json.dumps(
                        {"error": f"{type(exc).__name__}: {exc}"[:300]}
                    ),
                    flush=True,
                )
            served += 1
            print(
                json.dumps(
                    {
                        "served": served,
                        "ok": ok,
                        "wall_s": round(time.perf_counter() - t0, 3),
                    }
                ),
                flush=True,
            )
    finally:
        _serving = False


def run_search_cli(args) -> None:
    """The ``search`` subcommand: run the learn → hold out → regret
    pipeline (``pivot_tpu/experiments/search.py``) and print/emit the
    report JSON."""
    import json

    from pivot_tpu.experiments.search import (
        load_config,
        run_search_experiment,
    )

    kw = dict(
        method=args.method,
        generations=args.generations,
        popsize=args.popsize,
        seed=args.seed,
        n_hosts=args.hosts,
        n_apps=args.num_apps,
        horizon=args.horizon,
        n_replicas=args.replicas,
        holdout=args.holdout,
        backend=args.backend,
        bad_init=args.bad_init,
        oracle=not args.no_oracle,
        des_validate=args.des_validate,
    )
    if args.config:
        kw.update(load_config(args.config))
    mesh = None
    if kw["backend"] == "sharded_rollout":
        import jax

        from pivot_tpu.parallel.mesh import replica_mesh

        mesh = replica_mesh(len(jax.devices()))
    report = run_search_experiment(mesh=mesh, **kw)
    text = json.dumps(report, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")


def main(argv=None) -> None:
    # Respect an explicit JAX_PLATFORMS pin at the config level too: the
    # accelerator site package force-updates jax_platforms at interpreter
    # start (beating the env var), which would make a CPU-pinned CLI run
    # dial — and hang on — the single-tenant accelerator tunnel anyway.
    # Same hard override as tests/conftest.py.
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    args = parse_args(argv)
    if args.command == "worker":
        run_worker()
        return
    if args.command == "serve":
        run_serve_stream(args)
        return
    if args.command == "search":
        run_search_cli(args)
        return
    from pivot_tpu.experiments import plots

    if args.command == "overall":
        exp_dir = run_overall(args)
        print(plots.plot_overall(exp_dir))
        print(plots.plot_transfers(exp_dir))
    elif args.command == "ensemble":
        run_ensemble(args)
    elif args.command == "calibrate":
        run_calibrate(args)
    elif args.command == "autotune":
        run_autotune(args)
    elif args.command == "sensitivity":
        run_sensitivity(args)
    elif args.command == "capacity":
        run_capacity(args)
    elif args.command == "apps":
        run_apps(args)
    else:
        exp_dir = run_num_apps(args)
        print(plots.plot_financial_cost(exp_dir, args.host_hourly_rate))


if __name__ == "__main__":
    main()
