"""Experiment drivers, CLI, and analysis for the Alibaba trace workload."""
