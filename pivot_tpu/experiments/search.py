"""Policy-search experiment harness: learn, hold out, report regret.

The ``search`` CLI (``python -m pivot_tpu.experiments.cli search``), the
``policy_search`` bench row, the smoke lane's tiny CEM gate, and
``tests/test_search.py`` all drive this module.  One run:

  1. **Train** — build the seeded train :class:`SearchEnv` (market
     hazards + the hazard-drawn preemption plan) and run the chosen
     optimizer (:func:`~pivot_tpu.search.cem.cem_search` /
     :func:`~pivot_tpu.search.es.es_search`); every generation scores
     its whole candidate population as one fused ensemble dispatch.
  2. **Hold out** — rebuild fresh environments at unseen seeds (new
     market draw, new workload, new preemption plan) and score the
     learned vector against the hand-tuned arms through the SAME
     evaluator: the headline ``learned_beats_hand_tuned`` compares
     mean cost-per-completed-task over the held-out seeds.
  3. **Regret** — on a small single-wave instance the branch-and-bound
     oracle can solve exactly (``search/oracle.py``), report each
     arm's greedy-placement objective as regret against the proven
     optimum, not just as a delta between heuristics.
  4. optionally **DES-validate** — play learned vs hand-tuned through
     the exact simulator (``experiments/spot.py`` with ``weights=``)
     under the held-out market, billing the true piecewise price
     integral.

Everything is seeded and replayable: same config ⇒ bit-identical
report (the smoke lane runs the committed ``data/search/ci_seed.json``
config twice and diffs).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from pivot_tpu.search.weights import DEFAULT_WEIGHTS, PolicyWeights

__all__ = [
    "BAD_INIT",
    "HAND_TUNED_ARMS",
    "run_search_experiment",
    "small_oracle_instance",
]

#: The hand-tuned reference arms every learned vector must beat —
#: today's shipped configurations as weight vectors: the reference
#: cost-aware score, and the PR-9 risk-aware arm at its bench knobs.
HAND_TUNED_ARMS: Dict[str, PolicyWeights] = {
    "hand_tuned_default": DEFAULT_WEIGHTS,
    "hand_tuned_risk_aware": PolicyWeights(risk_weight=1.0, rework_cost=50.0),
}

#: The deliberately-bad initial vector the smoke gate starts from: all
#: score exponents zeroed (every host scores 1.0 ⇒ the argmin
#: degenerates to host 0 — maximal crowding, egress-blind) and the risk
#: term off under a hazardous market.  Any competent sample beats it.
BAD_INIT = PolicyWeights(w_cost=0.0, w_bw=0.0, w_norm=0.0, risk_weight=0.0)


def small_oracle_instance(seed: int, *, n_hosts: int = 6, n_apps: int = 4,
                          hazard_scale: float = 10.0,
                          tightness: float = 1.6):
    """A small, exactly-solvable consumer wave derived from the seeded
    spot world: the two-stage DAGs' producer instances land round-robin
    across hosts (a fixed, placement-history-like context), the
    consumer wave is the decision to optimize, and the hazard row is
    the market's t=0 per-host trace (scaled so the risk dimension has
    bite at wave scale).  ``tightness`` shrinks each host's snapshot to
    ~``tightness / H`` of the wave's total demand so capacity actually
    binds (a slack wave makes every arm trivially optimal and the
    regret report says nothing).  Returns ``(instance, env)``."""
    from pivot_tpu.search.fitness import make_search_env
    from pivot_tpu.search.oracle import instance_from_wave

    env = make_search_env(
        n_hosts=n_hosts, seed=seed, n_apps=n_apps, horizon=200.0,
        n_replicas=2,
    )
    wl = env.workload
    group_of = np.asarray(wl.group_of)
    pred = np.asarray(wl.pred_group)
    # Producer groups: no predecessors.  Consumers: everything else.
    is_root_group = pred.sum(axis=1) == 0
    producer_mask = is_root_group[group_of]
    T = wl.n_tasks
    pp = np.full(T, -1, dtype=np.int64)
    prod_idx = np.nonzero(producer_mask)[0]
    pp[prod_idx] = np.arange(len(prod_idx)) % n_hosts
    consumer_mask = ~producer_mask
    hazard = None
    if env.hazard is not None:
        hazard = hazard_scale * np.asarray(env.hazard[1])[0]  # t=0 row
    avail = np.asarray(env.avail0, dtype=np.float64).copy()
    dem = np.asarray(wl.demands, dtype=np.float64)[consumer_mask]
    cap = dem.sum(axis=0) * (tightness / n_hosts)
    # Resources the wave never asks for keep the snapshot's value (a
    # zeroed row would fail the greedy arm's strict fit on 0 > 0).
    binds = cap > 0
    avail[:, binds] = np.minimum(avail[:, binds], cap[binds][None, :])
    inst = instance_from_wave(
        wl, env.topo, avail, pp, consumer_mask,
        hazard=hazard, weights=DEFAULT_WEIGHTS,
    )
    return inst, env


def _holdout_scores(
    arms: Dict[str, PolicyWeights],
    seeds: List[int],
    env_kw: dict,
) -> Dict[str, dict]:
    """Each arm's mean cost-per-completed-task over fresh environments
    at the held-out seeds — one population dispatch per seed (all arms
    ride one batch: paired comparisons).  Always the unsharded backend:
    the tiny fixed-arm batch (3 × R rows) rarely divides a mesh and
    never amortizes one, and the two backends are bit-identical by the
    parity contract (tests/test_search.py) — a sharded TRAINING run's
    holdout numbers are unchanged by this choice."""
    from pivot_tpu.search.fitness import make_search_env
    from pivot_tpu.sched.sensitivity import evaluate_candidates

    names = list(arms)
    pop = PolicyWeights.stack([arms[n] for n in names])
    per_seed = {n: [] for n in names}
    for s in seeds:
        env = make_search_env(seed=s, **env_kw)
        scores = evaluate_candidates(pop, env)
        for n, sc in zip(names, scores):
            per_seed[n].append(float(sc))
    return {
        n: {
            "mean_cost_per_task": float(np.mean(per_seed[n])),
            "per_seed": per_seed[n],
        }
        for n in names
    }


def run_search_experiment(
    *,
    method: str = "cem",
    generations: int = 6,
    popsize: int = 12,
    seed: int = 5,
    n_hosts: int = 12,
    n_apps: int = 8,
    horizon: float = 600.0,
    n_replicas: int = 8,
    holdout: int = 2,
    backend: str = "rollout",
    mesh=None,
    bad_init: bool = False,
    oracle: bool = True,
    des_validate: bool = False,
    search_kw: Optional[dict] = None,
) -> dict:
    """Run the full learn → hold out → regret pipeline; returns the
    JSON-serializable report (see the module docstring)."""
    from pivot_tpu.search.cem import cem_search
    from pivot_tpu.search.es import es_search
    from pivot_tpu.search.fitness import make_search_env

    if method not in ("cem", "es"):
        raise ValueError(f"method must be cem|es, got {method!r}")
    env_kw = dict(
        n_hosts=n_hosts, n_apps=n_apps, horizon=horizon,
        n_replicas=n_replicas,
    )
    train_env = make_search_env(seed=seed, **env_kw)
    init = BAD_INIT if bad_init else DEFAULT_WEIGHTS
    search_fn = cem_search if method == "cem" else es_search
    search_kw = dict(search_kw or {})
    if method == "cem" and not bad_init:
        # Warm-start from the hand-tuned arms (generation-0 anchor
        # rows): the search's job is to BEAT the best known vectors,
        # not to rediscover them from scratch; the bad-init smoke mode
        # deliberately skips this so the gate proves real search
        # progress.
        search_kw.setdefault("anchors", list(HAND_TUNED_ARMS.values()))
    result = search_fn(
        train_env, generations=generations, popsize=popsize, seed=seed,
        init=init, backend=backend, mesh=mesh, **search_kw,
    )
    learned = result.best

    holdout_seeds = [seed + 1 + i for i in range(holdout)]
    arms = dict(HAND_TUNED_ARMS)
    arms["learned"] = learned
    holdout_report = (
        _holdout_scores(arms, holdout_seeds, env_kw)
        if holdout > 0 else {}
    )
    report = {
        "config": {
            "method": method, "generations": generations,
            "popsize": popsize, "seed": seed, "n_hosts": n_hosts,
            "n_apps": n_apps, "horizon": horizon,
            "n_replicas": n_replicas, "holdout": holdout,
            "backend": backend, "bad_init": bad_init,
        },
        "search": result.to_dict(),
        "beats_bad_init": bool(result.best_score < result.init_score),
        "holdout_seeds": holdout_seeds,
        "holdout": holdout_report,
    }
    if holdout_report:
        hand = {
            n: holdout_report[n]["mean_cost_per_task"]
            for n in HAND_TUNED_ARMS
        }
        best_hand = min(hand, key=hand.get)
        report["best_hand_tuned_arm"] = best_hand
        report["learned_beats_hand_tuned"] = bool(
            holdout_report["learned"]["mean_cost_per_task"] < hand[best_hand]
        )

    if oracle:
        from pivot_tpu.search.oracle import (
            greedy_placement,
            placement_objective,
            solve_instance,
        )

        inst, _ = small_oracle_instance(seed + 101, n_hosts=min(n_hosts, 6))
        opt_p, opt_obj, stats = solve_instance(inst)
        regrets = {}
        for name, w in arms.items():
            p = greedy_placement(inst, w)
            regrets[name] = float(placement_objective(inst, p) - opt_obj)
        report["oracle"] = {
            "optimum_objective": float(opt_obj),
            "optimum_placement": [int(h) for h in opt_p],
            "nodes": stats["nodes"],
            "n_tasks": inst.n_tasks,
            "n_hosts": inst.n_hosts,
            "regret": regrets,
        }

    if des_validate and holdout:
        from pivot_tpu.experiments.spot import run_spot_arm, spot_market

        s = holdout_seeds[0]
        market = spot_market(n_hosts, seed=s, horizon=horizon)
        des = {}
        for name, w in arms.items():
            r = run_spot_arm(
                market, n_hosts=n_hosts, seed=s, n_apps=n_apps,
                weights=w, proactive=True,
            )
            des[name] = {
                "cost_per_completed_task": r["cost_per_completed_task"],
                "dead_letter_rate": r["dead_letter_rate"],
                "audit_violations": r["audit_violations"],
            }
        report["des_validation"] = des
    return report


def load_config(path: str) -> dict:
    """Read a committed search config (the smoke lane's replay anchor,
    ``data/search/ci_seed.json``) into :func:`run_search_experiment`
    keyword arguments."""
    with open(path) as fh:
        cfg = json.load(fh)
    allowed = {
        "method", "generations", "popsize", "seed", "n_hosts", "n_apps",
        "horizon", "n_replicas", "holdout", "backend", "bad_init",
        "oracle", "des_validate",
    }
    unknown = set(cfg) - allowed
    if unknown:
        raise ValueError(
            f"unknown search-config keys {sorted(unknown)} in {path}"
        )
    return cfg
