"""Spot-market survival harness: one seeded arm of the eviction game.

The ``spot_survival`` experiment (bench row, acceptance soak, and the
``tools/market_replay.py`` CLI all drive this module) plays the same
seeded world twice:

  * **hazard-blind** (``risk_weight=0``, ``proactive=False``) — the
    pre-market scheduler: cost-aware placement packs work onto the
    cheapest zones, which under a spot market are exactly the most
    evictable ones; preemptions are discovered reactively when the abort
    kills the host, and every lost execution re-enters the retry loop.
  * **risk-aware + proactive** (``risk_weight>0``, ``proactive=True``) —
    placement prices eviction risk into every score
    (``policies.resolve_risk``), and the preemption *warning* triggers
    the drain → migrate → restart handler
    (``GlobalScheduler.on_preempt_warning``): queued tasks re-decide off
    the doomed host, provably-doomed residents restart immediately
    instead of burning the lead window.

Both arms run under the IDENTICAL :class:`MarketSchedule` and the
identical hazard-drawn fault plan (``MarketSchedule.spot_schedule`` is a
pure function of cluster topology, market, and seed — placement cannot
perturb it), so the delta is attributable to the survival machinery
alone.  The report's headline metrics are **cost per completed task**
(price-trace-integrated instance cost + metered egress, over finished
tasks) and the **dead-letter rate** (Bamboo / SpotServe's collapse axis,
PAPERS.md).

Everything is seeded and replayable: same (market, seed, arm knobs) ⇒
bit-identical fault log, task outcomes, and meter snapshot —
``tools/market_replay.py diff`` and the CI smoke lane hold it to that.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from pivot_tpu.infra.market import MarketSchedule

__all__ = ["run_spot_arm", "spot_market", "synthetic_spot_apps"]


def spot_market(
    n_hosts: int,
    seed: int,
    horizon: float = 600.0,
    *,
    n_segments: int = 6,
    hot_fraction: float = 0.4,
    hot_hazard: float = 2e-2,
    hot_discount: float = 0.65,
    base_hazard: float = 5e-4,
    price_vol: float = 0.15,
) -> MarketSchedule:
    """The experiment's seeded market, drawn against the same synthetic
    cluster :func:`run_spot_arm` builds (``utils.config.build_cluster``
    is deterministic per (n_hosts, seed), so the zone catalog matches by
    construction).  Defaults bias toward the adversarial shape: a large
    discounted-and-hazardous spot pool next to calm on-demand zones."""
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    reset_ids()
    cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed))
    return MarketSchedule.generate(
        cluster.meta,
        seed=seed,
        horizon=horizon,
        n_segments=n_segments,
        hot_fraction=hot_fraction,
        hot_hazard=hot_hazard,
        hot_discount=hot_discount,
        base_hazard=base_hazard,
        price_vol=price_vol,
    )


def synthetic_spot_apps(n_apps: int, seed: int) -> List:
    """Seeded two-stage DAGs (same shape as the chaos-replay workload:
    a fan-out source feeding one sink) — long enough that a mid-run
    preemption costs real rework, numerous enough that placement spreads
    across zones."""
    from pivot_tpu.workload import Application, TaskGroup

    rng = np.random.default_rng(seed)
    apps = []
    for i in range(n_apps):
        src = TaskGroup(
            "src", cpus=4, mem=256, runtime=float(rng.uniform(60, 140)),
            output_size=float(rng.uniform(100, 400)),
            instances=int(rng.integers(2, 5)),
        )
        dst = TaskGroup(
            "dst", cpus=4, mem=256, runtime=float(rng.uniform(40, 80)),
            dependencies=["src"],
        )
        apps.append(Application(f"spot-app-{i}", [src, dst]))
    return apps


def run_spot_arm(
    market: MarketSchedule,
    *,
    n_hosts: int = 12,
    seed: int = 0,
    n_apps: int = 10,
    risk_weight: float = 0.0,
    rework_cost: float = 1.0,
    proactive: bool = False,
    lead: float = 15.0,
    outage: float = 100.0,
    horizon: Optional[float] = None,
    max_retries: int = 1,
    breaker_k: Optional[int] = None,
    interval: float = 5.0,
    rate_per_hour: float = 1.0,
    fault_seed: Optional[int] = None,
    arrival_spacing: float = 40.0,
    weights=None,
) -> dict:
    """Run ONE arm of the spot-survival game to completion and report.

    Builds the seeded synthetic world (cluster, cost-aware CPU policy,
    retry governor), attaches ``market`` to the scheduler (time-varying
    cost matrix + per-tick hazard vector), draws the hazard-proportional
    preemption plan (``fault_seed`` defaults to ``seed`` — pass the same
    value to every arm so they face the identical fault plan), replays
    it through a :class:`FaultInjector`, and drives the workload dry.

    Returns a JSON-serializable report: the fault log, meter summary,
    audit violations (conservation + cluster + meter, rework included),
    and the headline ``cost_per_completed_task`` / ``dead_letter_rate``.
    """
    from pivot_tpu.infra.audit import (
        audit_cluster,
        audit_conservation,
        audit_meter,
    )
    from pivot_tpu.infra.faults import FaultInjector
    from pivot_tpu.infra.meter import Meter
    from pivot_tpu.sched import (
        GlobalScheduler,
        HostCircuitBreaker,
        RetryPolicy,
    )
    from pivot_tpu.sched.policies import CostAwarePolicy
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    from pivot_tpu.des import Environment

    reset_ids()  # host-N ids must match across arms and replays
    proto = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed))
    env = Environment()
    meter = Meter(env, proto.meta)
    # Clone with the meter attached so every host bills its busy
    # intervals (a post-hoc ``cluster.meter = ...`` never reaches the
    # already-constructed hosts — the instance-cost integral would read
    # an empty ledger).
    cluster = proto.clone(env, meter)
    # ``weights`` (a search-learned PolicyWeights vector) supersedes the
    # legacy risk-knob pair — the round-16 DES validation path: play a
    # learned vector through the exact simulator under the same market.
    if weights is not None:
        policy = CostAwarePolicy(weights=weights)
        # The resolved vector (resolve_weights coerces array-likes), not
        # the raw argument — the report builder reads its _fields.
        weights = policy.weights
        risk_weight = policy.risk_weight
        rework_cost = policy.rework_cost
    else:
        policy = CostAwarePolicy(
            risk_weight=risk_weight, rework_cost=rework_cost
        )
    scheduler = GlobalScheduler(
        cluster.env,
        cluster,
        policy,
        interval=interval,
        seed=seed,
        meter=meter,
        retry=RetryPolicy(max_retries=max_retries, base=1.0, seed=seed),
        breaker=(
            HostCircuitBreaker(k=breaker_k, cooldown=60.0)
            if breaker_k else None
        ),
        market=market,
    )
    cluster.start()
    scheduler.start()

    injector = FaultInjector(cluster, seed=seed)
    spot_plan = market.spot_schedule(
        cluster,
        seed=seed if fault_seed is None else fault_seed,
        lead=lead,
        outage=outage,
        horizon=horizon,
    )
    injector.apply_schedule(spot_plan)
    if proactive:
        scheduler.enable_proactive_drain(injector)

    # Staggered arrivals: app i enters at i × spacing, so the workload
    # overlaps the whole price/hazard trace instead of draining before
    # the first preemption fires (the reactive arm must actually live
    # through the market it is blind to).
    apps = synthetic_spot_apps(n_apps, seed)
    for i, app in enumerate(apps):
        if i == 0 or arrival_spacing <= 0:
            scheduler.submit(app)
        else:
            env.schedule_callback_at(
                i * arrival_spacing,
                (lambda a: (lambda: scheduler.submit(a)))(app),
            )
    scheduler.stop()
    cluster.env.run()

    tasks = [t for a in apps for g in a.groups for t in g.tasks]
    # Rate denominator: the SPEC's task count, not the materialized one —
    # a failed app cancels downstream groups before their tasks exist,
    # and a shrinking denominator would flatter the arm that failed.
    n_tasks = sum(g.instances for a in apps for g in a.groups)
    n_done = sum(t.is_finished for t in tasks)
    n_dead = len(scheduler.dead_letters)
    instance_cost = market.billed_instance_cost(
        meter, cluster, rate_per_hour=rate_per_hour, end=cluster.env.now
    )
    summary = meter.summary()
    summary.pop("wall_clock", None)  # the one non-deterministic field
    egress = summary["egress_cost"]
    violations = (
        audit_cluster(cluster)
        + audit_conservation(scheduler, apps)
        + audit_meter(meter)
    )
    return {
        "arm": {
            "risk_weight": risk_weight,
            "rework_cost": rework_cost,
            **(
                {"weights": {k: float(v) for k, v in
                             zip(type(weights)._fields, weights)}}
                if weights is not None else {}
            ),
            "proactive": proactive,
            "n_hosts": n_hosts,
            "seed": seed,
            "n_apps": n_apps,
            "max_retries": max_retries,
            "lead": lead,
            "outage": outage,
        },
        "n_preemptions": len(spot_plan),
        "fault_log": [[t, target, ev] for t, target, ev in injector.log],
        "n_tasks": n_tasks,
        "n_completed_tasks": n_done,
        "n_dead_letters": n_dead,
        "dead_letter_rate": (n_dead / n_tasks) if n_tasks else 0.0,
        "finished_apps": sum(a.is_finished for a in apps),
        "failed_apps": sum(a.failed for a in apps),
        "n_migrated": scheduler.n_migrated,
        "n_proactive_restarts": scheduler.n_proactive_restarts,
        "instance_cost": instance_cost,
        "egress_cost": egress,
        "total_cost": instance_cost + egress,
        # None (not inf) when nothing completed: json.dump would emit the
        # non-standard ``Infinity`` token and break strict JSON consumers.
        "cost_per_completed_task": (
            (instance_cost + egress) / n_done if n_done else None
        ),
        "rework_seconds": meter.rework_seconds,
        "makespan": float(cluster.env.now),
        "meter": summary,
        "audit_violations": violations,
    }
