"""Analysis plots over experiment output directories.

Capability parity with the reference's three figures
(``alibaba/sim.py:55-165``), reading the same on-disk layout
(``<exp_dir>/data/<iter>/<label>/{general,transfers}.json``):

  * :func:`plot_overall`        — egress cost / host cost / app runtime per
    scheduler, normalized to the per-metric max (ref ``overall.pdf``).
  * :func:`plot_transfers`      — per-task data-transfer time split into
    transmission (propagation) vs congestion (queueing) (ref
    ``transfer.pdf``).
  * :func:`plot_financial_cost` — total host + egress $ vs number of apps
    (ref ``financial-cost.pdf``; host $ = instance-hours × hourly rate).
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Dict, List

import numpy as np

__all__ = [
    "collect_general",
    "plot_overall",
    "plot_transfers",
    "plot_financial_cost",
    "plot_host_usage",
    "plot_resource_usage",
    "plot_ensemble_distribution",
    "plot_capacity_frontier",
    "plot_apps_cost",
    "plot_calibration_spread",
    "POLICY_ORDER",
]

POLICY_ORDER = ["Opportunistic", "Cost-Aware", "VBP"]
METRIC_ORDER = ["egress_cost", "cum_instance_hours", "avg_runtime"]
METRIC_LABELS = ["egress cost", "host cost", "app. runtime"]

#: Fixed per-policy colors (entity-stable: the same arm keeps its color no
#: matter which subset of arms a figure shows), covering both the display
#: labels the DES experiments use and the policy names the estimator uses.
ENTITY_COLORS = {
    "Opportunistic": "C0", "opportunistic": "C0",
    "Cost-Aware": "C1", "cost-aware": "C1",
    "VBP": "C2", "first-fit": "C2",
    "best-fit": "C3",
}


def _plot_cost_lines(series, ylabel: str, out: str) -> str:
    """Shared cost-vs-#apps renderer (solid = host $, dashed = egress $).

    ``series``: label → list of (n_apps, egress, host) rows, any order —
    rows are sorted by n_apps here.  Used by :func:`plot_financial_cost`
    (DES results) and :func:`plot_apps_cost` (estimator results) so the
    two analog figures cannot drift.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    markers = ["x", "+", "1", "2", "3"]
    plt.figure(figsize=(8, 5))
    items = sorted(series.items())
    # Solid (host) and dashed (egress) twins of one arm must share a
    # color; a label outside ENTITY_COLORS would otherwise get two
    # different auto-cycle colors (separate plot calls), so capture the
    # solid line's assigned color and reuse it for the dashed twin.
    label_colors = dict(ENTITY_COLORS)
    for solid in (True, False):
        for i, (label, rows) in enumerate(items):
            rows = sorted(rows)
            xs = [r[0] for r in rows]
            ys = [r[2] if solid else r[1] for r in rows]
            (line,) = plt.plot(
                xs, ys,
                ls="-" if solid else "--",
                color=label_colors.get(label),
                marker=markers[i % len(markers)], markersize=11,
                label=f"{label} ({'host' if solid else 'egress'})",
            )
            label_colors.setdefault(label, line.get_color())
    plt.xlabel("# of running applications", fontsize=13)
    plt.ylabel(ylabel, fontsize=13)
    plt.legend(ncol=2, frameon=False, fontsize=10)
    plt.tight_layout()
    plt.savefig(out)
    plt.close()
    return out


def _iterdirs(path: str) -> List[str]:
    return sorted(d for d in os.listdir(path) if os.path.isdir(os.path.join(path, d)))


def collect_general(data_dir: str) -> Dict[str, Dict[str, list]]:
    """label → metric → [value per iteration]."""
    metrics: Dict[str, Dict[str, list]] = defaultdict(lambda: defaultdict(list))
    for it in _iterdirs(data_dir):
        for label in _iterdirs(os.path.join(data_dir, it)):
            with open(os.path.join(data_dir, it, label, "general.json")) as f:
                for k, v in json.load(f).items():
                    metrics[label][k].append(v)
    return metrics


def plot_overall(exp_dir: str) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    data_dir, plot_dir = os.path.join(exp_dir, "data"), os.path.join(exp_dir, "plot")
    os.makedirs(plot_dir, exist_ok=True)
    metrics = collect_general(data_dir)
    labels = [l for l in POLICY_ORDER if l in metrics] + [
        l for l in sorted(metrics) if l not in POLICY_ORDER
    ]
    # Tolerate partial grids (a crashed run): use the common iteration count.
    n_iter = min(len(metrics[l][METRIC_ORDER[0]]) for l in labels)
    if n_iter == 0:
        raise SystemExit(f"no complete iterations under {data_dir}")
    # Normalize each (metric, iteration) column to its max across labels.
    norm = {l: [] for l in labels}
    for key in METRIC_ORDER:
        per_label = {l: metrics[l][key] for l in labels}
        vals = np.zeros(len(labels))
        for i in range(n_iter):
            col_max = max(per_label[l][i] for l in labels)
            for j, l in enumerate(labels):
                vals[j] += per_label[l][i] / col_max if col_max else 0.0
        for j, l in enumerate(labels):
            norm[l].append(vals[j] / n_iter)

    width, gap = 0.25, 0.1
    hatches = ["/", "+", "-", "x", "."]
    x = np.arange(len(METRIC_ORDER)) * (width + gap) * len(labels)
    plt.figure(figsize=(7, 4))
    for j, label in enumerate(labels):
        plt.bar(x + width * j, norm[label], width=width, label=label,
                hatch=hatches[j % len(hatches)])
    plt.xticks(x + width * len(labels) / 2 - gap, METRIC_LABELS, fontsize=13)
    plt.ylim(0, 1.15)
    plt.ylabel("Cost/runtime norm. to max.", fontsize=13)
    plt.legend(ncol=3, frameon=False, fontsize=11)
    plt.tight_layout()
    out = os.path.join(plot_dir, "overall.pdf")
    plt.savefig(out, format="pdf")
    plt.close()
    return out


def plot_transfers(exp_dir: str) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    data_dir, plot_dir = os.path.join(exp_dir, "data"), os.path.join(exp_dir, "plot")
    os.makedirs(plot_dir, exist_ok=True)
    split: Dict[str, list] = defaultdict(list)
    for it in _iterdirs(data_dir):
        for label in _iterdirs(os.path.join(data_dir, it)):
            with open(os.path.join(data_dir, it, label, "transfers.json")) as f:
                transfers = json.load(f)
            if not transfers:
                split[label].append((0.0, 0.0))
                continue
            prop = float(np.mean([t["propagation_delay"] for t in transfers]))
            queue = float(
                np.mean([t["total_delay"] - t["propagation_delay"] for t in transfers])
            )
            split[label].append((prop, queue))
    labels = [l for l in POLICY_ORDER if l in split] + [
        l for l in sorted(split) if l not in POLICY_ORDER
    ]
    prop = np.array([np.mean([v[0] for v in split[l]]) for l in labels])
    queue = np.array([np.mean([v[1] for v in split[l]]) for l in labels])

    y = np.arange(len(labels)) * 0.25
    plt.figure(figsize=(7, 3))
    plt.barh(y, prop, height=0.2, hatch="/", label="Transmission")
    plt.barh(y, queue, height=0.2, left=prop, hatch="-", label="Congestion")
    plt.yticks(y, labels, rotation=45, fontsize=12)
    plt.xlabel("Data transfer time per task (seconds)", fontsize=12)
    plt.legend(ncol=2, frameon=False, fontsize=11)
    plt.tight_layout()
    out = os.path.join(plot_dir, "transfer.pdf")
    plt.savefig(out, format="pdf")
    plt.close()
    return out


def plot_financial_cost(exp_dir: str, host_hourly_rate: float = 0.932) -> str:
    data_dir, plot_dir = os.path.join(exp_dir, "data"), os.path.join(exp_dir, "plot")
    os.makedirs(plot_dir, exist_ok=True)
    # layout: data/<n_apps>/<iter>/<label>/general.json
    metrics: Dict[str, Dict[int, list]] = defaultdict(lambda: defaultdict(list))
    for n_apps in _iterdirs(data_dir):
        for it in _iterdirs(os.path.join(data_dir, n_apps)):
            for label in _iterdirs(os.path.join(data_dir, n_apps, it)):
                with open(
                    os.path.join(data_dir, n_apps, it, label, "general.json")
                ) as f:
                    g = json.load(f)
                metrics[label][int(n_apps)].append(
                    (g["egress_cost"], g["cum_instance_hours"] * host_hourly_rate)
                )
    series = {
        label: [
            (
                n,
                float(np.mean([v[0] for v in vals])) / 1000,
                float(np.mean([v[1] for v in vals])) / 1000,
            )
            for n, vals in per_n.items()
        ]
        for label, per_n in metrics.items()
    }
    return _plot_cost_lines(
        series, "Total host/egress cost ($1K)",
        os.path.join(plot_dir, "cost.pdf"),
    )


def plot_ensemble_distribution(run_dir: str, out: str = None) -> str:
    """Replica-distribution figure for one ensemble run: the empirical CDF
    of makespan across Monte-Carlo replicas, with the p5/p50/p95 quantiles
    marked.  Reads the ``rollout.npz`` the ``ensemble`` subcommand writes.

    No reference analog: the reference has one trajectory per (seeded) run
    and nothing to take a distribution over.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    with np.load(os.path.join(run_dir, "rollout.npz")) as arrs:
        mk = np.sort(np.asarray(arrs["makespan"], dtype=np.float64))
    frac = np.arange(1, len(mk) + 1) / len(mk)
    plt.figure(figsize=(7, 4))
    plt.step(mk, frac, where="post", linewidth=2)
    for q, name in ((5, "p5"), (50, "p50"), (95, "p95")):
        v = float(np.percentile(mk, q))
        plt.axvline(v, color="0.6", linewidth=1, linestyle=":")
        plt.text(v, 0.03, f" {name}={v:.0f}s", fontsize=10, color="0.35",
                 rotation=90, va="bottom")
    plt.xlabel(f"Makespan (s) across {len(mk)} replicas", fontsize=13)
    plt.ylabel("Fraction of replicas", fontsize=13)
    plt.ylim(0, 1.02)
    plt.grid(axis="y", color="0.9", linewidth=0.8)
    plt.tight_layout()
    out = out or os.path.join(run_dir, "makespan_cdf.pdf")
    plt.savefig(out)
    plt.close()
    return out


def plot_capacity_frontier(run_dir: str, out: str = None) -> str:
    """Cost/makespan frontier over candidate cluster sizes: provisioned
    total cost vs mean makespan, one point per size (direct-labeled),
    connected in host-count order.  Reads the ``summary.json`` the
    ``capacity`` subcommand writes.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    cands = sorted(summary["candidates"], key=lambda c: c["hosts"])
    done = [c for c in cands if c["unfinished_max"] == 0]
    trunc = [c for c in cands if c["unfinished_max"] > 0]
    plt.figure(figsize=(7, 4))
    # Only finished candidates form the frontier line; horizon-truncated
    # sizes (clamped lower bounds, not measurements) sit apart as ×.
    plt.plot([c["makespan_mean"] for c in done],
             [c["total_cost_mean"] for c in done],
             marker="o", markersize=8, linewidth=2)
    if trunc:
        plt.scatter([c["makespan_mean"] for c in trunc],
                    [c["total_cost_mean"] for c in trunc],
                    marker="x", s=80, color="0.45", zorder=3)
    plt.margins(x=0.15, y=0.15)  # keep point annotations inside the axes
    for c in cands:
        suffix = "" if c["unfinished_max"] == 0 else " (unfinished ≥)"
        plt.annotate(f'{c["hosts"]} hosts{suffix}',
                     (c["makespan_mean"], c["total_cost_mean"]), fontsize=10,
                     textcoords="offset points", xytext=(8, 6))
    best = summary.get("best")
    if best:
        plt.scatter([best["makespan_mean"]], [best["total_cost_mean"]],
                    s=160, facecolors="none", edgecolors="0.2", linewidths=1.5,
                    zorder=3)
    plt.xlabel("Mean makespan (s)", fontsize=13)
    plt.ylabel("Provisioned cost ($)", fontsize=13)
    plt.title("hosts × makespan × hourly rate + egress", fontsize=10,
              color="0.35")
    plt.grid(color="0.9", linewidth=0.8)
    plt.tight_layout()
    out = out or os.path.join(run_dir, "capacity_frontier.pdf")
    plt.savefig(out)
    plt.close()
    return out


def plot_calibration_spread(run_dir: str, out: str = None) -> str:
    """Distributional-calibration figure: DES vs estimator per sample.

    Reads the ``report.json`` a distributional ``calibrate`` run writes
    (``--cluster-seeds N``: one sample per generated cluster;
    ``--des-seeds N`` on one cluster: one sample per DES policy seed) and
    plots, per metric, the DES's per-sample values against the
    estimator's — making the bias-vs-chaos separation visible: a stable
    estimator line through a scattered DES cloud is bias; tracking
    scatter is fidelity.  No reference analog (single engine, no
    estimator to calibrate).
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    with open(os.path.join(run_dir, "report.json")) as f:
        report = json.load(f)

    metrics = ["egress_cost", "instance_hours", "avg_runtime"]
    labels = ["egress cost ($)", "instance hours", "app. runtime (s)"]
    if "clusters" in report:
        samples = report["clusters"]
        xlabel = "cluster seed sample"
        modes = [m for m in ("static", "congested", "realtime") if m in samples[0]]
        des_pts = {k: [c["des"][k] for c in samples] for k in metrics}
        est_pts = {
            (m, k): [c[m][k] for c in samples] for m in modes for k in metrics
        }
        summary = report.get("cluster_summary", {})
    elif "des_per_seed" in report:
        samples = report["des_per_seed"]
        xlabel = "DES policy seed sample"
        modes = [m for m in ("static", "congested", "realtime") if m in report]
        des_pts = {k: [d[k] for d in samples] for k in metrics}
        # One estimator run vs N DES seeds: a flat line per mode.
        est_pts = {
            (m, k): [report[m][k]] * len(samples)
            for m in modes
            for k in metrics
        }
        summary = {}
    else:
        raise ValueError(
            "report has neither 'clusters' nor 'des_per_seed' — run "
            "calibrate with --cluster-seeds or --des-seeds > 1"
        )

    x = np.arange(len(samples))
    mode_marks = {"static": "s", "congested": "^", "realtime": "v"}
    fig, axes = plt.subplots(1, len(metrics), figsize=(4 * len(metrics), 3.6))
    for ax, k, lab in zip(axes, metrics, labels):
        ax.plot(x, des_pts[k], marker="o", linewidth=1.5, color="0.25",
                label="DES")
        for m in modes:
            ax.plot(x, est_pts[(m, k)], marker=mode_marks[m], linewidth=1.2,
                    linestyle="--", label=f"estimator ({m})")
        title = lab
        s = summary.get(modes[0], {}).get(k) if summary else None
        if s and s.get("mean_rel_err") is not None:
            title += (
                f"\n{modes[0]} rel err {100 * s['mean_rel_err']:+.0f}%"
                f" ± {100 * s['std_rel_err']:.0f}%"
            )
        ax.set_title(title, fontsize=11)
        ax.set_xlabel(xlabel, fontsize=11)
        ax.set_xticks(x)
        ax.grid(color="0.9", linewidth=0.8)
    axes[0].legend(fontsize=9, frameon=False)
    fig.suptitle(
        f"{report['policy']} @ {report['n_hosts']} hosts — DES spread vs "
        "estimator", fontsize=12,
    )
    fig.tight_layout()
    out = out or os.path.join(run_dir, "calibration_spread.pdf")
    fig.savefig(out)
    plt.close(fig)
    return out


def plot_apps_cost(run_dir: str, out: str = None) -> str:
    """Estimator analog of the reference's financial-cost figure
    (``alibaba/sim.py:132-165``): host/egress $ vs workload size per
    policy arm, from the ``apps`` subcommand's ``summary.json`` —
    rendered through the same :func:`_plot_cost_lines` body as the DES
    figure, with entity-stable per-policy colors.
    """
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    series = {
        policy: [
            (r["n_apps"], r["egress_mean"], r["host_cost_mean"])
            for r in rows
        ]
        for policy, rows in summary["arms"].items()
    }
    return _plot_cost_lines(
        series, "Mean host/egress cost ($)",
        out or os.path.join(run_dir, "apps_cost.pdf"),
    )


def plot_host_usage(run_dir: str, out: str = None) -> str:
    """Busy-host count over time for one run — renders the curve the meter
    serializes as ``host_usage.json`` (ref ``resources/meter.py:135-148``).

    ``run_dir`` is a ``data/<iter>/<label>`` directory.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    with open(os.path.join(run_dir, "host_usage.json")) as f:
        usage = json.load(f)
    xs = [end for _start, end in usage["timestamps"]]
    plt.figure(figsize=(8, 4))
    plt.step(xs, usage["n_hosts"], where="pre")
    plt.xlabel("Simulation time (s)", fontsize=13)
    plt.ylabel("# of busy hosts", fontsize=13)
    plt.tight_layout()
    out = out or os.path.join(run_dir, "host_usage.pdf")
    plt.savefig(out, format="pdf")
    plt.close()
    return out


def plot_resource_usage(meter, resources=("cpus", "mem"), out: str = "resource_usage.pdf") -> str:
    """Mean normalized per-dimension host utilization over time, from a live
    :class:`~pivot_tpu.infra.meter.Meter` (ref ``resources/meter.py:150-159``
    — the reference likewise plots this from the in-memory meter; it is not
    part of the serialized four-file layout)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    plt.figure(figsize=(8, 4))
    for res in resources:
        xs, ys = meter.resource_usage_curve(res)
        plt.plot(xs, ys, label=res)
    plt.xlabel("Simulation time (s)", fontsize=13)
    plt.ylabel("Mean normalized utilization", fontsize=13)
    plt.ylim(0, 1)
    plt.legend(frameon=False)
    plt.tight_layout()
    plt.savefig(out, format="pdf")
    plt.close()
    return out
