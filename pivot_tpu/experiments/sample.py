"""Alibaba 2018 cluster-trace sampler: raw CSVs → windowed job files.

Capability parity with the reference's offline sampler
(``alibaba/sample.py:12-127``), which produced the bundled
``jobs-<n>-<p>-<start>-<end>`` files:

  * ``batch_task.csv`` rows carry the DAG in the task name — ``M1_2_3``
    means task id 1 depending on tasks 2 and 3; ``task...``/``MergeTask``
    names are standalone (ref ``:61-65``).  CPU demands are /100 (trace
    stores percent-of-core), memory stays normalized.
  * ``batch_instance.csv`` is streamed to attach per-task runtimes
    (mean-free: last instance wins, as in the reference ``:117-120``) and
    to filter jobs — instance runtime within [min, max], at least
    ``min_deps`` dependent tasks, fan-out ≤ ``max_parallel``, all declared
    dependencies present (ref ``:86-113``).
  * Surviving jobs are bucketed into ``interval``-second windows by first
    task start; each window holds at most ``n_jobs`` jobs and is written
    as ``jobs-{n}-{p}-{start}-{end}.yaml`` (ref ``:197-199``) and/or the
    framework's columnar ``.npz`` (``pivot_tpu.workload.convert``).

Usage:
  python -m pivot_tpu.experiments.sample -n 5000 -s 86400 -i 86400 \\
      --batch-task csv/batch_task.csv --batch-instance csv/batch_instance.csv \\
      -o data/jobs [--npz]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, Optional

import yaml

__all__ = ["parse_task_name", "load_job_dags", "sample_windows", "main"]


def parse_task_name(name: str):
    """Task name → (task_id, [dep ids]); None for standalone tasks."""
    if name.startswith("task") or name == "MergeTask":
        return name, []
    parts = [
        p for p in name[1:].strip().split("_") if p and not p.startswith("Stg")
    ]
    return int(parts[0]), [int(d) for d in parts[1:]]


def load_job_dags(batch_task_csv: str) -> Dict[str, dict]:
    """First pass: job DAG skeletons from batch_task.csv.

    A job with any Failed task row is excluded permanently — later rows of
    the same job must not resurrect it (exclusion is row-order independent).
    """
    jobs: Dict[str, dict] = {}
    failed = set()
    with open(batch_task_csv) as f:
        for line in f:
            fields = line.rstrip("\n").split(",")
            if len(fields) < 9:
                continue
            t_name, n_inst, j_name, _t_type, status, start, end, cpus, mem = fields[:9]
            if not (t_name and j_name and cpus and mem and start and end):
                continue
            if j_name in failed:
                continue
            if status == "Failed":
                failed.add(j_name)
                jobs.pop(j_name, None)
                continue
            job = jobs.setdefault(
                j_name,
                {"id": j_name, "tasks": {}, "submit_time": float("inf"), "finish_time": 0},
            )
            start, end = int(start), int(end)
            job["submit_time"] = min(job["submit_time"], start)
            job["finish_time"] = max(job["finish_time"], end)
            task_id, deps = parse_task_name(t_name)
            job["tasks"][task_id] = {
                "id": task_id,
                "cpus": float(cpus) / 100.0,
                "mem": float(mem),
                "n_instances": int(n_inst),
                "dependencies": deps,
                "start_time": start,
                "end_time": end,
            }
    return jobs


def _job_ok(job: dict, min_deps: int, max_parallel: int) -> bool:
    tasks = job["tasks"]
    if not tasks:
        return False
    if max(t["n_instances"] for t in tasks.values()) > max_parallel:
        return False
    if sum(1 for t in tasks.values() if t["dependencies"]) < min_deps:
        return False
    # Every declared dependency must resolve, and every task needs a runtime.
    for t in tasks.values():
        if "runtime" not in t or t["start_time"] >= t["end_time"]:
            return False
        for d in t["dependencies"]:
            if d not in tasks:
                return False
    return True


def sample_windows(
    batch_instance_csv: str,
    jobs: Dict[str, dict],
    n_jobs: int,
    start: int,
    interval: int,
    min_runtime: int = 60,
    max_runtime: int = 1000,
    min_deps: int = 1,
    max_parallel: int = 100,
    progress=None,
) -> Dict[int, list]:
    """Second pass: stream instances, attach runtimes, filter, window."""
    excluded = set()
    windows: Dict[int, dict] = {}
    placed_key: Dict[str, int] = {}
    with open(batch_instance_csv) as f:
        for line in f:
            fields = line.rstrip("\n").split(",")
            if len(fields) < 8:
                continue
            _, t_name, j_name, _, status, t_start, t_end, machine = fields[:8]
            if (
                not t_name
                or not j_name
                or j_name in excluded
                or j_name not in jobs
                or status == "Failed"
                or not t_start
                or not t_end
                or not machine
            ):
                continue
            t_start, t_end = int(t_start), int(t_end)
            if t_start <= 0 or t_end <= 0 or t_start >= t_end or t_end - t_start > max_runtime:
                excluded.add(j_name)
                for w in windows.values():
                    w.pop(j_name, None)
                continue
            job = jobs[j_name]
            task_id, _ = parse_task_name(t_name)
            task = job["tasks"].get(task_id)
            if task is None:
                excluded.add(j_name)
                continue
            task["start_time"], task["end_time"] = t_start, t_end
            task["runtime"] = t_end - t_start
            # Window membership is (re-)evaluated as runtimes accumulate.
            first = min(t["start_time"] for t in job["tasks"].values())
            last = max(t["end_time"] for t in job["tasks"].values())
            if first <= start or last - first < min_runtime:
                continue
            if not _job_ok(job, min_deps, max_parallel):
                continue
            key = first // interval * interval
            # A later instance row can shift the job's first start into a
            # different window — move it, never duplicate across windows.
            prev_key = placed_key.get(j_name)
            if prev_key is not None and prev_key != key:
                windows.get(prev_key, {}).pop(j_name, None)
                placed_key.pop(j_name)
            bucket = windows.setdefault(key, {})
            if j_name in bucket or len(bucket) < n_jobs:
                bucket[j_name] = job
                placed_key[j_name] = key
                if progress:
                    progress({k: len(v) for k, v in sorted(windows.items())})
            if windows and all(len(b) >= n_jobs for b in windows.values()):
                break
    # Finalize: strip bookkeeping fields.
    out: Dict[int, list] = {}
    for key, bucket in windows.items():
        fin = []
        for job in bucket.values():
            fin.append(
                {
                    "id": job["id"],
                    "submit_time": int(job["submit_time"]),
                    "finish_time": int(job["finish_time"]),
                    "tasks": [
                        {
                            "id": t["id"],
                            "cpus": t["cpus"],
                            "mem": t["mem"],
                            "n_instances": t["n_instances"],
                            "runtime": t["runtime"],
                            "dependencies": t["dependencies"],
                        }
                        for t in job["tasks"].values()
                    ],
                }
            )
        out[key] = fin
    return out


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-jobs", "-n", type=int, required=True)
    parser.add_argument("--min-runtime", "-l", type=int, default=60)
    parser.add_argument("--max-runtime", "-u", type=int, default=1000)
    parser.add_argument("--start", "-s", type=int, required=True)
    parser.add_argument("--interval", "-i", type=int, required=True)
    parser.add_argument("--min-deps", "-d", type=int, default=1)
    parser.add_argument("--max-parallel", "-p", type=int, default=100)
    parser.add_argument("--batch-task", default="csv/batch_task.csv")
    parser.add_argument("--batch-instance", default="csv/batch_instance.csv")
    parser.add_argument("--output-dir", "-o", required=True)
    parser.add_argument(
        "--npz", action="store_true", help="also write columnar .npz archives"
    )
    args = parser.parse_args(argv)

    os.makedirs(args.output_dir, exist_ok=True)
    print("loading job DAGs ...")
    jobs = load_job_dags(args.batch_task)
    print(f"{len(jobs)} candidate jobs; sampling ...")
    windows = sample_windows(
        args.batch_instance,
        jobs,
        args.num_jobs,
        args.start,
        args.interval,
        args.min_runtime,
        args.max_runtime,
        args.min_deps,
        args.max_parallel,
        progress=lambda c: print(f"\rsampled: {c}", end="", file=sys.stderr),
    )
    print(f"\nwriting {len(windows)} window files ...")
    for key, window_jobs in windows.items():
        base = f"jobs-{args.num_jobs}-{args.max_parallel}-{key}-{key + args.interval}"
        yaml_path = os.path.join(args.output_dir, base + ".yaml")
        with open(yaml_path, "w") as f:
            yaml.safe_dump(window_jobs, f, default_flow_style=False)
        if args.npz:
            from pivot_tpu.workload.convert import convert_yaml_trace

            convert_yaml_trace(yaml_path, os.path.join(args.output_dir, base + ".npz"))
        print(f"  {base}: {len(window_jobs)} jobs")


if __name__ == "__main__":
    main()
