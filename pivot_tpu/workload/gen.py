"""Synthetic application generators.

Capability parity with the reference's ``application/gen.py``:
random-DAG apps (``:12-77``), sequential chains (``:80-122``), and
data-parallel stage DAGs (``:125-195``).  All generators take an explicit
``numpy.random.Generator`` — no hidden global seeding (the reference calls
``rnd.seed`` in constructors, ``application/gen.py:30``) — so ensembles can
fan out over independent streams.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pivot_tpu.utils import LogMixin, fresh_id
from pivot_tpu.workload import Application, TaskGroup

__all__ = [
    "random_dag_edges",
    "RandomApplicationGenerator",
    "SequentialApplicationGenerator",
    "DataParallelApplicationGenerator",
]


def random_dag_edges(
    rng: np.random.Generator, n_nodes: int, edge_density: float
) -> List[Tuple[int, int]]:
    """Random DAG edge list: keep gnp edges (u, v) with u < v.

    Same construction as the reference's RandomDAGGenerator
    (``application/gen.py:33-36``) — sampling a directed gnp graph and
    keeping only forward edges guarantees acyclicity.
    """
    mask = rng.random((n_nodes, n_nodes)) < edge_density
    upper = np.triu(mask, k=1)
    return [(int(u), int(v)) for u, v in zip(*np.nonzero(upper))]


class _RangeSpec:
    """Bounds holder for group attribute sampling."""

    def __init__(
        self,
        cpus: Tuple[float, float],
        mem: Tuple[float, float],
        disk: Tuple[float, float] = (0, 0),
        gpus: Tuple[int, int] = (0, 0),
        runtime: Tuple[float, float] = (1, 1),
        output_size: Tuple[float, float] = (0, 0),
    ):
        assert 0 < cpus[0] <= cpus[1]
        assert 0 < mem[0] <= mem[1]
        assert 0 <= disk[0] <= disk[1]
        assert 0 <= gpus[0] <= gpus[1]
        assert 0 < runtime[0] <= runtime[1]
        assert 0 <= output_size[0] <= output_size[1]
        self.cpus, self.mem, self.disk, self.gpus = cpus, mem, disk, gpus
        self.runtime, self.output_size = runtime, output_size

    def sample_group(self, rng: np.random.Generator, gid: str) -> TaskGroup:
        return TaskGroup(
            gid,
            cpus=float(rng.uniform(*self.cpus)),
            mem=float(rng.integers(self.mem[0], self.mem[1] + 1)),
            disk=float(rng.integers(self.disk[0], self.disk[1] + 1)),
            gpus=float(rng.integers(self.gpus[0], self.gpus[1] + 1)),
            runtime=float(rng.uniform(*self.runtime)),
            output_size=float(
                rng.integers(self.output_size[0], self.output_size[1] + 1)
            ),
        )


class RandomApplicationGenerator(LogMixin):
    """Applications over random gnp DAGs (ref ``application/gen.py:39-77``)."""

    def __init__(
        self,
        n_nodes: Tuple[int, int],
        edge_density: Tuple[float, float],
        spec: _RangeSpec,
        seed: Optional[int] = None,
    ):
        assert 1 < n_nodes[0] <= n_nodes[1]
        assert 0 < edge_density[0] <= edge_density[1] <= 1
        self._n_nodes = n_nodes
        self._edge_density = edge_density
        self._spec = spec
        self._rng = np.random.default_rng(seed)

    def generate(self) -> Application:
        rng = self._rng
        n = int(rng.integers(self._n_nodes[0], self._n_nodes[1] + 1))
        density = float(rng.uniform(*self._edge_density))
        edges = random_dag_edges(rng, n, density)
        groups = {i: self._spec.sample_group(rng, str(i)) for i in range(n)}
        for u, v in edges:
            groups[v].add_dependencies(str(u))
        return Application(fresh_id("app"), list(groups.values()))


class SequentialApplicationGenerator(LogMixin):
    """Chain-DAG applications (ref ``application/gen.py:80-122``)."""

    def __init__(
        self, n_nodes: Tuple[int, int], spec: _RangeSpec, seed: Optional[int] = None
    ):
        assert 0 < n_nodes[0] <= n_nodes[1]
        self._n_nodes = n_nodes
        self._spec = spec
        self._rng = np.random.default_rng(seed)

    def generate(self) -> Application:
        rng = self._rng
        n = int(rng.integers(self._n_nodes[0], self._n_nodes[1] + 1))
        groups = [self._spec.sample_group(rng, str(i)) for i in range(n)]
        for i in range(1, n):
            groups[i].add_dependencies(str(i - 1))
        return Application(fresh_id("app"), groups)


class DataParallelApplicationGenerator(LogMixin):
    """Alternating sequential / fan-out stages (ref ``application/gen.py:125-195``).

    Each stage is either one group (sequential) or ``parallel_level`` groups
    (parallel); every group in a stage depends round-robin on the groups of
    the previous stage, mirroring the reference's modulo wiring
    (``application/gen.py:180-189``).
    """

    def __init__(
        self,
        seq_steps: Tuple[int, int],
        parallel_steps: Tuple[int, int],
        parallel_level: Tuple[int, int],
        spec: _RangeSpec,
        seed: Optional[int] = None,
    ):
        assert 0 <= seq_steps[0] <= seq_steps[1]
        assert 0 <= parallel_steps[0] <= parallel_steps[1]
        assert 1 < parallel_level[0] <= parallel_level[1]
        self._seq_steps = seq_steps
        self._parallel_steps = parallel_steps
        self._parallel_level = parallel_level
        self._spec = spec
        self._rng = np.random.default_rng(seed)

    def generate(self) -> Application:
        rng = self._rng
        n_seq = int(rng.integers(self._seq_steps[0], self._seq_steps[1] + 1))
        n_par = int(rng.integers(self._parallel_steps[0], self._parallel_steps[1] + 1))
        total = n_seq + n_par
        assert total > 0, "at least one stage required"
        p_seq = n_seq / total
        stage_kinds = rng.random(total) < p_seq

        groups: List[TaskGroup] = []
        last_stage: List[str] = []
        next_id = 1
        for is_seq in stage_kinds:
            if is_seq:
                g = self._spec.sample_group(rng, str(next_id))
                g.output_size = g.output_size * g.runtime
                g.add_dependencies(*last_stage)
                groups.append(g)
                last_stage = [g.id]
                next_id += 1
            else:
                level = (
                    int(
                        rng.integers(
                            self._parallel_level[0], self._parallel_level[1] + 1
                        )
                    )
                    if len(last_stage) < 2
                    else len(last_stage)
                )
                stage_ids = []
                for i in range(level):
                    g = self._spec.sample_group(rng, str(next_id + i))
                    g.output_size = g.output_size * g.runtime
                    # Round-robin wiring onto the previous stage.
                    g.add_dependencies(*last_stage[i % max(level, 1) :: level])
                    groups.append(g)
                    stage_ids.append(g.id)
                last_stage = stage_ids
                next_id += level
        return Application(fresh_id("app"), groups)
