"""Workload model: Application DAGs of task groups fanned out into tasks.

Capability parity with the reference's ``application/__init__.py``:
  * ``Application``  — a DAG of task groups with readiness semantics
    (ref ``application/__init__.py:15-156``).
  * ``TaskGroup``    — one DAG node: a task *type* replicated into
    ``instances`` identical tasks (ref "Container",
    ``application/__init__.py:215-326``).
  * ``Task``         — the schedulable unit (ref ``:167-212``).

Differences by design (TPU-first):
  * The DAG is stored as plain predecessor/successor index lists — no
    networkx.  Cycle detection is a Kahn topological sort.  Dense integer
    indices are the native currency of the placement kernels
    (``pivot_tpu.ops``), so the DAG also exports its structure as numpy
    arrays (``demand_matrix``, ``pred_matrix``) for device-resident rollouts.
  * ``Task.set_nascent`` actually resets state (the reference has a
    no-op ``==`` typo at ``application/__init__.py:203``; the retry path
    still works there only because ``placement`` is cleared — we implement
    the evident intent and test the retry path explicitly).
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from pivot_tpu.utils import LogMixin, fresh_id

__all__ = ["TaskState", "Task", "TaskGroup", "Application", "DagError", "Dataflow"]


class DagError(ValueError):
    """Raised when a task-group dependency graph is not a DAG."""


class TaskState(enum.Enum):
    NASCENT = "nascent"
    SUBMITTED = "submitted"
    RUNNING = "running"
    FINISHED = "finished"
    #: Terminal dead-letter state (retry governance, ``sched/retry.py``):
    #: the task exhausted its retry budget and will never be resubmitted.
    #: Not FINISHED — a dead task never counts toward group completion,
    #: so its application cannot silently "finish" around a lost task.
    DEAD = "dead"


class Task:
    """One replica instance of a task group — the unit of placement.

    Composite id ``<group_id>/<ordinal>`` as in the reference
    (``application/__init__.py:182-184``).
    """

    __slots__ = ("group", "ordinal", "placement", "state", "runtime")

    def __init__(self, group: "TaskGroup", ordinal: int):
        self.group = group
        self.ordinal = ordinal
        self.placement: Optional[str] = None
        self.state = TaskState.NASCENT
        # Per-task runtime enables Monte-Carlo perturbation of individual
        # replicas; defaults to the group's runtime.
        self.runtime = group.runtime

    @property
    def id(self) -> str:
        return f"{self.group.id}/{self.ordinal}"

    @property
    def application(self) -> "Application":
        return self.group.application

    @property
    def cpus(self) -> float:
        return self.group.cpus

    @property
    def mem(self) -> float:
        return self.group.mem

    @property
    def disk(self) -> float:
        return self.group.disk

    @property
    def gpus(self) -> float:
        return self.group.gpus

    @property
    def output_size(self) -> float:
        return self.group.output_size

    @property
    def demand(self) -> np.ndarray:
        """[4] demand vector (shared with the group; do not mutate)."""
        return self.group.demand_np

    @property
    def is_nascent(self) -> bool:
        return self.state == TaskState.NASCENT

    @property
    def is_finished(self) -> bool:
        return self.state == TaskState.FINISHED

    @property
    def is_dead(self) -> bool:
        return self.state == TaskState.DEAD

    def _leave_finished(self) -> None:
        if self.state == TaskState.FINISHED:
            self.group._n_finished -= 1

    def set_nascent(self) -> None:
        self._leave_finished()
        self.state = TaskState.NASCENT

    def set_submitted(self) -> None:
        self._leave_finished()
        self.state = TaskState.SUBMITTED

    def set_running(self) -> None:
        self._leave_finished()
        self.state = TaskState.RUNNING

    def set_finished(self) -> None:
        if self.state != TaskState.FINISHED:
            self.group._n_finished += 1
        self.state = TaskState.FINISHED

    def set_dead(self) -> None:
        """Dead-letter terminal transition (see ``TaskState.DEAD``)."""
        self._leave_finished()
        self.state = TaskState.DEAD

    def __repr__(self) -> str:
        return f"Task({self.id}@{self.placement})"


class TaskGroup(LogMixin):
    """A DAG node: one task type fanned out into ``instances`` replicas."""

    def __init__(
        self,
        id: str,
        cpus: float,
        mem: float,
        disk: float = 0.0,
        gpus: float = 0.0,
        runtime: float = 0.0,
        output_size: float = 0.0,
        instances: int = 1,
        dependencies: Sequence[str] = (),
    ):
        if instances < 1:
            raise ValueError(f"instances must be >= 1, got {instances}")
        self.id = str(id)
        self.cpus = float(cpus)
        self.mem = float(mem)
        self.disk = float(disk)
        self.gpus = float(gpus)
        self.runtime = float(runtime)
        self.output_size = float(output_size)
        self.instances = int(instances)
        self.dependencies: List[str] = [str(d) for d in dependencies]
        self.application: Optional["Application"] = None
        self._tasks: List[Task] = []
        self._demand_np: Optional[np.ndarray] = None
        self._n_finished = 0  # maintained by Task state setters

    @property
    def demand_np(self) -> np.ndarray:
        """Cached [4] demand vector shared by all task instances (treat as
        immutable — the group's shape never changes after construction)."""
        if self._demand_np is None:
            self._demand_np = np.array(
                [self.cpus, self.mem, self.disk, self.gpus], dtype=np.float64
            )
        return self._demand_np

    @property
    def tasks(self) -> List[Task]:
        return list(self._tasks)

    @property
    def is_finished(self) -> bool:
        # A group with no materialized tasks is NOT finished (ref
        # ``application/__init__.py:297-299``).  O(1) via the counter.
        return 0 < len(self._tasks) == self._n_finished

    def materialize_tasks(self) -> List[Task]:
        """Create (once) and return the group's task replicas."""
        while len(self._tasks) < self.instances:
            self._tasks.append(Task(self, len(self._tasks)))
        return list(self._tasks)

    def add_dependencies(self, *group_ids: str) -> None:
        self.dependencies = sorted(set(self.dependencies) | set(map(str, group_ids)))

    def clone(self) -> "TaskGroup":
        return TaskGroup(
            self.id,
            self.cpus,
            self.mem,
            self.disk,
            self.gpus,
            self.runtime,
            self.output_size,
            self.instances,
            self.dependencies,
        )

    def __repr__(self) -> str:
        return f"TaskGroup({self.id} x{self.instances})"


class Application(LogMixin):
    """A DAG of task groups — the unit of submission.

    Readiness semantics mirror the reference: a group is ready when every
    predecessor group is finished (``application/__init__.py:101-105``); the
    app is finished when all sink groups are finished (``:66-68``).
    """

    def __init__(self, id: str, groups: Iterable[TaskGroup]):
        self.id = str(id)
        self._groups: Dict[str, TaskGroup] = {}
        for g in groups:
            if g.id in self._groups:
                raise ValueError(f"duplicate task group id {g.id!r}")
            self._groups[g.id] = g
            g.application = self
        self._order: List[str] = list(self._groups)  # insertion order -> index
        self._index: Dict[str, int] = {gid: i for i, gid in enumerate(self._order)}
        self._preds: List[List[int]] = [[] for _ in self._order]
        self._succs: List[List[int]] = [[] for _ in self._order]
        for gid, g in self._groups.items():
            i = self._index[gid]
            for dep in g.dependencies:
                if dep not in self._index:
                    raise DagError(f"unknown dependency {dep!r} of group {gid!r}")
                j = self._index[dep]
                if i not in self._succs[j]:
                    self._succs[j].append(i)
                    self._preds[i].append(j)
        self._check_acyclic()
        self.start_time: float = 0.0
        self.end_time: float = 0.0
        #: Set by retry governance when a task of this app is
        #: dead-lettered: the DAG can never finish, the scheduler stops
        #: tracking it, and the serving layer reaps it as a failed job.
        self.failed: bool = False

    # -- structure -------------------------------------------------------
    def _check_acyclic(self) -> None:
        indeg = [len(p) for p in self._preds]
        frontier = [i for i, d in enumerate(indeg) if d == 0]
        seen = 0
        while frontier:
            node = frontier.pop()
            seen += 1
            for s in self._succs[node]:
                indeg[s] -= 1
                if indeg[s] == 0:
                    frontier.append(s)
        if seen != len(self._order):
            raise DagError(f"dependencies of application {self.id!r} form a cycle")

    @property
    def groups(self) -> List[TaskGroup]:
        return [self._groups[gid] for gid in self._order]

    # Reference-familiar alias ("containers").
    containers = groups

    @property
    def avg_output_size(self) -> float:
        return float(np.mean([g.output_size for g in self.groups]))

    def get_group(self, gid: str) -> Optional[TaskGroup]:
        return self._groups.get(str(gid))

    def get_predecessors(self, gid: str) -> List[TaskGroup]:
        i = self._require_index(gid)
        return [self._groups[self._order[j]] for j in self._preds[i]]

    def get_successors(self, gid: str) -> List[TaskGroup]:
        i = self._require_index(gid)
        return [self._groups[self._order[j]] for j in self._succs[i]]

    def get_unfinished_predecessors(self, gid: str) -> List[TaskGroup]:
        return [p for p in self.get_predecessors(gid) if not p.is_finished]

    def get_ready_successors(self, gid: str) -> List[TaskGroup]:
        return [
            s
            for s in self.get_successors(gid)
            if not self.get_unfinished_predecessors(s.id)
        ]

    def get_sources(self) -> List[TaskGroup]:
        return [
            self._groups[self._order[i]]
            for i in range(len(self._order))
            if not self._preds[i]
        ]

    def get_sinks(self) -> List[TaskGroup]:
        return [
            self._groups[self._order[i]]
            for i in range(len(self._order))
            if not self._succs[i]
        ]

    @property
    def is_finished(self) -> bool:
        return all(s.is_finished for s in self.get_sinks())

    def clone(self) -> "Application":
        return Application(fresh_id("app"), [g.clone() for g in self.groups])

    def _require_index(self, gid: str) -> int:
        i = self._index.get(str(gid))
        if i is None:
            raise KeyError(f"unknown task group {gid!r}")
        return i

    # -- analytics -------------------------------------------------------
    def critical_path_runtime(self) -> float:
        """Longest runtime path through the DAG (lower bound on makespan).

        The reference's never-called ``estimate_local_runtime``
        (``application/__init__.py:115-126``) computes the same quantity; here
        it is a clean longest-path DP in topological order and *is* used (by
        the ensemble rollout engine as a normalization reference).
        """
        n = len(self._order)
        finish = [0.0] * n
        indeg = [len(p) for p in self._preds]
        frontier = [i for i, d in enumerate(indeg) if d == 0]
        while frontier:
            i = frontier.pop()
            g = self._groups[self._order[i]]
            base = max((finish[j] for j in self._preds[i]), default=0.0)
            finish[i] = base + g.runtime
            for s in self._succs[i]:
                indeg[s] -= 1
                if indeg[s] == 0:
                    frontier.append(s)
        return max(finish, default=0.0)

    # -- dense exports for the TPU kernels -------------------------------
    def demand_matrix(self) -> np.ndarray:
        """[G, 4] per-group resource demand (cpus, mem, disk, gpus)."""
        return np.array(
            [[g.cpus, g.mem, g.disk, g.gpus] for g in self.groups], dtype=np.float32
        )

    def pred_matrix(self) -> np.ndarray:
        """[G, G] boolean: entry (i, j) true iff group j is a predecessor of i."""
        n = len(self._order)
        mat = np.zeros((n, n), dtype=bool)
        for i, preds in enumerate(self._preds):
            mat[i, preds] = True
        return mat

    def group_vectors(self) -> Dict[str, np.ndarray]:
        """Runtime / output-size / instance-count vectors, index-aligned."""
        groups = self.groups
        return {
            "runtime": np.array([g.runtime for g in groups], dtype=np.float32),
            "output_size": np.array([g.output_size for g in groups], dtype=np.float32),
            "instances": np.array([g.instances for g in groups], dtype=np.int32),
        }

    def __repr__(self) -> str:
        return f"Application({self.id}, {len(self._order)} groups)"


# Reference-familiar alias.
Container = TaskGroup


class Dataflow:
    """A (source group, destination group, data size) edge record.

    API-parity shim for the reference's ``Dataflow``
    (``application/__init__.py:329-352``), which is dead code there — never
    instantiated; edge weight is carried by ``Container.output_size``
    instead.  Kept here (equally unused by the framework) so code written
    against the reference's full surface imports cleanly; prefer
    ``TaskGroup.output_size``.
    """

    __slots__ = ("src", "dst", "data_size")

    def __init__(self, src: str, dst: str, data_size: float = 0.0):
        self.src = src
        self.dst = dst
        self.data_size = data_size

    def __eq__(self, other):
        return (
            isinstance(other, Dataflow)
            and (self.src, self.dst, self.data_size)
            == (other.src, other.dst, other.data_size)
        )

    def __hash__(self):
        return hash((self.src, self.dst, self.data_size))

    def __repr__(self):
        return f"Dataflow({self.src} -> {self.dst}, {self.data_size} MB)"
