"""Convert sampled Alibaba job YAML traces into columnar ``.npz`` archives.

The YAML schema (one list entry per job — ref ``alibaba/jobs/*.yaml``,
``alibaba/sample.py:197-199``) parses slowly (~seconds per 3 MB file); the
columnar form loads in milliseconds and is the canonical on-disk workload
format of this framework.  Layout (all arrays index-aligned):

  jobs:   ``job_id`` [J] str, ``submit_time`` [J] f64, ``finish_time`` [J]
          f64, ``task_start`` [J+1] i64 (CSR offsets into the task arrays)
  tasks:  ``task_id`` [T] i64, ``cpus`` [T] f32, ``mem`` [T] f32,
          ``n_instances`` [T] i32, ``runtime`` [T] f32,
          ``dep_start`` [T+1] i64, ``deps`` [D] i64 (CSR of task-id deps)

Usage:  python -m pivot_tpu.workload.convert SRC.yaml... --out-dir DIR
"""

from __future__ import annotations

import argparse
import os
from typing import List

import numpy as np
import yaml

__all__ = ["convert_yaml_trace", "main"]


def convert_yaml_trace(yaml_path: str, npz_path: str) -> dict:
    with open(yaml_path) as f:
        jobs = yaml.safe_load(f)

    job_id: List[str] = []
    submit_time: List[float] = []
    finish_time: List[float] = []
    task_start = [0]
    task_id: List[int] = []
    cpus: List[float] = []
    mem: List[float] = []
    n_instances: List[int] = []
    runtime: List[float] = []
    dep_start = [0]
    deps: List[int] = []

    for j in jobs:
        job_id.append(str(j["id"]))
        submit_time.append(float(j["submit_time"]))
        finish_time.append(float(j.get("finish_time", 0)))
        for t in j["tasks"]:
            task_id.append(int(t["id"]))
            cpus.append(float(t["cpus"]))
            mem.append(float(t["mem"]))
            n_instances.append(int(t["n_instances"]))
            runtime.append(float(t["runtime"]))
            deps.extend(int(d) for d in t.get("dependencies", ()))
            dep_start.append(len(deps))
        task_start.append(len(task_id))

    arrays = {
        "job_id": np.array(job_id),
        "submit_time": np.array(submit_time, dtype=np.float64),
        "finish_time": np.array(finish_time, dtype=np.float64),
        "task_start": np.array(task_start, dtype=np.int64),
        "task_id": np.array(task_id, dtype=np.int64),
        "cpus": np.array(cpus, dtype=np.float32),
        "mem": np.array(mem, dtype=np.float32),
        "n_instances": np.array(n_instances, dtype=np.int32),
        "runtime": np.array(runtime, dtype=np.float32),
        "dep_start": np.array(dep_start, dtype=np.int64),
        "deps": np.array(deps, dtype=np.int64),
    }
    np.savez_compressed(npz_path, **arrays)
    return {"jobs": len(job_id), "tasks": len(task_id), "deps": len(deps)}


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("sources", nargs="+", help="YAML trace files")
    parser.add_argument("--out-dir", required=True)
    args = parser.parse_args(argv)
    os.makedirs(args.out_dir, exist_ok=True)
    for src in args.sources:
        base = os.path.splitext(os.path.basename(src))[0]
        dst = os.path.join(args.out_dir, base + ".npz")
        stats = convert_yaml_trace(src, dst)
        print(f"{src} -> {dst}: {stats}")


if __name__ == "__main__":
    main()
