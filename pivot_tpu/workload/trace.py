"""Alibaba 2018 cluster-trace workload loading.

Capability parity with the reference's ``TraceBasedApplicationGenerator``
(``alibaba/runner.py:55-136``):

  * Job YAML schema: ``{id, submit_time, finish_time, tasks: [{id, cpus, mem,
    n_instances, runtime, dependencies}]}`` (ref ``alibaba/jobs/*.yaml``).
  * ``MEM_SCALE_FACTOR = 7.68 * 1024``: trace memory demands are normalized;
    assuming 96-core / 768 GB machines (r5d.24xlarge-equivalent) makes them
    absolute MB values (rationale documented at ``alibaba/runner.py:56-69``).
  * ``output_size = mem * output_size_scale_factor`` (ref
    ``alibaba/runner.py:97-100``) — a task's output data volume is modeled
    as proportional to its memory demand.

The loader itself is pure (file → sorted submission schedule); replaying the
schedule into a scheduler is the job of ``pivot_tpu.experiments.runner``.
"""

from __future__ import annotations

from bisect import insort
from typing import List, Tuple

import yaml

from pivot_tpu.utils import LogMixin
from pivot_tpu.workload import Application, TaskGroup

__all__ = ["MEM_SCALE_FACTOR", "load_trace_jobs", "TraceSchedule"]

MEM_SCALE_FACTOR = 7.68 * 1024  # normalized trace mem -> absolute MB


def _job_to_application(job: dict, output_size_scale_factor: float) -> Application:
    groups = []
    for t in job["tasks"]:
        groups.append(
            TaskGroup(
                str(t["id"]),
                cpus=float(t["cpus"]),
                mem=float(t["mem"]) * MEM_SCALE_FACTOR,
                output_size=float(t["mem"]) * output_size_scale_factor,
                runtime=float(t["runtime"]),
                instances=int(t["n_instances"]),
                dependencies=[str(d) for d in t.get("dependencies", ())],
            )
        )
    return Application(str(job["id"]), groups)


class TraceSchedule:
    """Submission schedule: a time-sorted list of (submit_time, [apps])."""

    def __init__(self, bins: List[Tuple[float, List[Application]]]):
        self.bins = bins

    @property
    def apps(self) -> List[Application]:
        return [a for _, apps in self.bins for a in apps]

    def __len__(self) -> int:
        return sum(len(apps) for _, apps in self.bins)

    def take(self, n_apps: int) -> "TraceSchedule":
        """First ``n_apps`` applications in submission order."""
        out, count = [], 0
        for ts, apps in self.bins:
            if count >= n_apps:
                break
            chunk = apps[: n_apps - count]
            out.append((ts, chunk))
            count += len(chunk)
        return TraceSchedule(out)


def _iter_yaml_jobs(trace_file: str):
    with open(trace_file) as f:
        yield from yaml.safe_load(f)


def _iter_npz_jobs(trace_file: str):
    """Stream jobs out of the columnar archive (see workload/convert.py)."""
    import numpy as np

    with np.load(trace_file, allow_pickle=False) as data:
        job_id = data["job_id"]
        submit = data["submit_time"]
        finish = data["finish_time"]
        tstart = data["task_start"]
        task_id = data["task_id"]
        cpus = data["cpus"]
        mem = data["mem"]
        n_inst = data["n_instances"]
        runtime = data["runtime"]
        dstart = data["dep_start"]
        deps = data["deps"]
    for j in range(len(job_id)):
        lo, hi = int(tstart[j]), int(tstart[j + 1])
        tasks = [
            {
                "id": int(task_id[t]),
                "cpus": float(cpus[t]),
                "mem": float(mem[t]),
                "n_instances": int(n_inst[t]),
                "runtime": float(runtime[t]),
                "dependencies": [
                    int(d) for d in deps[int(dstart[t]) : int(dstart[t + 1])]
                ],
            }
            for t in range(lo, hi)
        ]
        yield {
            "id": str(job_id[j]),
            "submit_time": float(submit[j]),
            "finish_time": float(finish[j]),
            "tasks": tasks,
        }


def load_trace_jobs(
    trace_file: str, output_size_scale_factor: float = 1000.0
) -> TraceSchedule:
    """Parse a sampled Alibaba trace (``.yaml`` or columnar ``.npz``) into a
    time-sorted submission schedule."""
    if trace_file.endswith(".npz"):
        jobs = _iter_npz_jobs(trace_file)
    else:
        jobs = _iter_yaml_jobs(trace_file)
    times: List[float] = []
    index = {}
    for job in jobs:
        app = _job_to_application(job, output_size_scale_factor)
        ts = float(job["submit_time"])
        if ts in index:
            index[ts].append(app)
        else:
            index[ts] = [app]
            insort(times, ts)
    return TraceSchedule([(ts, index[ts]) for ts in times])
