"""The searchable scoring-weight vector shared by every backend.

Until this round the scoring knobs lived as scattered constructor
arguments: ``risk_weight`` / ``rework_cost`` on every policy
(``sched/policies.py``, ``sched/tpu.py``, consumed by the kernels'
``risk`` operand via ``policies.resolve_risk``) and the fit / egress /
bandwidth coefficients hard-coded as implicit 1.0 exponents inside each
score expression (``cost_rt × decay / (norm × bw_rt)``).  The ensemble
estimator already exposed the exponent triple as ``score_params``
(``score_param_sweep``) — but nothing typed the full vector, so there
was nothing a search loop could optimize over.

:class:`PolicyWeights` is that vector.  Five dimensions:

  ==============  =====================================================
  ``w_cost``      exponent on the round-trip egress-cost term
  ``w_bw``        exponent on the round-trip bandwidth term
  ``w_norm``      exponent on the residual-capacity (fit) norm
  ``risk_weight`` weight of the eviction-risk penalty
                  (``risk_weight × hazard × rework_cost``, PR 9's rule)
  ``rework_cost`` scalar price of a lost placement (the risk term's
                  other factor)
  ==============  =====================================================

**Bit-parity contract**: the default vector is exactly today's
hand-tuned configuration — exponents ``(1, 1, 1)`` and a disengaged
risk term — and every backend that accepts ``weights=`` must route the
default through its existing unparameterized code path (the CPU
policies branch on :meth:`score_exponents` returning None; the device
wrappers reduce it to the ``risk=None`` operand), so constructing a
policy with ``weights=PolicyWeights()`` reproduces current decisions
bit for bit.  ``tests/test_search.py`` pins this.

The module is deliberately dependency-light (numpy only): it sits at
the bottom of the search subsystem and is imported by ``sched`` — the
one place the layering inverts, and it must never drag the optimizer
stack along.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PolicyWeights", "SearchSpace", "DEFAULT_WEIGHTS"]


class PolicyWeights(NamedTuple):
    """One point in scoring-weight space.  See the module docstring for
    dimension semantics and the bit-parity contract of the default."""

    w_cost: float = 1.0
    w_bw: float = 1.0
    w_norm: float = 1.0
    risk_weight: float = 0.0
    rework_cost: float = 1.0

    #: Dimensionality of the searchable vector (the optimizers' D).
    DIM = 5
    #: Field names in vector order (``to_array`` / ``from_array``).
    NAMES = ("w_cost", "w_bw", "w_norm", "risk_weight", "rework_cost")

    # -- vector codec ------------------------------------------------------
    def to_array(self) -> np.ndarray:
        """[5] float64 vector in :data:`NAMES` order."""
        return np.asarray(tuple(self), dtype=np.float64)

    @classmethod
    def from_array(cls, arr) -> "PolicyWeights":
        a = np.asarray(arr, dtype=np.float64).reshape(-1)
        if a.shape[0] != cls.DIM:
            raise ValueError(
                f"PolicyWeights vector must have {cls.DIM} entries "
                f"({', '.join(cls.NAMES)}), got shape {np.shape(arr)}"
            )
        if not np.all(np.isfinite(a)):
            raise ValueError(f"PolicyWeights entries must be finite, got {a}")
        return cls(*(float(x) for x in a))

    @classmethod
    def stack(cls, seq: Sequence["PolicyWeights"]) -> np.ndarray:
        """[B, 5] candidate matrix — the population shape the fitness
        evaluator consumes (``evaluate_candidates``)."""
        rows = [
            w.to_array() if isinstance(w, PolicyWeights)
            else cls.from_array(w).to_array()
            for w in seq
        ]
        if not rows:
            raise ValueError("cannot stack an empty PolicyWeights population")
        return np.stack(rows)

    # -- backend resolution ------------------------------------------------
    def score_exponents(self) -> Optional[Tuple[float, float, float]]:
        """``(w_cost, w_bw, w_norm)`` when any exponent departs from the
        reference shape, else None — the None return IS the bit-parity
        switch: backends keep their exact unparameterized score
        expression (no ``pow``) whenever it is None, exactly like
        ``resolve_risk`` returning None keeps the risk-free program."""
        exps = (self.w_cost, self.w_bw, self.w_norm)
        if exps == (1.0, 1.0, 1.0):
            return None
        return exps

    def risk_coefficient(self) -> float:
        """``risk_weight × rework_cost`` — the scalar the per-host hazard
        row is scaled by (the two knobs only ever enter as this product;
        keeping both dimensions lets the search freeze one — see
        :class:`SearchSpace`)."""
        return self.risk_weight * self.rework_cost

    def validate(self) -> "PolicyWeights":
        """Self with the invariants every backend assumes: finite entries
        and a non-negative risk term (a negative risk weight would turn
        hazard into a *reward* and break the lexicographic first-fit
        rule's tie semantics)."""
        arr = self.to_array()
        if not np.all(np.isfinite(arr)):
            raise ValueError(f"PolicyWeights entries must be finite: {self}")
        if self.risk_weight < 0 or self.rework_cost < 0:
            raise ValueError(
                "risk_weight and rework_cost must be >= 0 "
                f"(got {self.risk_weight}, {self.rework_cost})"
            )
        return self


#: The hand-tuned configuration every backend shipped with — the search
#: loops' parity anchor and the regret reports' "hand-tuned" arm.
DEFAULT_WEIGHTS = PolicyWeights()


class SearchSpace(NamedTuple):
    """Box-bounded search domain over :class:`PolicyWeights` vectors.

    ``lo`` / ``hi`` are [5] bounds in :data:`PolicyWeights.NAMES` order;
    ``frozen`` marks dimensions the optimizers must pin to their initial
    value (``rework_cost`` defaults frozen: it prices the environment's
    restart overhead, and since the risk penalty only consumes the
    product ``risk_weight × rework_cost`` the pair is not jointly
    identifiable — searching both just adds a flat direction).
    """

    lo: np.ndarray
    hi: np.ndarray
    frozen: np.ndarray  # [5] bool

    @classmethod
    def default(
        cls,
        exp_lo: float = 0.0,
        exp_hi: float = 3.0,
        risk_hi: float = 50.0,
        freeze_rework: bool = True,
    ) -> "SearchSpace":
        lo = np.array([exp_lo, exp_lo, exp_lo, 0.0, 1.0], dtype=np.float64)
        hi = np.array([exp_hi, exp_hi, exp_hi, risk_hi, 1.0], dtype=np.float64)
        frozen = np.array([False, False, False, False, freeze_rework])
        if not freeze_rework:
            hi[4] = risk_hi
        return cls(lo=lo, hi=hi, frozen=frozen)

    def clip(self, pop: np.ndarray, anchor: np.ndarray) -> np.ndarray:
        """Population [B, 5] clipped into the box, frozen dims reset to
        ``anchor``'s values.  Pure and deterministic — the optimizers'
        projection step."""
        out = np.clip(np.asarray(pop, dtype=np.float64), self.lo, self.hi)
        out[:, self.frozen] = np.asarray(anchor, dtype=np.float64)[self.frozen]
        return out
