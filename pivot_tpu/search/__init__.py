"""Policy search at ensemble scale: learned schedulers with a regret oracle.

The subsystem that closes ROADMAP item 2 — it turns the vmapped rollout
ensemble from an evaluation engine into an *optimization* engine:

  * :mod:`pivot_tpu.search.weights` — :class:`PolicyWeights`, the typed
    scoring-weight vector every backend accepts (exponents on the
    fit/egress/bandwidth score terms + the PR-9 risk pair), with the
    hand-tuned defaults reproducing today's decisions bit-identically.
  * :mod:`pivot_tpu.search.fitness` — :class:`SearchEnv`, a seeded
    spot-market evaluation environment (``MarketSchedule`` hazards +
    the hazard-drawn ``ChaosSchedule`` preemption plan rendered into
    ensemble fault triples), and the jitted population evaluator behind
    ``pivot_tpu.sched.sensitivity.evaluate_candidates``: a [B]
    candidate population × R seeded Monte-Carlo rollouts as ONE device
    dispatch per generation, host-shardable over the mesh's replica
    axis so populations reach 10k+ rows.
  * :mod:`pivot_tpu.search.es` / :mod:`pivot_tpu.search.cem` —
    evolution-strategies and cross-entropy-method optimizers over that
    evaluator; seed-replayable end to end (same seed + same env ⇒ the
    identical winning vector and generation-by-generation fitness
    trace, across both fitness backends).
  * :mod:`pivot_tpu.search.oracle` — an exact small-instance
    branch-and-bound solver over the same fit + egress + risk
    objective, so "learned beats hand-tuned" is reported as *regret
    against an optimum* instead of a delta between heuristics.

The package ``__init__`` stays import-light on purpose: ``sched``
imports :class:`PolicyWeights` from here (the one place the layering
inverts), so the optimizer/fitness stack loads lazily via PEP 562 —
importing ``pivot_tpu.search`` must never drag JAX in.
"""

from __future__ import annotations

from pivot_tpu.search.weights import (  # noqa: F401
    DEFAULT_WEIGHTS,
    PolicyWeights,
    SearchSpace,
)

__all__ = [
    "DEFAULT_WEIGHTS",
    "PolicyWeights",
    "SearchSpace",
    "SearchEnv",
    "make_search_env",
    "evaluate_candidates",
    "cem_search",
    "es_search",
    "OracleInstance",
    "solve_instance",
    "placement_objective",
    "greedy_placement",
    "regret",
]

#: Lazily-resolved public names → defining submodule.  ``evaluate_candidates``
#: resolves through ``sched.sensitivity`` — the library exposure of the
#: batched-arm market evaluator (see that module) — so the two surfaces
#: are one function.
_LAZY = {
    "SearchEnv": "pivot_tpu.search.fitness",
    "make_search_env": "pivot_tpu.search.fitness",
    "evaluate_candidates": "pivot_tpu.sched.sensitivity",
    "cem_search": "pivot_tpu.search.cem",
    "es_search": "pivot_tpu.search.es",
    "OracleInstance": "pivot_tpu.search.oracle",
    "solve_instance": "pivot_tpu.search.oracle",
    "placement_objective": "pivot_tpu.search.oracle",
    "greedy_placement": "pivot_tpu.search.oracle",
    "regret": "pivot_tpu.search.oracle",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
