"""Exact small-instance placement solver — the search's regret reference.

Every headline of the policy-search subsystem before this module is a
*delta between heuristics* ("learned beats hand-tuned").  The oracle
turns that into **regret against an optimum**: for instances small
enough to solve exactly, branch-and-bound over the integer program

    minimize    Σ_placed  egress(t, zone(h_t)) + risk_coeff · hazard[h_t]
                + penalty · #unplaced
    subject to  Σ_{t on h} demand_t ≤ avail_h   (per host, 4 resources)

— the same fit + egress + risk objective the simulator meters, over a
single decision wave.  :func:`placement_objective` IS the objective
(one definition, used by the solver, the brute-force referee, and the
regret report), and :func:`instance_from_wave` derives the egress
coefficients from the ensemble's own sampled-pull bill
(``parallel.ensemble.bill._sampled_egress``'s expected-cost-per-pull
formula), so the oracle's dollars are the estimator meter's dollars
for the same placement — ``tests/test_oracle.py`` pins both the
optimality (brute-force cross-check) and the no-objective-drift match.

Scope, stated honestly: the oracle solves ONE wave's placement (ready
tasks against a frozen availability snapshot) — the greedy policies'
actual decision point — not the full multi-tick scheduling game; and
its ``risk_coeff × hazard`` term prices eviction exposure exactly like
``policies.resolve_risk`` does at a tick, not the realized rework of a
specific fault draw.  Branch-and-bound is exact within that scope: it
either returns the proven optimum or raises when the node budget is
exhausted (it never silently degrades to a heuristic).
"""

from __future__ import annotations

import itertools
from typing import NamedTuple, Optional, Tuple

import numpy as np

from pivot_tpu.search.weights import DEFAULT_WEIGHTS, PolicyWeights

__all__ = [
    "OracleInstance",
    "brute_force",
    "greedy_placement",
    "instance_from_wave",
    "placement_objective",
    "regret",
    "solve_instance",
]


class OracleInstance(NamedTuple):
    """One placement decision wave, objective-ready.

    ``egress_tz[t, z]`` is the expected egress dollars of landing task
    ``t`` in zone ``z`` (built by :func:`instance_from_wave` from the
    ensemble's sampled-pull bill, or synthetically in tests);
    ``anchor_zone`` / ``cost_zz`` / ``bw_zz`` additionally feed the
    greedy heuristic arm's cost-aware score.  ``risk_coeff`` is
    ``risk_weight × rework_cost`` and ``hazard`` the per-host rate —
    the PR-9 risk term at this wave's instant.
    """

    avail: np.ndarray        # [H, 4] availability snapshot
    demands: np.ndarray      # [T, 4]
    host_zone: np.ndarray    # [H] i32
    egress_tz: np.ndarray    # [T, Z] $ by destination zone
    hazard: np.ndarray       # [H] preemption rate per host
    risk_coeff: float        # risk_weight × rework_cost
    unplaced_penalty: float  # $ per task left unplaced
    anchor_zone: np.ndarray  # [T] i32 (greedy scoring)
    cost_zz: np.ndarray      # [Z, Z] egress-cost matrix (greedy scoring)
    bw_zz: np.ndarray        # [Z, Z] bandwidth matrix (greedy scoring)

    @property
    def n_tasks(self) -> int:
        return self.demands.shape[0]

    @property
    def n_hosts(self) -> int:
        return self.avail.shape[0]

    def cost_matrix(self) -> np.ndarray:
        """[T, H] per-placement objective cost (egress + risk)."""
        ez = self.egress_tz[:, self.host_zone]  # [T, H]
        return ez + self.risk_coeff * np.asarray(self.hazard)[None, :]


def placement_objective(inst: OracleInstance, placement) -> float:
    """THE objective — one definition for solver, referee, and reports.
    ``placement`` is [T] host indices, −1 = unplaced.  Infeasible
    placements (capacity overflow) raise: the objective is only defined
    on the feasible set, and silently scoring an infeasible vector
    would corrupt every regret built on it."""
    p = np.asarray(placement, dtype=np.int64)
    if p.shape != (inst.n_tasks,):
        raise ValueError(
            f"placement must be [{inst.n_tasks}], got {p.shape}"
        )
    used = np.zeros_like(np.asarray(inst.avail, dtype=np.float64))
    C = inst.cost_matrix()
    total = 0.0
    for t in range(inst.n_tasks):
        h = int(p[t])
        if h < 0:
            total += inst.unplaced_penalty
            continue
        used[h] += inst.demands[t]
        total += float(C[t, h])
    over = used - np.asarray(inst.avail, dtype=np.float64)
    if np.any(over > 1e-9):
        bad = int(np.argmax(np.max(over, axis=1)))
        raise ValueError(
            f"infeasible placement: host {bad} over capacity by "
            f"{np.max(over[bad]):.6g}"
        )
    return total


def instance_from_wave(
    workload,
    topo,
    avail,
    producer_placement,
    consumer_mask,
    *,
    hazard: Optional[np.ndarray] = None,
    weights: PolicyWeights = DEFAULT_WEIGHTS,
    unplaced_penalty: float = 1.0,
) -> OracleInstance:
    """Build the oracle instance for one consumer wave of an
    :class:`~pivot_tpu.parallel.ensemble.EnsembleWorkload`.

    ``producer_placement`` is the [T] host vector of already-finished
    instances (−1 = not placed / not done); ``consumer_mask`` the [T]
    bool mask of the wave to place now.  ``egress_tz`` reproduces the
    ensemble bill's expected cost per sampled pull: consumer instance
    of group c pulls ``samp[c, g]`` instances of each predecessor
    group g, each pull costing ``out_g × Σ_s src_frac[g, s] ×
    cost[s, z] / 8000`` with sources distributed like the producer's
    placed instances (``bill._sampled_egress``) — so the oracle's
    egress for a placement equals the estimator meter's, pinned by
    ``tests/test_oracle.py``.
    """
    from pivot_tpu.parallel.ensemble.bill import _sampling_table

    pred_group = np.asarray(workload.pred_group, dtype=np.float64)
    out_group = np.asarray(workload.out_group, dtype=np.float64)
    group_of = np.asarray(workload.group_of)
    host_zone = np.asarray(topo.host_zone)
    cost = np.asarray(topo.cost, dtype=np.float64)
    bw = np.asarray(topo.bw, dtype=np.float64)
    Z = cost.shape[0]
    pp = np.asarray(producer_placement, dtype=np.int64)
    cm = np.asarray(consumer_mask, dtype=bool)

    # [G, Z] placed-producer counts → source distribution per group.
    G = pred_group.shape[0]
    zcp = np.zeros((G, Z), dtype=np.float64)
    for t in np.nonzero(pp >= 0)[0]:
        zcp[group_of[t], host_zone[pp[t]]] += 1.0
    n_placed = zcp.sum(axis=1, keepdims=True)
    src_frac = np.where(n_placed > 0, zcp / np.maximum(n_placed, 1.0), 0.0)
    _, samp = _sampling_table(workload)
    samp = np.asarray(samp, dtype=np.float64)
    # d[g, z]: $ of one pull from group g into zone z (output-scaled).
    d = (src_frac * out_group[:, None]) @ cost  # [G, Z]
    pulls = (pred_group * samp)[group_of]  # [T, G]
    egress_tz = (pulls @ d) / 8000.0  # [T, Z]

    idx = np.nonzero(cm)[0]
    demands = np.asarray(workload.demands, dtype=np.float64)[idx]
    H = host_zone.shape[0]
    if hazard is None:
        hazard = np.zeros(H, dtype=np.float64)
    # Consumer anchors for the greedy arm: the majority producer zone
    # (the DES vote), ties to the lowest zone index.
    anchor = np.zeros(len(idx), dtype=np.int32)
    for j, t in enumerate(idx):
        votes = pred_group[group_of[t]] @ zcp  # [Z]
        anchor[j] = int(np.argmax(votes)) if votes.any() else 0
    return OracleInstance(
        avail=np.asarray(avail, dtype=np.float64),
        demands=demands,
        host_zone=host_zone.astype(np.int32),
        egress_tz=egress_tz[idx],
        hazard=np.asarray(hazard, dtype=np.float64),
        risk_coeff=float(weights.risk_coefficient()),
        unplaced_penalty=float(unplaced_penalty),
        anchor_zone=anchor,
        cost_zz=cost,
        bw_zz=bw,
    )


# -- solvers -----------------------------------------------------------------


def brute_force(inst: OracleInstance) -> Tuple[np.ndarray, float]:
    """Exhaustive optimum over every (H+1)^T assignment — the test
    referee for :func:`solve_instance`; refuses instances too large to
    enumerate."""
    T, H = inst.n_tasks, inst.n_hosts
    if (H + 1) ** T > 2_000_000:
        raise ValueError(
            f"brute force over {(H + 1) ** T} assignments is not a test "
            "any more — shrink the instance"
        )
    best, best_obj = None, np.inf
    for combo in itertools.product(range(-1, H), repeat=T):
        p = np.asarray(combo, dtype=np.int64)
        try:
            obj = placement_objective(inst, p)
        except ValueError:
            continue  # infeasible
        if obj < best_obj - 1e-15:
            best, best_obj = p, obj
    return best, float(best_obj)


def solve_instance(
    inst: OracleInstance,
    *,
    max_nodes: int = 2_000_000,
) -> Tuple[np.ndarray, float, dict]:
    """Branch-and-bound optimum: ``(placement [T], objective, stats)``.

    Exact: the admissible bound (each remaining task pays at least its
    capacity-ignoring cheapest option) only ever prunes provably
    dominated subtrees, and the search raises if ``max_nodes`` runs out
    — it never degrades to a heuristic silently.  Tasks branch in
    descending demand-norm order (tight tasks first ⇒ early capacity
    conflicts ⇒ smaller trees); children best-cost-first so the greedy
    incumbent lands early.
    """
    T, H = inst.n_tasks, inst.n_hosts
    C = inst.cost_matrix()  # [T, H]
    pen = inst.unplaced_penalty
    demands = np.asarray(inst.demands, dtype=np.float64)
    order = np.argsort(
        -np.sqrt(np.sum(demands * demands, axis=1)), kind="stable"
    )
    # Admissible per-task floor and its suffix sums along the branch
    # order: cheapest option ignoring capacity (unplaced included).
    floor = np.minimum(C.min(axis=1), pen)
    suffix = np.zeros(T + 1, dtype=np.float64)
    for i in range(T - 1, -1, -1):
        suffix[i] = suffix[i + 1] + floor[order[i]]

    # Greedy incumbent: cheapest feasible option per task in branch
    # order — feasible by construction, so the bound has a target.
    inc = np.full(T, -1, dtype=np.int64)
    avail = np.asarray(inst.avail, dtype=np.float64).copy()
    inc_obj = 0.0
    for t in order:
        fits = np.all(avail >= demands[t], axis=1)
        choice = -1
        cost_t = pen
        if fits.any():
            h = int(np.argmin(np.where(fits, C[t], np.inf)))
            if C[t, h] <= pen:
                choice, cost_t = h, float(C[t, h])
        if choice >= 0:
            avail[choice] -= demands[t]
        inc[t] = choice
        inc_obj += cost_t

    best = inc.copy()
    best_obj = inc_obj
    nodes = 0
    placement = np.full(T, -1, dtype=np.int64)
    avail = np.asarray(inst.avail, dtype=np.float64).copy()

    def dfs(i: int, acc: float):
        nonlocal nodes, best, best_obj
        nodes += 1
        if nodes > max_nodes:
            raise RuntimeError(
                f"branch-and-bound exhausted its {max_nodes}-node budget "
                f"on a T={T}, H={H} instance — shrink the instance or "
                "raise max_nodes (the oracle never returns a heuristic)"
            )
        if i == T:
            if acc < best_obj - 1e-15:
                best, best_obj = placement.copy(), acc
            return
        t = order[i]
        fits = np.all(avail >= demands[t], axis=1)
        # Children: feasible hosts + the unplaced arm, best-first.
        opts = [(float(C[t, h]), int(h)) for h in np.nonzero(fits)[0]]
        opts.append((pen, -1))
        opts.sort()
        for cost_t, h in opts:
            if acc + cost_t + suffix[i + 1] >= best_obj - 1e-15:
                break  # sorted: every later child is dominated too
            placement[t] = h
            if h >= 0:
                avail[h] -= demands[t]
            dfs(i + 1, acc + cost_t)
            if h >= 0:
                avail[h] += demands[t]
            placement[t] = -1

    dfs(0, 0.0)
    return best, float(best_obj), {"nodes": nodes, "incumbent": inc_obj}


def greedy_placement(
    inst: OracleInstance,
    weights: PolicyWeights = DEFAULT_WEIGHTS,
    *,
    bin_pack: str = "best-fit",
) -> np.ndarray:
    """The heuristic arm: cost-aware greedy placement of the instance
    under ``weights`` — the single-wave mirror of ``CostAwarePolicy``'s
    two bin-pack modes, so regret reports compare the *policy family
    the search tunes* against the optimum, not a strawman.  Tasks run
    demand-decreasing; per mode (matching ``sched/policies.py``):

      * ``"first-fit"`` — score ``cost_rt^w_cost / (norm^w_norm ×
        bw_rt^w_bw)`` of the LIVE availability, pick the best host
        among **strict** fits (ref ``cost_aware.py:124``);
      * ``"best-fit"`` — score ``cost_rt^w_cost × residual^w_norm /
        bw_rt^w_bw`` (residual = norm of ``avail − demand``), pick the
        best host among **non-strict** fits (ref ``:87``; the decay
        factor is 1 — a single wave has no resident-task counts).

    Both add the shared ``+ risk`` term.
    """
    if bin_pack not in ("first-fit", "best-fit"):
        raise ValueError(f"bin_pack must be first-fit|best-fit, got {bin_pack}")
    T, H = inst.n_tasks, inst.n_hosts
    demands = np.asarray(inst.demands, dtype=np.float64)
    avail = np.asarray(inst.avail, dtype=np.float64).copy()
    hz = inst.host_zone
    cost_rt = inst.cost_zz[:, hz] + inst.cost_zz[hz, :].T  # [Z, H]
    bw_rt = inst.bw_zz[:, hz] + inst.bw_zz[hz, :].T
    risk = (
        weights.risk_coefficient() * np.asarray(inst.hazard)
        if weights.risk_coefficient() > 0 else None
    )
    placement = np.full(T, -1, dtype=np.int64)
    order = np.argsort(
        -np.sqrt(np.sum(demands * demands, axis=1)), kind="stable"
    )
    exps = weights.score_exponents()
    wc, wb, wn = exps if exps is not None else (1.0, 1.0, 1.0)
    for t in order:
        if bin_pack == "first-fit":
            fits = np.all(avail > demands[t], axis=1)  # strict, ref :124
        else:
            fits = np.all(avail >= demands[t], axis=1)  # non-strict, :87
        if not fits.any():
            continue
        cr = cost_rt[inst.anchor_zone[t]]
        br = bw_rt[inst.anchor_zone[t]]
        with np.errstate(divide="ignore", invalid="ignore"):
            if bin_pack == "first-fit":
                norm = np.sqrt(np.sum(avail * avail, axis=1))
                if exps is None:
                    score = cr / (norm * br)
                else:
                    score = cr ** wc / (norm ** wn * br ** wb)
            else:
                residual = np.sqrt(
                    np.sum((avail - demands[t]) ** 2, axis=1)
                )
                if exps is None:
                    score = cr * residual / br
                else:
                    score = cr ** wc * residual ** wn / br ** wb
        if risk is not None:
            score = score + risk
        h = int(np.argmin(np.where(fits, score, np.inf)))
        avail[h] -= demands[t]
        placement[t] = h
    return placement


def regret(
    inst: OracleInstance, placement, optimum: Optional[float] = None
) -> float:
    """``objective(placement) − objective(optimum)`` — ≥ 0 by
    optimality; ``optimum`` may be passed to amortize one solve across
    several arms."""
    if optimum is None:
        _, optimum, _ = solve_instance(inst)
    return placement_objective(inst, placement) - optimum
