"""The search loop's fitness environment and population evaluator.

A :class:`SearchEnv` is one seeded spot-market evaluation world, fully
rendered into device operands:

  * a synthetic cluster + two-stage DAG workload (the spot-survival
    shape, ``experiments/spot.py``), flattened to an
    :class:`~pivot_tpu.parallel.ensemble.EnsembleWorkload`;
  * the seeded :class:`~pivot_tpu.infra.market.MarketSchedule` rendered
    twice — its per-host piecewise hazard trace ``([P], [P, H])`` feeds
    the tick body's risk term, and its hazard-drawn
    :class:`~pivot_tpu.infra.faults.ChaosSchedule` preemption plan
    (``spot_schedule``) is converted to the ensemble's fault triple, so
    every candidate lives through the *identical* eviction game
    (common random numbers: between-candidate variance excludes the
    fault scenario);
  * billing constants for the cost-per-completed-task score.

:func:`evaluate_rows` scores a ``[B]`` candidate population of
:class:`~pivot_tpu.search.weights.PolicyWeights` vectors under R seeded
Monte-Carlo rollouts each — ``B × R`` rows through the ensemble's
row-based runner as **one jitted device dispatch per generation**
(``_fitness_rows``; the inner segment/finalize programs inline).  Two
backends, held bit-identical by ``tests/test_search.py``:

  * ``"rollout"`` — the plain single-device program;
  * ``"sharded_rollout"`` — the same program with its ``[B × R]`` row
    axis sharded over a replica mesh (``NamedSharding`` outputs, the
    ``sharded_rollout`` idiom), which is what lets candidate
    populations reach 10k+ rows on a pod: per-row rollouts are
    embarrassingly parallel, so XLA partitions the vmapped while_loop
    with zero cross-row traffic.  Per-candidate reductions happen
    host-side in one fixed order for both backends — the
    generation-by-generation fitness trace is backend-invariant bit
    for bit.

The public library surface is
:func:`pivot_tpu.sched.sensitivity.evaluate_candidates` — the
batched-arm market evaluator refactored out of the gated-policy class
(see that module's docstring); the optimizers (``search/es.py``,
``search/cem.py``) call it, and it delegates here.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from pivot_tpu.ops.kernels import DeviceTopology
from pivot_tpu.ops.shard import check_row_divisibility, row_sharding
from pivot_tpu.parallel.ensemble.draws import _perturbations
from pivot_tpu.parallel.ensemble.sweeps import _run_rows, _tile_rows
from pivot_tpu.search.weights import PolicyWeights

__all__ = [
    "SearchEnv",
    "chaos_to_faults",
    "evaluate_rows",
    "make_search_env",
]

#: Fitness backends (tests sweep both for bit-identity).
BACKENDS = ("rollout", "sharded_rollout")


class SearchEnv(NamedTuple):
    """One seeded fitness world, device-operand-ready.  Built by
    :func:`make_search_env`; consumed by :func:`evaluate_rows` and the
    optimizers.  All array members are committed device/host arrays —
    the environment itself is immutable across generations, so staging
    happens once."""

    workload: object          # EnsembleWorkload
    topo: DeviceTopology
    avail0: jax.Array         # [H, 4]
    storage_zones: jax.Array  # [S] i32
    hazard: Optional[Tuple[jax.Array, jax.Array]]  # ([P], [P, H]) or None
    # Shared-plan mode: [F] triple (every replica lives the same
    # eviction game).  Redraw mode (``redraw_faults=True``): [R, F]
    # triple, one seeded plan per replica, padded with inert rows.
    faults: Optional[Tuple[jax.Array, jax.Array, jax.Array]]
    tick: float
    max_ticks: int
    n_replicas: int
    perturb: float
    rate_per_hour: float
    price_scale: float        # time-mean market price multiplier
    incomplete_penalty: float  # $ per task still pending at the horizon
    seed: int
    n_preemptions: int        # diagnostics: events in the fault plan

    @property
    def n_tasks(self) -> int:
        return self.workload.n_tasks


def chaos_to_faults(schedule, cluster):
    """Render a :class:`ChaosSchedule` into the ensemble's fault triple
    ``([F] host index, [F] fail_at, [F] recover_at)``.

    Preemptions abort at ``at + lead`` (the warning window is the DES's
    proactive-drain affordance; the estimator has no drain machinery,
    so the abort instant is the fault) and recover after ``duration``
    (None ⇒ never); plain host outages abort at ``at``.  Stragglers and
    partitions have no tick-resolution analog in the estimator and are
    skipped — the fitness environment's plans are preemption-only
    (``MarketSchedule.spot_schedule``), so nothing is silently dropped
    there.  Events sort by abort time for a stable layout.  Returns
    None for an event-free plan.
    """
    index = {h.id: i for i, h in enumerate(cluster.hosts)}
    rows = []
    for ev in schedule.events:
        if ev.kind == "preemption":
            fail = ev.at + ev.lead
        elif ev.kind == "host_outage":
            fail = ev.at
        else:
            continue
        rec = fail + ev.duration if ev.duration is not None else np.inf
        rows.append((fail, index[ev.target], rec))
    if not rows:
        return None
    rows.sort()
    host = np.asarray([r[1] for r in rows], dtype=np.int32)
    fail = np.asarray([r[0] for r in rows], dtype=np.float64)
    rec = np.asarray([r[2] for r in rows], dtype=np.float64)
    return host, fail, rec


def make_search_env(
    n_hosts: int = 12,
    seed: int = 3,
    n_apps: int = 8,
    horizon: float = 600.0,
    *,
    tick: float = 5.0,
    max_ticks: Optional[int] = None,
    n_replicas: int = 8,
    perturb: float = 0.1,
    rate_per_hour: float = 1.0,
    incomplete_penalty: float = 1.0,
    arrival_spacing: float = 40.0,
    lead: float = 15.0,
    outage: float = 100.0,
    fault_seed: Optional[int] = None,
    redraw_faults: bool = False,
    cluster=None,
    market=None,
    dtype=jnp.float32,
    # MarketSchedule.generate knobs — the spot-survival defaults
    # (experiments/spot.py): a large discounted-and-hazardous pool next
    # to calm on-demand zones, so the risk dimension has signal.
    n_segments: int = 6,
    hot_fraction: float = 0.4,
    hot_hazard: float = 2e-2,
    hot_discount: float = 0.65,
    base_hazard: float = 5e-4,
    price_vol: float = 0.15,
) -> SearchEnv:
    """Build one seeded fitness world.  A pure function of its
    arguments: the cluster, workload, market, and preemption plan are
    all derived from ``seed`` (``fault_seed`` defaults to it), so two
    calls yield operand-identical environments — the replay anchor the
    determinism suite holds the search to.  Held-out evaluation is just
    this function at different seeds.

    ``redraw_faults=True`` draws R *independent* seeded preemption
    plans (seeds ``fault_seed + r``) instead of one shared plan:
    candidate comparisons stay paired (candidate b's replica r faces
    the same plan as candidate b′'s replica r), but fitness variance
    now includes eviction-plan risk rather than conditioning every
    score on a single draw.  Still a pure function of its arguments.

    ``cluster``/``market`` inject a live world instead of generating a
    synthetic one — the model-predictive controller's template-env
    path (``pivot_tpu.mpc.forecast``).  Injecting a cluster skips the
    global id reset: ``reset_ids()`` mid-serve would collide fresh ids
    with the sessions' live apps.
    """
    from pivot_tpu.experiments.spot import synthetic_spot_apps
    from pivot_tpu.infra.market import MarketSchedule
    from pivot_tpu.parallel.ensemble import EnsembleWorkload
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    if cluster is None:
        reset_ids()  # deterministic host-N ids per (n_hosts, seed)
        cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed))
    if market is None:
        market = MarketSchedule.generate(
            cluster.meta, seed=seed, horizon=horizon,
            n_segments=n_segments, hot_fraction=hot_fraction,
            hot_hazard=hot_hazard, hot_discount=hot_discount,
            base_hazard=base_hazard, price_vol=price_vol,
        )
    apps = synthetic_spot_apps(n_apps, seed)
    arrivals = [
        (i * arrival_spacing if arrival_spacing > 0 else 0.0)
        for i in range(len(apps))
    ]
    workload = EnsembleWorkload.from_applications(
        apps, arrivals=arrivals, dtype=dtype
    )
    topo = DeviceTopology.from_cluster(cluster, dtype)
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=dtype)
    storage_zones = jnp.asarray(cluster.storage_zone_vector())

    host_zones = np.asarray(topo.host_zone)
    hz_rows = market.hazard[:, host_zones]  # [P, H]
    hazard = None
    if hz_rows.any():
        hazard = (
            jnp.asarray(market.times, dtype=dtype),
            jnp.asarray(hz_rows, dtype=dtype),
        )

    fs = seed if fault_seed is None else fault_seed
    faults = None
    n_preempt = 0
    if redraw_faults:
        # One seeded plan per replica, padded to a common event count
        # with inert rows (``fail_at = inf`` never fires inside the
        # horizon, so padding is shape-only).  Replica r's seed is
        # ``fs + r`` — adjacent SeedSequence streams are independent,
        # and the layout replays bit-for-bit from the same arguments.
        triples = [
            chaos_to_faults(
                market.spot_schedule(
                    cluster, seed=fs + r, lead=lead, outage=outage,
                    horizon=horizon,
                ),
                cluster,
            )
            for r in range(n_replicas)
        ]
        sizes = [0 if t is None else int(t[0].shape[0]) for t in triples]
        n_preempt = sum(sizes)
        F = max(sizes)
        if F > 0:
            host = np.zeros((n_replicas, F), dtype=np.int32)
            fail = np.full((n_replicas, F), np.inf, dtype=np.float64)
            rec = np.full((n_replicas, F), np.inf, dtype=np.float64)
            for r, t in enumerate(triples):
                if t is None:
                    continue
                k = t[0].shape[0]
                host[r, :k], fail[r, :k], rec[r, :k] = t
            faults = (
                jnp.asarray(host),
                jnp.asarray(fail, dtype=dtype),
                jnp.asarray(rec, dtype=dtype),
            )
    else:
        plan = market.spot_schedule(
            cluster, seed=fs, lead=lead, outage=outage, horizon=horizon,
        )
        triple = chaos_to_faults(plan, cluster)
        if triple is not None:
            host, fail, rec = triple
            n_preempt = int(host.shape[0])
            faults = (
                jnp.asarray(host),
                jnp.asarray(fail, dtype=dtype),
                jnp.asarray(rec, dtype=dtype),
            )

    # Time-mean price multiplier: the estimator's busy integral is one
    # scalar per rollout (no per-zone attribution), so instance dollars
    # bill at the market's duration-weighted mean multiplier.  The DES
    # harness (experiments/search.py) re-validates winners under the
    # exact piecewise-price integral (billed_instance_cost).
    bounds = np.append(market.times, horizon)
    durs = np.maximum(np.diff(bounds), 0.0)
    total = float(durs.sum())
    price_scale = (
        float((durs * market.price.mean(axis=1)).sum() / total)
        if total > 0 else 1.0
    )

    if max_ticks is None:
        # Horizon plus slack for preemption rework; the while_loop
        # early-exits once every task is done, so slack is free.
        max_ticks = int(np.ceil(horizon / tick)) * 2

    return SearchEnv(
        workload=workload,
        topo=topo,
        avail0=avail0,
        storage_zones=storage_zones,
        hazard=hazard,
        faults=faults,
        tick=float(tick),
        max_ticks=int(max_ticks),
        n_replicas=int(n_replicas),
        perturb=float(perturb),
        rate_per_hour=float(rate_per_hour),
        price_scale=price_scale,
        incomplete_penalty=float(incomplete_penalty),
        seed=int(seed),
        n_preemptions=n_preempt,
    )


# -- the jitted population programs ------------------------------------------
#
# Two programs per generation, split on purpose: the Monte-Carlo draws
# are a tiny ALWAYS-UNSHARDED program shared verbatim by both fitness
# backends, because ``jax.random`` lowers its counters differently when
# the consuming computation is partitioned (``jax_threefry_partitionable``
# is off repo-wide to keep every existing result bit-stable) — drawing
# inside the sharded program would silently change the scenarios under
# the mesh.  The population rollout itself — the heavy part — is ONE
# device dispatch per generation in either backend.


def _draw_rows_impl(
    key,
    workload,
    avail0,
    storage_zones,
    n_candidates: int,
    n_replicas: int,
    perturb: float,
):
    """[B × R] candidate-major draw rows (runtimes, arrivals, anchors):
    the R Monte-Carlo scenarios drawn ONCE and tiled across candidates
    (paired comparisons — common random numbers)."""
    rt, arr, ra = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    B = n_candidates
    return _tile_rows(rt, B), _tile_rows(arr, B), _tile_rows(ra, B)


_draw_rows = jax.jit(
    _draw_rows_impl,
    static_argnames=("n_candidates", "n_replicas", "perturb"),
)


def _fitness_rows_impl(
    rt_rows,         # [B·R, T] tiled perturbed runtimes (_draw_rows)
    arr_rows,        # [B·R, T] tiled perturbed arrivals
    ra_rows,         # [B·R, T] i32 tiled root anchors
    warr,            # [B, 5] candidate PolicyWeights matrix
    avail0,          # [H, 4]
    workload,
    topo: DeviceTopology,
    hazard,          # ([P], [P, H]) or None — replica-shared market trace
    faults,          # ([F]×3) shared plan or ([R, F]×3) per-replica plans
    cap_rows,        # [B] capacity scale per candidate, or None
    active_rows,     # [B, T] bool admit mask per candidate, or None
    tick: float,
    max_ticks: int,
    forms: str,
    tick_order: str,
):
    """[B × R] candidate rows to slim per-row metrics, as ONE program.

    Row layout is candidate-major (row b = candidate ``b // R``, replica
    ``b % R``).  Every candidate's exponents ride the ``score_params``
    pow path and its risk product the ``risk_coeff`` channel —
    including the hand-tuned anchors, so population scoring is one
    compiled program and candidate deltas can never come from path
    divergence.  ``cap_rows``/``active_rows`` are the model-predictive
    planner's action channels — per-candidate capacity scaling
    (grow/drain) and task admission masks (admit/shed); ``None`` traces
    the plain program.  Returns ``(egress, instance_hours,
    n_unfinished, makespan)``, each ``[B × R]`` — the full
    finish/placement tensors stay on device.
    """
    B = warr.shape[0]
    n_rows = rt_rows.shape[0]
    R = n_rows // B
    warr = jnp.asarray(warr, avail0.dtype)
    avail_rows = jnp.broadcast_to(avail0, (B * R,) + avail0.shape)
    if cap_rows is not None:
        scale = jnp.repeat(jnp.asarray(cap_rows, avail0.dtype), R)
        avail_rows = avail_rows * scale[:, None, None]
    active = (
        jnp.repeat(jnp.asarray(active_rows, bool), R, axis=0)
        if active_rows is not None else None
    )
    sp = jnp.repeat(warr[:, :3], R, axis=0)          # [B·R, 3] exponents
    # The risk channel rides only when the environment has a hazard
    # trace — without one the term is disengaged for every candidate
    # (``resolve_risk`` semantics: no market ⇒ no risk ops traced).
    rc = (
        jnp.repeat(warr[:, 3] * warr[:, 4], R)       # [B·R] risk coeff
        if hazard is not None else None
    )
    fault_rows = None
    totals = None
    if faults is not None:
        fh, ff, fr = faults
        F = fh.shape[-1]
        if fh.ndim == 2:
            # Per-replica redrawn plans [R, F]: tile candidate-major to
            # match the draw rows — row b·R + r gets replica r's plan
            # for EVERY candidate b, so comparisons stay paired.
            fault_rows = (
                _tile_rows(fh, B), _tile_rows(ff, B), _tile_rows(fr, B)
            )
        else:
            fault_rows = (
                jnp.broadcast_to(fh, (B * R, F)),
                jnp.broadcast_to(ff, (B * R, F)),
                jnp.broadcast_to(fr, (B * R, F)),
            )
        totals = avail_rows
    res = _run_rows(
        avail_rows, rt_rows, arr_rows, ra_rows,
        workload, topo, tick, max_ticks, None,
        "cost-aware", False, False,
        faults=fault_rows,
        totals=totals,
        score_params=sp,
        risk_coeff=rc,
        active=active,
        hazard=hazard,
        forms=forms,
        tick_order=tick_order,
    )
    return (
        res.egress_cost, res.instance_hours, res.n_unfinished, res.makespan
    )


#: The single-device fitness program: one dispatch per generation.
_fitness_rows = jax.jit(
    _fitness_rows_impl,
    static_argnames=("tick", "max_ticks", "forms", "tick_order"),
)


@functools.lru_cache(maxsize=32)
def _sharded_fitness_fn(mesh, tick, max_ticks, forms, tick_order):
    """Cached jitted fitness per (mesh, static config): the identical
    row program with its ``[B × R]`` row axis sharded over the mesh's
    ``replica`` axis — the ``sharded_rollout`` idiom (replicated
    inputs, ``NamedSharding`` outputs; per-row rollouts partition with
    zero cross-row traffic)."""
    out = row_sharding(mesh)
    return jax.jit(
        functools.partial(
            _fitness_rows_impl,
            tick=tick, max_ticks=max_ticks, forms=forms,
            tick_order=tick_order,
        ),
        out_shardings=(out, out, out, out),
    )


def evaluate_rows(
    weights,
    env: SearchEnv,
    *,
    key=None,
    backend: str = "rollout",
    mesh=None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
    cap_rows=None,
    active_rows=None,
) -> Tuple[np.ndarray, dict]:
    """Score a candidate population under ``env``.

    ``weights`` is a ``[B, 5]`` matrix (``PolicyWeights.stack``) or a
    sequence of :class:`PolicyWeights`.  Returns ``(scores [B],
    details)`` where ``scores[b]`` is candidate b's mean
    cost-per-completed-task over the R paired rollouts (lower is
    better; incomplete rollouts pay ``env.incomplete_penalty`` per
    pending task) and ``details`` carries the per-candidate metric
    breakdown.  ``key`` defaults to ``PRNGKey(env.seed)``; optimizers
    fold the generation index in so draws refresh while staying
    seed-replayable.

    ``backend="sharded_rollout"`` requires ``mesh`` (a replica mesh,
    ``parallel.mesh.replica_mesh``) and ``B × R`` divisible over its
    replica axis; per-row values — and therefore scores — are
    bit-identical to the ``"rollout"`` backend.

    ``cap_rows`` ([B], capacity scale) and ``active_rows`` ([B, T]
    bool, admit masks) attach per-candidate *actions* to the rollout —
    the model-predictive planner's channels.  Shed tasks (mask False)
    never run and don't bill the incomplete penalty; scores divide by
    each candidate's admitted-and-completed count, so shedding trades
    throughput against cost inside the same score.
    """
    from pivot_tpu.parallel.ensemble.state import _resolve_forms

    if backend not in BACKENDS:
        raise ValueError(
            f"unknown fitness backend {backend!r} — one of {BACKENDS}"
        )
    warr = (
        np.asarray(weights, dtype=np.float64)
        if isinstance(weights, np.ndarray)
        else PolicyWeights.stack(list(weights))
    )
    if warr.ndim != 2 or warr.shape[1] != PolicyWeights.DIM:
        raise ValueError(
            f"weights must be [B, {PolicyWeights.DIM}], got {warr.shape}"
        )
    if not np.all(np.isfinite(warr)):
        raise ValueError("candidate weights must be finite")
    B, R = warr.shape[0], env.n_replicas
    if cap_rows is not None:
        cap_rows = np.asarray(cap_rows, np.float64)
        if cap_rows.shape != (B,):
            raise ValueError(
                f"cap_rows must be [B={B}], got {cap_rows.shape}"
            )
        if not np.all(np.isfinite(cap_rows)) or np.any(cap_rows < 0):
            raise ValueError("cap_rows must be finite and non-negative")
    if active_rows is not None:
        active_rows = np.asarray(active_rows, dtype=bool)
        if active_rows.shape != (B, env.n_tasks):
            raise ValueError(
                f"active_rows must be [B={B}, T={env.n_tasks}], "
                f"got {active_rows.shape}"
            )
    if key is None:
        key = jax.random.PRNGKey(env.seed)
    forms = _resolve_forms(forms)
    # Draws come from the shared UNSHARDED program in both backends —
    # see the draw/rollout split note above (threefry lowering).
    rt_rows, arr_rows, ra_rows = _draw_rows(
        key, env.workload, env.avail0, env.storage_zones,
        n_candidates=B, n_replicas=R, perturb=env.perturb,
    )
    args = (
        rt_rows, arr_rows, ra_rows, jnp.asarray(warr), env.avail0,
        env.workload, env.topo, env.hazard, env.faults,
        None if cap_rows is None else jnp.asarray(cap_rows),
        None if active_rows is None else jnp.asarray(active_rows),
    )
    statics = dict(
        tick=env.tick, max_ticks=env.max_ticks, forms=forms,
        tick_order=tick_order,
    )
    if backend == "sharded_rollout":
        if mesh is None:
            raise ValueError(
                "backend='sharded_rollout' needs a replica mesh "
                "(parallel.mesh.replica_mesh)"
            )
        check_row_divisibility(mesh, B * R)
        fn = _sharded_fitness_fn(mesh, **statics)
        egress, ihours, unfin, makespan = fn(*args)
    else:
        egress, ihours, unfin, makespan = _fitness_rows(*args, **statics)

    # Host-side per-candidate reduction, ONE fixed order for both
    # backends (the device programs return per-row scalars; a device
    # cross-row mean could re-associate differently under sharding).
    egress = np.asarray(egress, np.float64).reshape(B, R)
    ihours = np.asarray(ihours, np.float64).reshape(B, R)
    unfin = np.asarray(unfin, np.float64).reshape(B, R)
    makespan = np.asarray(makespan, np.float64).reshape(B, R)
    T = env.n_tasks
    # Shed (inactive) tasks never run: they are neither unfinished nor
    # completed, so the divisor is each candidate's ADMITTED count.
    admitted = (
        np.broadcast_to(
            active_rows.sum(axis=1).astype(np.float64)[:, None], (B, R)
        )
        if active_rows is not None else float(T)
    )
    completed = admitted - unfin
    cost = (
        ihours * env.rate_per_hour * env.price_scale
        + egress
        + env.incomplete_penalty * unfin
    )
    per_row = np.where(completed > 0, cost / np.maximum(completed, 1.0),
                       np.inf)
    scores = per_row.mean(axis=1)
    details = {
        "scores": scores,
        "egress": egress.mean(axis=1),
        "instance_cost": (
            ihours * env.rate_per_hour * env.price_scale
        ).mean(axis=1),
        "unfinished": unfin.mean(axis=1),
        "makespan": makespan.mean(axis=1),
        "completed": completed.mean(axis=1),
        "admitted": np.broadcast_to(admitted, (B, R)).mean(axis=1),
        "n_rows": B * R,
        "backend": backend,
    }
    return scores, details
