"""Evolution-strategies search over :class:`PolicyWeights` space.

OpenAI-ES shape: antithetic Gaussian perturbations around a center
``theta``, rank-shaped utilities, a gradient *estimate* from the
utility-weighted noise — the population-based mirror of the CEM
optimizer, trading CEM's distribution refits for a smoother trajectory
on noisy fitness (both share the fused-dispatch evaluator and the
replay contract; see ``search/loop.py``).  ``theta`` itself rides along
as the last candidate every generation, so the incumbent is always
re-scored and the result's ``best`` is always an *evaluated* vector.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pivot_tpu.search.loop import SearchResult, score_population, trace_entry
from pivot_tpu.search.weights import (
    DEFAULT_WEIGHTS,
    PolicyWeights,
    SearchSpace,
)

__all__ = ["es_search"]


def es_search(
    env,
    *,
    generations: int = 8,
    popsize: int = 16,
    seed: int = 0,
    init: Optional[PolicyWeights] = None,
    space: Optional[SearchSpace] = None,
    sigma0: float = 0.2,
    lr: float = 0.5,
    backend: str = "rollout",
    mesh=None,
    tick_order: str = "fifo",
) -> SearchResult:
    """Minimize cost-per-completed-task over ``env`` with antithetic ES.

    ``popsize`` counts evaluated candidates per generation: ``popsize −
    1`` antithetic perturbations (rounded down to an even count) plus
    the incumbent ``theta``.  ``sigma0`` is the per-dimension noise
    scale as a fraction of the search box width; ``lr`` the step size
    on the rank-shaped gradient estimate.
    """
    if popsize < 3:
        raise ValueError(f"popsize must be >= 3 (2 antithetic + theta), got {popsize}")
    if generations < 1:
        raise ValueError(f"generations must be >= 1, got {generations}")
    half = (popsize - 1) // 2
    space = space if space is not None else SearchSpace.default()
    init = (init if init is not None else DEFAULT_WEIGHTS).validate()
    anchor = init.to_array()
    D = PolicyWeights.DIM
    rng = np.random.default_rng(seed)
    width = space.hi - space.lo
    sigma = np.where(space.frozen, 0.0, sigma0 * width)
    theta = space.clip(anchor[None], anchor)[0]

    best_vec = theta.copy()
    best_score = np.inf
    init_score = None
    trace = []
    for g in range(generations):
        eps = rng.standard_normal((half, D))
        pop = np.concatenate(
            [
                theta[None, :] + sigma[None, :] * eps,
                theta[None, :] - sigma[None, :] * eps,
                theta[None, :],
            ]
        )
        pop = space.clip(pop, anchor)
        scores = score_population(
            pop, env, g, backend=backend, mesh=mesh, tick_order=tick_order
        )
        if init_score is None:
            init_score = float(scores[-1])  # theta_0, generation 0
        k = int(np.argmin(scores))
        if scores[k] < best_score:
            best_score = float(scores[k])
            best_vec = pop[k].copy()
        # Rank-shaped utilities over the 2·half perturbed candidates
        # (theta excluded): centered in [−0.5, 0.5], best (lowest
        # score) highest — robust to the fitness scale and to the inf
        # scores incomplete rollouts produce.
        pair_scores = scores[: 2 * half]
        ranks = np.argsort(np.argsort(pair_scores, kind="stable"))
        util = 0.5 - ranks / max(2 * half - 1, 1)
        grad = (util[:half] - util[half:]) @ eps / max(half, 1)  # [D]
        theta = space.clip(
            (theta + lr * sigma * grad)[None], anchor
        )[0]
        entry = trace_entry(g, pop, scores)
        entry["theta"] = [float(x) for x in theta]
        entry["best_so_far"] = float(best_score)
        trace.append(entry)
    return SearchResult(
        best=PolicyWeights.from_array(best_vec),
        best_score=float(best_score),
        init_score=float(init_score),
        trace=trace,
        method="es",
        seed=seed,
        generations=generations,
        popsize=popsize,
        backend=backend,
    )
