"""Cross-entropy-method search over :class:`PolicyWeights` space.

The simplest robust optimizer for a 5-dimensional, noisy,
simulation-defined fitness surface: sample a Gaussian population, keep
the elite quantile, refit the Gaussian, repeat.  Every generation's
population — the current mean rides along as candidate 0, so the
incumbent is always re-scored under the generation's scenarios — is
scored by ONE fused ensemble dispatch
(``sched.sensitivity.evaluate_candidates``).  Deterministic end to end:
population sampling from ``default_rng(seed)``, scenario draws from the
env-keyed generation keys (``search/loop.py``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pivot_tpu.search.loop import SearchResult, score_population, trace_entry
from pivot_tpu.search.weights import (
    DEFAULT_WEIGHTS,
    PolicyWeights,
    SearchSpace,
)

__all__ = ["cem_search"]


def cem_search(
    env,
    *,
    generations: int = 8,
    popsize: int = 16,
    elite_frac: float = 0.25,
    seed: int = 0,
    init: Optional[PolicyWeights] = None,
    space: Optional[SearchSpace] = None,
    sigma0: float = 0.25,
    min_sigma: float = 0.02,
    alpha: float = 0.7,
    backend: str = "rollout",
    mesh=None,
    tick_order: str = "fifo",
    anchors=None,
) -> SearchResult:
    """Minimize cost-per-completed-task over ``env`` with CEM.

    ``sigma0`` / ``min_sigma`` are fractions of each dimension's box
    width (``space.hi − space.lo``); ``alpha`` is the distribution
    update momentum.  The result's ``best`` is the best candidate ever
    *evaluated* (never a merely-predicted mean), and ``init_score`` is
    the initial vector's fitness under generation 0's scenarios — the
    "beats a deliberately-bad initial vector" smoke gate compares the
    two directly.

    ``anchors`` warm-starts the search: the given vectors (e.g. the
    hand-tuned arms) replace the first sampled rows of generation 0
    only — same popsize, same compiled program — so the elite refit
    can move straight to the best known region instead of spending
    generations rediscovering it, and the best-evaluated result can
    never lose to an anchor on the training scenarios.
    """
    if popsize < 2:
        raise ValueError(f"popsize must be >= 2, got {popsize}")
    if generations < 1:
        raise ValueError(f"generations must be >= 1, got {generations}")
    anchors = [PolicyWeights(*a).validate() for a in (anchors or [])]
    if len(anchors) > popsize - 1:
        raise ValueError(
            f"{len(anchors)} anchors do not fit a popsize-{popsize} "
            "generation (row 0 is the incumbent mean)"
        )
    n_elite = max(1, int(round(elite_frac * popsize)))
    space = space if space is not None else SearchSpace.default()
    init = (init if init is not None else DEFAULT_WEIGHTS).validate()
    anchor = init.to_array()
    if anchors and bool(space.frozen[4]) and not bool(space.frozen[3]):
        # The risk pair only enters fitness as its product, so an
        # anchor expressed as (risk_weight, rework_cost) re-expresses
        # losslessly in a frozen-rework space as (product / frozen
        # rework, frozen rework) — without this, clipping the frozen
        # dim back to the init value would silently gut the anchor's
        # risk term (e.g. the hand-tuned (1, 50) arm becoming (1, 1)).
        rw = init.rework_cost if init.rework_cost > 0 else 1.0
        anchors = [
            a._replace(
                risk_weight=a.risk_coefficient() / rw, rework_cost=rw
            )
            for a in anchors
        ]
    D = PolicyWeights.DIM
    rng = np.random.default_rng(seed)
    width = space.hi - space.lo
    mean = space.clip(anchor[None], anchor)[0]
    sigma = np.where(space.frozen, 0.0, sigma0 * width)

    best_vec = mean.copy()
    best_score = np.inf
    init_score = None
    trace = []
    for g in range(generations):
        pop = mean[None, :] + sigma[None, :] * rng.standard_normal((popsize, D))
        pop[0] = mean  # the incumbent always re-scores this generation
        if g == 0:
            for i, a in enumerate(anchors):
                pop[1 + i] = a.to_array()
        pop = space.clip(pop, anchor)
        scores = score_population(
            pop, env, g, backend=backend, mesh=mesh, tick_order=tick_order
        )
        if init_score is None:
            init_score = float(scores[0])  # the initial mean, generation 0
        k = int(np.argmin(scores))
        if scores[k] < best_score:
            best_score = float(scores[k])
            best_vec = pop[k].copy()
        elite = pop[np.argsort(scores, kind="stable")[:n_elite]]
        mean = space.clip(
            (alpha * elite.mean(axis=0) + (1 - alpha) * mean)[None], anchor
        )[0]
        sigma = np.where(
            space.frozen,
            0.0,
            np.maximum(
                alpha * elite.std(axis=0) + (1 - alpha) * sigma,
                min_sigma * width,
            ),
        )
        entry = trace_entry(g, pop, scores)
        entry["mean"] = [float(x) for x in mean]
        entry["sigma"] = [float(x) for x in sigma]
        entry["best_so_far"] = float(best_score)
        trace.append(entry)
    return SearchResult(
        best=PolicyWeights.from_array(best_vec),
        best_score=float(best_score),
        init_score=float(init_score),
        trace=trace,
        method="cem",
        seed=seed,
        generations=generations,
        popsize=popsize,
        backend=backend,
    )
