"""Shared search-loop scaffolding: result type, key discipline, tracing.

Both optimizers (``search/es.py``, ``search/cem.py``) drive the same
fitness surface — :func:`pivot_tpu.sched.sensitivity.evaluate_candidates`
over a :class:`~pivot_tpu.search.fitness.SearchEnv` — and share the
replay contract this module pins down:

  * **population sampling** comes from one ``np.random.default_rng(seed)``
    owned by the optimizer;
  * **scenario draws** for generation ``g`` come from
    ``fold_in(PRNGKey(env.seed), g)`` — a pure function of the
    environment and the generation index, NOT of the optimizer seed, so
    two methods (or two seeds of one method) face the identical
    scenario sequence and their traces compare paired;
  * the **trace** records every generation's population statistics and
    the best-so-far vector, so "same seed ⇒ identical winning weight
    vector and identical generation-by-generation fitness trace" is a
    plain equality test (``tests/test_search.py``) across runs AND
    across fitness backends.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from pivot_tpu.search.weights import PolicyWeights

__all__ = ["SearchResult", "generation_key", "score_population"]


class SearchResult(NamedTuple):
    """Outcome of one search run (JSON-serializable via :meth:`to_dict`)."""

    best: PolicyWeights          # best candidate ever evaluated
    best_score: float            # its fitness (cost per completed task)
    init_score: float            # the initial vector's fitness, generation 0
    trace: List[dict]            # per-generation record
    method: str
    seed: int
    generations: int
    popsize: int
    backend: str

    def to_dict(self) -> dict:
        return {
            "method": self.method,
            "seed": self.seed,
            "generations": self.generations,
            "popsize": self.popsize,
            "backend": self.backend,
            "best_weights": dict(zip(PolicyWeights.NAMES, self.best)),
            "best_score": self.best_score,
            "init_score": self.init_score,
            "trace": self.trace,
        }


def generation_key(env, gen: int):
    """Scenario key for generation ``gen`` — env-seeded, optimizer-blind
    (see the module docstring)."""
    import jax

    return jax.random.fold_in(jax.random.PRNGKey(env.seed), gen)


def score_population(
    pop: np.ndarray,
    env,
    gen: int,
    *,
    backend: str = "rollout",
    mesh=None,
    tick_order: str = "fifo",
) -> np.ndarray:
    """One generation's fitness call: the [B] population through the
    library evaluator (``sched.sensitivity.evaluate_candidates``) under
    this generation's scenario key — ONE fused device dispatch."""
    from pivot_tpu.sched.sensitivity import evaluate_candidates

    return np.asarray(
        evaluate_candidates(
            pop, env, key=generation_key(env, gen), backend=backend,
            mesh=mesh, tick_order=tick_order,
        ),
        dtype=np.float64,
    )


def trace_entry(gen: int, pop: np.ndarray, scores: np.ndarray) -> dict:
    """One generation's trace record — plain floats/lists so traces are
    JSON round-trippable and directly comparable across runs."""
    k = int(np.argmin(scores))
    return {
        "gen": gen,
        "pop_best_score": float(scores[k]),
        "pop_best": [float(x) for x in pop[k]],
        "pop_mean_score": float(np.mean(scores)),
        "pop_worst_score": float(np.max(scores)),
    }
