"""Device-mesh construction for the ensemble/scale path.

The reference's only parallelism is OS-process fan-out of independent
experiment runs (``alibaba/sim.py:187-195``, ``alibaba/runner.py:13-52``);
the TPU-native equivalent shards work across a ``jax.sharding.Mesh``:

  * ``replica`` axis — Monte-Carlo replicas / independent experiment runs
    (the data-parallel axis of this domain).
  * ``host`` axis — the simulated-host dimension of the state arrays
    ([R, H, 4] availability, [T, H] score matrices), the model-parallel
    axis for clusters too large for one chip's convenient working set.

Collectives (all-gathers for the over-hosts argmin, psums for metric
reductions) are inserted by XLA from sharding annotations — never written
by hand.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

__all__ = [
    "build_mesh",
    "build_hybrid_mesh",
    "host_axis_size",
    "host_sharded_mesh",
    "replica_mesh",
]


def build_mesh(
    n_devices: Optional[int] = None,
    axis_names: Tuple[str, str] = ("replica", "host"),
    host_parallel: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a 2-D (replica × host) mesh over the available devices.

    ``host_parallel`` fixes the host-axis size (must divide the device
    count); by default the mesh is replica-only (host axis = 1), which is
    the right layout while per-replica state fits one chip — replicas are
    embarrassingly parallel, so ICI traffic is zero.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    hp = host_parallel or 1
    if n % hp != 0:
        raise ValueError(f"host_parallel={hp} does not divide {n} devices")
    import numpy as np

    grid = np.array(devs).reshape(n // hp, hp)
    return Mesh(grid, axis_names)


def replica_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Replica-only (data-parallel) mesh: every device on the ``replica``
    axis, host axis = 1 — the layout for Monte-Carlo ensembles and the
    cross-run dispatch batcher's [G] axis (``sched/batch.py``), where
    rows are embarrassingly parallel and ICI traffic is zero."""
    return build_mesh(n_devices, ("replica", "host"), host_parallel=1,
                      devices=devices)


def host_sharded_mesh(
    n_shards: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Host-only (model-parallel) mesh: every selected device on the
    ``host`` axis, replica axis = 1 — the layout for pod-scale sharded
    placement (``ops/shard.py``), where one cluster's ``[H]`` state is
    partitioned into contiguous index blocks across devices and the
    per-step argmin runs as a two-stage sharded reduce."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = n_shards if n_shards is not None else len(devs)
    return build_mesh(n, ("replica", "host"), host_parallel=n, devices=devs)


def host_axis_size(mesh: Mesh) -> int:
    """Size of ``mesh``'s host axis (1 on a replica-only mesh)."""
    return int(mesh.shape["host"])


def build_hybrid_mesh(
    host_parallel: int = 1,
    axis_names: Tuple[str, str, str] = ("replica_dcn", "replica", "host"),
) -> Mesh:
    """3-D mesh for multi-host (multi-slice / multi-process) runs:
    ``replica_dcn × replica × host``.

    Axis-to-fabric mapping follows the bandwidth hierarchy: the outer
    replica axis crosses the slow DCN boundary (replicas are
    embarrassingly parallel — zero steady-state DCN traffic), while the
    inner ``replica`` and ``host`` axes stay inside one process's slice so
    the host-axis collectives (over-hosts argmin all-gathers) ride ICI.
    Built with ``mesh_utils.create_hybrid_device_mesh`` so device order
    respects physical topology; on a single process it degenerates to
    ``replica_dcn=1`` and is equivalent to :func:`build_mesh` with a
    leading unit axis.

    The reference's multi-machine story is "run more OS processes"
    (``alibaba/sim.py:187-195``); this is its collective-aware equivalent.

    Round 17 made this the canonical 2-D *serving* mesh: with
    ``host_parallel=S`` on one process it is the ``replica × host``
    layout the composed batching × sharding programs partition
    (``ops/shard.py`` ``*_kernel_sharded_batched`` /
    ``sharded_batched_tick_run``) — handed to
    ``DispatchBatcher(mesh=...)`` / ``ServeDriver(mesh=...)`` and to
    ``policy.enable_sharding`` (the serve CLI's ``--shard-hosts``).
    """
    from jax.experimental import mesh_utils

    n_proc = jax.process_count()
    per_proc = jax.local_device_count()
    if per_proc % host_parallel != 0:
        raise ValueError(
            f"host_parallel={host_parallel} does not divide the "
            f"{per_proc} per-process devices"
        )
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(1, per_proc // host_parallel, host_parallel),
        dcn_mesh_shape=(n_proc, 1, 1),
        devices=jax.devices(),
        # Granule = process: DCN crosses process boundaries.  (TPU slices
        # would also work via slice_index, but CPU/virtual devices — the
        # test fabric — only carry process structure.)
        process_is_granule=True,
    )
    return Mesh(devices, axis_names)
