"""Device-mesh construction for the ensemble/scale path.

The reference's only parallelism is OS-process fan-out of independent
experiment runs (``alibaba/sim.py:187-195``, ``alibaba/runner.py:13-52``);
the TPU-native equivalent shards work across a ``jax.sharding.Mesh``:

  * ``replica`` axis — Monte-Carlo replicas / independent experiment runs
    (the data-parallel axis of this domain).
  * ``host`` axis — the simulated-host dimension of the state arrays
    ([R, H, 4] availability, [T, H] score matrices), the model-parallel
    axis for clusters too large for one chip's convenient working set.

Collectives (all-gathers for the over-hosts argmin, psums for metric
reductions) are inserted by XLA from sharding annotations — never written
by hand.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

__all__ = ["build_mesh"]


def build_mesh(
    n_devices: Optional[int] = None,
    axis_names: Tuple[str, str] = ("replica", "host"),
    host_parallel: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a 2-D (replica × host) mesh over the available devices.

    ``host_parallel`` fixes the host-axis size (must divide the device
    count); by default the mesh is replica-only (host axis = 1), which is
    the right layout while per-replica state fits one chip — replicas are
    embarrassingly parallel, so ICI traffic is zero.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    hp = host_parallel or 1
    if n % hp != 0:
        raise ValueError(f"host_parallel={hp} does not divide {n} devices")
    import numpy as np

    grid = np.array(devs).reshape(n // hp, hp)
    return Mesh(grid, axis_names)
