"""Device-resident Monte-Carlo ensemble rollouts of DAG scheduling.

The capability the reference cannot express: evaluating a placement policy
under R perturbed what-if scenarios *simultaneously*.  The reference's only
tool is forking one OS process per experiment run (``alibaba/runner.py:13``,
``alibaba/sim.py:187-195``); here the whole rollout — readiness tracking,
anchor voting, cost-aware placement, transfer/compute timing — is a single
jitted ``lax.while_loop`` over ticks, vmapped over replicas, shardable over
a device mesh (BASELINE.json configs 4-5: 1024 vmapped replicas with
perturbed runtimes / arrival times).

Execution model (deliberately simplified vs the event simulator — this is
the *ensemble estimator*, not the ground-truth DES; use
``pivot_tpu.experiments.runner`` for exact simulation):

  * Time advances in fixed scheduler ticks (the reference's 5 s grid).
  * A task becomes ready when its arrival time has passed and every
    predecessor instance is finished (readiness = one [T, T] bool matmul).
  * Placement: the same fused cost-aware kernel as the live scheduler
    (``pivot_tpu.ops.kernels.cost_aware_kernel``), anchors from an
    on-device majority vote over predecessor placement hosts
    (segment-sum counts + argmax, mirroring
    ``scheduler/cost_aware.py:45-58``).
  * Transfer time: propagation delay ``size / bw(zone→zone)`` (the same
    estimate the reference's scheduler uses for scoring;
    ``resources/__init__.py:327-331``).  By default no packet-level
    congestion; ``congestion=True`` adds a tick-resolution backlog model —
    every (source zone → destination host) aggregate is one FIFO pipe with
    a queued-MB state that new pulls join and bandwidth drains, the
    ensemble analog of the DES's per-route round-robin chunk service
    (``infra.network.Route``; ref ``resources/network.py:86-100``).
  * Egress cost: one bill of ``cost(zone_src → zone_dst) × output_mb /
    8000`` (``resources/__init__.py:565-569``) per *sampled* pull, with
    the DES's ``max(round(n_producers / n_consumers), 1)``-instance
    sampling rule and sources distributed like the producer's placements.
  * Instance-hours: tick-resolution busy-host integral (a host is busy in a
    window iff a task runs on it), the estimator analog of the DES meter's
    merged busy intervals (``infra.meter.Meter.cumulative_instance_hours``).

Monte-Carlo axes: per-replica multiplicative jitter on task runtimes and
arrivals, independent random root anchors, and — with ``n_faults > 0`` —
independent per-replica host-crash/recovery schedules (resilience what-if
ensembles; tick-resolution mirror of the DES fault model in
``infra.faults``).
"""

from __future__ import annotations

import functools
import weakref
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pivot_tpu.ops.kernels import DeviceTopology, cost_aware_kernel

__all__ = [
    "EnsembleWorkload",
    "RolloutResult",
    "RolloutState",
    "capacity_grid",
    "capacity_sweep",
    "rollout",
    "rollout_checkpointed",
    "score_param_sweep",
    "shard_sweep",
    "sharded_rollout",
    "sweep_out_shardings",
    "workload_sweep",
]


# check_group_demands verdict cache: (id(demands), id(group_of)) →
# (weakref(demands), weakref(group_of)).  The invariant being cached is a
# property of the PAIR — a ``_replace(group_of=...)`` reusing an
# already-checked demands array must re-validate — and the weakrefs guard
# against id reuse after garbage collection: an entry only counts if both
# refs still point at the SAME live arrays.
_checked_demands: dict = {}


class EnsembleWorkload(NamedTuple):
    """Dense, instance-level workload description (static across replicas).

    Built from an :class:`pivot_tpu.workload.Application` (or several) via
    :func:`EnsembleWorkload.from_applications`; every task-group instance
    becomes one row.

    Alongside the instance-level ``pred`` matrix (used for the [T]-vector
    readiness matvec), the workload carries its **group structure** —
    instances of a group share output size and predecessor groups, so
    transfer delays, anchor votes, and egress cost all reduce *exactly*
    to [G, Z]-sized tensors via matmuls.  Without this, those quantities
    need per-replica [T, T] products: at T≈3.6k and 1024 replicas that is
    a 55 GB allocation — 3× the chip's HBM.
    """

    demands: jax.Array  # [T, 4]
    runtime: jax.Array  # [T]
    output_size: jax.Array  # [T]
    arrival: jax.Array  # [T] submission time of the owning app
    pred: jax.Array  # [T, T] f32 — pred[i, p] = 1 iff p precedes i
    group_of: jax.Array  # [T] i32 — owning group index per instance
    group_onehot: jax.Array  # [T, G] f32 — one_hot(group_of)
    pred_group: jax.Array  # [G, G] f32 — group-level adjacency
    out_group: jax.Array  # [G] per-group output size (MB)
    app_of: jax.Array  # [T] i32 — owning application index per instance

    @property
    def n_tasks(self) -> int:
        return self.runtime.shape[0]

    @property
    def n_groups(self) -> int:
        return self.out_group.shape[0]

    def check_group_demands(self) -> None:
        """Raise if any group's instances disagree on their demand vector.

        The rollout's group-level fit collapse and in-loop demand
        re-derivation rely on this invariant; ``from_applications``
        guarantees it, but ``EnsembleWorkload`` is a plain NamedTuple, so
        a ``_replace(demands=...)`` with per-instance jitter would
        silently corrupt placements.  Called by the public rollout
        entries on concrete (non-traced) inputs.

        The [T, 4] device fetch costs a full link round-trip on a remote
        chip (~70–80 ms on this deployment's tunnel — measured as a
        −44 % bench-rollout regression when checked per call), so the
        verdict is cached per live demands array: repeated rollouts over
        one workload pay it once.
        """
        if isinstance(self.demands, jax.core.Tracer):
            return  # inside jit: the constructor invariant is the contract
        key = (id(self.demands), id(self.group_of))
        refs = _checked_demands.get(key)
        if (
            refs is not None
            and refs[0]() is self.demands
            and refs[1]() is self.group_of
        ):
            return
        dem = np.asarray(self.demands)
        go = np.asarray(self.group_of)
        table = np.zeros((self.n_groups, dem.shape[1]), dem.dtype)
        table[go] = dem
        if not np.array_equal(table[go], dem):
            bad = np.nonzero(np.any(table[go] != dem, axis=1))[0]
            raise ValueError(
                "EnsembleWorkload demands vary within a group (first "
                f"offending task rows: {bad[:5].tolist()}); the rollout's "
                "group-level fit test requires group-constant demands — "
                "build workloads via EnsembleWorkload.from_applications"
            )
        if len(_checked_demands) > 256:  # prune dead refs, bound growth
            dead = [
                k
                for k, (rd, rg) in _checked_demands.items()
                if rd() is None or rg() is None
            ]
            for k in dead:
                del _checked_demands[k]
        _checked_demands[key] = (
            weakref.ref(self.demands),
            weakref.ref(self.group_of),
        )

    @classmethod
    def from_applications(cls, apps, arrivals=None, dtype=jnp.float32):
        """Flatten applications to instance level.

        Every instance of a group depends on every instance of each
        predecessor group (the ensemble estimator's conservative stand-in
        for the DES's sampled 1/n-instance pulls,
        ``resources/__init__.py:263-267``).
        """
        demands, runtime, output, arrival = [], [], [], []
        group_of, out_group, app_of = [], [], []
        offset = 0
        gi = 0
        edges = []
        group_edges = []
        for ai, app in enumerate(apps):
            at = float(arrivals[ai]) if arrivals is not None else 0.0
            index = {}
            for g in app.groups:
                index[g.id] = (offset, g.instances, gi)
                out_group.append(g.output_size)
                for _ in range(g.instances):
                    demands.append([g.cpus, g.mem, g.disk, g.gpus])
                    runtime.append(g.runtime)
                    output.append(g.output_size)
                    arrival.append(at)
                    group_of.append(gi)
                    app_of.append(ai)
                offset += g.instances
                gi += 1
            for g in app.groups:
                gs, gn, gg = index[g.id]
                for dep in g.dependencies:
                    ps, pn, pg = index[dep]
                    edges.append(((gs, gn), (ps, pn)))
                    group_edges.append((gg, pg))
        T, G = offset, gi
        pred = np.zeros((T, T), dtype=np.float32)
        for (gs, gn), (ps, pn) in edges:
            pred[gs : gs + gn, ps : ps + pn] = 1.0
        pred_group = np.zeros((G, G), dtype=np.float32)
        for gg, pg in group_edges:
            pred_group[gg, pg] = 1.0
        group_of_arr = np.asarray(group_of, dtype=np.int32)
        group_onehot = np.zeros((T, G), dtype=np.float32)
        group_onehot[np.arange(T), group_of_arr] = 1.0
        return cls(
            demands=jnp.asarray(np.array(demands), dtype=dtype),
            runtime=jnp.asarray(np.array(runtime), dtype=dtype),
            output_size=jnp.asarray(np.array(output), dtype=dtype),
            arrival=jnp.asarray(np.array(arrival), dtype=dtype),
            pred=jnp.asarray(pred, dtype=dtype),
            group_of=jnp.asarray(group_of_arr),
            group_onehot=jnp.asarray(group_onehot, dtype=dtype),
            pred_group=jnp.asarray(pred_group, dtype=dtype),
            out_group=jnp.asarray(np.array(out_group), dtype=dtype),
            app_of=jnp.asarray(np.asarray(app_of, dtype=np.int32)),
        )


class RolloutResult(NamedTuple):
    makespan: jax.Array  # [R]
    egress_cost: jax.Array  # [R]
    finish_time: jax.Array  # [R, T]
    placement: jax.Array  # [R, T] host index
    n_unfinished: jax.Array  # [R] tasks still pending at the horizon
    instance_hours: jax.Array  # [R] busy host-hours (tick-resolution)


class RolloutState(NamedTuple):
    """The full mutable state of one replica's rollout — pure arrays, which
    is what makes mid-flight checkpoint/resume trivial (something the
    reference's generator-based processes could never serialize)."""

    t: jax.Array  # scalar sim time
    stage: jax.Array  # [T] i32
    finish: jax.Array  # [T]
    place: jax.Array  # [T] i32
    avail: jax.Array  # [H, 4]
    busy: jax.Array  # scalar busy host-seconds accumulator
    q: jax.Array  # [Z, H] queued MB per (src zone → dst host) pipe
    qpos: jax.Array  # [T] i32 last-batch position of a still-waiting task
    # (−1 otherwise) — the wait-queue order carry for tick_order="lifo"
    # (the DES re-drains its wait dict in reverse insertion order every
    # tick; see _rollout_segment).  Dead weight under "fifo".


# Task stages.
_PENDING, _RUNNING, _DONE = 0, 1, 2


def _resolve_forms(forms: Optional[str]) -> str:
    """Backend default for the tick-body op forms (see
    :func:`_rollout_segment`): index/segment ops on the CPU backend,
    one-hot vector forms on accelerators.  Resolved at trace time by the
    public entries; pass ``forms`` explicitly to pin a form (the parity
    suite runs both on one backend)."""
    if forms is not None:
        return forms
    return "indexed" if jax.default_backend() == "cpu" else "vector"


def _init_state(avail0, T, Z) -> RolloutState:
    dtype = avail0.dtype
    H = avail0.shape[0]
    return RolloutState(
        t=jnp.asarray(0.0, dtype),
        stage=jnp.full((T,), _PENDING, dtype=jnp.int32),
        finish=jnp.full((T,), jnp.inf, dtype=dtype),
        place=jnp.full((T,), -1, dtype=jnp.int32),
        avail=avail0,
        busy=jnp.asarray(0.0, dtype),
        q=jnp.zeros((Z, H), dtype=dtype),
        qpos=jnp.full((T,), -1, dtype=jnp.int32),
    )


def _rollout_segment(
    state: RolloutState,
    runtime,  # [T] perturbed
    arrival,  # [T] perturbed
    root_anchor,  # [T] i32 random storage zone per task (used for roots)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    n_ticks: int,
    faults=None,  # optional ([F] i32 host, [F] fail_at, [F] recover_at)
    totals=None,  # [H, 4] full capacity (fault recovery resets to this)
    score_params=None,  # optional [3] exponents (w_cost, w_bw, w_norm)
    policy: str = "cost-aware",  # | first-fit | best-fit | opportunistic
    task_u=None,  # [T] uniforms (opportunistic draws, one per task)
    congestion: bool = False,
    realtime_scoring: bool = False,
    active=None,  # optional [T] bool: early-exit ignores inactive tasks
    forms: str = "vector",  # | "indexed" — tick-body op forms, see below
    tick_order: str = "fifo",  # | "lifo" — within-tick batch order, see below
) -> RolloutState:
    """Advance one replica's rollout by at most ``n_ticks`` scheduler ticks
    (stops early once every task is done).

    ``forms`` selects between two implementations of the tick-body's
    reduction/selection ops — same math, backend-matched lowering
    (VERDICT r02 item 3):

      * ``"vector"`` (the TPU form): one-hot select-reduces, membership-
        mask masked reductions, and HIGHEST-precision one-hot matmuls.
        Under vmap these stay on the VPU/MXU; the index-based forms they
        replace lower to batched scatter/gathers whose per-replica index
        vectors land in TPU scalar memory and serialize on the scalar
        core (~1 ms/tick each — the round-2 "scalar-core lesson",
        docs/ARCHITECTURE.md).
      * ``"indexed"`` (the CPU form): plain ``segment_sum``/``segment_max``
        /``segment_min`` and gather/scatter indexing.  On CPU these are
        O(T) loops, where the vector forms are O(T·H)/O(T·G) dense
        sweeps — measured 5× end-to-end on the bench rollout metric
        (round-2's TPU-first rewrite regressed the CPU fallback 47 → 9
        rollouts/s; this restores the indexed forms there).

    Public entries resolve ``forms=None`` to the backend default
    (``indexed`` on cpu, ``vector`` elsewhere).  The two forms are held
    bit-identical on every rollout output by
    ``tests/test_ensemble.py::test_tick_body_forms_bit_identical``.

    With ``faults``, each tick applies the crash/recovery schedule at tick
    resolution, mirroring the DES fault semantics (``infra.faults`` +
    ``FastExecutor.abort_host``): a crash in the window aborts the host's
    running tasks back to PENDING with no capacity refund (they re-enter
    the placement pass like the DES retry loop), a down host's rows carry
    the −1 sentinel so no fit can select it, and recovery restores full
    capacity.  Completions in the same tick window as the crash retire
    first — the tick-resolution analog of the DES completion-wins tie.

    With ``congestion``, transfer delays account for link contention via
    the per-replica ``state.q`` backlog tensor (see the placement step for
    the exact pipe model); without it ``q`` is carried untouched, so the
    flag cannot perturb the default path.

    With ``realtime_scoring`` (requires ``congestion``), the cost-aware
    score's inbound-bandwidth term is discounted by the tick-start pipe
    backlog — ``bw_in / (queued_mb + 1)``, the estimator analog of the
    DES ``realtime_bw`` arm (``Route.realtime_bw``, ref
    ``resources/network.py:70-73``): placement actively steers AROUND
    congested links instead of merely paying for them.
    """
    if realtime_scoring and not congestion:
        raise ValueError("realtime_scoring needs congestion=True (the "
                         "backlog state is the bandwidth signal)")
    if realtime_scoring and policy != "cost-aware":
        raise ValueError("realtime_scoring applies to the cost-aware arm "
                         "only — no other policy scores on bandwidth")
    if realtime_scoring and score_params is not None:
        raise ValueError("realtime_scoring and parameterized score "
                         "exponents are mutually exclusive")
    if forms not in ("vector", "indexed"):
        raise ValueError(f"forms must be 'vector' or 'indexed', got {forms!r}")
    if tick_order not in ("fifo", "lifo"):
        raise ValueError(
            f"tick_order must be 'fifo' or 'lifo', got {tick_order!r}"
        )
    vector = forms == "vector"
    # Within-tick batch order (round-3 bias diagnosis, VERDICT r02
    # item 4): the reference drains its ready/wait dicts with
    # ``popitem()`` — LIFO (``scheduler/__init__.py:93-94,187``) — so the
    # DES's within-tick batch runs DESCENDING task index, while the
    # estimator historically placed ascending ("fifo").  On uniform
    # clusters every best-fit score ties, so the order permutes which
    # app's instances land on which host from the very first wave —
    # measured as the packing arms' consistent-sign egress bias
    # (best-fit +54% mean across clusters).  "lifo" mirrors the DES:
    # fresh cohorts descending, first-fit norm ties descending, and
    # cost-aware buckets first-seen over the descending batch.
    lifo = tick_order == "lifo"
    T = workload.n_tasks
    H = state.avail.shape[0]
    Z = topo.cost.shape[0]
    dtype = state.avail.dtype
    has_pred = jnp.sum(workload.pred, axis=1) > 0  # [T]
    if faults is not None:
        fault_host, fail_at, recover_at = faults
        fault_idx = jnp.where(fault_host >= 0, fault_host, H)  # pad → drop

        if vector:

            def _scatter_hosts(hit):  # [F] bool mask -> [H] bool host mask
                # One-hot any-reduce, not ``.at[fault_idx].max``: under
                # vmap the scatter's per-replica index vector lands in
                # scalar memory and serializes on the scalar core (three
                # calls per tick in fault ensembles — see
                # ARCHITECTURE.md, "the scalar-core lesson").  Padded
                # entries (idx == H) hit no host, exactly like the old
                # scatter-then-slice.
                return jnp.any(
                    (fault_idx[:, None] == jnp.arange(H)[None, :])
                    & hit[:, None],
                    axis=0,
                )

        else:

            def _scatter_hosts(hit):  # [F] bool mask -> [H] bool host mask
                # Boolean scatter (exact): misses and padded entries
                # write the sacrificial H row, sliced off.
                idx = jnp.where(hit, fault_idx, H)
                return jnp.zeros((H + 1,), bool).at[idx].set(True)[:H]
    # [Z, H] round-trip score tables (pure topology — hoisted out of ticks).
    cost_rt = topo.cost[:, topo.host_zone] + topo.cost[topo.host_zone, :].T
    bw_rt = topo.bw[:, topo.host_zone] + topo.bw[topo.host_zone, :].T
    # Static within-tick task order (see the placement step).
    if policy in ("first-fit", "cost-aware"):
        dem_norms = jnp.sqrt(jnp.sum(workload.demands**2, axis=1))
        task_order = jnp.argsort(-dem_norms, stable=True)
    else:
        task_order = jnp.arange(T)
    task_rank = jnp.argsort(task_order)  # static inverse permutation
    if congestion:
        # Pipe tables for the backlog model: bandwidth of the (src zone →
        # dst host) aggregate and its reciprocal, plus per-group instance
        # counts (the DES pulls a ~1/n_instances sample of predecessor
        # instances per consumer, ``resources/__init__.py:263-267`` — pull
        # volumes are scaled by the same fraction).
        bw_zh = topo.bw[:, topo.host_zone]  # [Z, H]
        inv_bw_zh = jnp.where(bw_zh > 0, 1.0 / bw_zh, 0.0)
        # Static pull-volume table: pull_frac[c, g] is a consumer
        # instance's pulled MB from group g per done g-instance, so this
        # tick's zone-resolved volume is just ``pull_frac @ zc``.
        inst, samp = _sampling_table(workload)
        pull_frac = (
            workload.pred_group * samp * (workload.out_group / inst)[None, :]
        )  # [G, G] consumer × producer
    if score_params is not None:
        # Parameterized scoring for on-device policy autotuning: exponents
        # (1, 1, 1) recover the reference score shape (modulo
        # pow-vs-identity float paths — the unparameterized branch in
        # place_body stays THE bit-exact default program).  The cost/bw
        # pow tables are pure (topology × params) — hoisted like
        # cost_rt/bw_rt; only norm ** w_norm depends on loop state.
        w_norm = score_params[2]
        cost_pow = cost_rt ** score_params[0]
        bw_pow = bw_rt ** score_params[1]
    inf = jnp.asarray(jnp.inf, dtype)
    G = workload.pred_group.shape[0]
    # Static one-hot expansion tables, hoisted out of the tick loop.
    # They replace per-tick [R, T] gathers (group→task and host→zone
    # expansions), which lower to scalar-memory gathers inside the
    # vmapped while loop — serialized on the scalar core, measured as
    # the dominant per-tick cost.  Select-reduces over them are exact:
    # each row has exactly one hit, and adding zeros is IEEE-exact.
    g_oh = workload.group_of[:, None] == jnp.arange(G)[None, :]  # [T, G]
    zone_onehot = (
        topo.host_zone[:, None] == jnp.arange(Z)[None, :]
    ).astype(dtype)  # [H, Z] — integer counts matmul (bf16-exact < 256)
    # [G, 4] per-group demand table: instances of a group share one
    # demand vector by construction (``from_applications`` appends the
    # group row per instance; no other constructor exists), so the
    # per-tick fit test collapses exactly to group level — T/G ≈ 12×
    # less compare-reduce work at the canonical scale, measured as the
    # largest single tick-body op.  Static scatter (shared indices).
    dem_group = jnp.zeros((G, 4), dtype).at[workload.group_of].set(
        workload.demands
    )

    def cond(carry):
        i, state = carry
        pending = state.stage != _DONE
        if active is not None:
            # Masked-out tasks (workload-size sweeps) stay PENDING forever
            # with arrival = inf; they must not keep the loop alive.
            pending = pending & active
        return (i < n_ticks) & jnp.any(pending)

    def body(carry):
        i, (t, stage, finish, place, avail, busy, q, qpos) = carry

        # 1. Retire finished tasks and refund their resources.
        #    Select-reduce over a [T, H] membership mask, NOT a
        #    segment_sum: under vmap the segment form lowers to a
        #    scatter-add whose [R, T] index vector lives in scalar
        #    memory — profiled at ~1 ms/tick serialized on the scalar
        #    core, 28% of the whole rollout (the same class the
        #    placement-loop rewrite eliminated; ARCHITECTURE.md, "the
        #    scalar-core lesson").  A one-hot MATMUL would be faster
        #    still but is not exact for real-valued f32 demands (MXU
        #    truncates operands to bf16); the select-reduce stays on the
        #    VPU with full f32 adds.  Summation is XLA's tree order
        #    rather than the scatter's index order — refunds of several
        #    tasks on one host can differ by ULPs from the old path
        #    (both deterministic; the DES is the semantic referee and
        #    sums per-event anyway).
        newly_done = (stage == _RUNNING) & (finish <= t)
        if vector:
            # ONE [T, H] placement one-hot shared by the refund sum and
            # the done-count einsum (their masks differ only in the stage
            # predicate ANDed on; fault aborts between them only touch
            # RUNNING rows, which the done predicate excludes).  The busy
            # max below rebuilds it because placements land in ``place``
            # first.  Unplaced rows carry the -1 sentinel and match no
            # host column.
            place_oh = place[:, None] == jnp.arange(H)[None, :]
            refund_per_host = jnp.sum(
                jnp.where(
                    (place_oh & newly_done[:, None])[:, :, None],
                    workload.demands[:, None, :],
                    jnp.zeros((), dtype),
                ),
                axis=0,
            )  # [H, 4]
        else:
            # Scatter-add over the retiring tasks' placements (misses →
            # the sacrificial H row).  Same sum, different accumulation
            # order than the tree reduce above — held bit-identical on
            # every rollout output by the forms parity suite.
            refund_per_host = jax.ops.segment_sum(
                jnp.where(
                    newly_done[:, None], workload.demands,
                    jnp.zeros((), dtype),
                ),
                jnp.where(newly_done, place, H),
                num_segments=H + 1,
            )[:H]  # [H, 4]
        avail = avail + refund_per_host
        stage = jnp.where(newly_done, _DONE, stage)

        # 1b. Faults: crashes strike after this window's completions
        #     retire (completion-wins tie at tick resolution).
        if faults is not None:
            struck = _scatter_hosts((fail_at > t - tick) & (fail_at <= t))
            down = _scatter_hosts((fail_at <= t) & (t < recover_at))
            prev_down = _scatter_hosts(
                (fail_at <= t - tick) & (t - tick < recover_at)
            )
            aborted = (
                (stage == _RUNNING)
                & (place >= 0)
                & struck[jnp.clip(place, 0, H - 1)]
            )
            stage = jnp.where(aborted, _PENDING, stage)
            place = jnp.where(aborted, -1, place)
            finish = jnp.where(aborted, inf, finish)
            # Recovery hands back a fresh machine (DES Host.recover);
            # covers both outages ending this window and sub-tick ones.
            recovered = (prev_down | struck) & ~down
            avail = jnp.where(recovered[:, None], totals, avail)
            # Down rows carry the −1 sentinel (no refund for lost work —
            # reapplied every tick so stray refunds cannot resurrect one).
            avail = jnp.where(down[:, None], jnp.asarray(-1.0, dtype), avail)
            if congestion:
                # A crash cancels the host's pending inbound staging
                # (FastExecutor.abort_host cancels queued transfers).
                q = jnp.where(struck[None, :], jnp.asarray(0.0, dtype), q)

        # 2. Readiness: the DES dispatch pipeline at tick resolution
        #    (measured on the live scheduler, tests/test_sched.py):
        #      * roots enter the global submit queue at submission time
        #        and dispatch at the first global tick STRICTLY after it
        #        (the t=0 tick precedes the local pump);
        #      * a successor's readiness event is its last predecessor
        #        instance's finish τ; the app-local pump (period = tick,
        #        phase = the app's submission time) picks it up at the
        #        first boundary STRICTLY after τ (a boundary coinciding
        #        with τ fires before the completion notification lands),
        #        and the global tick dispatches STRICTLY after the pump.
        #    Round 1 dispatched successors at the first tick ≥ τ — one to
        #    two ticks early — which shifted tick-batch composition off
        #    the DES's at capacity boundaries and was a dominant source
        #    of packing-arm placement divergence.
        done_f = (stage == _DONE).astype(dtype)
        unfinished_preds = workload.pred @ (1.0 - done_f)  # [T]
        fin_done = jnp.where(stage == _DONE, finish, -inf)
        gf = jax.ops.segment_max(
            fin_done, workload.group_of, num_segments=G
        )  # [G] latest finish among a group's done instances
        tau_g = jnp.max(
            jnp.where(workload.pred_group > 0, gf[None, :], -inf), axis=1
        )  # [G] readiness event time (−inf for root groups)
        if vector:
            tau = jnp.sum(
                jnp.where(g_oh, tau_g[None, :], jnp.zeros((), dtype)), axis=1
            )  # [T] — select-reduce, not the [R, T] gather (scalar core)
        else:
            tau = tau_g[workload.group_of]  # [T] gather (exact selection)
        pump = arrival + (jnp.floor((tau - arrival) / tick) + 1.0) * tick
        ready_time = jnp.where(has_pred, pump, arrival)
        ready = (
            (stage == _PENDING) & (ready_time < t) & (unfinished_preds == 0)
        )

        # 2b. Batch rank (tick_order="lifo"): each ready task's position
        #     in the DES's ready batch this tick.  The reference drains
        #     its wait dict first, in REVERSE insertion order (popitem),
        #     and insertion order was last tick's batch order — so the
        #     wait cohort runs in reverse of its previous positions
        #     (``qpos`` carry).  Fresh tasks follow, ordered by pump
        #     event time, then app creation order, then the local
        #     scheduler's LIFO stack pop (descending task index).  Two
        #     [T] sorts per tick: one to order, one to invert (no
        #     scatter on the vector path).
        iota_t = jnp.arange(T, dtype=jnp.int32)
        if lifo:
            # Three keys, not six: the wait/fresh/non-ready cohorts and
            # the wait cohort's reverse re-drain fold into ONE i32 key
            # (waits carry −qpos ≤ 0, fresh 1, non-ready 2 — integer
            # selection, order identical to the unfolded keys), and the
            # fresh cohort's (app creation order, LIFO stack pop) pair
            # is the STATIC key app·T + (T−1−index); only pump time
            # stays its own key.
            wait_c = (qpos >= 0) & ready
            k1 = jnp.where(
                ready, jnp.where(wait_c, -qpos, 1), jnp.asarray(2, jnp.int32)
            )
            if T <= 46340:  # app·T + T ≤ T² + T < 2³¹ (app_of < n_apps ≤ T)
                fresh_static = (
                    workload.app_of.astype(jnp.int32) * T + (T - 1 - iota_t)
                )
                keys = (k1, ready_time, fresh_static, iota_t)
                nk = 3
            else:  # unreachable with a [T, T] pred matrix in HBM; exact
                keys = (
                    k1, ready_time, workload.app_of.astype(jnp.int32),
                    -iota_t, iota_t,
                )
                nk = 4
            border = lax.sort(keys, num_keys=nk)[
                len(keys) - 1
            ]  # [T] batch order (task index at each position)
            if vector:
                brank = lax.sort((border, iota_t), num_keys=1)[1]
            else:
                brank = jnp.zeros((T,), jnp.int32).at[border].set(iota_t)
        else:
            brank = iota_t  # legacy: batch order = task index order

        # 3. Anchors: majority vote over predecessor placement hosts
        #    (ref cost_aware.py:45-58); roots use their pre-drawn keyed
        #    storage zone.  Group-wise: zc[g, z] counts group g's done
        #    instances in zone z, and summing counts over predecessor
        #    groups gives exactly the instance-level vote counts without
        #    any per-replica [T, T] product.  (zc also feeds the
        #    transfer estimate, so it is computed for every policy; the
        #    vote itself only matters to cost-aware.)
        done_mask = stage == _DONE
        if vector:
            # Done-instance counts per (group, host) as ONE bf16 one-hot
            # contraction over tasks: hv[g, h] = Σ_t 1[group_of[t]=g] ·
            # 1[place[t]=h, done].  The segment-sum form below lowers
            # (under vmap) to a scatter-add with a per-replica [R, T]
            # scalar-memory index vector — profiled at ~1 ms/tick
            # serialized on the scalar core, 22% of the whole rollout.
            # The matmul form is integer-EXACT: one-hot factors are 0/1
            # (exact in bf16), counts ≤ max instances < 256, and the MXU
            # accumulates in f32 — same argument as ``hv @ zone_onehot``
            # below.  (The former [R, T] ``host_zone[place]`` gather was
            # removed by the round-2 rewrite for the same reason.)
            place_done_oh = place_oh & done_mask[:, None]  # [T, H]
            hv = jnp.einsum(
                "tg,th->gh",
                g_oh.astype(jnp.bfloat16),
                place_done_oh.astype(jnp.bfloat16),
                preferred_element_type=dtype,
            )  # [G, H] done counts per host
        else:
            # Flattened (group × host) scatter-add of ones — integer
            # counts, exact in any accumulation order.
            flat = workload.group_of * (H + 1) + jnp.where(
                done_mask, place, H
            )
            hv = jax.ops.segment_sum(
                jnp.where(done_mask, jnp.ones((T,), dtype),
                          jnp.zeros((), dtype)),
                flat,
                num_segments=G * (H + 1),
            ).reshape(G, H + 1)[:, :H]  # [G, H] done counts per host
        zc = hv @ zone_onehot  # [G, Z]
        if policy == "cost-aware":
            # The DES/reference vote is per HOST, not per zone (Counter
            # over predecessor task *placements*, cost_aware.py:52-55):
            # the anchor is the single most-loaded host's zone.  A
            # zone-level vote (round 1) aggregates same-zone hosts and
            # can crown a different zone whenever an app's instances
            # spread across several hosts of one zone — measured as a
            # successor-anchor drift between the engines.  Ties resolve
            # to the lowest host index — an approximation of the DES's
            # first-seen insertion order (exact only while host score
            # order is static over the vote window; a vectorized
            # first-seen tie-break would need per-instance placement
            # timestamps).
            votes_h = workload.pred_group @ hv  # [G, H] pred-instance votes
            majority_host = jnp.argmax(votes_h, axis=1)  # [G]
            if vector:
                # Zone of each group's majority host, then group → task
                # expansion — both as integer select-reduces on the VPU
                # (the ``host_zone[majority_host][group_of]`` double
                # gather runs on the scalar core under vmap; sums of one
                # non-zero int are exact).
                mh_oh = jnp.arange(H)[None, :] == majority_host[:, None]
                mz_g = jnp.sum(
                    jnp.where(mh_oh, topo.host_zone[None, :], 0), axis=1
                )  # [G]
                majority_zone = jnp.sum(
                    jnp.where(g_oh, mz_g[None, :], 0), axis=1
                )  # [T]
            else:
                majority_zone = topo.host_zone[majority_host][
                    workload.group_of
                ]  # [T] double gather (exact selection)
            anchor = jnp.where(has_pred, majority_zone, root_anchor)
        else:
            anchor = root_anchor  # unused by the other arms

        # 4. Placement — same greedy cost-aware decision as the live
        #    scheduler's fused kernel (first-fit, sorted hosts, per-task
        #    score group), but the sequential chain is cut to the tasks
        #    that can actually place this tick:
        #      * availability only DECREASES within a tick (releases land
        #        at tick boundaries), so a ready task with no strictly
        #        fitting host at tick start can never place this tick —
        #        it is excluded from the chain with placement −1, exactly
        #        what its in-chain step would produce.  This is what keeps
        #        saturated phases cheap, where thousands of tasks wait but
        #        only a handful can land.
        #      * the eligible tasks are compacted to the front (stable, so
        #        index order — and therefore every placement — is
        #        bit-identical to the full scan) and a bounded while_loop
        #        runs max-over-replicas(n_eligible) steps instead of T.
        strict = policy in ("cost-aware", "best-fit")  # ref :124 / vbp :45
        # Group-level fit test (exact — see ``dem_group``), expanded per
        # task by a shared-index gather (constant across replicas, so it
        # lowers cheap, not to a batched scalar-memory gather).
        if strict:
            fits_g = jnp.all(
                avail[None, :, :] > dem_group[:, None, :], axis=2
            )  # [G, H]
        else:
            fits_g = jnp.all(
                avail[None, :, :] >= dem_group[:, None, :], axis=2
            )
        fits_at_start = jnp.any(fits_g, axis=1)[workload.group_of]  # [T]
        eligible = ready & fits_at_start
        # Within-tick order mirrors the canonical DES arms.  Cost-aware
        # processes anchor *buckets* group-major (the DES groups the
        # batch by anchor — Storage node for successors, the Application
        # for roots — and places one bucket at a time), with tasks inside
        # a bucket demand-norm-decreasing (sort_tasks).  VBP first-fit
        # runs one global decreasing sort; best-fit/opportunistic place
        # in batch order.
        if policy == "cost-aware":
            # Bucket code: successor groups merge by anchor zone
            # (Storage identity), root groups stay per-app (Application
            # identity) — Z + app_of keeps the two key spaces disjoint.
            bucket = jnp.where(
                has_pred, anchor, Z + workload.app_of.astype(jnp.int32)
            )
            # Bucket order keys on the min READY index — the DES buckets
            # first-seen over the full ready batch, including tasks with
            # no fitting host (they still pin their bucket's position).
            # Computed as [T, B] one-hot min/select-reduces on the VPU
            # (the former segment_min + ``first_in_bucket[bucket]`` pair
            # both lowered to scalar-memory scatter/gather inside the
            # loop).  B = Z + G bounds the bucket key space statically:
            # successor buckets are zones (< Z) and root buckets are
            # Z + app index, with #apps ≤ G (every app owns ≥ 1 group) —
            # linear in T, unlike a [T, T] same-bucket compare, which is
            # 13M cells/replica at the calibrate scale (T≈3.6k).
            B = Z + G
            # Bucket rank = first-seen position in the DES's ready batch
            # (``brank``: task index order under "fifo", the emulated
            # LIFO queue order under "lifo").
            ready_idx = jnp.where(ready, brank, T).astype(jnp.int32)
            if vector:
                b_oh = bucket[:, None] == jnp.arange(B)[None, :]  # [T, B]
                fib = jnp.min(
                    jnp.where(b_oh, ready_idx[:, None], T), axis=0
                )  # [B] first ready position per bucket
                bfirst = jnp.sum(
                    jnp.where(b_oh, fib[None, :], 0), axis=1
                ).astype(jnp.int32)
            else:
                # Integer min-scatter + gather (exact; empty buckets fill
                # INT_MAX vs the vector form's T, but bfirst only reads a
                # task's OWN bucket, which contains it).
                fib = jax.ops.segment_min(
                    ready_idx, bucket, num_segments=B
                )  # [B]
                bfirst = fib[bucket]  # [T]
            key3 = -dem_norms  # norm-decreasing inside a bucket
        else:
            bfirst = jnp.zeros((T,), jnp.int32)
            if policy == "first-fit":
                # VBP decreasing sort; the tie key below resolves equal
                # norms in batch order (the legacy path keys on the
                # precomputed rank, whose ties are baked in ascending).
                key3 = -dem_norms if lifo else task_rank
            else:
                # Batch order arms: the tie key IS the order.
                key3 = jnp.zeros((T,), jnp.int32) if lifo else task_rank
        # ONE multi-operand sort carrying every per-task payload through,
        # replacing lexsort + four ``x[order]`` gathers (each a batched
        # gather with scalar-memory indices — the dominant per-tick cost
        # before this rewrite).
        # Demands are NOT carried as payloads: the loop re-derives each
        # step's demand row from the group table (``dem_group[g_p[j]]``
        # as a tiny [G, 4] select-reduce) — four fewer [R, T] sort
        # operands per tick, exact by group-wise demand constancy.
        # Keys (major → minor): ineligible-last, bucket first-seen,
        # policy key, batch-rank tie.  Under "fifo" the batch rank IS
        # the task index, so ``iota_t`` serves as both the tie key and
        # the permutation payload — the round-2 seven-operand shape, no
        # extra [R, T] operand on the throughput hot path.  Under
        # "lifo" the per-tick ``brank`` is the tie key and ``iota_t``
        # rides as a separate payload.
        operands = [
            (~eligible).astype(jnp.int32),
            bfirst,
            key3,
        ]
        if lifo:
            operands.extend([brank, iota_t])
            payload0 = 4
        else:
            operands.append(iota_t)
            payload0 = 3
        operands.extend([anchor, workload.group_of.astype(jnp.int32)])
        if task_u is not None:
            operands.append(task_u)
        sorted_ops = lax.sort(tuple(operands), num_keys=4)
        order = sorted_ops[payload0]
        bf_p = sorted_ops[1]
        az_p = sorted_ops[payload0 + 1]
        g_p = sorted_ops[payload0 + 2]
        u_p = sorted_ops[payload0 + 3] if task_u is not None else None
        n_ready = jnp.sum(eligible)
        if realtime_scoring and policy == "cost-aware":
            # Discount the inbound leg of the round-trip bandwidth by the
            # tick-start backlog on each (anchor zone → host) pipe — the
            # outbound leg has no tracked queue and stays static.  This is
            # the signal the DES realtime_bw arm reads from live route
            # queues (ref ``resources/network.py:70-73``).  The where
            # keeps empty pipes BIT-identical to the static table (the
            # algebraic form bw_rt − bw_zh + bw_zh can round 1 ulp off).
            score_bw_rt = jnp.where(
                q > 0, bw_rt - bw_zh + bw_zh / (q + 1.0), bw_rt
            )
        else:
            score_bw_rt = bw_rt

        # 5a. Transfer-delay table — BEFORE the placement loop (it only
        #     reads zc, which predates placement): max over predecessor
        #     instances of size / bw(src zone → dst zone).  All instances
        #     of a producer group share one output size, so the max
        #     reduces exactly to zone *presence* per group: GD[g, z] =
        #     out_g × max over source zones s with a done g-instance of
        #     1/bw[s, z] ([G, Z]), then CD[c, z] = max over c's
        #     predecessor groups of GD.  Each placement selects its
        #     CD[g, zone(h)] entry inside the loop (tiny VPU selects);
        #     the former post-loop path gathered [R, T] ``new_zone`` and
        #     ``CD[group_of, new_zone]`` through scalar memory.
        inv_bw = jnp.where(topo.bw > 0, 1.0 / topo.bw, 0.0)  # [Z, Z]
        presence = (zc > 0).astype(dtype)  # [G, Z]
        GD = (
            jnp.max(presence[:, :, None] * inv_bw[None, :, :], axis=1)
            * workload.out_group[:, None]
        )  # [G, Z]
        CD = lax.map(
            lambda col: jnp.max(workload.pred_group * col[None, :], axis=1),
            GD.T,
        ).T  # [G, Z] max over predecessor groups, zone column at a time

        def place_cond(c):
            j, _avail, _pl, _dl, _ns, _bf = c
            return j < n_ready

        def place_body(c):
            j, avail, pl, delay, norm_snap, prev_bf = c
            if vector:
                # One [G, 1] group mask for this step, shared by the
                # demand re-derivation here and the CD row select below.
                g_hit = (jnp.arange(G) == g_p[j])[:, None]
                # Demand row from the group table (one [G, 4]
                # select-reduce; exactly one non-zero term — bit-exact,
                # and g_p[j] is the batched index the sort carries).
                demand = jnp.sum(
                    jnp.where(g_hit, dem_group, jnp.zeros((), dtype)), axis=0
                )  # [4]
            else:
                demand = dem_group[g_p[j]]  # [4] row gather
            if strict:
                fit = jnp.all(avail > demand[None, :], axis=1)
            else:
                fit = jnp.all(avail >= demand[None, :], axis=1)
            if policy == "cost-aware":
                # Stale-score semantics (ref cost_aware.py:104-119, DES
                # CostAwarePolicy._first_fit): host scores are computed
                # ONCE per anchor bucket from availability at bucket
                # start, then tasks first-fit in that frozen order with
                # LIVE fit checks.  Re-scoring per task (live norms) was
                # round 1's model — it spreads load as a host's residual
                # shrinks, where the DES keeps concentrating on it;
                # measured as the dominant cost-aware egress/IH bias.
                live_norm = jnp.sqrt(jnp.sum(avail * avail, axis=1))
                new_bucket = bf_p[j] != prev_bf
                norm_snap = jnp.where(new_bucket, live_norm, norm_snap)
                prev_bf = bf_p[j]
                # Anchor-zone row selection.  Vector form: one-hot
                # select-reduce, NOT ``table[az_p[j]]`` — under vmap the
                # indexed form lowers to a batched gather whose [R]
                # index vector lives in scalar memory, serialized on the
                # scalar core, measured as a dominant rollout cost.  The
                # select-reduce stays on the VPU and is bit-exact (the
                # sum has exactly one non-zero term; adding zeros is
                # IEEE-exact for finite table entries).  Indexed form:
                # the row gather (exact selection, fast on CPU).
                if vector:
                    zoh = (jnp.arange(Z) == az_p[j])[:, None]  # [Z, 1]
                    zero = jnp.zeros((), dtype)
                    if score_params is None:
                        cost_row = jnp.sum(
                            jnp.where(zoh, cost_rt, zero), axis=0
                        )
                        bw_row = jnp.sum(
                            jnp.where(zoh, score_bw_rt, zero), axis=0
                        )
                    else:
                        cost_row = jnp.sum(
                            jnp.where(zoh, cost_pow, zero), axis=0
                        )
                        bw_row = jnp.sum(jnp.where(zoh, bw_pow, zero), axis=0)
                else:
                    if score_params is None:
                        cost_row = cost_rt[az_p[j]]
                        bw_row = score_bw_rt[az_p[j]]
                    else:
                        cost_row = cost_pow[az_p[j]]
                        bw_row = bw_pow[az_p[j]]
                if score_params is None:
                    score = cost_row / (norm_snap * bw_row)
                else:
                    score = cost_row / (norm_snap ** w_norm * bw_row)
                h = jnp.argmin(jnp.where(fit, score, inf))
            elif policy == "first-fit":
                h = jnp.argmax(fit)  # lowest-index fit (ref vbp.py:6-29)
            elif policy == "best-fit":
                resid = avail - demand[None, :]
                score = jnp.sqrt(jnp.sum(resid * resid, axis=1))
                h = jnp.argmin(jnp.where(fit, score, inf))
            else:  # opportunistic: uniform among fits (ref opportunistic.py)
                # Per-tick redraw via a Weyl rotation of the task's base
                # uniform (the DES redraws per tick, policies.py:105; a
                # retrying task must not deterministically re-target the
                # same rank every tick).  Keyed on absolute time, so
                # checkpoint segmentation cannot shift the sequence.
                tick_idx = (t / tick).astype(jnp.int32)
                u_eff = jnp.mod(
                    u_p[j] + tick_idx.astype(u_p.dtype) * 0.6180339887498949,
                    1.0,
                )
                n_fit = jnp.sum(fit)
                k = jnp.minimum((u_eff * n_fit).astype(jnp.int32), n_fit - 1)
                rank = jnp.cumsum(fit) - 1  # rank among fitting hosts
                h = jnp.argmax(fit & (rank == k))
            ok = jnp.any(fit)
            if vector:
                # One-hot state updates, NOT ``.at[h].add`` /
                # ``.at[...].set``: under vmap those lower to batched
                # scatters with scalar-memory index vectors (serialized
                # on the scalar core — with the row gathers above, ~85%
                # of rollout wall before the round-2 rewrite).
                # Bit-exact: x − d·1 ≡ x + (−d), x − d·0 ≡ x.
                host_hit = (jnp.arange(avail.shape[0]) == h)[:, None]
                avail = avail - jnp.where(
                    host_hit & ok, demand[None, :],
                    jnp.zeros((), avail.dtype),
                )
                task_hit = jnp.arange(T) == order[j]
                pl = jnp.where(
                    task_hit, jnp.where(ok, h, -1).astype(jnp.int32), pl
                )
                # Transfer delay CD[group, zone(h)] for this placement
                # via three tiny VPU selects (zone of h, CD group row,
                # zone entry); unplaced tasks keep 0, masked by
                # ``placed`` below.
                z_h = jnp.sum(
                    jnp.where(jnp.arange(H) == h, topo.host_zone, 0)
                )
                cd_row = jnp.sum(
                    jnp.where(g_hit, CD, jnp.zeros((), dtype)), axis=0
                )  # [Z]
                d_j = jnp.sum(
                    jnp.where(
                        jnp.arange(Z) == z_h, cd_row, jnp.zeros((), dtype)
                    )
                )
                delay = jnp.where(task_hit & ok, d_j, delay)
            else:
                # Index forms (exact: x − d ≡ x + (−d); a miss scatters
                # to the dropped H row instead of adding 0).
                avail = avail.at[jnp.where(ok, h, H)].add(
                    -demand, mode="drop"
                )
                pl = pl.at[order[j]].set(
                    jnp.where(ok, h, -1).astype(jnp.int32)
                )
                z_h = topo.host_zone[h]
                d_j = CD[g_p[j], z_h]
                delay = delay.at[order[j]].set(
                    jnp.where(ok, d_j, jnp.zeros((), dtype))
                )
            return j + 1, avail, pl, delay, norm_snap, prev_bf

        _, avail, placements, xfer_delay, _, _ = lax.while_loop(
            place_cond,
            place_body,
            (
                jnp.asarray(0, jnp.int32),
                avail,
                jnp.full((T,), -1, dtype=jnp.int32),
                jnp.zeros((T,), dtype),
                jnp.sqrt(jnp.sum(avail * avail, axis=1)),
                jnp.asarray(-1, jnp.int32),
            ),
        )
        placed = placements >= 0
        if lifo:
            # Wait-queue carry: a ready task that did not place this
            # tick re-enters the wait dict at its batch position (the
            # DES inserts unplaced tasks in schedule-return order =
            # batch order; next tick's re-drain reverses on -qpos
            # above).  Placed / non-ready rows reset to the -1 sentinel
            # (an aborted task re-enters as FRESH, like the DES's
            # resubmission through submit_q).
            qpos = jnp.where(
                ready & ~placed, brank, jnp.asarray(-1, jnp.int32)
            )

        if congestion:
            # Backlog pipe model: every (src zone s → dst host h) aggregate
            # is one FIFO pipe with queued-MB state q[s, h]; a pull joins
            # the backlog and completes when the pipe has drained it, so
            # its delay is (backlog + this tick's volume) / bw — the
            # tick-resolution analog of the DES's per-route round-robin
            # chunk service, where concurrent transfers on one route all
            # finish together at backlog-drain time.  Pull volumes follow
            # the DES sampling rule via the hoisted ``pull_frac`` table;
            # aggregation is one matmul + one segment sum — nothing bigger
            # than [T, Z] is materialized.
            pull_gz = pull_frac @ zc  # [G, Z] pulled MB per consumer instance
            # Group → task expansion kept as a shared-index gather: a
            # g_oh one-hot MATMUL here would not be bit-exact (pull_gz
            # carries real f32 values, which the MXU truncates to bf16 —
            # unlike the integer-count ``hv @ zone_onehot`` above), and a
            # where/reduce select would build an [R, T, G, Z] broadcast.
            # The index vector (group_of) is shared across replicas, so
            # this lowers to a constant-index gather, not the batched
            # scalar-memory form the placement-loop rewrite eliminated.
            vol_tz = pull_gz[workload.group_of] * placed[:, None]  # [T, Z]
            if vector:
                # Round-3 congestion-arm vectorization (VERDICT r02
                # item 1): the two per-tick scalar-core ops below — a
                # scatter-add with a per-replica [R, T] segment-id
                # vector and a batched gather on placements — were the
                # arm's remaining toll (11.4 s vs 2.6–3.1 s for the
                # static arms at the canonical scale) after both round-2
                # purges.  Both become HIGHEST-precision one-hot matmuls
                # on the MXU: the f32 emulation's split-product of x
                # with an exact 0/1 operand is exact (x·1 = hi + lo = x,
                # x·0 = 0), so the pipe sums differ from the scatter
                # form only in accumulation order (tree vs index —
                # empirically bit-identical on the parity workloads; the
                # forms suite holds every rollout output to exact
                # equality), and the ratio "gather" is a one-non-zero-
                # term select, exact outright.
                place_oh_f = (
                    placements[:, None] == jnp.arange(H)[None, :]
                ).astype(dtype)  # [T, H]; unplaced rows are all-zero
                v_new = jnp.einsum(
                    "tz,th->zh", vol_tz, place_oh_f,
                    precision=lax.Precision.HIGHEST,
                )  # [Z, H] new queued MB per pipe
            else:
                v_new = jax.ops.segment_sum(
                    vol_tz, jnp.where(placed, placements, H),
                    num_segments=H + 1,
                )[:H].T  # [Z, H] new queued MB per pipe
            q_now = q + v_new
            # Per-task congested delay: max over source zones this task
            # pulls NONZERO volume from of backlog/bw at its destination
            # host (a zero-output predecessor transfers nothing — the DES
            # skips it, ``resources/__init__.py:263-267`` — so backlog
            # from other tasks must not delay this one through it).
            pulls_from = vol_tz > 0
            if vector:
                # q_now depends on ALL of this tick's placements, so the
                # per-pipe ratio cannot be selected during the placement
                # loop — but the post-loop selection needs no gather:
                # each task's ratio row is a one-non-zero-term one-hot
                # contraction of its placement column (exact, on-MXU).
                ratio_t = jnp.einsum(
                    "th,zh->tz", place_oh_f, q_now * inv_bw_zh,
                    precision=lax.Precision.HIGHEST,
                )  # [T, Z]
            else:
                ratio_t = (
                    q_now * inv_bw_zh
                )[:, jnp.clip(placements, 0, H - 1)].T
            cong_delay = jnp.max(
                jnp.where(pulls_from, ratio_t, 0.0), axis=1
            )  # [T]
            # Never undercut the uncongested bound: an empty pipe with one
            # puller reduces to the static size/bw estimate or below (the
            # sampled volume is a 1/n fraction), so take the max.
            xfer_delay = jnp.maximum(xfer_delay, cong_delay)
            # Drain the pipes over the coming window.
            q = jnp.maximum(q_now - bw_zh * tick, 0.0)

        stage = jnp.where(placed, _RUNNING, stage)
        place = jnp.where(placed, placements, place)
        finish = jnp.where(placed, t + xfer_delay + runtime, finish)

        # 6. Busy-host integral (instance-hours estimator).  Tasks only
        #    start at tick boundaries, so a host's busy interval inside
        #    this window always begins at t and ends at the latest
        #    resident finish (capped at the window) — the per-window
        #    integral max_tasks(min(finish − t, tick)) is exact within
        #    the rollout's own timing model, not a whole-tick rounding.
        #    Select-max over a [T, H] membership mask, NOT a segment_max
        #    (the vmapped segment form is a scalar-memory scatter like
        #    the refund above — profiled at ~1 ms/tick, 22% of the
        #    rollout).  Max is order-independent, so this is bit-exact
        #    vs the old path; empty hosts reduce to the 0 identity the
        #    old ``maximum(·, 0)`` clamp produced.  The mask is rebuilt
        #    rather than shared with the tick-start ``place_oh``: this
        #    tick's placements have landed in ``place`` by now and must
        #    count toward busy time.
        contrib = jnp.where(
            stage == _RUNNING, jnp.clip(finish - t, 0.0, tick), 0.0
        )
        if vector:
            run_at = (
                (place[:, None] == jnp.arange(H)[None, :])
                & (stage == _RUNNING)[:, None]
            )  # [T, H]
            busy_host = jnp.max(
                jnp.where(run_at, contrib[:, None], jnp.zeros((), dtype)),
                axis=0,
            )  # [H]
        else:
            # Max-scatter (order-independent, exact); empty hosts fill
            # −inf, clamped back to the vector form's 0 identity
            # (contrib ≥ 0, so the clamp cannot alter a busy host).
            busy_host = jnp.maximum(
                jax.ops.segment_max(
                    contrib,
                    jnp.where(stage == _RUNNING, place, H),
                    num_segments=H + 1,
                )[:H],
                0.0,
            )  # [H]
        busy = busy + jnp.sum(busy_host)

        return (
            i + 1,
            RolloutState(
                t + tick, stage, finish, place, avail, busy, q, qpos
            ),
        )

    _, out = lax.while_loop(cond, body, (jnp.asarray(0, jnp.int32), state))
    return out


def _sampling_table(workload: EnsembleWorkload):
    """(inst, samp): per-group instance counts and the DES pull-sample
    table — each consumer instance of group c pulls ``samp[c, g] =
    max(round(inst[g] / inst[c]), 1)`` predecessor instances of group g
    (``resources/__init__.py:263-267``; ``jnp.round`` matches Python's
    banker's rounding).  The ONE definition shared by the congestion
    timing model and the egress bill, so the two cannot desynchronize."""
    inst = jnp.maximum(jnp.sum(workload.group_onehot, axis=0), 1.0)  # [G]
    samp = jnp.maximum(jnp.round(inst[None, :] / inst[:, None]), 1.0)
    return inst, samp


def _sampled_egress(workload, topo, zcp, pz, placed):
    """DES-faithful egress estimate in three small matmuls.

    The DES bills one transfer per *sampled* pull (see
    :func:`_sampling_table`) — totalling ≈ max(n_p, n_c) transfers per
    group edge, NOT the n_p × n_c of naive all-pairs counting (which
    would inflate fan-out egress ~16× on the Alibaba traces).  Expected
    cost per pull = Σ_s P(source in zone s) × cost[s, consumer zone],
    with the source distributed like the producer's placed instances
    (zcp row, normalized).
    """
    n_placed_g = jnp.sum(zcp, axis=1, keepdims=True)  # [G, 1]
    src_frac = jnp.where(n_placed_g > 0, zcp / jnp.maximum(n_placed_g, 1.0), 0.0)
    _, samp = _sampling_table(workload)
    # d[g, i]: expected $/8000·MB⁻¹-weighted cost of one pull from group g
    # into task i's zone, scaled by g's output size.
    d = (src_frac * workload.out_group[:, None]) @ topo.cost[:, pz]  # [G, T]
    pulls = (workload.pred_group * samp)[workload.group_of]  # [T, G]
    return jnp.sum(placed * jnp.sum(pulls * d.T, axis=1)) / 8000.0


def _finalize(
    state: RolloutState,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    active=None,  # optional [T] bool — inactive tasks don't count unfinished
) -> RolloutResult:
    H = state.avail.shape[0]
    dtype = state.avail.dtype
    finish, place, stage = state.finish, state.place, state.stage
    done = stage == _DONE
    makespan = jnp.max(jnp.where(done, finish, 0.0))
    # Egress: one bill per DES-sampled pull (see _sampled_egress), counting
    # only pulls whose consumer was actually placed (an unplaced consumer
    # at the horizon must not be billed as if on host 0).
    pz = topo.host_zone[jnp.clip(place, 0, H - 1)]
    placed = (place >= 0).astype(dtype)
    Z = topo.cost.shape[0]
    zcp = workload.group_onehot.T @ (
        jax.nn.one_hot(pz, Z, dtype=dtype) * placed[:, None]
    )  # [G, Z] placed-instance counts
    egress = _sampled_egress(workload, topo, zcp, pz, placed)
    return RolloutResult(
        makespan=makespan,
        egress_cost=egress,
        finish_time=finish,
        placement=place,
        n_unfinished=jnp.sum(~done if active is None else (~done & active)),
        instance_hours=state.busy / 3600.0,
    )


def _single_rollout(
    avail0,  # [H, 4]
    runtime,  # [T] perturbed
    arrival,  # [T] perturbed
    root_anchor,  # [T] i32 random storage zone per task (used for roots)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    max_ticks: int,
    faults=None,
    score_params=None,
    policy: str = "cost-aware",
    task_u=None,
    congestion: bool = False,
    realtime_scoring: bool = False,
    active=None,  # optional [T] bool — tasks outside the mask never run
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    state = _init_state(avail0, workload.n_tasks, topo.cost.shape[0])
    state = _rollout_segment(
        state, runtime, arrival, root_anchor, workload, topo, tick, max_ticks,
        faults=faults, totals=avail0, score_params=score_params,
        policy=policy, task_u=task_u, congestion=congestion,
        realtime_scoring=realtime_scoring, active=active,
        forms=_resolve_forms(forms), tick_order=tick_order,
    )
    return _finalize(state, workload, topo, active=active)


def _fault_schedule(key, n_replicas, n_faults, n_hosts, horizon, mttr, dtype):
    """Per-replica random crash schedules, mirroring
    ``FaultInjector.random_host_failures``: ``n_faults`` crashes at uniform
    times in ``[0, horizon)`` on uniformly drawn hosts, each recovering
    after an Exp(mean=``mttr``) outage (never, if ``mttr`` is None)."""
    k_t, k_h, k_d = jax.random.split(key, 3)
    fail_at = jax.random.uniform(
        k_t, (n_replicas, n_faults), minval=0.0, maxval=horizon, dtype=dtype
    )
    host = jax.random.randint(k_h, (n_replicas, n_faults), 0, n_hosts).astype(
        jnp.int32
    )
    if mttr is None:
        recover_at = jnp.full((n_replicas, n_faults), jnp.inf, dtype=dtype)
    else:
        outage = jax.random.exponential(k_d, (n_replicas, n_faults), dtype=dtype)
        recover_at = fail_at + mttr * outage
    return host, fail_at, recover_at


def _make_fault_schedule(
    key, n_replicas, n_faults, avail0, tick, max_ticks, fault_horizon, mttr
):
    """The one place fault draws derive from the rollout key: fold_in (not
    split) so the fault-free path's draws — and thus every existing result
    and checkpoint — are unchanged; shared by :func:`rollout` and
    :func:`rollout_checkpointed` so segmented runs stay bit-identical."""
    horizon = fault_horizon if fault_horizon is not None else tick * max_ticks
    return _fault_schedule(
        jax.random.fold_in(key, 0x0FA17), n_replicas, n_faults,
        avail0.shape[0], horizon, mttr, avail0.dtype,
    )



def _pack_extras(faults=None, task_u=None, totals=None, score_params=None,
                 active=None):
    """Flatten the optional per-replica/per-row axes for a vmap body.

    Returns ``(spec, extras_list)``; ``spec`` is the static presence
    tuple consumed by :func:`_unpack_extras` — together they are the ONE
    place the positional bookkeeping lives, shared by :func:`rollout`,
    :func:`_segment_step`, and the row-based sweep runner so the
    execution paths cannot drift.  ``spec`` is hashable, so it can cross
    a jit boundary as a static argument.
    """
    spec = (
        faults is not None, task_u is not None, totals is not None,
        score_params is not None, active is not None,
    )
    extras = []
    if faults is not None:
        extras.extend(faults)
    for x in (task_u, totals, score_params, active):
        if x is not None:
            extras.append(x)
    return spec, extras


def _unpack_extras(spec, ex):
    """Rebuild ``(faults, task_u, totals, score_params, active)`` from a
    flat extras tuple, per the presence ``spec`` from :func:`_pack_extras`."""
    has_f, has_u, has_tot, has_sp, has_act = spec
    i = 0
    f = u = tot = sp = act = None
    if has_f:
        f = (ex[0], ex[1], ex[2])
        i = 3
    if has_u:
        u = ex[i]
        i += 1
    if has_tot:
        tot = ex[i]
        i += 1
    if has_sp:
        sp = ex[i]
        i += 1
    if has_act:
        act = ex[i]
        i += 1
    return f, u, tot, sp, act


def _opportunistic_uniforms(key, n_replicas, n_tasks, dtype):
    """Base uniform per (replica, task) for the opportunistic arm; the
    placement step rotates it by the golden ratio per tick (Weyl
    sequence), approximating the DES's independent per-tick redraws
    (``tick_uniforms``, policies.py:105) without materializing a
    [ticks, T] draw tensor.  fold_in keeps the other arms' streams
    untouched."""
    return jax.random.uniform(
        jax.random.fold_in(key, 0x09901), (n_replicas, n_tasks), dtype=dtype
    )


def _seed_bits(key):
    """uint32 seed word of a PRNG key: for ``jax.random.PRNGKey(s)`` this
    is exactly ``s`` (key data ``[0, s]``), which is what pairs the
    estimator's keyed root-anchor draws with a DES run seeded ``s``."""
    try:
        data = jax.random.key_data(key)
    except TypeError:  # already a raw uint32 key array
        data = key
    return data.reshape(-1)[-1].astype(jnp.uint32)


def _keyed_storage_index_jax(seed_bits, app_ids, n_storage, salt):
    """JAX twin of :func:`pivot_tpu.sched.rand.keyed_storage_index` —
    identical uint32 math (tested bit-equal), so estimator replica 0
    anchors exactly match the DES policies' keyed draws."""
    A = jnp.uint32(0x9E3779B9)
    B = jnp.uint32(0x85EBCA6B)
    C = jnp.uint32(0xC2B2AE35)
    x = seed_bits.astype(jnp.uint32) * A + salt.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * B + app_ids.astype(jnp.uint32) * A
    x = x ^ (x >> 13)
    x = x * C
    x = x ^ (x >> 16)
    return (x % jnp.uint32(n_storage)).astype(jnp.int32)


def _perturbations(key, workload, storage_zones, n_replicas, perturb, dtype):
    """Deterministic per-replica Monte-Carlo draws — regenerated (not
    stored) on checkpoint resume, since they are a pure function of key."""
    T = workload.n_tasks
    # Still split in 3: threefry subkeys depend on the total split count
    # (counters pair by halves), so dropping to split(key, 2) would
    # silently change every rt/arr draw — breaking bit-stability with
    # existing results and regenerated-on-resume checkpoints.  The third
    # key (the retired jax.random anchor draw) is simply unused.
    k_rt, k_arr, _k_retired = jax.random.split(key, 3)
    rt = workload.runtime[None, :] * jax.random.uniform(
        k_rt, (n_replicas, T), minval=1 - perturb, maxval=1 + perturb,
        dtype=dtype,
    )
    arr = workload.arrival[None, :] * jax.random.uniform(
        k_arr, (n_replicas, T), minval=1 - perturb, maxval=1 + perturb,
        dtype=dtype,
    )
    # Root anchors are shared PER APPLICATION, mirroring the DES cost-aware
    # policy: all root task groups of one app bucket under the app and draw
    # ONE storage anchor (``sched/policies.py`` group_tasks; ref
    # ``scheduler/cost_aware.py:38-39``).  The draw is the entity-keyed
    # function shared with the DES (replica salt r; r = 0 IS the DES's
    # draw for a scheduler seeded with this key's seed word), so nominal
    # calibration runs see identical anchors in both engines.
    salts = jnp.arange(n_replicas, dtype=jnp.uint32)
    anchor_idx = _keyed_storage_index_jax(
        _seed_bits(key),
        workload.app_of[None, :],
        storage_zones.shape[0],
        salts[:, None],
    )
    root_anchor = storage_zones[anchor_idx].astype(jnp.int32)
    return rt, arr, root_anchor


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_replicas", "tick", "max_ticks", "perturb",
        "n_faults", "fault_horizon", "mttr", "policy", "congestion",
        "realtime_scoring", "forms", "tick_order",
    ),
)
def _rollout_states(
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    n_replicas: int,
    tick: float,
    max_ticks: int,
    perturb: float,
    n_faults: int,
    fault_horizon: Optional[float],
    mttr: Optional[float],
    policy: str,
    congestion: bool,
    realtime_scoring: bool,
    forms: str = "vector",
    tick_order: str = "fifo",
) -> RolloutState:
    """The jitted rollout body: [R]-stacked final states (no finalize)."""
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail0.dtype
    ) if policy == "opportunistic" else None
    faults = (
        _make_fault_schedule(
            key, n_replicas, n_faults, avail0, tick, max_ticks,
            fault_horizon, mttr,
        )
        if n_faults
        else None
    )
    spec, extras = _pack_extras(faults, task_u)
    Z = topo.cost.shape[0]

    def one(r, a, ra, *ex):
        f, u, _tot, _sp, _act = _unpack_extras(spec, ex)
        state = _init_state(avail0, workload.n_tasks, Z)
        return _rollout_segment(
            state, r, a, ra, workload, topo, tick, max_ticks,
            faults=f, totals=avail0, policy=policy, task_u=u,
            congestion=congestion, realtime_scoring=realtime_scoring,
            forms=forms, tick_order=tick_order,
        )

    return jax.vmap(one)(rt, arr, root_anchor, *extras)


@jax.jit
def _finalize_batch(
    states: RolloutState,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    active=None,  # optional [B, T] bool, one mask per state row
) -> RolloutResult:
    """The ONE finalize program shared by every execution path — plain,
    sharded, checkpointed rollouts and the row-based sweeps all derive
    result metrics from final states through this exact compiled
    computation, so segmented runs are bit-identical to monolithic ones
    (XLA reduction order would otherwise differ between a fused
    rollout+finalize program and a standalone finalize)."""
    if active is None:
        return jax.vmap(lambda s: _finalize(s, workload, topo))(states)
    return jax.vmap(
        lambda s, a: _finalize(s, workload, topo, active=a)
    )(states, active)


def rollout(
    key,
    avail0,  # [H, 4] initial availability (shared base)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,  # [S] i32 candidate root-anchor zones
    n_replicas: int = 64,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """Vmapped Monte-Carlo rollout: [R]-leading-axis results.

    Replica r perturbs task runtimes and arrivals by ``±perturb`` and draws
    independent random root anchors — the BASELINE.json ensemble configs.

    With ``n_faults > 0`` each replica additionally draws an independent
    random host-crash schedule (``n_faults`` crashes uniform in
    ``[0, fault_horizon)``, Exp(``mttr``) outages; see ``_fault_schedule``)
    — resilience-under-failures what-if analysis as one device program,
    where the DES needs one full simulation per fault scenario.
    ``fault_horizon`` defaults to the nominal ``tick × max_ticks`` span.
    ``avail0`` must be full host capacity (recovery resets to it).
    """
    workload.check_group_demands()
    states = _rollout_states(
        key, avail0, workload, topo, storage_zones,
        n_replicas=n_replicas, tick=tick, max_ticks=max_ticks,
        perturb=perturb, n_faults=n_faults, fault_horizon=fault_horizon,
        mttr=mttr, policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring, forms=_resolve_forms(forms),
        tick_order=tick_order,
    )
    return _finalize_batch(states, workload, topo)


@functools.lru_cache(maxsize=32)
def _sharded_rollout_fn(
    mesh, n_replicas, tick, max_ticks, perturb, n_faults, fault_horizon,
    mttr, policy, congestion, realtime_scoring, tick_order,
):
    """Cached jitted rollout per (mesh, static config) — repeated calls
    (key sweeps, perturbation sweeps) reuse the compiled program."""
    out_shard = NamedSharding(mesh, P("replica"))
    return jax.jit(
        functools.partial(
            rollout,
            n_replicas=n_replicas,
            tick=tick,
            max_ticks=max_ticks,
            perturb=perturb,
            n_faults=n_faults,
            fault_horizon=fault_horizon,
            mttr=mttr,
            policy=policy,
            congestion=congestion,
            realtime_scoring=realtime_scoring,
            tick_order=tick_order,
        ),
        out_shardings=RolloutResult(
            makespan=out_shard,
            egress_cost=out_shard,
            finish_time=NamedSharding(mesh, P("replica", None)),
            placement=NamedSharding(mesh, P("replica", None)),
            n_unfinished=out_shard,
            instance_hours=out_shard,
        ),
    )


def sharded_rollout(
    mesh,
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    n_replicas: int = 64,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    tick_order: str = "fifo",
) -> RolloutResult:
    """Rollout with the replica axis sharded over ``mesh`` ('replica' axis).

    Inputs are replicated; per-replica state and all outputs are sharded
    ``P('replica')`` — XLA partitions the vmapped while_loop across devices
    with zero cross-replica traffic (embarrassingly parallel), and any
    downstream ensemble statistics (means/quantiles over replicas) become
    psums over ICI.  Fault parameters as in :func:`rollout`.
    """
    fn = _sharded_rollout_fn(
        mesh, n_replicas, tick, max_ticks, perturb, n_faults, fault_horizon,
        mttr, policy, congestion, realtime_scoring, tick_order,
    )
    return fn(key, avail0, workload, topo, storage_zones)


def sweep_out_shardings(mesh) -> RolloutResult:
    """Output shardings for the [K, R, ...] what-if sweeps
    (:func:`score_param_sweep`, :func:`capacity_sweep`,
    :func:`workload_sweep`): the replica axis (axis 1) shards over the
    mesh, candidates and task axes stay unsharded.  Most callers want
    :func:`shard_sweep` instead.
    """
    two = NamedSharding(mesh, P(None, "replica"))
    three = NamedSharding(mesh, P(None, "replica", None))
    return RolloutResult(
        makespan=two,
        egress_cost=two,
        finish_time=three,
        placement=three,
        n_unfinished=two,
        instance_hours=two,
    )


def shard_sweep(sweep_fn, fallback_segment_ticks=None, force_mesh=False,
                **static_kw):
    """Bind a what-if sweep's static config and shard it over the
    available devices ('replica' axis, like :func:`sharded_rollout`) —
    XLA partitions the vmapped while_loops with zero cross-replica
    traffic.  Falls back to the unsharded call on a single device, when
    the replica count does not divide the mesh, or on the CPU backend
    (a forced-host-device "mesh" shares the physical cores — measured
    >5× slower than unsharded at scale; it exists to VALIDATE sharding,
    which tests opt into via ``force_mesh=True``).  On the fallback,
    ``fallback_segment_ticks`` (if set and not already in the config)
    runs the sweep in bounded device calls — the decision lives HERE
    because the segmented host loop is untraceable and must never reach
    the jitted sharded path.
    """
    import inspect

    from pivot_tpu.parallel.mesh import build_mesh
    from pivot_tpu.utils import get_logger

    n_dev = len(jax.devices())
    # The divisibility guard must judge the replica count the sweep will
    # actually run with — a caller relying on the sweep's own default
    # would otherwise bypass the check (0 % n_dev == 0) and fail at run
    # time inside the sharded program.
    n_replicas = static_kw.get("n_replicas")
    if n_replicas is None:
        try:
            default = inspect.signature(sweep_fn).parameters["n_replicas"].default
        except (KeyError, TypeError, ValueError):
            default = inspect.Parameter.empty
        n_replicas = None if default is inspect.Parameter.empty else default
    reason = None
    if n_dev <= 1:
        pass  # nothing to shard over — not worth a log line
    elif static_kw.get("segment_ticks") is not None:
        # The segmented runner is a host-side loop (block_until_ready +
        # data-dependent early exit) — untraceable under jit, so an
        # explicit segment request always takes the unsharded path.
        reason = "explicit segment_ticks requests the host-side segmented loop"
    elif n_replicas is None or n_replicas % n_dev:
        reason = (
            f"replicas ({n_replicas}) not divisible by {n_dev} devices"
        )
    elif jax.default_backend() == "cpu" and not force_mesh:
        reason = (
            "CPU backend (forced-host-device meshes share the physical "
            "cores; pass force_mesh=True to shard anyway)"
        )
    if n_dev <= 1 or reason is not None:
        if reason is not None:
            get_logger("ensemble").info("sweep runs unsharded: %s", reason)
        if fallback_segment_ticks is not None:
            static_kw.setdefault("segment_ticks", fallback_segment_ticks)
        return functools.partial(sweep_fn, **static_kw)
    mesh = build_mesh(n_dev, ("replica", "host"))
    return jax.jit(
        functools.partial(sweep_fn, **static_kw),
        out_shardings=sweep_out_shardings(mesh),
    )


# -- row-based sweep runner ---------------------------------------------------
#
# Every what-if sweep is K candidates × R replicas of the same rollout with
# per-cell inputs.  Flattening (K, R) to B = K·R *rows* lets one vmapped
# segment program serve all three sweeps — and makes segmented execution
# (bounded device calls, like ``rollout_checkpointed``) structural instead
# of per-sweep surgery.  Finalization always goes through the ONE shared
# ``_finalize_batch`` program, the same bit-consistency discipline as the
# plain rollout.


@functools.partial(
    jax.jit,
    static_argnames=(
        "tick", "policy", "congestion", "realtime_scoring", "spec", "forms",
        "tick_order",
    ),
)
def _row_segment_step(
    states,  # [B]-stacked RolloutState
    rt,  # [B, T]
    arr,  # [B, T]
    ra,  # [B, T] i32
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    segment_ticks,  # traced i32 — partial segments must not recompile
    spec,  # static (has_faults, has_task_u, has_totals, has_sp, has_active)
    *extras,  # the present per-row arrays, in spec order
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: str = "vector",
    tick_order: str = "fifo",
):
    """Advance every row by at most ``segment_ticks`` scheduler ticks."""

    def seg(s, r, a, ra_, *ex):
        f, u, tot, sp, act = _unpack_extras(spec, ex)
        return _rollout_segment(
            s, r, a, ra_, workload, topo, tick, segment_ticks,
            faults=f, totals=tot, score_params=sp, policy=policy,
            task_u=u, congestion=congestion,
            realtime_scoring=realtime_scoring, active=act, forms=forms,
            tick_order=tick_order,
        )

    return jax.vmap(seg)(states, rt, arr, ra, *extras)


def _run_rows(
    avail_rows,  # [B, H, 4] initial availability per row
    rt, arr, ra,  # [B, T] perturbed inputs per row
    workload, topo, tick, max_ticks, segment_ticks,
    policy, congestion, realtime_scoring,
    faults=None,  # optional ([B,F] i32, [B,F], [B,F])
    task_u=None,  # optional [B, T]
    totals=None,  # optional [B, H, 4] (fault recovery target)
    score_params=None,  # optional [B, 3]
    active=None,  # optional [B, T] bool
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """Run B rows to the horizon and finalize through the shared program.

    ``segment_ticks=None`` issues ONE bounded device call of ``max_ticks``
    (the while_loop still early-exits) — fully traceable, so
    :func:`shard_sweep` can jit over it.  An integer runs the rollout in
    that many device calls per ``segment_ticks`` ticks with host-side
    early exit between segments — the remote-transport-friendly mode
    (``rollout_checkpointed``'s rationale): a monolithic multi-thousand-
    tick program is one minutes-long execution some transports kill.
    """
    Z = topo.cost.shape[0]
    spec, extras = _pack_extras(faults, task_u, totals, score_params, active)
    forms = _resolve_forms(forms)

    states = jax.vmap(lambda av: _init_state(av, workload.n_tasks, Z))(
        avail_rows
    )
    if segment_ticks is None:
        states = _row_segment_step(
            states, rt, arr, ra, workload, topo, tick,
            jnp.asarray(max_ticks, jnp.int32), spec, *extras,
            policy=policy, congestion=congestion,
            realtime_scoring=realtime_scoring, forms=forms,
            tick_order=tick_order,
        )
    else:
        ticks = 0
        while ticks < max_ticks:
            seg = min(segment_ticks, max_ticks - ticks)
            states = _row_segment_step(
                states, rt, arr, ra, workload, topo, tick,
                jnp.asarray(seg, jnp.int32), spec, *extras,
                policy=policy, congestion=congestion,
                realtime_scoring=realtime_scoring, forms=forms,
                tick_order=tick_order,
            )
            jax.block_until_ready(states)
            ticks += seg
            pending = states.stage != _DONE
            if active is not None:
                pending = pending & active
            if not bool(jnp.any(pending)):
                break
    return _finalize_batch(states, workload, topo, active)


def _reshape_rows(res: RolloutResult, K: int, R: int) -> RolloutResult:
    """[B, ...] row results back to [K, R, ...]."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((K, R) + x.shape[1:]), res
    )


def _tile_rows(x, K):
    """Tile a per-replica array to per-row (candidate-major: row b =
    candidate b // R, replica b % R)."""
    return jnp.tile(x, (K,) + (1,) * (x.ndim - 1))


# -- policy autotuning --------------------------------------------------------


def score_param_sweep(
    key,
    avail0,  # [H, 4] full host capacity
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,  # [S] i32
    param_grid,  # [K, 3] exponents (w_cost, w_bw, w_norm) per candidate
    n_replicas: int = 32,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    congestion: bool = False,
    segment_ticks: Optional[int] = None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """On-device policy autotuning: sweep the cost-aware score exponents.

    The candidate scoring function is ``cost^w_cost / (norm^w_norm ×
    bw^w_bw)`` — ``(1, 1, 1)`` is the reference's score shape
    (``scheduler/cost_aware.py:104-119``).  Every candidate × replica pair
    rolls out in ONE device program (double vmap, [K, R] leading axes), so
    a K-point scheduler-hyperparameter grid search under R Monte-Carlo
    scenarios costs one dispatch — the reference would need K × R full OS
    processes.  All candidates share the same perturbation/anchor draws,
    so candidate comparisons are paired (common random numbers: the
    between-candidate variance excludes scenario noise).

    Pick a winner downstream, e.g.
    ``param_grid[jnp.argmin(res.makespan.mean(axis=1))]`` or any
    makespan/egress trade-off.
    """
    grid = jnp.asarray(param_grid, avail0.dtype)
    K, R = grid.shape[0], n_replicas
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    res = _run_rows(
        jnp.broadcast_to(avail0, (K * R,) + avail0.shape),
        _tile_rows(rt, K), _tile_rows(arr, K), _tile_rows(root_anchor, K),
        workload, topo, tick, max_ticks, segment_ticks,
        policy="cost-aware", congestion=congestion, realtime_scoring=False,
        score_params=jnp.repeat(grid, R, axis=0), forms=forms,
        tick_order=tick_order,
    )
    return _reshape_rows(res, K, R)


# -- capacity planning --------------------------------------------------------


def capacity_grid(avail0, host_counts) -> jax.Array:
    """[K, H, 4] candidate capacity matrices: candidate k keeps the first
    ``host_counts[k]`` hosts and masks the rest with the −1 down-host
    sentinel (no fit can select them; they never accrue busy time).

    Keeping a prefix preserves the generator's round-robin zone balance
    (``infra/gen.py``), so every candidate is a smaller but equally
    balanced cluster.
    """
    H = avail0.shape[0]
    counts = jnp.asarray(host_counts, jnp.int32)
    keep = jnp.arange(H)[None, :] < counts[:, None]  # [K, H]
    return jnp.where(
        keep[:, :, None], avail0[None, :, :], jnp.asarray(-1.0, avail0.dtype)
    )


def capacity_sweep(
    key,
    avail_grid,  # [K, H, 4] candidate capacity matrices (capacity_grid)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    n_replicas: int = 32,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    segment_ticks: Optional[int] = None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """On-device capacity planning: how does the workload behave on K
    candidate cluster sizes?  Every candidate × replica pair rolls out in
    ONE device program ([K, R] leading axes) with shared Monte-Carlo
    draws, so candidate comparisons are paired — "how many hosts do I
    need?" costs one dispatch where the reference needs a full OS-process
    experiment per cluster size (``alibaba/sim.py:168-196`` regenerates
    the cluster and re-forks per configuration).

    With ``n_faults > 0`` each replica draws an independent random
    host-crash schedule (shared across candidates — paired scenarios):
    resilience-aware sizing, "how many hosts do I need *given* N crashes".
    Crash hosts are drawn over the LARGEST candidate's host range (the
    union of all candidates — drawing over the full base cluster would
    silently dilute the fault count whenever the base is bigger than
    every candidate); a crash landing on a host a smaller candidate
    masked out is a no-op there, while the same crash hits the larger
    candidates — the SAME physical failure trace applied to each
    provisioning choice.

    Downstream, combine ``instance_hours × hourly_rate + egress_cost``
    for the cost/makespan trade-off (the reference's financial-cost
    analysis, ``alibaba/sim.py:132-165``); candidates with
    ``n_unfinished > 0`` are undersized for the horizon.
    """
    K, R = avail_grid.shape[0], n_replicas
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail_grid.dtype
    )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail_grid.dtype
    ) if policy == "opportunistic" else None
    faults = None
    if n_faults:
        # Hosts alive in ANY candidate — the union of all candidates'
        # ranges.  jax.random.randint accepts a traced bound, so no
        # static host count is needed.
        alive = jnp.any(avail_grid[:, :, 0] >= 0, axis=0)  # [H]
        n_alive = jnp.sum(alive)
        horizon = (
            fault_horizon if fault_horizon is not None else tick * max_ticks
        )
        host_rank, fail_at, recover_at = _fault_schedule(
            jax.random.fold_in(key, 0x0FA17), n_replicas, n_faults,
            n_alive, horizon, mttr, avail_grid.dtype,
        )
        # The draw is a *rank* in [0, n_alive); map it to the actual host
        # index so crashes land on alive hosts for ANY candidate grid.
        # For capacity_grid's prefix-shaped grids this is the identity
        # (bit-stable with the pre-mapping draws); for a caller-supplied
        # non-prefix grid it fixes crashes silently hitting masked hosts
        # and missing alive ones.
        host = jnp.searchsorted(
            jnp.cumsum(alive.astype(jnp.int32)), host_rank + 1
        ).astype(jnp.int32)
        faults = (host, fail_at, recover_at)
    avail_rows = jnp.repeat(avail_grid, R, axis=0)  # [B, H, 4]
    res = _run_rows(
        avail_rows,
        _tile_rows(rt, K), _tile_rows(arr, K), _tile_rows(root_anchor, K),
        workload, topo, tick, max_ticks, segment_ticks,
        policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring,
        faults=(
            tuple(_tile_rows(f, K) for f in faults)
            if faults is not None else None
        ),
        task_u=_tile_rows(task_u, K) if task_u is not None else None,
        totals=avail_rows if faults is not None else None,
        forms=forms, tick_order=tick_order,
    )
    return _reshape_rows(res, K, R)


def workload_sweep(
    key,
    avail0,  # [H, 4]
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    app_counts,  # [K] i32 — candidate k runs the first app_counts[k] apps
    n_replicas: int = 32,
    tick: float = 5.0,
    max_ticks: int = 2048,
    perturb: float = 0.1,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    segment_ticks: Optional[int] = None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """On-device workload-size sweep: how do cost and makespan scale with
    the number of applications?  Candidate k activates the first
    ``app_counts[k]`` apps (later apps' tasks get arrival = ∞ and are
    excluded from the unfinished count); every candidate × replica pair
    rolls out in ONE device program with shared Monte-Carlo draws, so the
    cost-vs-#apps curve (the reference's ``num-apps`` experiment,
    ``alibaba/sim.py:199-230``) comes from one dispatch per policy arm
    instead of one OS process per (arm, count, trace).

    ``workload`` must carry the FULL app set; since DAG edges never cross
    applications, masked tasks can neither gate readiness nor bill
    egress.
    """
    counts = jnp.asarray(app_counts, jnp.int32)
    K, R = counts.shape[0], n_replicas
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail0.dtype
    ) if policy == "opportunistic" else None
    act = workload.app_of[None, :] < counts[:, None]  # [K, T]
    act_rows = jnp.repeat(act, R, axis=0)  # [B, T]
    arr_rows = jnp.where(
        act_rows, _tile_rows(arr, K), jnp.asarray(jnp.inf, avail0.dtype)
    )
    res = _run_rows(
        jnp.broadcast_to(avail0, (K * R,) + avail0.shape),
        _tile_rows(rt, K), arr_rows, _tile_rows(root_anchor, K),
        workload, topo, tick, max_ticks, segment_ticks,
        policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring,
        task_u=_tile_rows(task_u, K) if task_u is not None else None,
        active=act_rows,
        forms=forms, tick_order=tick_order,
    )
    return _reshape_rows(res, K, R)


# -- checkpoint / resume -----------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "tick", "policy", "congestion", "realtime_scoring", "forms",
        "tick_order",
    ),
)
def _segment_step(
    state: RolloutState,
    rt,  # [R, T] perturbed runtimes (constant for the run — computed once)
    arr,  # [R, T] perturbed arrivals
    root_anchor,  # [R, T] i32
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    segment_ticks,  # traced i32 scalar — the final partial segment must
    faults=None,  # optional ([R, F] i32, [R, F], [R, F]) crash schedules
    totals=None,  # [H, 4]
    policy: str = "cost-aware",
    task_u=None,  # [R, T] opportunistic uniforms
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: str = "vector",
    tick_order: str = "fifo",
) -> RolloutState:  # not trigger an XLA recompile of the whole rollout
    """One jitted, vmapped checkpoint segment (at most ``segment_ticks``)."""
    spec, extras = _pack_extras(faults, task_u)

    def seg(s, r, a, ra, *ex):
        f, u, _tot, _sp, _act = _unpack_extras(spec, ex)
        return _rollout_segment(
            s, r, a, ra, workload, topo, tick, segment_ticks,
            faults=f, totals=totals, policy=policy, task_u=u,
            congestion=congestion, realtime_scoring=realtime_scoring,
            forms=forms, tick_order=tick_order,
        )

    return jax.vmap(seg)(state, rt, arr, root_anchor, *extras)


def _fingerprint(
    key, n_replicas, tick, max_ticks, perturb, workload, topo, avail0,
    storage_zones, fault_cfg=(0, None, None), policy="cost-aware",
    congestion=False, realtime_scoring=False, tick_order="fifo",
) -> str:
    """Hash of every input that determines the rollout trajectory —
    including array *contents*, so a checkpoint can never be resumed
    against edited workload data that merely kept its shapes."""
    import hashlib

    # "v2": the tick body's refund select-reduce (round-2 scatter purge)
    # sums in tree order — ULP-different from the old scatter order for
    # multiple same-host refunds — so checkpoints written by the old body
    # must restart, not resume into a mixed-order trajectory.
    base = ("v2", np.asarray(key).tolist(), n_replicas, tick, max_ticks,
            perturb)
    if policy != "cost-aware":
        # Appended only for non-default arms so cost-aware fingerprints
        # within a body version are unchanged by this field's existence.
        base = base + (policy,)
    if fault_cfg[0]:
        # Appended only for fault runs (same compat-within-version rule).
        base = base + (fault_cfg,)
    if congestion:
        # Appended only when the backlog model is on (same compat rule).
        base = base + ("congestion",)
    if realtime_scoring:
        base = base + ("realtime_scoring",)
    if tick_order != "fifo":
        # Batch order changes actual placements, not just ULPs — a fifo
        # checkpoint resuming under lifo would be a mixed-order
        # trajectory (appended only for non-default order, same
        # compat-within-version rule as the fields above).
        base = base + (("tick_order", tick_order),)
    h = hashlib.sha256(repr(base).encode())
    for tree in (workload, topo, (avail0, storage_zones)):
        for arr in jax.tree_util.tree_leaves(tree):
            a = np.ascontiguousarray(np.asarray(arr))
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    return h.hexdigest()[:16]


def rollout_checkpointed(
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    checkpoint_path: Optional[str],
    n_replicas: int = 64,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    segment_ticks: int = 256,
    resume: bool = True,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """:func:`rollout` with mid-flight checkpoint/resume.

    The rollout runs in jitted segments of ``segment_ticks``; after each
    segment the ``[R]``-stacked :class:`RolloutState` (pure arrays) is
    written atomically (tmp + rename) to ``checkpoint_path`` (``.npz``).
    The 256-tick default balances per-segment host round-trips against
    call duration (measured at the canonical 25-app × 256-replica
    scale: 64-tick segments cost +49 % over one monolithic call,
    256-tick +14 %, each call ~1.4 s); callers wanting a finer
    checkpoint cadence or shorter calls on a flaky transport pass a
    smaller ``segment_ticks`` — results are bit-identical at any value.
    If the process dies, rerunning with ``resume=True`` loads the last
    state and continues — the final result is bit-identical to an
    uninterrupted :func:`rollout` with the same arguments, because the
    Monte-Carlo draws are a pure function of ``key`` (regenerated, not
    stored) and segmentation does not change the tick sequence.

    ``checkpoint_path=None`` runs the same segmented schedule without
    touching disk — useful in its own right because each segment is one
    bounded device execution (a monolithic multi-thousand-tick while_loop
    is a minutes-long single execution, which remote-device transports
    may kill).

    A config fingerprint stored alongside the state refuses to resume a
    checkpoint produced by different arguments.  The reference has no
    analog: its runs are one-shot to event exhaustion
    (``alibaba/runner.py:44``), and its process state (generator frames)
    could not be serialized anyway.
    """
    import os

    workload.check_group_demands()
    forms = _resolve_forms(forms)

    fp = _fingerprint(
        key, n_replicas, tick, max_ticks, perturb, workload, topo, avail0,
        storage_zones, fault_cfg=(n_faults, fault_horizon, mttr),
        policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring, tick_order=tick_order,
    )

    ticks_done = 0
    state = None
    if checkpoint_path and resume and os.path.exists(checkpoint_path):
        with np.load(checkpoint_path, allow_pickle=False) as ckpt:
            fields = set(RolloutState._fields)
            if str(ckpt["fingerprint"]) == fp and fields <= set(ckpt.files):
                # A checkpoint missing state fields (written by an older
                # layout) is ignored rather than resumed partial — resume
                # must be bit-identical or not happen at all.
                state = RolloutState(
                    **{f: jnp.asarray(ckpt[f]) for f in RolloutState._fields}
                )
                ticks_done = int(ckpt["ticks_done"])
    if state is None:
        Z = topo.cost.shape[0]
        state = jax.vmap(
            lambda _: _init_state(avail0, workload.n_tasks, Z)
        )(jnp.arange(n_replicas))

    # Monte-Carlo draws are a pure function of ``key`` and constant for the
    # whole run: generated once here (and regenerated once on resume), not
    # per segment.
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    faults = None
    if n_faults:
        faults = _make_fault_schedule(
            key, n_replicas, n_faults, avail0, tick, max_ticks,
            fault_horizon, mttr,
        )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail0.dtype
    ) if policy == "opportunistic" else None

    while ticks_done < max_ticks and bool(jnp.any(state.stage != _DONE)):
        seg = min(segment_ticks, max_ticks - ticks_done)
        state = _segment_step(
            state,
            rt,
            arr,
            root_anchor,
            workload,
            topo,
            tick=tick,
            segment_ticks=jnp.asarray(seg, jnp.int32),
            faults=faults,
            totals=avail0,
            policy=policy,
            task_u=task_u,
            congestion=congestion,
            realtime_scoring=realtime_scoring,
            forms=forms,
            tick_order=tick_order,
        )
        jax.block_until_ready(state)
        ticks_done += seg
        if checkpoint_path:
            tmp = checkpoint_path + ".tmp.npz"  # np.savez keeps an .npz suffix
            np.savez(
                tmp,
                fingerprint=fp,
                ticks_done=ticks_done,
                **{f: np.asarray(v) for f, v in zip(RolloutState._fields, state)},
            )
            os.replace(tmp, checkpoint_path)

    return _finalize_batch(state, workload, topo)


def rollout_chunked(
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    checkpoint_path: Optional[str],
    replica_chunk: int,
    n_replicas: int = 64,
    segment_ticks: int = 256,
    resume: bool = True,
    **kw,
) -> RolloutResult:
    """Ensemble rollout in replica chunks of ``replica_chunk``.

    Why chunk: bound the per-call working set and duration.  When the
    tick body still carried vmapped scatters, R=1024 went superlinear
    (scalar-memory scatter operands spilled; chunking at 512 measured
    1.65×).  After the segment-op purge removed those scatters the
    R-axis scales near-linearly (R=1024 ≈ 4.5× the R=256 wall) and
    chunking is ~neutral at bench scale (2,520 vs 2,475 rollouts/s) —
    it remains the pressure valve for replica counts beyond what HBM
    comfortably holds, and keeps each device call short on remote
    transports that kill long executions (RESULTS.md, round-2 scaling
    tables before/after the purge).

    Execution shape per chunk: WITHOUT a ``checkpoint_path``, each chunk
    is one monolithic :func:`rollout` call (routing chunks through the
    segmented executor pays per-segment host round-trips).  WITH a
    ``checkpoint_path``, each chunk runs segmented via
    :func:`rollout_checkpointed`, checkpointing (and resuming) at
    ``<root>.c<c><ext>``; finished chunks resume straight to finalize.

    Sample-set semantics: chunk 0 uses ``key`` verbatim — it is
    bit-identical to ``rollout(key, n_replicas=replica_chunk)``, so the
    replica-0 ⇔ DES anchor pairing (``_perturbations``) survives
    chunking.  Chunk ``c > 0`` draws from ``fold_in(key, c)``.  The
    combined set is therefore a *different* (equally i.i.d.) Monte-Carlo
    sample than one monolithic ``n_replicas`` draw — threefry counters
    pair by array halves, so a bitwise-prefix chunking cannot exist —
    which is why the CLI keeps chunking opt-in (``--replica-chunk``):
    existing seeded results stay bit-stable unless the caller asks.

    Deterministic: same ``key``/config/chunking → same results.
    ``replica_chunk <= 0`` (or ``>= n_replicas``) delegates to the
    unchunked segmented path unchanged.
    """
    import os

    if replica_chunk <= 0 or n_replicas <= replica_chunk:
        return rollout_checkpointed(
            key, avail0, workload, topo, storage_zones, checkpoint_path,
            n_replicas=n_replicas, segment_ticks=segment_ticks,
            resume=resume, **kw,
        )
    root, ext = os.path.splitext(checkpoint_path) if checkpoint_path else ("", "")
    parts = []
    done = 0
    while done < n_replicas:
        c = len(parts)
        n = min(replica_chunk, n_replicas - done)
        ck = key if c == 0 else jax.random.fold_in(key, c)
        if checkpoint_path:
            parts.append(
                rollout_checkpointed(
                    ck, avail0, workload, topo, storage_zones,
                    f"{root}.c{c}{ext}", n_replicas=n,
                    segment_ticks=segment_ticks, resume=resume, **kw,
                )
            )
        else:
            parts.append(
                rollout(
                    ck, avail0, workload, topo, storage_zones,
                    n_replicas=n, **kw,
                )
            )
        done += n
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *parts)
