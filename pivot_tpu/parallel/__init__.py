"""Multi-device scaling: meshes, sharded ensemble scheduling, rollouts."""

from pivot_tpu.parallel.mesh import build_mesh  # noqa: F401
