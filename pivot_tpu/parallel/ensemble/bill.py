"""Result finalization and the DES-faithful egress bill."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pivot_tpu.ops.kernels import DeviceTopology
from pivot_tpu.parallel.ensemble.state import (
    _DONE,
    EnsembleWorkload,
    RolloutResult,
    RolloutState,
)

def _sampling_table(workload: EnsembleWorkload):
    """(inst, samp): per-group instance counts and the DES pull-sample
    table — each consumer instance of group c pulls ``samp[c, g] =
    max(round(inst[g] / inst[c]), 1)`` predecessor instances of group g
    (``resources/__init__.py:263-267``; ``jnp.round`` matches Python's
    banker's rounding).  The ONE definition shared by the congestion
    timing model and the egress bill, so the two cannot desynchronize."""
    inst = jnp.maximum(jnp.sum(workload.group_onehot, axis=0), 1.0)  # [G]
    samp = jnp.maximum(jnp.round(inst[None, :] / inst[:, None]), 1.0)
    return inst, samp


def _sampled_egress(workload, topo, zcp, pz, placed):
    """DES-faithful egress estimate in three small matmuls.

    The DES bills one transfer per *sampled* pull (see
    :func:`_sampling_table`) — totalling ≈ max(n_p, n_c) transfers per
    group edge, NOT the n_p × n_c of naive all-pairs counting (which
    would inflate fan-out egress ~16× on the Alibaba traces).  Expected
    cost per pull = Σ_s P(source in zone s) × cost[s, consumer zone],
    with the source distributed like the producer's placed instances
    (zcp row, normalized).
    """
    n_placed_g = jnp.sum(zcp, axis=1, keepdims=True)  # [G, 1]
    src_frac = jnp.where(n_placed_g > 0, zcp / jnp.maximum(n_placed_g, 1.0), 0.0)
    _, samp = _sampling_table(workload)
    # d[g, i]: expected $/8000·MB⁻¹-weighted cost of one pull from group g
    # into task i's zone, scaled by g's output size.
    d = (src_frac * workload.out_group[:, None]) @ topo.cost[:, pz]  # [G, T]
    pulls = (workload.pred_group * samp)[workload.group_of]  # [T, G]
    return jnp.sum(placed * jnp.sum(pulls * d.T, axis=1)) / 8000.0


def _finalize(
    state: RolloutState,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    active=None,  # optional [T] bool — inactive tasks don't count unfinished
) -> RolloutResult:
    H = state.avail.shape[0]
    dtype = state.avail.dtype
    finish, place, stage = state.finish, state.place, state.stage
    done = stage == _DONE
    makespan = jnp.max(jnp.where(done, finish, 0.0))
    # Egress: one bill per DES-sampled pull (see _sampled_egress), counting
    # only pulls whose consumer was actually placed (an unplaced consumer
    # at the horizon must not be billed as if on host 0).
    pz = topo.host_zone[jnp.clip(place, 0, H - 1)]
    placed = (place >= 0).astype(dtype)
    Z = topo.cost.shape[0]
    zcp = workload.group_onehot.T @ (
        jax.nn.one_hot(pz, Z, dtype=dtype) * placed[:, None]
    )  # [G, Z] placed-instance counts
    egress = _sampled_egress(workload, topo, zcp, pz, placed)
    return RolloutResult(
        makespan=makespan,
        egress_cost=egress,
        finish_time=finish,
        placement=place,
        n_unfinished=jnp.sum(~done if active is None else (~done & active)),
        instance_hours=state.busy / 3600.0,
    )

@jax.jit
def _finalize_batch(
    states: RolloutState,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    active=None,  # optional [B, T] bool, one mask per state row
) -> RolloutResult:
    """The ONE finalize program shared by every execution path — plain,
    sharded, checkpointed rollouts and the row-based sweeps all derive
    result metrics from final states through this exact compiled
    computation, so segmented runs are bit-identical to monolithic ones
    (XLA reduction order would otherwise differ between a fused
    rollout+finalize program and a standalone finalize)."""
    if active is None:
        return jax.vmap(lambda s: _finalize(s, workload, topo))(states)
    return jax.vmap(
        lambda s, a: _finalize(s, workload, topo, active=a)
    )(states, active)

