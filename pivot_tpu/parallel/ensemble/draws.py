"""Monte-Carlo draws and the packed-extras calling convention.

Per-replica perturbations, fault schedules, opportunistic uniforms, and
the keyed root-anchor draws shared bit-for-bit with the DES policies
(``pivot_tpu.sched.rand``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from pivot_tpu.parallel.ensemble.state import EnsembleWorkload

def _fault_schedule(key, n_replicas, n_faults, n_hosts, horizon, mttr, dtype):
    """Per-replica random crash schedules, mirroring
    ``FaultInjector.random_host_failures``: ``n_faults`` crashes at uniform
    times in ``[0, horizon)`` on uniformly drawn hosts, each recovering
    after an Exp(mean=``mttr``) outage (never, if ``mttr`` is None)."""
    k_t, k_h, k_d = jax.random.split(key, 3)
    fail_at = jax.random.uniform(
        k_t, (n_replicas, n_faults), minval=0.0, maxval=horizon, dtype=dtype
    )
    host = jax.random.randint(k_h, (n_replicas, n_faults), 0, n_hosts).astype(
        jnp.int32
    )
    if mttr is None:
        recover_at = jnp.full((n_replicas, n_faults), jnp.inf, dtype=dtype)
    else:
        outage = jax.random.exponential(k_d, (n_replicas, n_faults), dtype=dtype)
        recover_at = fail_at + mttr * outage
    return host, fail_at, recover_at


def _make_fault_schedule(
    key, n_replicas, n_faults, avail0, tick, max_ticks, fault_horizon, mttr
):
    """The one place fault draws derive from the rollout key: fold_in (not
    split) so the fault-free path's draws — and thus every existing result
    and checkpoint — are unchanged; shared by :func:`rollout` and
    :func:`rollout_checkpointed` so segmented runs stay bit-identical."""
    horizon = fault_horizon if fault_horizon is not None else tick * max_ticks
    return _fault_schedule(
        jax.random.fold_in(key, 0x0FA17), n_replicas, n_faults,
        avail0.shape[0], horizon, mttr, avail0.dtype,
    )



def _pack_extras(faults=None, task_u=None, totals=None, score_params=None,
                 active=None, risk_coeff=None):
    """Flatten the optional per-replica/per-row axes for a vmap body.

    Returns ``(spec, extras_list)``; ``spec`` is the static presence
    tuple consumed by :func:`_unpack_extras` — together they are the ONE
    place the positional bookkeeping lives, shared by :func:`rollout`,
    :func:`_segment_step`, and the row-based sweep runner so the
    execution paths cannot drift.  ``spec`` is hashable, so it can cross
    a jit boundary as a static argument.

    ``risk_coeff`` (round 16, the policy-search fitness environment) is
    the per-row scalar ``risk_weight × rework_cost`` — the eviction-risk
    term's weight; the [P, H] hazard rows it scales are replica-SHARED
    (one market per environment) and ride the tick body's closed-over
    ``hazard`` operand instead of this per-row channel.
    """
    spec = (
        faults is not None, task_u is not None, totals is not None,
        score_params is not None, active is not None,
        risk_coeff is not None,
    )
    extras = []
    if faults is not None:
        extras.extend(faults)
    for x in (task_u, totals, score_params, active, risk_coeff):
        if x is not None:
            extras.append(x)
    return spec, extras


def _unpack_extras(spec, ex):
    """Rebuild ``(faults, task_u, totals, score_params, active,
    risk_coeff)`` from a flat extras tuple, per the presence ``spec``
    from :func:`_pack_extras`."""
    has_f, has_u, has_tot, has_sp, has_act, has_rc = spec
    i = 0
    f = u = tot = sp = act = rc = None
    if has_f:
        f = (ex[0], ex[1], ex[2])
        i = 3
    if has_u:
        u = ex[i]
        i += 1
    if has_tot:
        tot = ex[i]
        i += 1
    if has_sp:
        sp = ex[i]
        i += 1
    if has_act:
        act = ex[i]
        i += 1
    if has_rc:
        rc = ex[i]
        i += 1
    return f, u, tot, sp, act, rc


def _opportunistic_uniforms(key, n_replicas, n_tasks, dtype):
    """Base uniform per (replica, task) for the opportunistic arm; the
    placement step rotates it by the golden ratio per tick (Weyl
    sequence), approximating the DES's independent per-tick redraws
    (``tick_uniforms``, policies.py:105) without materializing a
    [ticks, T] draw tensor.  fold_in keeps the other arms' streams
    untouched."""
    return jax.random.uniform(
        jax.random.fold_in(key, 0x09901), (n_replicas, n_tasks), dtype=dtype
    )


def _seed_bits(key):
    """uint32 seed word of a PRNG key: for ``jax.random.PRNGKey(s)`` this
    is exactly ``s`` (key data ``[0, s]``), which is what pairs the
    estimator's keyed root-anchor draws with a DES run seeded ``s``."""
    try:
        data = jax.random.key_data(key)
    except TypeError:  # already a raw uint32 key array
        data = key
    return data.reshape(-1)[-1].astype(jnp.uint32)


def _keyed_storage_index_jax(seed_bits, app_ids, n_storage, salt):
    """JAX twin of :func:`pivot_tpu.sched.rand.keyed_storage_index` —
    identical uint32 math (tested bit-equal), so estimator replica 0
    anchors exactly match the DES policies' keyed draws."""
    A = jnp.uint32(0x9E3779B9)
    B = jnp.uint32(0x85EBCA6B)
    C = jnp.uint32(0xC2B2AE35)
    x = seed_bits.astype(jnp.uint32) * A + salt.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * B + app_ids.astype(jnp.uint32) * A
    x = x ^ (x >> 13)
    x = x * C
    x = x ^ (x >> 16)
    return (x % jnp.uint32(n_storage)).astype(jnp.int32)


def _perturbations(key, workload, storage_zones, n_replicas, perturb, dtype):
    """Deterministic per-replica Monte-Carlo draws — regenerated (not
    stored) on checkpoint resume, since they are a pure function of key."""
    T = workload.n_tasks
    # Still split in 3: threefry subkeys depend on the total split count
    # (counters pair by halves), so dropping to split(key, 2) would
    # silently change every rt/arr draw — breaking bit-stability with
    # existing results and regenerated-on-resume checkpoints.  The third
    # key (the retired jax.random anchor draw) is simply unused.
    k_rt, k_arr, _k_retired = jax.random.split(key, 3)
    rt = workload.runtime[None, :] * jax.random.uniform(
        k_rt, (n_replicas, T), minval=1 - perturb, maxval=1 + perturb,
        dtype=dtype,
    )
    arr = workload.arrival[None, :] * jax.random.uniform(
        k_arr, (n_replicas, T), minval=1 - perturb, maxval=1 + perturb,
        dtype=dtype,
    )
    # Root anchors are shared PER APPLICATION, mirroring the DES cost-aware
    # policy: all root task groups of one app bucket under the app and draw
    # ONE storage anchor (``sched/policies.py`` group_tasks; ref
    # ``scheduler/cost_aware.py:38-39``).  The draw is the entity-keyed
    # function shared with the DES (replica salt r; r = 0 IS the DES's
    # draw for a scheduler seeded with this key's seed word), so nominal
    # calibration runs see identical anchors in both engines.
    salts = jnp.arange(n_replicas, dtype=jnp.uint32)
    anchor_idx = _keyed_storage_index_jax(
        _seed_bits(key),
        workload.app_of[None, :],
        storage_zones.shape[0],
        salts[:, None],
    )
    root_anchor = storage_zones[anchor_idx].astype(jnp.int32)
    return rt, arr, root_anchor

