"""Ensemble state types: workload encoding, rollout state/result, forms.

Split out of the round-3 monolithic ``ensemble.py`` (VERDICT r03 item 8);
see the package ``__init__`` for the module map.  Nothing here changed in
the split — the forms-parity and checkpoint suites pin behavior.
"""

from __future__ import annotations

import weakref
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

# check_group_demands verdict cache: (id(demands), id(group_of)) →
# (weakref(demands), weakref(group_of)).  The invariant being cached is a
# property of the PAIR — a ``_replace(group_of=...)`` reusing an
# already-checked demands array must re-validate — and the weakrefs guard
# against id reuse after garbage collection: an entry only counts if both
# refs still point at the SAME live arrays.
_checked_demands: dict = {}


class EnsembleWorkload(NamedTuple):
    """Dense, instance-level workload description (static across replicas).

    Built from an :class:`pivot_tpu.workload.Application` (or several) via
    :func:`EnsembleWorkload.from_applications`; every task-group instance
    becomes one row.

    Alongside the instance-level ``pred`` matrix (used for the [T]-vector
    readiness matvec), the workload carries its **group structure** —
    instances of a group share output size and predecessor groups, so
    transfer delays, anchor votes, and egress cost all reduce *exactly*
    to [G, Z]-sized tensors via matmuls.  Without this, those quantities
    need per-replica [T, T] products: at T≈3.6k and 1024 replicas that is
    a 55 GB allocation — 3× the chip's HBM.
    """

    demands: jax.Array  # [T, 4]
    runtime: jax.Array  # [T]
    output_size: jax.Array  # [T]
    arrival: jax.Array  # [T] submission time of the owning app
    pred: jax.Array  # [T, T] f32 — pred[i, p] = 1 iff p precedes i
    group_of: jax.Array  # [T] i32 — owning group index per instance
    group_onehot: jax.Array  # [T, G] f32 — one_hot(group_of)
    pred_group: jax.Array  # [G, G] f32 — group-level adjacency
    out_group: jax.Array  # [G] per-group output size (MB)
    app_of: jax.Array  # [T] i32 — owning application index per instance

    @property
    def n_tasks(self) -> int:
        return self.runtime.shape[0]

    @property
    def n_groups(self) -> int:
        return self.out_group.shape[0]

    def check_group_demands(self) -> None:
        """Raise if any group's instances disagree on their demand vector.

        The rollout's group-level fit collapse and in-loop demand
        re-derivation rely on this invariant; ``from_applications``
        guarantees it, but ``EnsembleWorkload`` is a plain NamedTuple, so
        a ``_replace(demands=...)`` with per-instance jitter would
        silently corrupt placements.  Called by the public rollout
        entries on concrete (non-traced) inputs.

        The [T, 4] device fetch costs a full link round-trip on a remote
        chip (~70–80 ms on this deployment's tunnel — measured as a
        −44 % bench-rollout regression when checked per call), so the
        verdict is cached per live demands array: repeated rollouts over
        one workload pay it once.
        """
        if isinstance(self.demands, jax.core.Tracer):
            return  # inside jit: the constructor invariant is the contract
        key = (id(self.demands), id(self.group_of))
        refs = _checked_demands.get(key)
        if (
            refs is not None
            and refs[0]() is self.demands
            and refs[1]() is self.group_of
        ):
            return
        dem = np.asarray(self.demands)
        go = np.asarray(self.group_of)
        table = np.zeros((self.n_groups, dem.shape[1]), dem.dtype)
        table[go] = dem
        if not np.array_equal(table[go], dem):
            bad = np.nonzero(np.any(table[go] != dem, axis=1))[0]
            raise ValueError(
                "EnsembleWorkload demands vary within a group (first "
                f"offending task rows: {bad[:5].tolist()}); the rollout's "
                "group-level fit test requires group-constant demands — "
                "build workloads via EnsembleWorkload.from_applications"
            )
        if len(_checked_demands) > 256:  # prune dead refs, bound growth
            dead = [
                k
                for k, (rd, rg) in _checked_demands.items()
                if rd() is None or rg() is None
            ]
            for k in dead:
                del _checked_demands[k]
        _checked_demands[key] = (
            weakref.ref(self.demands),
            weakref.ref(self.group_of),
        )

    @classmethod
    def from_applications(cls, apps, arrivals=None, dtype=jnp.float32):
        """Flatten applications to instance level.

        Every instance of a group depends on every instance of each
        predecessor group (the ensemble estimator's conservative stand-in
        for the DES's sampled 1/n-instance pulls,
        ``resources/__init__.py:263-267``).
        """
        demands, runtime, output, arrival = [], [], [], []
        group_of, out_group, app_of = [], [], []
        offset = 0
        gi = 0
        edges = []
        group_edges = []
        for ai, app in enumerate(apps):
            at = float(arrivals[ai]) if arrivals is not None else 0.0
            index = {}
            for g in app.groups:
                index[g.id] = (offset, g.instances, gi)
                out_group.append(g.output_size)
                for _ in range(g.instances):
                    demands.append([g.cpus, g.mem, g.disk, g.gpus])
                    runtime.append(g.runtime)
                    output.append(g.output_size)
                    arrival.append(at)
                    group_of.append(gi)
                    app_of.append(ai)
                offset += g.instances
                gi += 1
            for g in app.groups:
                gs, gn, gg = index[g.id]
                for dep in g.dependencies:
                    ps, pn, pg = index[dep]
                    edges.append(((gs, gn), (ps, pn)))
                    group_edges.append((gg, pg))
        T, G = offset, gi
        pred = np.zeros((T, T), dtype=np.float32)
        for (gs, gn), (ps, pn) in edges:
            pred[gs : gs + gn, ps : ps + pn] = 1.0
        pred_group = np.zeros((G, G), dtype=np.float32)
        for gg, pg in group_edges:
            pred_group[gg, pg] = 1.0
        group_of_arr = np.asarray(group_of, dtype=np.int32)
        group_onehot = np.zeros((T, G), dtype=np.float32)
        group_onehot[np.arange(T), group_of_arr] = 1.0
        return cls(
            demands=jnp.asarray(np.array(demands), dtype=dtype),
            runtime=jnp.asarray(np.array(runtime), dtype=dtype),
            output_size=jnp.asarray(np.array(output), dtype=dtype),
            arrival=jnp.asarray(np.array(arrival), dtype=dtype),
            pred=jnp.asarray(pred, dtype=dtype),
            group_of=jnp.asarray(group_of_arr),
            group_onehot=jnp.asarray(group_onehot, dtype=dtype),
            pred_group=jnp.asarray(pred_group, dtype=dtype),
            out_group=jnp.asarray(np.array(out_group), dtype=dtype),
            app_of=jnp.asarray(np.asarray(app_of, dtype=np.int32)),
        )


class RolloutResult(NamedTuple):
    makespan: jax.Array  # [R]
    egress_cost: jax.Array  # [R]
    finish_time: jax.Array  # [R, T]
    placement: jax.Array  # [R, T] host index
    n_unfinished: jax.Array  # [R] tasks still pending at the horizon
    instance_hours: jax.Array  # [R] busy host-hours (tick-resolution)


class RolloutState(NamedTuple):
    """The full mutable state of one replica's rollout — pure arrays, which
    is what makes mid-flight checkpoint/resume trivial (something the
    reference's generator-based processes could never serialize)."""

    t: jax.Array  # scalar sim time
    stage: jax.Array  # [T] i32
    finish: jax.Array  # [T]
    place: jax.Array  # [T] i32
    avail: jax.Array  # [H, 4]
    busy: jax.Array  # scalar busy host-seconds accumulator
    q: jax.Array  # [Z, H] queued MB per (src zone → dst host) pipe
    qpos: jax.Array  # [T] i32 last-batch position of a still-waiting task
    # (−1 otherwise) — the wait-queue order carry for tick_order="lifo"
    # (the DES re-drains its wait dict in reverse insertion order every
    # tick; see _rollout_segment).  Dead weight under "fifo".


# Task stages.
_PENDING, _RUNNING, _DONE = 0, 1, 2


def _resolve_forms(forms: Optional[str]) -> str:
    """Backend default for the tick-body op forms (see
    :func:`_rollout_segment`): index/segment ops on the CPU backend,
    one-hot vector forms on accelerators.  Resolved at trace time by the
    public entries; pass ``forms`` explicitly to pin a form (the parity
    suite runs both on one backend)."""
    if forms is not None:
        return forms
    return "indexed" if jax.default_backend() == "cpu" else "vector"


def _init_state(avail0, T, Z, congestion=False) -> RolloutState:
    dtype = avail0.dtype
    H = avail0.shape[0]
    # Backlog-pipe state rows are source ZONES for the default model and
    # source HOSTS for the host-pair refinement rung
    # (``congestion="pairs"`` — see tick.py); columns are always
    # destination hosts.
    src_rows = H if congestion == "pairs" else Z
    return RolloutState(
        t=jnp.asarray(0.0, dtype),
        stage=jnp.full((T,), _PENDING, dtype=jnp.int32),
        finish=jnp.full((T,), jnp.inf, dtype=dtype),
        place=jnp.full((T,), -1, dtype=jnp.int32),
        avail=avail0,
        busy=jnp.asarray(0.0, dtype),
        q=jnp.zeros((src_rows, H), dtype=dtype),
        qpos=jnp.full((T,), -1, dtype=jnp.int32),
    )


