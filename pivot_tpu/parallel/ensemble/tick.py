"""The tick body: one jitted segment of the ensemble rollout.

``_rollout_segment`` is the whole estimator — readiness, batch ordering,
anchor voting, placement, transfer/congestion timing, busy integral — as
one ``lax.while_loop`` over ticks.  See the package ``__init__`` for the
execution model and the vector/indexed forms contract.

This is the original device-resident tick loop — the estimator-fidelity
ancestor of the DES-exact fused span driver (``ops/tickloop.py``, round
8): both keep the availability carry and meters on-device across ticks
and return to host only at genuine decision points.  The body is a
registered hot path of ``tools/hotpath_lint.py`` — no host
synchronization (fetches, ``.item()``, scalar coercion of tracers) may
appear inside it; the lint runs in tier 1 (``tests/test_meta.py``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pivot_tpu.ops.kernels import DeviceTopology, cost_aware_kernel
from pivot_tpu.parallel.ensemble.bill import _sampling_table
from pivot_tpu.parallel.ensemble.state import (
    _DONE,
    _PENDING,
    _RUNNING,
    EnsembleWorkload,
    RolloutState,
)

def _rollout_segment(
    state: RolloutState,
    runtime,  # [T] perturbed
    arrival,  # [T] perturbed
    root_anchor,  # [T] i32 random storage zone per task (used for roots)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    n_ticks: int,
    faults=None,  # optional ([F] i32 host, [F] fail_at, [F] recover_at)
    totals=None,  # [H, 4] full capacity (fault recovery resets to this)
    score_params=None,  # optional [3] exponents (w_cost, w_bw, w_norm)
    policy: str = "cost-aware",  # | first-fit | best-fit | opportunistic
    task_u=None,  # [T] uniforms (opportunistic draws, one per task)
    congestion: bool = False,
    realtime_scoring: bool = False,
    active=None,  # optional [T] bool: early-exit ignores inactive tasks
    forms: str = "vector",  # | "indexed" — tick-body op forms, see below
    tick_order: str = "fifo",  # | "lifo" — within-tick batch order, see below
    risk_coeff=None,  # optional scalar: risk_weight × rework_cost
    hazard=None,  # optional ([P] segment starts, [P, H] per-host hazards)
) -> RolloutState:
    """Advance one replica's rollout by at most ``n_ticks`` scheduler ticks
    (stops early once every task is done).

    ``forms`` selects between two implementations of the tick-body's
    reduction/selection ops — same math, backend-matched lowering
    (VERDICT r02 item 3):

      * ``"vector"`` (the TPU form): one-hot select-reduces, membership-
        mask masked reductions, and HIGHEST-precision one-hot matmuls.
        Under vmap these stay on the VPU/MXU; the index-based forms they
        replace lower to batched scatter/gathers whose per-replica index
        vectors land in TPU scalar memory and serialize on the scalar
        core (~1 ms/tick each — the round-2 "scalar-core lesson",
        docs/ARCHITECTURE.md).
      * ``"indexed"`` (the CPU form): plain ``segment_sum``/``segment_max``
        /``segment_min`` and gather/scatter indexing.  On CPU these are
        O(T) loops, where the vector forms are O(T·H)/O(T·G) dense
        sweeps — measured 5× end-to-end on the bench rollout metric
        (round-2's TPU-first rewrite regressed the CPU fallback 47 → 9
        rollouts/s; this restores the indexed forms there).

    Public entries resolve ``forms=None`` to the backend default
    (``indexed`` on cpu, ``vector`` elsewhere).  The two forms are held
    bit-identical on every rollout output by
    ``tests/test_ensemble.py::test_tick_body_forms_bit_identical``.

    With ``faults``, each tick applies the crash/recovery schedule at tick
    resolution, mirroring the DES fault semantics (``infra.faults`` +
    ``FastExecutor.abort_host``): a crash in the window aborts the host's
    running tasks back to PENDING with no capacity refund (they re-enter
    the placement pass like the DES retry loop), a down host's rows carry
    the −1 sentinel so no fit can select it, and recovery restores full
    capacity.  Completions in the same tick window as the crash retire
    first — the tick-resolution analog of the DES completion-wins tie.

    With ``congestion``, transfer delays account for link contention via
    the per-replica ``state.q`` backlog tensor (see the placement step for
    the exact pipe model); without it ``q`` is carried untouched, so the
    flag cannot perturb the default path.

    With ``realtime_scoring`` (requires ``congestion``), the cost-aware
    score's inbound-bandwidth term is discounted by the tick-start pipe
    backlog — ``bw_in / (queued_mb + 1)``, the estimator analog of the
    DES ``realtime_bw`` arm (``Route.realtime_bw``, ref
    ``resources/network.py:70-73``): placement actively steers AROUND
    congested links instead of merely paying for them.

    With ``risk_coeff`` + ``hazard`` (round 16, the policy-search
    fitness environment), placement prices eviction risk exactly like
    the DES backends price ``policies.resolve_risk``'s vector: each
    tick's per-host penalty is ``risk_coeff × hazard_row(t)``, where
    ``hazard = (times [P], rows [P, H])`` is the market's
    piecewise-constant per-host hazard trace (replica-shared — one
    market per environment; ``risk_coeff = risk_weight × rework_cost``
    is per-row, so a candidate population sweeps it).  The shared
    cross-backend consumption rules apply unchanged: score-based
    selections (cost-aware, best-fit) add the penalty, first-fit's
    index order becomes the lexicographic (risk, index) order, and the
    opportunistic draw restricts to the minimum-risk tier of fitting
    hosts (same uniform, narrower support).  Both args None (the
    default) keeps today's compiled program untouched.
    """
    if congestion not in (False, True, "pairs"):
        raise ValueError(
            f"congestion must be False, True, or 'pairs', got {congestion!r}"
        )
    # Host-pair pipe resolution (the congestion-ladder rung RESULTS.md
    # round 3 evaluated on paper): one FIFO pipe per (src HOST → dst
    # host) with the zone-pair bandwidth — matching the DES's per-route
    # service, where each host-pair route drains independently at its
    # own bandwidth, instead of all same-zone sources sharing one
    # aggregate.  ~H/Z more pipe state per replica; a fidelity
    # diagnostic, not the throughput path.
    pairs = congestion == "pairs"
    if realtime_scoring and not congestion:
        raise ValueError("realtime_scoring needs congestion=True (the "
                         "backlog state is the bandwidth signal)")
    if realtime_scoring and pairs:
        raise ValueError("realtime_scoring reads zone-resolution backlog "
                         "(the score tables are [Z, H]); use "
                         "congestion=True with it")
    if realtime_scoring and policy != "cost-aware":
        raise ValueError("realtime_scoring applies to the cost-aware arm "
                         "only — no other policy scores on bandwidth")
    if realtime_scoring and score_params is not None:
        raise ValueError("realtime_scoring and parameterized score "
                         "exponents are mutually exclusive")
    if forms not in ("vector", "indexed"):
        raise ValueError(f"forms must be 'vector' or 'indexed', got {forms!r}")
    if (hazard is None) != (risk_coeff is None):
        raise ValueError(
            "the risk term needs BOTH hazard (the [P]/[P, H] market "
            "trace) and risk_coeff (risk_weight × rework_cost) — pass "
            "neither to keep the risk-free program"
        )
    if tick_order not in ("fifo", "lifo"):
        raise ValueError(
            f"tick_order must be 'fifo' or 'lifo', got {tick_order!r}"
        )
    vector = forms == "vector"
    # Within-tick batch order (round-3 bias diagnosis, VERDICT r02
    # item 4): the reference drains its ready/wait dicts with
    # ``popitem()`` — LIFO (``scheduler/__init__.py:93-94,187``) — so the
    # DES's within-tick batch runs DESCENDING task index, while the
    # estimator historically placed ascending ("fifo").  On uniform
    # clusters every best-fit score ties, so the order permutes which
    # app's instances land on which host from the very first wave —
    # measured as the packing arms' consistent-sign egress bias
    # (best-fit +54% mean across clusters).  "lifo" mirrors the DES:
    # fresh cohorts descending, first-fit norm ties descending, and
    # cost-aware buckets first-seen over the descending batch.
    lifo = tick_order == "lifo"
    T = workload.n_tasks
    H = state.avail.shape[0]
    Z = topo.cost.shape[0]
    dtype = state.avail.dtype
    has_pred = jnp.sum(workload.pred, axis=1) > 0  # [T]
    if faults is not None:
        fault_host, fail_at, recover_at = faults
        fault_idx = jnp.where(fault_host >= 0, fault_host, H)  # pad → drop

        if vector:

            def _scatter_hosts(hit):  # [F] bool mask -> [H] bool host mask
                # One-hot any-reduce, not ``.at[fault_idx].max``: under
                # vmap the scatter's per-replica index vector lands in
                # scalar memory and serializes on the scalar core (three
                # calls per tick in fault ensembles — see
                # ARCHITECTURE.md, "the scalar-core lesson").  Padded
                # entries (idx == H) hit no host, exactly like the old
                # scatter-then-slice.
                return jnp.any(
                    (fault_idx[:, None] == jnp.arange(H)[None, :])
                    & hit[:, None],
                    axis=0,
                )

        else:

            def _scatter_hosts(hit):  # [F] bool mask -> [H] bool host mask
                # Boolean scatter (exact): misses and padded entries
                # write the sacrificial H row, sliced off.
                idx = jnp.where(hit, fault_idx, H)
                return jnp.zeros((H + 1,), bool).at[idx].set(True)[:H]
    # [Z, H] round-trip score tables (pure topology — hoisted out of ticks).
    cost_rt = topo.cost[:, topo.host_zone] + topo.cost[topo.host_zone, :].T
    bw_rt = topo.bw[:, topo.host_zone] + topo.bw[topo.host_zone, :].T
    # Static within-tick task order (see the placement step).
    if policy in ("first-fit", "cost-aware"):
        dem_norms = jnp.sqrt(jnp.sum(workload.demands**2, axis=1))
        task_order = jnp.argsort(-dem_norms, stable=True)
    else:
        task_order = jnp.arange(T)
    task_rank = jnp.argsort(task_order)  # static inverse permutation
    if congestion:
        # Pipe tables for the backlog model: bandwidth of the (src zone →
        # dst host) aggregate and its reciprocal, plus per-group instance
        # counts (the DES pulls a ~1/n_instances sample of predecessor
        # instances per consumer, ``resources/__init__.py:263-267`` — pull
        # volumes are scaled by the same fraction).
        bw_zh = topo.bw[:, topo.host_zone]  # [Z, H]
        inv_bw_zh = jnp.where(bw_zh > 0, 1.0 / bw_zh, 0.0)
        if pairs:
            # Per-route tables: row s is source HOST s, carrying its
            # zone's bandwidth to each destination (static gather of the
            # zone table's rows — pure topology, hoisted).
            bw_hh = bw_zh[topo.host_zone]  # [H, H]
            inv_bw_hh = inv_bw_zh[topo.host_zone]
        # Static pull-volume table: pull_frac[c, g] is a consumer
        # instance's pulled MB from group g per done g-instance, so this
        # tick's zone-resolved volume is just ``pull_frac @ zc``.
        inst, samp = _sampling_table(workload)
        pull_frac = (
            workload.pred_group * samp * (workload.out_group / inst)[None, :]
        )  # [G, G] consumer × producer
    if score_params is not None:
        # Parameterized scoring for on-device policy autotuning: exponents
        # (1, 1, 1) recover the reference score shape (modulo
        # pow-vs-identity float paths — the unparameterized branch in
        # place_body stays THE bit-exact default program).  The cost/bw
        # pow tables are pure (topology × params) — hoisted like
        # cost_rt/bw_rt; only norm ** w_norm depends on loop state.
        w_norm = score_params[2]
        cost_pow = cost_rt ** score_params[0]
        bw_pow = bw_rt ** score_params[1]
    inf = jnp.asarray(jnp.inf, dtype)
    G = workload.pred_group.shape[0]
    # Static one-hot expansion tables, hoisted out of the tick loop.
    # They replace per-tick [R, T] gathers (group→task and host→zone
    # expansions), which lower to scalar-memory gathers inside the
    # vmapped while loop — serialized on the scalar core, measured as
    # the dominant per-tick cost.  Select-reduces over them are exact:
    # each row has exactly one hit, and adding zeros is IEEE-exact.
    g_oh = workload.group_of[:, None] == jnp.arange(G)[None, :]  # [T, G]
    zone_onehot = (
        topo.host_zone[:, None] == jnp.arange(Z)[None, :]
    ).astype(dtype)  # [H, Z] — integer counts matmul (bf16-exact < 256)
    # [G, 4] per-group demand table: instances of a group share one
    # demand vector by construction (``from_applications`` appends the
    # group row per instance; no other constructor exists), so the
    # per-tick fit test collapses exactly to group level — T/G ≈ 12×
    # less compare-reduce work at the canonical scale, measured as the
    # largest single tick-body op.  Static scatter (shared indices).
    dem_group = jnp.zeros((G, 4), dtype).at[workload.group_of].set(
        workload.demands
    )

    def cond(carry):
        i, state = carry
        pending = state.stage != _DONE
        if active is not None:
            # Masked-out tasks (workload-size sweeps) stay PENDING forever
            # with arrival = inf; they must not keep the loop alive.
            pending = pending & active
        return (i < n_ticks) & jnp.any(pending)

    def body(carry):
        i, (t, stage, finish, place, avail, busy, q, qpos) = carry

        # 1. Retire finished tasks and refund their resources.
        #    Select-reduce over a [T, H] membership mask, NOT a
        #    segment_sum: under vmap the segment form lowers to a
        #    scatter-add whose [R, T] index vector lives in scalar
        #    memory — profiled at ~1 ms/tick serialized on the scalar
        #    core, 28% of the whole rollout (the same class the
        #    placement-loop rewrite eliminated; ARCHITECTURE.md, "the
        #    scalar-core lesson").  A one-hot MATMUL would be faster
        #    still but is not exact for real-valued f32 demands (MXU
        #    truncates operands to bf16); the select-reduce stays on the
        #    VPU with full f32 adds.  Summation is XLA's tree order
        #    rather than the scatter's index order — refunds of several
        #    tasks on one host can differ by ULPs from the old path
        #    (both deterministic; the DES is the semantic referee and
        #    sums per-event anyway).
        newly_done = (stage == _RUNNING) & (finish <= t)
        if vector:
            # ONE [T, H] placement one-hot shared by the refund sum and
            # the done-count einsum (their masks differ only in the stage
            # predicate ANDed on; fault aborts between them only touch
            # RUNNING rows, which the done predicate excludes).  The busy
            # max below rebuilds it because placements land in ``place``
            # first.  Unplaced rows carry the -1 sentinel and match no
            # host column.
            place_oh = place[:, None] == jnp.arange(H)[None, :]
            refund_per_host = jnp.sum(
                jnp.where(
                    (place_oh & newly_done[:, None])[:, :, None],
                    workload.demands[:, None, :],
                    jnp.zeros((), dtype),
                ),
                axis=0,
            )  # [H, 4]
        else:
            # Scatter-add over the retiring tasks' placements (misses →
            # the sacrificial H row).  Same sum, different accumulation
            # order than the tree reduce above — held bit-identical on
            # every rollout output by the forms parity suite.
            refund_per_host = jax.ops.segment_sum(
                jnp.where(
                    newly_done[:, None], workload.demands,
                    jnp.zeros((), dtype),
                ),
                jnp.where(newly_done, place, H),
                num_segments=H + 1,
            )[:H]  # [H, 4]
        avail = avail + refund_per_host
        stage = jnp.where(newly_done, _DONE, stage)

        # 1b. Faults: crashes strike after this window's completions
        #     retire (completion-wins tie at tick resolution).
        if faults is not None:
            struck = _scatter_hosts((fail_at > t - tick) & (fail_at <= t))
            down = _scatter_hosts((fail_at <= t) & (t < recover_at))
            prev_down = _scatter_hosts(
                (fail_at <= t - tick) & (t - tick < recover_at)
            )
            aborted = (
                (stage == _RUNNING)
                & (place >= 0)
                & struck[jnp.clip(place, 0, H - 1)]
            )
            stage = jnp.where(aborted, _PENDING, stage)
            place = jnp.where(aborted, -1, place)
            finish = jnp.where(aborted, inf, finish)
            # Recovery hands back a fresh machine (DES Host.recover);
            # covers both outages ending this window and sub-tick ones.
            recovered = (prev_down | struck) & ~down
            avail = jnp.where(recovered[:, None], totals, avail)
            # Down rows carry the −1 sentinel (no refund for lost work —
            # reapplied every tick so stray refunds cannot resurrect one).
            avail = jnp.where(down[:, None], jnp.asarray(-1.0, dtype), avail)
            if congestion:
                # A crash cancels the host's pending inbound staging
                # (FastExecutor.abort_host cancels queued transfers).
                q = jnp.where(struck[None, :], jnp.asarray(0.0, dtype), q)
                if pairs:
                    # Host-resolution rows also let the OUTBOUND side
                    # cancel: pipes sourced at the struck host drain
                    # nothing any more (native transfer cancellation
                    # aborts both directions, ``pivot_net.cpp``).
                    q = jnp.where(
                        struck[:, None], jnp.asarray(0.0, dtype), q
                    )

        # 2. Readiness: the DES dispatch pipeline at tick resolution
        #    (measured on the live scheduler, tests/test_sched.py):
        #      * roots enter the global submit queue at submission time
        #        and dispatch at the first global tick STRICTLY after it
        #        (the t=0 tick precedes the local pump);
        #      * a successor's readiness event is its last predecessor
        #        instance's finish τ; the app-local pump (period = tick,
        #        phase = the app's submission time) picks it up at the
        #        first boundary STRICTLY after τ (a boundary coinciding
        #        with τ fires before the completion notification lands),
        #        and the global tick dispatches STRICTLY after the pump.
        #    Round 1 dispatched successors at the first tick ≥ τ — one to
        #    two ticks early — which shifted tick-batch composition off
        #    the DES's at capacity boundaries and was a dominant source
        #    of packing-arm placement divergence.
        done_f = (stage == _DONE).astype(dtype)
        unfinished_preds = workload.pred @ (1.0 - done_f)  # [T]
        fin_done = jnp.where(stage == _DONE, finish, -inf)
        gf = jax.ops.segment_max(
            fin_done, workload.group_of, num_segments=G
        )  # [G] latest finish among a group's done instances
        tau_g = jnp.max(
            jnp.where(workload.pred_group > 0, gf[None, :], -inf), axis=1
        )  # [G] readiness event time (−inf for root groups)
        if vector:
            tau = jnp.sum(
                jnp.where(g_oh, tau_g[None, :], jnp.zeros((), dtype)), axis=1
            )  # [T] — select-reduce, not the [R, T] gather (scalar core)
        else:
            tau = tau_g[workload.group_of]  # [T] gather (exact selection)
        pump = arrival + (jnp.floor((tau - arrival) / tick) + 1.0) * tick
        ready_time = jnp.where(has_pred, pump, arrival)
        ready = (
            (stage == _PENDING) & (ready_time < t) & (unfinished_preds == 0)
        )

        # 2b. Batch rank (tick_order="lifo"): each ready task's position
        #     in the DES's ready batch this tick.  The reference drains
        #     its wait dict first, in REVERSE insertion order (popitem),
        #     and insertion order was last tick's schedule-RETURN order
        #     (batch order for the batch-order arms, the decreasing sort
        #     for VBP first-fit — see the ``qpos`` write below) — so the
        #     wait cohort runs in reverse of its previous positions
        #     (``qpos`` carry).  Fresh tasks follow, ordered by pump
        #     event time, then app creation order, then the local
        #     scheduler's LIFO stack pop (descending task index).  Two
        #     [T] sorts per tick: one to order, one to invert (no
        #     scatter on the vector path).
        iota_t = jnp.arange(T, dtype=jnp.int32)
        if lifo:
            # Three keys, not six: the wait/fresh/non-ready cohorts and
            # the wait cohort's reverse re-drain fold into ONE i32 key
            # (waits carry −qpos ≤ 0, fresh 1, non-ready 2 — integer
            # selection, order identical to the unfolded keys), and the
            # fresh cohort's (app creation order, LIFO stack pop) pair
            # is the STATIC key app·T + (T−1−index); only pump time
            # stays its own key.
            wait_c = (qpos >= 0) & ready
            k1 = jnp.where(
                ready, jnp.where(wait_c, -qpos, 1), jnp.asarray(2, jnp.int32)
            )
            if T <= 46340:  # app·T + T ≤ T² + T < 2³¹ (app_of < n_apps ≤ T)
                fresh_static = (
                    workload.app_of.astype(jnp.int32) * T + (T - 1 - iota_t)
                )
                keys = (k1, ready_time, fresh_static, iota_t)
                nk = 3
            else:  # unreachable with a [T, T] pred matrix in HBM; exact
                keys = (
                    k1, ready_time, workload.app_of.astype(jnp.int32),
                    -iota_t, iota_t,
                )
                nk = 4
            border = lax.sort(keys, num_keys=nk)[
                len(keys) - 1
            ]  # [T] batch order (task index at each position)
            if vector:
                brank = lax.sort((border, iota_t), num_keys=1)[1]
            else:
                brank = jnp.zeros((T,), jnp.int32).at[border].set(iota_t)
        else:
            brank = iota_t  # legacy: batch order = task index order

        # 3. Anchors: majority vote over predecessor placement hosts
        #    (ref cost_aware.py:45-58); roots use their pre-drawn keyed
        #    storage zone.  Group-wise: zc[g, z] counts group g's done
        #    instances in zone z, and summing counts over predecessor
        #    groups gives exactly the instance-level vote counts without
        #    any per-replica [T, T] product.  (zc also feeds the
        #    transfer estimate, so it is computed for every policy; the
        #    vote itself only matters to cost-aware.)
        done_mask = stage == _DONE
        if vector:
            # Done-instance counts per (group, host) as ONE bf16 one-hot
            # contraction over tasks: hv[g, h] = Σ_t 1[group_of[t]=g] ·
            # 1[place[t]=h, done].  The segment-sum form below lowers
            # (under vmap) to a scatter-add with a per-replica [R, T]
            # scalar-memory index vector — profiled at ~1 ms/tick
            # serialized on the scalar core, 22% of the whole rollout.
            # The matmul form is integer-EXACT: one-hot factors are 0/1
            # (exact in bf16), counts ≤ max instances < 256, and the MXU
            # accumulates in f32 — same argument as ``hv @ zone_onehot``
            # below.  (The former [R, T] ``host_zone[place]`` gather was
            # removed by the round-2 rewrite for the same reason.)
            place_done_oh = place_oh & done_mask[:, None]  # [T, H]
            hv = jnp.einsum(
                "tg,th->gh",
                g_oh.astype(jnp.bfloat16),
                place_done_oh.astype(jnp.bfloat16),
                preferred_element_type=dtype,
            )  # [G, H] done counts per host
        else:
            # Flattened (group × host) scatter-add of ones — integer
            # counts, exact in any accumulation order.
            flat = workload.group_of * (H + 1) + jnp.where(
                done_mask, place, H
            )
            hv = jax.ops.segment_sum(
                jnp.where(done_mask, jnp.ones((T,), dtype),
                          jnp.zeros((), dtype)),
                flat,
                num_segments=G * (H + 1),
            ).reshape(G, H + 1)[:, :H]  # [G, H] done counts per host
        zc = hv @ zone_onehot  # [G, Z]
        if policy == "cost-aware":
            # The DES/reference vote is per HOST, not per zone (Counter
            # over predecessor task *placements*, cost_aware.py:52-55):
            # the anchor is the single most-loaded host's zone.  A
            # zone-level vote (round 1) aggregates same-zone hosts and
            # can crown a different zone whenever an app's instances
            # spread across several hosts of one zone — measured as a
            # successor-anchor drift between the engines.  Ties resolve
            # to the lowest host index — an approximation of the DES's
            # first-seen insertion order (exact only while host score
            # order is static over the vote window; a vectorized
            # first-seen tie-break would need per-instance placement
            # timestamps).
            votes_h = workload.pred_group @ hv  # [G, H] pred-instance votes
            majority_host = jnp.argmax(votes_h, axis=1)  # [G]
            if vector:
                # Zone of each group's majority host, then group → task
                # expansion — both as integer select-reduces on the VPU
                # (the ``host_zone[majority_host][group_of]`` double
                # gather runs on the scalar core under vmap; sums of one
                # non-zero int are exact).
                mh_oh = jnp.arange(H)[None, :] == majority_host[:, None]
                mz_g = jnp.sum(
                    jnp.where(mh_oh, topo.host_zone[None, :], 0), axis=1
                )  # [G]
                majority_zone = jnp.sum(
                    jnp.where(g_oh, mz_g[None, :], 0), axis=1
                )  # [T]
            else:
                majority_zone = topo.host_zone[majority_host][
                    workload.group_of
                ]  # [T] double gather (exact selection)
            anchor = jnp.where(has_pred, majority_zone, root_anchor)
        else:
            anchor = root_anchor  # unused by the other arms

        # 4. Placement — same greedy cost-aware decision as the live
        #    scheduler's fused kernel (first-fit, sorted hosts, per-task
        #    score group), but the sequential chain is cut to the tasks
        #    that can actually place this tick:
        #      * availability only DECREASES within a tick (releases land
        #        at tick boundaries), so a ready task with no strictly
        #        fitting host at tick start can never place this tick —
        #        it is excluded from the chain with placement −1, exactly
        #        what its in-chain step would produce.  This is what keeps
        #        saturated phases cheap, where thousands of tasks wait but
        #        only a handful can land.
        #      * the eligible tasks are compacted to the front (stable, so
        #        index order — and therefore every placement — is
        #        bit-identical to the full scan) and a bounded while_loop
        #        runs max-over-replicas(n_eligible) steps instead of T.
        strict = policy in ("cost-aware", "best-fit")  # ref :124 / vbp :45
        # Group-level fit test (exact — see ``dem_group``), expanded per
        # task by a shared-index gather (constant across replicas, so it
        # lowers cheap, not to a batched scalar-memory gather).
        if strict:
            fits_g = jnp.all(
                avail[None, :, :] > dem_group[:, None, :], axis=2
            )  # [G, H]
        else:
            fits_g = jnp.all(
                avail[None, :, :] >= dem_group[:, None, :], axis=2
            )
        fits_at_start = jnp.any(fits_g, axis=1)[workload.group_of]  # [T]
        eligible = ready & fits_at_start
        # Within-tick order mirrors the canonical DES arms.  Cost-aware
        # processes anchor *buckets* group-major (the DES groups the
        # batch by anchor — Storage node for successors, the Application
        # for roots — and places one bucket at a time), with tasks inside
        # a bucket demand-norm-decreasing (sort_tasks).  VBP first-fit
        # runs one global decreasing sort; best-fit/opportunistic place
        # in batch order.
        if policy == "cost-aware":
            # Bucket code: successor groups merge by anchor zone
            # (Storage identity), root groups stay per-app (Application
            # identity) — Z + app_of keeps the two key spaces disjoint.
            bucket = jnp.where(
                has_pred, anchor, Z + workload.app_of.astype(jnp.int32)
            )
            # Bucket order keys on the min READY index — the DES buckets
            # first-seen over the full ready batch, including tasks with
            # no fitting host (they still pin their bucket's position).
            # Computed as [T, B] one-hot min/select-reduces on the VPU
            # (the former segment_min + ``first_in_bucket[bucket]`` pair
            # both lowered to scalar-memory scatter/gather inside the
            # loop).  B = Z + G bounds the bucket key space statically:
            # successor buckets are zones (< Z) and root buckets are
            # Z + app index, with #apps ≤ G (every app owns ≥ 1 group) —
            # linear in T, unlike a [T, T] same-bucket compare, which is
            # 13M cells/replica at the calibrate scale (T≈3.6k).
            B = Z + G
            # Bucket rank = first-seen position in the DES's ready batch
            # (``brank``: task index order under "fifo", the emulated
            # LIFO queue order under "lifo").
            ready_idx = jnp.where(ready, brank, T).astype(jnp.int32)
            if vector:
                b_oh = bucket[:, None] == jnp.arange(B)[None, :]  # [T, B]
                fib = jnp.min(
                    jnp.where(b_oh, ready_idx[:, None], T), axis=0
                )  # [B] first ready position per bucket
                bfirst = jnp.sum(
                    jnp.where(b_oh, fib[None, :], 0), axis=1
                ).astype(jnp.int32)
            else:
                # Integer min-scatter + gather (exact; empty buckets fill
                # INT_MAX vs the vector form's T, but bfirst only reads a
                # task's OWN bucket, which contains it).
                fib = jax.ops.segment_min(
                    ready_idx, bucket, num_segments=B
                )  # [B]
                bfirst = fib[bucket]  # [T]
            key3 = -dem_norms  # norm-decreasing inside a bucket
        else:
            bfirst = jnp.zeros((T,), jnp.int32)
            if policy == "first-fit":
                # VBP decreasing sort; the tie key below resolves equal
                # norms in batch order (the legacy path keys on the
                # precomputed rank, whose ties are baked in ascending).
                key3 = -dem_norms if lifo else task_rank
            else:
                # Batch order arms: the tie key IS the order.
                key3 = jnp.zeros((T,), jnp.int32) if lifo else task_rank
        # ONE multi-operand sort carrying every per-task payload through,
        # replacing lexsort + four ``x[order]`` gathers (each a batched
        # gather with scalar-memory indices — the dominant per-tick cost
        # before this rewrite).
        # Demands are NOT carried as payloads: the loop re-derives each
        # step's demand row from the group table (``dem_group[g_p[j]]``
        # as a tiny [G, 4] select-reduce) — four fewer [R, T] sort
        # operands per tick, exact by group-wise demand constancy.
        # Keys (major → minor): ineligible-last, bucket first-seen,
        # policy key, batch-rank tie.  Under "fifo" the batch rank IS
        # the task index, so ``iota_t`` serves as both the tie key and
        # the permutation payload — the round-2 seven-operand shape, no
        # extra [R, T] operand on the throughput hot path.  Under
        # "lifo" the per-tick ``brank`` is the tie key and ``iota_t``
        # rides as a separate payload.
        operands = [
            (~eligible).astype(jnp.int32),
            bfirst,
            key3,
        ]
        if lifo:
            operands.extend([brank, iota_t])
            payload0 = 4
        else:
            operands.append(iota_t)
            payload0 = 3
        operands.extend([anchor, workload.group_of.astype(jnp.int32)])
        if task_u is not None:
            operands.append(task_u)
        sorted_ops = lax.sort(tuple(operands), num_keys=4)
        order = sorted_ops[payload0]
        bf_p = sorted_ops[1]
        az_p = sorted_ops[payload0 + 1]
        g_p = sorted_ops[payload0 + 2]
        u_p = sorted_ops[payload0 + 3] if task_u is not None else None
        n_ready = jnp.sum(eligible)
        if realtime_scoring and policy == "cost-aware":
            # Discount the inbound leg of the round-trip bandwidth by the
            # tick-start backlog on each (anchor zone → host) pipe — the
            # outbound leg has no tracked queue and stays static.  This is
            # the signal the DES realtime_bw arm reads from live route
            # queues (ref ``resources/network.py:70-73``).  The where
            # keeps empty pipes BIT-identical to the static table (the
            # algebraic form bw_rt − bw_zh + bw_zh can round 1 ulp off).
            score_bw_rt = jnp.where(
                q > 0, bw_rt - bw_zh + bw_zh / (q + 1.0), bw_rt
            )
        else:
            score_bw_rt = bw_rt

        # 4b. Eviction-risk penalty row for this tick: the market's
        #     piecewise-constant per-host hazard at t, scaled by the
        #     row's risk coefficient — hoisted out of the placement loop
        #     (the segment cannot change within a tick).  Vector form
        #     selects the segment row as a [P, H] one-hot reduce (P is
        #     the handful of price segments; the gather's per-replica
        #     index would land in scalar memory under vmap), indexed
        #     form keeps the exact row gather.
        if hazard is not None:
            h_times, h_rows = hazard
            Pn = h_rows.shape[0]
            seg = jnp.clip(
                jnp.searchsorted(h_times, t, side="right") - 1, 0, Pn - 1
            )
            if vector:
                seg_oh = (jnp.arange(Pn) == seg)[:, None]  # [P, 1]
                hz_row = jnp.sum(
                    jnp.where(seg_oh, h_rows, jnp.zeros((), dtype)), axis=0
                )  # [H]
            else:
                hz_row = h_rows[seg]  # [H] row gather (exact selection)
            risk_row = risk_coeff * hz_row
        else:
            risk_row = None

        # 5a. Transfer-delay table — BEFORE the placement loop (it only
        #     reads zc, which predates placement): max over predecessor
        #     instances of size / bw(src zone → dst zone).  All instances
        #     of a producer group share one output size, so the max
        #     reduces exactly to zone *presence* per group: GD[g, z] =
        #     out_g × max over source zones s with a done g-instance of
        #     1/bw[s, z] ([G, Z]), then CD[c, z] = max over c's
        #     predecessor groups of GD.  Each placement selects its
        #     CD[g, zone(h)] entry inside the loop (tiny VPU selects);
        #     the former post-loop path gathered [R, T] ``new_zone`` and
        #     ``CD[group_of, new_zone]`` through scalar memory.
        inv_bw = jnp.where(topo.bw > 0, 1.0 / topo.bw, 0.0)  # [Z, Z]
        presence = (zc > 0).astype(dtype)  # [G, Z]
        GD = (
            jnp.max(presence[:, :, None] * inv_bw[None, :, :], axis=1)
            * workload.out_group[:, None]
        )  # [G, Z]
        CD = lax.map(
            lambda col: jnp.max(workload.pred_group * col[None, :], axis=1),
            GD.T,
        ).T  # [G, Z] max over predecessor groups, zone column at a time

        # Round-6 two-phase audit (ops/kernels.py restructure): this loop
        # was swept for the same phase-1 hoisting.  The score-row
        # selections (cost_rt / score_bw_rt by anchor zone) are NOT
        # hoistable here — a per-task [R, T, H] materialization is out of
        # memory at calibrate scale, and folding cost/bw into one ratio
        # table changes operand association (breaks DES wave parity).
        # Per-step conditional skips also buy nothing: this loop is always
        # vmapped over replicas, where lax.cond lowers to a select that
        # evaluates both branches.  The one loop-invariant found in-loop
        # was the opportunistic arm's Weyl rotation, hoisted below.
        if policy == "opportunistic" and task_u is not None:
            tick_idx = (t / tick).astype(jnp.int32)
            weyl_rot = tick_idx.astype(u_p.dtype) * 0.6180339887498949
        else:
            weyl_rot = None

        def place_cond(c):
            j, _avail, _pl, _dl, _ns, _bf = c
            return j < n_ready

        def place_body(c):
            j, avail, pl, delay, norm_snap, prev_bf = c
            if vector:
                # One [G, 1] group mask for this step, shared by the
                # demand re-derivation here and the CD row select below.
                g_hit = (jnp.arange(G) == g_p[j])[:, None]
                # Demand row from the group table (one [G, 4]
                # select-reduce; exactly one non-zero term — bit-exact,
                # and g_p[j] is the batched index the sort carries).
                demand = jnp.sum(
                    jnp.where(g_hit, dem_group, jnp.zeros((), dtype)), axis=0
                )  # [4]
            else:
                demand = dem_group[g_p[j]]  # [4] row gather
            if strict:
                fit = jnp.all(avail > demand[None, :], axis=1)
            else:
                fit = jnp.all(avail >= demand[None, :], axis=1)
            if policy == "cost-aware":
                # Stale-score semantics (ref cost_aware.py:104-119, DES
                # CostAwarePolicy._first_fit): host scores are computed
                # ONCE per anchor bucket from availability at bucket
                # start, then tasks first-fit in that frozen order with
                # LIVE fit checks.  Re-scoring per task (live norms) was
                # round 1's model — it spreads load as a host's residual
                # shrinks, where the DES keeps concentrating on it;
                # measured as the dominant cost-aware egress/IH bias.
                live_norm = jnp.sqrt(jnp.sum(avail * avail, axis=1))
                new_bucket = bf_p[j] != prev_bf
                norm_snap = jnp.where(new_bucket, live_norm, norm_snap)
                prev_bf = bf_p[j]
                # Anchor-zone row selection.  Vector form: one-hot
                # select-reduce, NOT ``table[az_p[j]]`` — under vmap the
                # indexed form lowers to a batched gather whose [R]
                # index vector lives in scalar memory, serialized on the
                # scalar core, measured as a dominant rollout cost.  The
                # select-reduce stays on the VPU and is bit-exact (the
                # sum has exactly one non-zero term; adding zeros is
                # IEEE-exact for finite table entries).  Indexed form:
                # the row gather (exact selection, fast on CPU).
                if vector:
                    zoh = (jnp.arange(Z) == az_p[j])[:, None]  # [Z, 1]
                    zero = jnp.zeros((), dtype)
                    if score_params is None:
                        cost_row = jnp.sum(
                            jnp.where(zoh, cost_rt, zero), axis=0
                        )
                        bw_row = jnp.sum(
                            jnp.where(zoh, score_bw_rt, zero), axis=0
                        )
                    else:
                        cost_row = jnp.sum(
                            jnp.where(zoh, cost_pow, zero), axis=0
                        )
                        bw_row = jnp.sum(jnp.where(zoh, bw_pow, zero), axis=0)
                else:
                    if score_params is None:
                        cost_row = cost_rt[az_p[j]]
                        bw_row = score_bw_rt[az_p[j]]
                    else:
                        cost_row = cost_pow[az_p[j]]
                        bw_row = bw_pow[az_p[j]]
                if score_params is None:
                    score = cost_row / (norm_snap * bw_row)
                else:
                    score = cost_row / (norm_snap ** w_norm * bw_row)
                if risk_row is not None:
                    score = score + risk_row  # the shared score += risk rule
                h = jnp.argmin(jnp.where(fit, score, inf))
            elif policy == "first-fit":
                if risk_row is not None:
                    # Risk-aware first fit: the index order becomes the
                    # lexicographic (risk, index) order — argmin ties to
                    # the lowest index (resolve_risk's shared rule).
                    h = jnp.argmin(jnp.where(fit, risk_row, inf))
                else:
                    h = jnp.argmax(fit)  # lowest-index fit (ref vbp.py:6-29)
            elif policy == "best-fit":
                resid = avail - demand[None, :]
                score = jnp.sqrt(jnp.sum(resid * resid, axis=1))
                if risk_row is not None:
                    score = score + risk_row  # the shared score += risk rule
                h = jnp.argmin(jnp.where(fit, score, inf))
            else:  # opportunistic: uniform among fits (ref opportunistic.py)
                if risk_row is not None:
                    # Minimum-risk tier restriction (same draw, narrower
                    # support); no fits ⇒ rmin = inf and finite risk rows
                    # match nothing, so `ok` below stays False.
                    rmin = jnp.min(jnp.where(fit, risk_row, inf))
                    fit = fit & (risk_row == rmin)
                # Per-tick redraw via a Weyl rotation of the task's base
                # uniform (the DES redraws per tick, policies.py:105; a
                # retrying task must not deterministically re-target the
                # same rank every tick).  Keyed on absolute time, so
                # checkpoint segmentation cannot shift the sequence.  The
                # rotation is a per-tick constant, hoisted out of the loop
                # (same operands, same association — bit-exact).
                u_eff = jnp.mod(u_p[j] + weyl_rot, 1.0)
                n_fit = jnp.sum(fit)
                k = jnp.minimum((u_eff * n_fit).astype(jnp.int32), n_fit - 1)
                rank = jnp.cumsum(fit) - 1  # rank among fitting hosts
                h = jnp.argmax(fit & (rank == k))
            ok = jnp.any(fit)
            if vector:
                # One-hot state updates, NOT ``.at[h].add`` /
                # ``.at[...].set``: under vmap those lower to batched
                # scatters with scalar-memory index vectors (serialized
                # on the scalar core — with the row gathers above, ~85%
                # of rollout wall before the round-2 rewrite).
                # Bit-exact: x − d·1 ≡ x + (−d), x − d·0 ≡ x.
                host_hit = (jnp.arange(avail.shape[0]) == h)[:, None]
                avail = avail - jnp.where(
                    host_hit & ok, demand[None, :],
                    jnp.zeros((), avail.dtype),
                )
                task_hit = jnp.arange(T) == order[j]
                pl = jnp.where(
                    task_hit, jnp.where(ok, h, -1).astype(jnp.int32), pl
                )
                # Transfer delay CD[group, zone(h)] for this placement
                # via three tiny VPU selects (zone of h, CD group row,
                # zone entry); unplaced tasks keep 0, masked by
                # ``placed`` below.
                z_h = jnp.sum(
                    jnp.where(jnp.arange(H) == h, topo.host_zone, 0)
                )
                cd_row = jnp.sum(
                    jnp.where(g_hit, CD, jnp.zeros((), dtype)), axis=0
                )  # [Z]
                d_j = jnp.sum(
                    jnp.where(
                        jnp.arange(Z) == z_h, cd_row, jnp.zeros((), dtype)
                    )
                )
                delay = jnp.where(task_hit & ok, d_j, delay)
            else:
                # Index forms (exact: x − d ≡ x + (−d); a miss scatters
                # to the dropped H row instead of adding 0).
                avail = avail.at[jnp.where(ok, h, H)].add(
                    -demand, mode="drop"
                )
                pl = pl.at[order[j]].set(
                    jnp.where(ok, h, -1).astype(jnp.int32)
                )
                z_h = topo.host_zone[h]
                d_j = CD[g_p[j], z_h]
                delay = delay.at[order[j]].set(
                    jnp.where(ok, d_j, jnp.zeros((), dtype))
                )
            return j + 1, avail, pl, delay, norm_snap, prev_bf

        _, avail, placements, xfer_delay, _, _ = lax.while_loop(
            place_cond,
            place_body,
            (
                jnp.asarray(0, jnp.int32),
                avail,
                jnp.full((T,), -1, dtype=jnp.int32),
                jnp.zeros((T,), dtype),
                jnp.sqrt(jnp.sum(avail * avail, axis=1)),
                jnp.asarray(-1, jnp.int32),
            ),
        )
        placed = placements >= 0
        if lifo:
            # Wait-queue carry: a ready task that did not place this
            # tick re-enters the wait dict at its position in the
            # policy's SCHEDULE-RETURN order — the reference's tick loop
            # consumes ``schedule(ready_q)``'s return list, so insertion
            # order is batch order for the batch-order arms but the
            # decreasing-sorted order for VBP first-fit, which returns
            # the sorted list (ref ``scheduler/__init__.py:102-115``,
            # ``vbp.py:17``; the DES twin mirrors this via
            # ``TickContext.visit_order``).  Next tick's re-drain
            # reverses on -qpos above.  Placed / non-ready rows reset to
            # the -1 sentinel (an aborted task re-enters as FRESH, like
            # the DES's resubmission through submit_q).
            if policy == "first-fit":
                # Return-order rank over the FULL ready batch (including
                # tasks with no fitting host — the sorted list holds
                # them too): non-ready last, norm-decreasing, ties in
                # batch order (``sorted`` is stable).  The placement
                # sort above cannot be reused — it keys ineligible rows
                # last, which is provably placement-neutral but wrong
                # for insertion positions.
                s_nonready = (~ready).astype(jnp.int32)
                sord = lax.sort(
                    (s_nonready, -dem_norms, brank, iota_t), num_keys=3
                )[3]
                if vector:
                    srank = lax.sort((sord, iota_t), num_keys=1)[1]
                else:
                    srank = jnp.zeros((T,), jnp.int32).at[sord].set(iota_t)
            else:
                srank = brank  # batch-order arms: return order = batch
            qpos = jnp.where(
                ready & ~placed, srank, jnp.asarray(-1, jnp.int32)
            )

        if pairs:
            # Host-pair pipe rung: same FIFO-backlog recurrence as the
            # zone model below, but one pipe per (src HOST → dst host)
            # route with that route's own bandwidth — the DES serves
            # each host-pair route independently (round-robin chunks
            # WITHIN a route, ref ``resources/network.py:86-100``), so
            # zone-row aggregation overstates contention whenever
            # several same-zone sources feed one destination.  Volumes
            # distribute over source hosts by done-instance counts
            # (``hv`` — exactly the per-host disaggregation of the zone
            # model's ``zc``).  Indexed ops only: this is the fidelity
            # ladder's diagnostic rung (CPU-side calibration), not the
            # TPU throughput path.
            pull_gh = pull_frac @ hv  # [G, H] pulled MB per consumer inst
            vol_th = pull_gh[workload.group_of] * placed[:, None]  # [T, H]
            v_new = jax.ops.segment_sum(
                vol_th, jnp.where(placed, placements, H),
                num_segments=H + 1,
            )[:H].T  # [H_src, H_dst] new queued MB per route
            q_now = q + v_new
            pulls_from = vol_th > 0
            ratio_t = (
                q_now * inv_bw_hh
            )[:, jnp.clip(placements, 0, H - 1)].T  # [T, H_src]
            cong_delay = jnp.max(
                jnp.where(pulls_from, ratio_t, 0.0), axis=1
            )  # [T]
            xfer_delay = jnp.maximum(xfer_delay, cong_delay)
            q = jnp.maximum(q_now - bw_hh * tick, 0.0)
        elif congestion:
            # Backlog pipe model: every (src zone s → dst host h) aggregate
            # is one FIFO pipe with queued-MB state q[s, h]; a pull joins
            # the backlog and completes when the pipe has drained it, so
            # its delay is (backlog + this tick's volume) / bw — the
            # tick-resolution analog of the DES's per-route round-robin
            # chunk service, where concurrent transfers on one route all
            # finish together at backlog-drain time.  Pull volumes follow
            # the DES sampling rule via the hoisted ``pull_frac`` table;
            # aggregation is one matmul + one segment sum — nothing bigger
            # than [T, Z] is materialized.
            pull_gz = pull_frac @ zc  # [G, Z] pulled MB per consumer instance
            # Group → task expansion kept as a shared-index gather: a
            # g_oh one-hot MATMUL here would not be bit-exact (pull_gz
            # carries real f32 values, which the MXU truncates to bf16 —
            # unlike the integer-count ``hv @ zone_onehot`` above), and a
            # where/reduce select would build an [R, T, G, Z] broadcast.
            # The index vector (group_of) is shared across replicas, so
            # this lowers to a constant-index gather, not the batched
            # scalar-memory form the placement-loop rewrite eliminated.
            vol_tz = pull_gz[workload.group_of] * placed[:, None]  # [T, Z]
            if vector:
                # Round-3 congestion-arm vectorization (VERDICT r02
                # item 1): the two per-tick scalar-core ops below — a
                # scatter-add with a per-replica [R, T] segment-id
                # vector and a batched gather on placements — were the
                # arm's remaining toll (11.4 s vs 2.6–3.1 s for the
                # static arms at the canonical scale) after both round-2
                # purges.  Both become HIGHEST-precision one-hot matmuls
                # on the MXU: the f32 emulation's split-product of x
                # with an exact 0/1 operand is exact (x·1 = hi + lo = x,
                # x·0 = 0), so the pipe sums differ from the scatter
                # form only in accumulation order (tree vs index —
                # empirically bit-identical on the parity workloads; the
                # forms suite holds every rollout output to exact
                # equality), and the ratio "gather" is a one-non-zero-
                # term select, exact outright.
                place_oh_f = (
                    placements[:, None] == jnp.arange(H)[None, :]
                ).astype(dtype)  # [T, H]; unplaced rows are all-zero
                v_new = jnp.einsum(
                    "tz,th->zh", vol_tz, place_oh_f,
                    precision=lax.Precision.HIGHEST,
                )  # [Z, H] new queued MB per pipe
            else:
                v_new = jax.ops.segment_sum(
                    vol_tz, jnp.where(placed, placements, H),
                    num_segments=H + 1,
                )[:H].T  # [Z, H] new queued MB per pipe
            q_now = q + v_new
            # Per-task congested delay: max over source zones this task
            # pulls NONZERO volume from of backlog/bw at its destination
            # host (a zero-output predecessor transfers nothing — the DES
            # skips it, ``resources/__init__.py:263-267`` — so backlog
            # from other tasks must not delay this one through it).
            pulls_from = vol_tz > 0
            if vector:
                # q_now depends on ALL of this tick's placements, so the
                # per-pipe ratio cannot be selected during the placement
                # loop — but the post-loop selection needs no gather:
                # each task's ratio row is a one-non-zero-term one-hot
                # contraction of its placement column (exact, on-MXU).
                ratio_t = jnp.einsum(
                    "th,zh->tz", place_oh_f, q_now * inv_bw_zh,
                    precision=lax.Precision.HIGHEST,
                )  # [T, Z]
            else:
                ratio_t = (
                    q_now * inv_bw_zh
                )[:, jnp.clip(placements, 0, H - 1)].T
            cong_delay = jnp.max(
                jnp.where(pulls_from, ratio_t, 0.0), axis=1
            )  # [T]
            # Never undercut the uncongested bound: an empty pipe with one
            # puller reduces to the static size/bw estimate or below (the
            # sampled volume is a 1/n fraction), so take the max.
            xfer_delay = jnp.maximum(xfer_delay, cong_delay)
            # Drain the pipes over the coming window.
            q = jnp.maximum(q_now - bw_zh * tick, 0.0)

        stage = jnp.where(placed, _RUNNING, stage)
        place = jnp.where(placed, placements, place)
        finish = jnp.where(placed, t + xfer_delay + runtime, finish)

        # 6. Busy-host integral (instance-hours estimator).  Tasks only
        #    start at tick boundaries, so a host's busy interval inside
        #    this window always begins at t and ends at the latest
        #    resident finish (capped at the window) — the per-window
        #    integral max_tasks(min(finish − t, tick)) is exact within
        #    the rollout's own timing model, not a whole-tick rounding.
        #    Select-max over a [T, H] membership mask, NOT a segment_max
        #    (the vmapped segment form is a scalar-memory scatter like
        #    the refund above — profiled at ~1 ms/tick, 22% of the
        #    rollout).  Max is order-independent, so this is bit-exact
        #    vs the old path; empty hosts reduce to the 0 identity the
        #    old ``maximum(·, 0)`` clamp produced.  The mask is rebuilt
        #    rather than shared with the tick-start ``place_oh``: this
        #    tick's placements have landed in ``place`` by now and must
        #    count toward busy time.
        contrib = jnp.where(
            stage == _RUNNING, jnp.clip(finish - t, 0.0, tick), 0.0
        )
        if vector:
            run_at = (
                (place[:, None] == jnp.arange(H)[None, :])
                & (stage == _RUNNING)[:, None]
            )  # [T, H]
            busy_host = jnp.max(
                jnp.where(run_at, contrib[:, None], jnp.zeros((), dtype)),
                axis=0,
            )  # [H]
        else:
            # Max-scatter (order-independent, exact); empty hosts fill
            # −inf, clamped back to the vector form's 0 identity
            # (contrib ≥ 0, so the clamp cannot alter a busy host).
            busy_host = jnp.maximum(
                jax.ops.segment_max(
                    contrib,
                    jnp.where(stage == _RUNNING, place, H),
                    num_segments=H + 1,
                )[:H],
                0.0,
            )  # [H]
        busy = busy + jnp.sum(busy_host)

        return (
            i + 1,
            RolloutState(
                t + tick, stage, finish, place, avail, busy, q, qpos
            ),
        )

    _, out = lax.while_loop(cond, body, (jnp.asarray(0, jnp.int32), state))
    return out

