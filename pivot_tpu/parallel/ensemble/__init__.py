"""Device-resident Monte-Carlo ensemble rollouts of DAG scheduling.

The capability the reference cannot express: evaluating a placement policy
under R perturbed what-if scenarios *simultaneously*.  The reference's only
tool is forking one OS process per experiment run (``alibaba/runner.py:13``,
``alibaba/sim.py:187-195``); here the whole rollout — readiness tracking,
anchor voting, cost-aware placement, transfer/compute timing — is a single
jitted ``lax.while_loop`` over ticks, vmapped over replicas, shardable over
a device mesh (BASELINE.json configs 4-5: 1024 vmapped replicas with
perturbed runtimes / arrival times).

Execution model (deliberately simplified vs the event simulator — this is
the *ensemble estimator*, not the ground-truth DES; use
``pivot_tpu.experiments.runner`` for exact simulation):

  * Time advances in fixed scheduler ticks (the reference's 5 s grid).
  * A task becomes ready when its arrival time has passed and every
    predecessor instance is finished (readiness = one [T, T] bool matmul).
  * Placement: the same fused cost-aware kernel as the live scheduler
    (``pivot_tpu.ops.kernels.cost_aware_kernel``), anchors from an
    on-device majority vote over predecessor placement hosts
    (segment-sum counts + argmax, mirroring
    ``scheduler/cost_aware.py:45-58``).
  * Transfer time: propagation delay ``size / bw(zone→zone)`` (the same
    estimate the reference's scheduler uses for scoring;
    ``resources/__init__.py:327-331``).  By default no packet-level
    congestion; ``congestion=True`` adds a tick-resolution backlog model —
    every (source zone → destination host) aggregate is one FIFO pipe with
    a queued-MB state that new pulls join and bandwidth drains, the
    ensemble analog of the DES's per-route round-robin chunk service
    (``infra.network.Route``; ref ``resources/network.py:86-100``).
  * Egress cost: one bill of ``cost(zone_src → zone_dst) × output_mb /
    8000`` (``resources/__init__.py:565-569``) per *sampled* pull, with
    the DES's ``max(round(n_producers / n_consumers), 1)``-instance
    sampling rule and sources distributed like the producer's placements.
  * Instance-hours: tick-resolution busy-host integral (a host is busy in a
    window iff a task runs on it), the estimator analog of the DES meter's
    merged busy intervals (``infra.meter.Meter.cumulative_instance_hours``).

Monte-Carlo axes: per-replica multiplicative jitter on task runtimes and
arrivals, independent random root anchors, and — with ``n_faults > 0`` —
independent per-replica host-crash/recovery schedules (resilience what-if
ensembles; tick-resolution mirror of the DES fault model in
``infra.faults``).
"""

from __future__ import annotations

import functools
import weakref
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pivot_tpu.ops.kernels import DeviceTopology, cost_aware_kernel

__all__ = [
    "EnsembleWorkload",
    "RolloutResult",
    "RolloutState",
    "capacity_grid",
    "capacity_sweep",
    "rollout",
    "rollout_checkpointed",
    "score_param_sweep",
    "shard_sweep",
    "sharded_rollout",
    "sweep_out_shardings",
    "workload_sweep",
]

# Module map (round-4 split of the 2,400-line monolith, VERDICT r03
# item 8 — no behavior change; the forms-parity and checkpoint suites pin
# every output):
#   state.py      workload encoding, rollout state/result, op forms
#   tick.py       the tick body (_rollout_segment)
#   bill.py       finalization + the sampled egress bill
#   draws.py      Monte-Carlo draws, fault schedules, packed extras
#   sweeps.py     score/capacity/workload grid sweeps
#   checkpoint.py segmented checkpoint/resume + chunked rollouts
# This ``__init__`` keeps the public entries (rollout, sharded_rollout,
# shard_sweep) and re-exports the whole historical surface, so every
# ``pivot_tpu.parallel.ensemble.X`` reference — including the test
# suite's ``_segment_step`` monkeypatching — keeps working.

from pivot_tpu.parallel.ensemble.bill import (  # noqa: F401
    _finalize,
    _finalize_batch,
    _sampled_egress,
    _sampling_table,
)
from pivot_tpu.parallel.ensemble.checkpoint import (  # noqa: F401
    _fingerprint,
    _run_segments_pipelined,
    _segment_step,
    _segment_step_carry,
    rollout_checkpointed,
    rollout_chunked,
)
from pivot_tpu.parallel.ensemble.draws import (  # noqa: F401
    _fault_schedule,
    _keyed_storage_index_jax,
    _make_fault_schedule,
    _opportunistic_uniforms,
    _pack_extras,
    _perturbations,
    _seed_bits,
    _unpack_extras,
)
from pivot_tpu.parallel.ensemble.state import (  # noqa: F401
    _DONE,
    _PENDING,
    _RUNNING,
    EnsembleWorkload,
    RolloutResult,
    RolloutState,
    _checked_demands,
    _init_state,
    _resolve_forms,
)
from pivot_tpu.parallel.ensemble.sweeps import (  # noqa: F401
    _reshape_rows,
    _row_segment_step,
    _row_segment_step_carry,
    _run_rows,
    _tile_rows,
    capacity_grid,
    capacity_sweep,
    score_param_sweep,
    workload_sweep,
)
from pivot_tpu.parallel.ensemble.tick import _rollout_segment  # noqa: F401

def _single_rollout(
    avail0,  # [H, 4]
    runtime,  # [T] perturbed
    arrival,  # [T] perturbed
    root_anchor,  # [T] i32 random storage zone per task (used for roots)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    max_ticks: int,
    faults=None,
    score_params=None,
    policy: str = "cost-aware",
    task_u=None,
    congestion: bool = False,
    realtime_scoring: bool = False,
    active=None,  # optional [T] bool — tasks outside the mask never run
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    state = _init_state(avail0, workload.n_tasks, topo.cost.shape[0],
                        congestion=congestion)
    state = _rollout_segment(
        state, runtime, arrival, root_anchor, workload, topo, tick, max_ticks,
        faults=faults, totals=avail0, score_params=score_params,
        policy=policy, task_u=task_u, congestion=congestion,
        realtime_scoring=realtime_scoring, active=active,
        forms=_resolve_forms(forms), tick_order=tick_order,
    )
    return _finalize(state, workload, topo, active=active)



@functools.partial(
    jax.jit,
    static_argnames=(
        "n_replicas", "tick", "max_ticks", "perturb",
        "n_faults", "fault_horizon", "mttr", "policy", "congestion",
        "realtime_scoring", "forms", "tick_order",
    ),
)
def _rollout_states(
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    n_replicas: int,
    tick: float,
    max_ticks: int,
    perturb: float,
    n_faults: int,
    fault_horizon: Optional[float],
    mttr: Optional[float],
    policy: str,
    congestion: bool,
    realtime_scoring: bool,
    forms: str = "vector",
    tick_order: str = "fifo",
) -> RolloutState:
    """The jitted rollout body: [R]-stacked final states (no finalize)."""
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail0.dtype
    ) if policy == "opportunistic" else None
    faults = (
        _make_fault_schedule(
            key, n_replicas, n_faults, avail0, tick, max_ticks,
            fault_horizon, mttr,
        )
        if n_faults
        else None
    )
    spec, extras = _pack_extras(faults, task_u)
    Z = topo.cost.shape[0]

    def one(r, a, ra, *ex):
        f, u, _tot, _sp, _act, _rc = _unpack_extras(spec, ex)
        state = _init_state(avail0, workload.n_tasks, Z,
                            congestion=congestion)
        return _rollout_segment(
            state, r, a, ra, workload, topo, tick, max_ticks,
            faults=f, totals=avail0, policy=policy, task_u=u,
            congestion=congestion, realtime_scoring=realtime_scoring,
            forms=forms, tick_order=tick_order,
        )

    return jax.vmap(one)(rt, arr, root_anchor, *extras)


def rollout(
    key,
    avail0,  # [H, 4] initial availability (shared base)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,  # [S] i32 candidate root-anchor zones
    n_replicas: int = 64,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """Vmapped Monte-Carlo rollout: [R]-leading-axis results.

    Replica r perturbs task runtimes and arrivals by ``±perturb`` and draws
    independent random root anchors — the BASELINE.json ensemble configs.

    With ``n_faults > 0`` each replica additionally draws an independent
    random host-crash schedule (``n_faults`` crashes uniform in
    ``[0, fault_horizon)``, Exp(``mttr``) outages; see ``_fault_schedule``)
    — resilience-under-failures what-if analysis as one device program,
    where the DES needs one full simulation per fault scenario.
    ``fault_horizon`` defaults to the nominal ``tick × max_ticks`` span.
    ``avail0`` must be full host capacity (recovery resets to it).
    """
    workload.check_group_demands()
    states = _rollout_states(
        key, avail0, workload, topo, storage_zones,
        n_replicas=n_replicas, tick=tick, max_ticks=max_ticks,
        perturb=perturb, n_faults=n_faults, fault_horizon=fault_horizon,
        mttr=mttr, policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring, forms=_resolve_forms(forms),
        tick_order=tick_order,
    )
    return _finalize_batch(states, workload, topo)


@functools.lru_cache(maxsize=32)
def _sharded_rollout_fn(
    mesh, n_replicas, tick, max_ticks, perturb, n_faults, fault_horizon,
    mttr, policy, congestion, realtime_scoring, tick_order,
):
    """Cached jitted rollout per (mesh, static config) — repeated calls
    (key sweeps, perturbation sweeps) reuse the compiled program."""
    out_shard = NamedSharding(mesh, P("replica"))
    return jax.jit(
        functools.partial(
            rollout,
            n_replicas=n_replicas,
            tick=tick,
            max_ticks=max_ticks,
            perturb=perturb,
            n_faults=n_faults,
            fault_horizon=fault_horizon,
            mttr=mttr,
            policy=policy,
            congestion=congestion,
            realtime_scoring=realtime_scoring,
            tick_order=tick_order,
        ),
        out_shardings=RolloutResult(
            makespan=out_shard,
            egress_cost=out_shard,
            finish_time=NamedSharding(mesh, P("replica", None)),
            placement=NamedSharding(mesh, P("replica", None)),
            n_unfinished=out_shard,
            instance_hours=out_shard,
        ),
    )


def sharded_rollout(
    mesh,
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    n_replicas: int = 64,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    tick_order: str = "fifo",
) -> RolloutResult:
    """Rollout with the replica axis sharded over ``mesh`` ('replica' axis).

    Inputs are replicated; per-replica state and all outputs are sharded
    ``P('replica')`` — XLA partitions the vmapped while_loop across devices
    with zero cross-replica traffic (embarrassingly parallel), and any
    downstream ensemble statistics (means/quantiles over replicas) become
    psums over ICI.  Fault parameters as in :func:`rollout`.
    """
    n_rep_axis = int(mesh.shape["replica"])
    if n_replicas % n_rep_axis:
        raise ValueError(
            f"n_replicas={n_replicas} does not divide over the mesh's "
            f"{n_rep_axis} replica shards — NamedSharding partitions the "
            f"[R] axis into equal contiguous blocks; round the ensemble "
            f"up to a multiple of {n_rep_axis}"
        )
    fn = _sharded_rollout_fn(
        mesh, n_replicas, tick, max_ticks, perturb, n_faults, fault_horizon,
        mttr, policy, congestion, realtime_scoring, tick_order,
    )
    return fn(key, avail0, workload, topo, storage_zones)


def sweep_out_shardings(mesh) -> RolloutResult:
    """Output shardings for the [K, R, ...] what-if sweeps
    (:func:`score_param_sweep`, :func:`capacity_sweep`,
    :func:`workload_sweep`): the replica axis (axis 1) shards over the
    mesh, candidates and task axes stay unsharded.  Most callers want
    :func:`shard_sweep` instead.
    """
    two = NamedSharding(mesh, P(None, "replica"))
    three = NamedSharding(mesh, P(None, "replica", None))
    return RolloutResult(
        makespan=two,
        egress_cost=two,
        finish_time=three,
        placement=three,
        n_unfinished=two,
        instance_hours=two,
    )


def shard_sweep(sweep_fn, fallback_segment_ticks=None, force_mesh=False,
                **static_kw):
    """Bind a what-if sweep's static config and shard it over the
    available devices ('replica' axis, like :func:`sharded_rollout`) —
    XLA partitions the vmapped while_loops with zero cross-replica
    traffic.  Falls back to the unsharded call on a single device, when
    the replica count does not divide the mesh, or on the CPU backend
    (a forced-host-device "mesh" shares the physical cores — measured
    >5× slower than unsharded at scale; it exists to VALIDATE sharding,
    which tests opt into via ``force_mesh=True``).  On the fallback,
    ``fallback_segment_ticks`` (if set and not already in the config)
    runs the sweep in bounded device calls — the decision lives HERE
    because the segmented host loop is untraceable and must never reach
    the jitted sharded path.
    """
    import inspect

    from pivot_tpu.parallel.mesh import replica_mesh
    from pivot_tpu.utils import get_logger

    n_dev = len(jax.devices())
    # The divisibility guard must judge the replica count the sweep will
    # actually run with — a caller relying on the sweep's own default
    # would otherwise bypass the check (0 % n_dev == 0) and fail at run
    # time inside the sharded program.
    n_replicas = static_kw.get("n_replicas")
    if n_replicas is None:
        try:
            default = inspect.signature(sweep_fn).parameters["n_replicas"].default
        except (KeyError, TypeError, ValueError):
            default = inspect.Parameter.empty
        n_replicas = None if default is inspect.Parameter.empty else default
    reason = None
    if n_dev <= 1:
        pass  # nothing to shard over — not worth a log line
    elif static_kw.get("segment_ticks") is not None:
        # The segmented runner is a host-side loop (block_until_ready +
        # data-dependent early exit) — untraceable under jit, so an
        # explicit segment request always takes the unsharded path.
        reason = "explicit segment_ticks requests the host-side segmented loop"
    elif n_replicas is None or n_replicas % n_dev:
        reason = (
            f"replicas ({n_replicas}) not divisible by {n_dev} devices"
        )
    elif jax.default_backend() == "cpu" and not force_mesh:
        reason = (
            "CPU backend (forced-host-device meshes share the physical "
            "cores; pass force_mesh=True to shard anyway)"
        )
    if n_dev <= 1 or reason is not None:
        if reason is not None:
            get_logger("ensemble").info("sweep runs unsharded: %s", reason)
        if fallback_segment_ticks is not None:
            static_kw.setdefault("segment_ticks", fallback_segment_ticks)
        return functools.partial(sweep_fn, **static_kw)
    mesh = replica_mesh(n_dev)
    return jax.jit(
        functools.partial(sweep_fn, **static_kw),
        out_shardings=sweep_out_shardings(mesh),
    )


# -- row-based sweep runner ---------------------------------------------------
#
# Every what-if sweep is K candidates × R replicas of the same rollout with
# per-cell inputs.  Flattening (K, R) to B = K·R *rows* lets one vmapped
# segment program serve all three sweeps — and makes segmented execution
# (bounded device calls, like ``rollout_checkpointed``) structural instead
# of per-sweep surgery.  Finalization always goes through the ONE shared
# ``_finalize_batch`` program, the same bit-consistency discipline as the
# plain rollout.


