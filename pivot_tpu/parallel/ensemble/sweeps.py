"""Grid sweeps: K candidates × R replicas in one device program.

Score-exponent autotuning, capacity planning, and workload-size sweeps —
each a row-structured batch over the shared tick body with paired
Monte-Carlo draws.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from pivot_tpu.ops.kernels import DeviceTopology
from pivot_tpu.parallel.ensemble.bill import _finalize_batch
from pivot_tpu.parallel.ensemble.draws import (
    _fault_schedule,
    _opportunistic_uniforms,
    _pack_extras,
    _perturbations,
    _unpack_extras,
)
from pivot_tpu.parallel.ensemble.state import (
    _DONE,
    EnsembleWorkload,
    RolloutResult,
    _resolve_forms,
    _init_state,
)
from pivot_tpu.parallel.ensemble.tick import _rollout_segment

@functools.partial(
    jax.jit,
    static_argnames=(
        "tick", "policy", "congestion", "realtime_scoring", "spec", "forms",
        "tick_order",
    ),
)
def _row_segment_step(
    states,  # [B]-stacked RolloutState
    rt,  # [B, T]
    arr,  # [B, T]
    ra,  # [B, T] i32
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    segment_ticks,  # traced i32 — partial segments must not recompile
    spec,  # static (has_faults, has_task_u, has_totals, has_sp, has_active)
    *extras,  # the present per-row arrays, in spec order
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: str = "vector",
    tick_order: str = "fifo",
    hazard=None,  # optional replica-SHARED ([P], [P, H]) market trace
):
    """Advance every row by at most ``segment_ticks`` scheduler ticks."""
    return _vmapped_row_segment(
        states, rt, arr, ra, workload, topo, tick, segment_ticks, spec,
        extras, policy, congestion, realtime_scoring, forms, tick_order,
        hazard,
    )


def _vmapped_row_segment(
    states, rt, arr, ra, workload, topo, tick, segment_ticks, spec, extras,
    policy, congestion, realtime_scoring, forms, tick_order, hazard=None,
):
    """The one vmapped row-segment body behind :func:`_row_segment_step`
    and :func:`_row_segment_step_carry` — the twins differ only in jit
    decoration (donation) and the carry's pending-flag reduction.
    ``hazard`` is closed over (replica-shared market trace), unlike the
    per-row extras the vmap maps."""

    def seg(s, r, a, ra_, *ex):
        f, u, tot, sp, act, rc = _unpack_extras(spec, ex)
        return _rollout_segment(
            s, r, a, ra_, workload, topo, tick, segment_ticks,
            faults=f, totals=tot, score_params=sp, policy=policy,
            task_u=u, congestion=congestion,
            realtime_scoring=realtime_scoring, active=act, forms=forms,
            tick_order=tick_order, risk_coeff=rc, hazard=hazard,
        )

    return jax.vmap(seg)(states, rt, arr, ra, *extras)


@functools.partial(
    jax.jit,
    static_argnames=(
        "tick", "policy", "congestion", "realtime_scoring", "spec", "forms",
        "tick_order",
    ),
    donate_argnums=(0,),
)
def _row_segment_step_carry(
    states,  # [B]-stacked RolloutState — DONATED to the output
    rt,
    arr,
    ra,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    segment_ticks,
    spec,
    *extras,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: str = "vector",
    tick_order: str = "fifo",
    hazard=None,
):
    """:func:`_row_segment_step` with a donated carry and an on-device
    early-exit flag — the sweeps' analog of
    ``checkpoint._segment_step_carry`` (see its docstring for the
    donation contract).  ``pending`` honors the rows' ``active`` masks
    (workload-size sweeps park masked tasks at PENDING forever; they
    must not keep the pipeline alive)."""
    out = _vmapped_row_segment(
        states, rt, arr, ra, workload, topo, tick, segment_ticks, spec,
        extras, policy, congestion, realtime_scoring, forms, tick_order,
        hazard,
    )
    pending = out.stage != _DONE
    _f, _u, _tot, _sp, act, _rc = _unpack_extras(spec, extras)
    if act is not None:
        pending = pending & act
    return out, jnp.any(pending)


def _run_rows(
    avail_rows,  # [B, H, 4] initial availability per row
    rt, arr, ra,  # [B, T] perturbed inputs per row
    workload, topo, tick, max_ticks, segment_ticks,
    policy, congestion, realtime_scoring,
    faults=None,  # optional ([B,F] i32, [B,F], [B,F])
    task_u=None,  # optional [B, T]
    totals=None,  # optional [B, H, 4] (fault recovery target)
    score_params=None,  # optional [B, 3]
    active=None,  # optional [B, T] bool
    forms: Optional[str] = None,
    tick_order: str = "fifo",
    risk_coeff=None,  # optional [B] risk_weight × rework_cost per row
    hazard=None,  # optional replica-SHARED ([P], [P, H]) market trace
) -> RolloutResult:
    """Run B rows to the horizon and finalize through the shared program.

    ``segment_ticks=None`` issues ONE bounded device call of ``max_ticks``
    (the while_loop still early-exits) — fully traceable, so
    :func:`shard_sweep` can jit over it.  An integer runs the rollout in
    that many device calls per ``segment_ticks`` ticks with host-side
    early exit between segments — the remote-transport-friendly mode
    (``rollout_checkpointed``'s rationale): a monolithic multi-thousand-
    tick program is one minutes-long execution some transports kill.
    """
    if congestion == "pairs":
        raise ValueError(
            "the host-pair congestion rung is a calibration diagnostic "
            "(rollout / rollout_checkpointed / calibrate), not a sweep "
            "mode — use congestion=True here"
        )
    Z = topo.cost.shape[0]
    spec, extras = _pack_extras(
        faults, task_u, totals, score_params, active, risk_coeff
    )
    forms = _resolve_forms(forms)

    states = jax.vmap(lambda av: _init_state(av, workload.n_tasks, Z))(
        avail_rows
    )
    if segment_ticks is None:
        states = _row_segment_step(
            states, rt, arr, ra, workload, topo, tick,
            jnp.asarray(max_ticks, jnp.int32), spec, *extras,
            policy=policy, congestion=congestion,
            realtime_scoring=realtime_scoring, forms=forms,
            tick_order=tick_order, hazard=hazard,
        )
    else:
        # Host-side segmented loop (the remote-transport-friendly mode):
        # donated carry + double-buffered dispatch, same shape as the
        # checkpoint executor (``checkpoint._run_segments_pipelined``) —
        # the host inspects one scalar early-exit flag per boundary while
        # the next segment is already on the device queue.  The initial
        # copy breaks aliasing with ``avail_rows``/``totals``, which ride
        # every call as non-donated arguments.
        from pivot_tpu.parallel.ensemble.checkpoint import (
            _run_segments_pipelined,
        )

        def step(s, seg):
            return _row_segment_step_carry(
                s, rt, arr, ra, workload, topo, tick, seg, spec, *extras,
                policy=policy, congestion=congestion,
                realtime_scoring=realtime_scoring, forms=forms,
                tick_order=tick_order, hazard=hazard,
            )

        states = _run_segments_pipelined(
            step, jax.tree_util.tree_map(jnp.copy, states),
            max_ticks, segment_ticks,
        )
    return _finalize_batch(states, workload, topo, active)


def _reshape_rows(res: RolloutResult, K: int, R: int) -> RolloutResult:
    """[B, ...] row results back to [K, R, ...]."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((K, R) + x.shape[1:]), res
    )


def _tile_rows(x, K):
    """Tile a per-replica array to per-row (candidate-major: row b =
    candidate b // R, replica b % R)."""
    return jnp.tile(x, (K,) + (1,) * (x.ndim - 1))


# -- policy autotuning --------------------------------------------------------


def score_param_sweep(
    key,
    avail0,  # [H, 4] full host capacity
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,  # [S] i32
    param_grid,  # [K, 3] exponents (w_cost, w_bw, w_norm) per candidate
    n_replicas: int = 32,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    congestion: bool = False,
    segment_ticks: Optional[int] = None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """On-device policy autotuning: sweep the cost-aware score exponents.

    The candidate scoring function is ``cost^w_cost / (norm^w_norm ×
    bw^w_bw)`` — ``(1, 1, 1)`` is the reference's score shape
    (``scheduler/cost_aware.py:104-119``).  Every candidate × replica pair
    rolls out in ONE device program (double vmap, [K, R] leading axes), so
    a K-point scheduler-hyperparameter grid search under R Monte-Carlo
    scenarios costs one dispatch — the reference would need K × R full OS
    processes.  All candidates share the same perturbation/anchor draws,
    so candidate comparisons are paired (common random numbers: the
    between-candidate variance excludes scenario noise).

    Pick a winner downstream, e.g.
    ``param_grid[jnp.argmin(res.makespan.mean(axis=1))]`` or any
    makespan/egress trade-off.
    """
    grid = jnp.asarray(param_grid, avail0.dtype)
    K, R = grid.shape[0], n_replicas
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    res = _run_rows(
        jnp.broadcast_to(avail0, (K * R,) + avail0.shape),
        _tile_rows(rt, K), _tile_rows(arr, K), _tile_rows(root_anchor, K),
        workload, topo, tick, max_ticks, segment_ticks,
        policy="cost-aware", congestion=congestion, realtime_scoring=False,
        score_params=jnp.repeat(grid, R, axis=0), forms=forms,
        tick_order=tick_order,
    )
    return _reshape_rows(res, K, R)


# -- capacity planning --------------------------------------------------------


def capacity_grid(avail0, host_counts) -> jax.Array:
    """[K, H, 4] candidate capacity matrices: candidate k keeps the first
    ``host_counts[k]`` hosts and masks the rest with the −1 down-host
    sentinel (no fit can select them; they never accrue busy time).

    Keeping a prefix preserves the generator's round-robin zone balance
    (``infra/gen.py``), so every candidate is a smaller but equally
    balanced cluster.
    """
    H = avail0.shape[0]
    counts = jnp.asarray(host_counts, jnp.int32)
    keep = jnp.arange(H)[None, :] < counts[:, None]  # [K, H]
    return jnp.where(
        keep[:, :, None], avail0[None, :, :], jnp.asarray(-1.0, avail0.dtype)
    )


def capacity_sweep(
    key,
    avail_grid,  # [K, H, 4] candidate capacity matrices (capacity_grid)
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    n_replicas: int = 32,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    segment_ticks: Optional[int] = None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """On-device capacity planning: how does the workload behave on K
    candidate cluster sizes?  Every candidate × replica pair rolls out in
    ONE device program ([K, R] leading axes) with shared Monte-Carlo
    draws, so candidate comparisons are paired — "how many hosts do I
    need?" costs one dispatch where the reference needs a full OS-process
    experiment per cluster size (``alibaba/sim.py:168-196`` regenerates
    the cluster and re-forks per configuration).

    With ``n_faults > 0`` each replica draws an independent random
    host-crash schedule (shared across candidates — paired scenarios):
    resilience-aware sizing, "how many hosts do I need *given* N crashes".
    Crash hosts are drawn over the LARGEST candidate's host range (the
    union of all candidates — drawing over the full base cluster would
    silently dilute the fault count whenever the base is bigger than
    every candidate); a crash landing on a host a smaller candidate
    masked out is a no-op there, while the same crash hits the larger
    candidates — the SAME physical failure trace applied to each
    provisioning choice.

    Downstream, combine ``instance_hours × hourly_rate + egress_cost``
    for the cost/makespan trade-off (the reference's financial-cost
    analysis, ``alibaba/sim.py:132-165``); candidates with
    ``n_unfinished > 0`` are undersized for the horizon.
    """
    K, R = avail_grid.shape[0], n_replicas
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail_grid.dtype
    )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail_grid.dtype
    ) if policy == "opportunistic" else None
    faults = None
    if n_faults:
        # Hosts alive in ANY candidate — the union of all candidates'
        # ranges.  jax.random.randint accepts a traced bound, so no
        # static host count is needed.
        alive = jnp.any(avail_grid[:, :, 0] >= 0, axis=0)  # [H]
        n_alive = jnp.sum(alive)
        horizon = (
            fault_horizon if fault_horizon is not None else tick * max_ticks
        )
        host_rank, fail_at, recover_at = _fault_schedule(
            jax.random.fold_in(key, 0x0FA17), n_replicas, n_faults,
            n_alive, horizon, mttr, avail_grid.dtype,
        )
        # The draw is a *rank* in [0, n_alive); map it to the actual host
        # index so crashes land on alive hosts for ANY candidate grid.
        # For capacity_grid's prefix-shaped grids this is the identity
        # (bit-stable with the pre-mapping draws); for a caller-supplied
        # non-prefix grid it fixes crashes silently hitting masked hosts
        # and missing alive ones.
        host = jnp.searchsorted(
            jnp.cumsum(alive.astype(jnp.int32)), host_rank + 1
        ).astype(jnp.int32)
        faults = (host, fail_at, recover_at)
    avail_rows = jnp.repeat(avail_grid, R, axis=0)  # [B, H, 4]
    res = _run_rows(
        avail_rows,
        _tile_rows(rt, K), _tile_rows(arr, K), _tile_rows(root_anchor, K),
        workload, topo, tick, max_ticks, segment_ticks,
        policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring,
        faults=(
            tuple(_tile_rows(f, K) for f in faults)
            if faults is not None else None
        ),
        task_u=_tile_rows(task_u, K) if task_u is not None else None,
        totals=avail_rows if faults is not None else None,
        forms=forms, tick_order=tick_order,
    )
    return _reshape_rows(res, K, R)


def workload_sweep(
    key,
    avail0,  # [H, 4]
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    app_counts,  # [K] i32 — candidate k runs the first app_counts[k] apps
    n_replicas: int = 32,
    tick: float = 5.0,
    max_ticks: int = 2048,
    perturb: float = 0.1,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    segment_ticks: Optional[int] = None,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """On-device workload-size sweep: how do cost and makespan scale with
    the number of applications?  Candidate k activates the first
    ``app_counts[k]`` apps (later apps' tasks get arrival = ∞ and are
    excluded from the unfinished count); every candidate × replica pair
    rolls out in ONE device program with shared Monte-Carlo draws, so the
    cost-vs-#apps curve (the reference's ``num-apps`` experiment,
    ``alibaba/sim.py:199-230``) comes from one dispatch per policy arm
    instead of one OS process per (arm, count, trace).

    ``workload`` must carry the FULL app set; since DAG edges never cross
    applications, masked tasks can neither gate readiness nor bill
    egress.
    """
    counts = jnp.asarray(app_counts, jnp.int32)
    K, R = counts.shape[0], n_replicas
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail0.dtype
    ) if policy == "opportunistic" else None
    act = workload.app_of[None, :] < counts[:, None]  # [K, T]
    act_rows = jnp.repeat(act, R, axis=0)  # [B, T]
    arr_rows = jnp.where(
        act_rows, _tile_rows(arr, K), jnp.asarray(jnp.inf, avail0.dtype)
    )
    res = _run_rows(
        jnp.broadcast_to(avail0, (K * R,) + avail0.shape),
        _tile_rows(rt, K), arr_rows, _tile_rows(root_anchor, K),
        workload, topo, tick, max_ticks, segment_ticks,
        policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring,
        task_u=_tile_rows(task_u, K) if task_u is not None else None,
        active=act_rows,
        forms=forms, tick_order=tick_order,
    )
    return _reshape_rows(res, K, R)


# -- checkpoint / resume -----------------------------------------------------

