"""Checkpoint/resume and chunked variants of the ensemble rollout."""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from pivot_tpu.ops.kernels import DeviceTopology
from pivot_tpu.parallel.ensemble.bill import _finalize_batch
from pivot_tpu.parallel.ensemble.draws import (
    _make_fault_schedule,
    _opportunistic_uniforms,
    _pack_extras,
    _perturbations,
    _unpack_extras,
)
from pivot_tpu.parallel.ensemble.state import (
    _DONE,
    EnsembleWorkload,
    RolloutResult,
    RolloutState,
    _resolve_forms,
    _init_state,
)
from pivot_tpu.parallel.ensemble.tick import _rollout_segment

@functools.partial(
    jax.jit,
    static_argnames=(
        "tick", "policy", "congestion", "realtime_scoring", "forms",
        "tick_order",
    ),
)
def _segment_step(
    state: RolloutState,
    rt,  # [R, T] perturbed runtimes (constant for the run — computed once)
    arr,  # [R, T] perturbed arrivals
    root_anchor,  # [R, T] i32
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    segment_ticks,  # traced i32 scalar — the final partial segment must
    faults=None,  # optional ([R, F] i32, [R, F], [R, F]) crash schedules
    totals=None,  # [H, 4]
    policy: str = "cost-aware",
    task_u=None,  # [R, T] opportunistic uniforms
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: str = "vector",
    tick_order: str = "fifo",
) -> RolloutState:  # not trigger an XLA recompile of the whole rollout
    """One jitted, vmapped checkpoint segment (at most ``segment_ticks``)."""
    return _vmapped_segment(
        state, rt, arr, root_anchor, workload, topo, tick, segment_ticks,
        faults, totals, policy, task_u, congestion, realtime_scoring,
        forms, tick_order,
    )


def _vmapped_segment(
    state, rt, arr, root_anchor, workload, topo, tick, segment_ticks,
    faults, totals, policy, task_u, congestion, realtime_scoring, forms,
    tick_order,
) -> RolloutState:
    """The one vmapped segment body behind :func:`_segment_step` and
    :func:`_segment_step_carry` — the twins differ only in jit decoration
    (donation) and the carry's pending-flag reduction."""
    spec, extras = _pack_extras(faults, task_u)

    def seg(s, r, a, ra, *ex):
        f, u, _tot, _sp, _act, _rc = _unpack_extras(spec, ex)
        return _rollout_segment(
            s, r, a, ra, workload, topo, tick, segment_ticks,
            faults=f, totals=totals, policy=policy, task_u=u,
            congestion=congestion, realtime_scoring=realtime_scoring,
            forms=forms, tick_order=tick_order,
        )

    return jax.vmap(seg)(state, rt, arr, root_anchor, *extras)


@functools.partial(
    jax.jit,
    static_argnames=(
        "tick", "policy", "congestion", "realtime_scoring", "forms",
        "tick_order",
    ),
    donate_argnums=(0,),
)
def _segment_step_carry(
    state: RolloutState,
    rt,
    arr,
    root_anchor,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    tick: float,
    segment_ticks,
    faults=None,
    totals=None,
    policy: str = "cost-aware",
    task_u=None,
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: str = "vector",
    tick_order: str = "fifo",
):
    """:func:`_segment_step` with a **donated, device-resident carry**.

    Two differences from the plain step, both aimed at the per-segment
    host round-trip the segmented executor pays (RESULTS.md: 256-tick
    segments cost +14 % over one monolithic call — the toll is
    dispatch + state traffic, not compute):

      * ``donate_argnums=(0,)`` — the ``[R]``-stacked
        :class:`RolloutState` input buffers are donated to the output, so
        the carry stays device-resident across the whole rollout instead
        of holding two live copies per segment boundary (the tree has a
        [R, T] finish/stage/place/qpos set plus [R, H, 4] avail — the
        dominant live allocation at large R).  Callers must NOT reuse
        the passed state, and must never pass a buffer that aliases a
        non-donated argument (the segmented executors defensively copy
        the freshly-initialized state once, before the first call).
      * returns ``(state, pending)`` where ``pending`` is the scalar
        early-exit flag (any replica not DONE) computed on-device — the
        host inspects ONE scalar per segment boundary instead of pulling
        (or even readiness-checking) the full state tree.

    Same trajectory math as :func:`_segment_step` — a segment entered
    with nothing pending is a bit-exact no-op (the tick while_loop's
    condition fails at entry), which is what makes the speculative
    double-buffered pipeline in :func:`_run_segments_pipelined` safe.
    """
    out = _vmapped_segment(
        state, rt, arr, root_anchor, workload, topo, tick, segment_ticks,
        faults, totals, policy, task_u, congestion, realtime_scoring,
        forms, tick_order,
    )
    return out, jnp.any(out.stage != _DONE)


def _run_segments_pipelined(step, state, max_ticks: int, segment_ticks: int):
    """Drive a donated-carry segment step to the horizon, double-buffered.

    ``step(state, seg_i32) -> (state, pending)`` must donate its carry
    and be a bit-exact no-op when nothing is pending.  Segment k+1 is
    enqueued BEFORE segment k's early-exit flag is fetched, so the
    device never idles across a segment boundary waiting on the host's
    continue/stop decision; the flag fetch is one scalar, not the state
    tree.  When the flag says "done", the one speculative segment
    already in flight was a no-op, so the trailing state is identical to
    an unpipelined loop's — results are bit-identical at any
    ``segment_ticks`` (the ``rollout_checkpointed`` contract).

    The caller's ``state`` buffers are donated by the first call: pass a
    tree whose buffers nothing else aliases (copy freshly-initialized
    state — it can alias ``avail0``/``totals``, which ride every call).
    """
    ticks = 0
    flag = None
    while ticks < max_ticks:
        seg = min(segment_ticks, max_ticks - ticks)
        prev = flag
        state, flag = step(state, jnp.asarray(seg, jnp.int32))
        ticks += seg
        # Inspect segment k's flag only after k+1 is on the queue.
        if prev is not None and not bool(prev):
            break
    return state


def _fingerprint(
    key, n_replicas, tick, max_ticks, perturb, workload, topo, avail0,
    storage_zones, fault_cfg=(0, None, None), policy="cost-aware",
    congestion=False, realtime_scoring=False, tick_order="fifo",
    forms="indexed",
) -> str:
    """Hash of every input that determines the rollout trajectory —
    including array *contents*, so a checkpoint can never be resumed
    against edited workload data that merely kept its shapes."""
    import hashlib

    # "v2": the tick body's refund select-reduce (round-2 scatter purge)
    # sums in tree order — ULP-different from the old scatter order for
    # multiple same-host refunds — so checkpoints written by the old body
    # must restart, not resume into a mixed-order trajectory.
    # Normalize truthy non-bool congestion (1, np.True_) so the identity
    # check below agrees with the tick body's equality-based validation —
    # the trajectory is the same, so the fingerprint must be too.
    congestion = "pairs" if congestion == "pairs" else bool(congestion)
    base = ("v2", np.asarray(key).tolist(), n_replicas, tick, max_ticks,
            perturb)
    if policy != "cost-aware":
        # Appended only for non-default arms so cost-aware fingerprints
        # within a body version are unchanged by this field's existence.
        base = base + (policy,)
    if fault_cfg[0]:
        # Appended only for fault runs (same compat-within-version rule).
        base = base + (fault_cfg,)
    if congestion:
        # Appended only when the backlog model is on (same compat rule).
        # The host-pair rung is a different trajectory family, so it
        # fingerprints distinctly; plain True keeps the historical token.
        base = base + (
            ("congestion",) if congestion is True
            else (("congestion", congestion),)
        )
    if realtime_scoring:
        base = base + ("realtime_scoring",)
    if tick_order != "fifo":
        # Batch order changes actual placements, not just ULPs — a fifo
        # checkpoint resuming under lifo would be a mixed-order
        # trajectory (appended only for non-default order, same
        # compat-within-version rule as the fields above).
        base = base + (("tick_order", tick_order),)
        if policy == "first-fit":
            # Round-4 wait-reinsertion change: lifo first-fit now carries
            # the schedule-RETURN-order rank (the decreasing sort) as the
            # wait re-entry key instead of the batch rank — a different
            # trajectory for exactly this (policy, order) pair, so
            # pre-change checkpoints must restart, not resume mixed.
            base = base + (("qpos", "return-order"),)
    if forms != "indexed":
        # The tick-body forms are only *empirically* bit-identical (tree
        # vs sequential f32 pipe sums), so a vector-form checkpoint must
        # not silently resume under the indexed forms (e.g. a TPU-written
        # state moved to CPU, where the backend default flips).  The
        # sentinel is the fixed value "indexed" — NOT the backend default
        # — because a backend-relative rule would let a TPU default
        # (vector, unappended) match a CPU default (indexed, unappended),
        # exactly the cross-form resume being excluded.  Keying on
        # "indexed" also keeps every historical CPU-written checkpoint
        # (resolved indexed, unappended) resumable.
        base = base + (("forms", forms),)
    h = hashlib.sha256(repr(base).encode())
    for tree in (workload, topo, (avail0, storage_zones)):
        for arr in jax.tree_util.tree_leaves(tree):
            a = np.ascontiguousarray(np.asarray(arr))
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    return h.hexdigest()[:16]


def rollout_checkpointed(
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    checkpoint_path: Optional[str],
    n_replicas: int = 64,
    tick: float = 5.0,
    max_ticks: int = 512,
    perturb: float = 0.1,
    segment_ticks: int = 256,
    resume: bool = True,
    n_faults: int = 0,
    fault_horizon: Optional[float] = None,
    mttr: Optional[float] = None,
    policy: str = "cost-aware",
    congestion: bool = False,
    realtime_scoring: bool = False,
    forms: Optional[str] = None,
    tick_order: str = "fifo",
) -> RolloutResult:
    """:func:`rollout` with mid-flight checkpoint/resume.

    The rollout runs in jitted segments of ``segment_ticks``; after each
    segment the ``[R]``-stacked :class:`RolloutState` (pure arrays) is
    written atomically (tmp + rename) to ``checkpoint_path`` (``.npz``).
    The 256-tick default balances per-segment host round-trips against
    call duration (measured at the canonical 25-app × 256-replica
    scale: 64-tick segments cost +49 % over one monolithic call,
    256-tick +14 %, each call ~1.4 s); callers wanting a finer
    checkpoint cadence or shorter calls on a flaky transport pass a
    smaller ``segment_ticks`` — results are bit-identical at any value.
    If the process dies, rerunning with ``resume=True`` loads the last
    state and continues — the final result is bit-identical to an
    uninterrupted :func:`rollout` with the same arguments, because the
    Monte-Carlo draws are a pure function of ``key`` (regenerated, not
    stored) and segmentation does not change the tick sequence.

    ``checkpoint_path=None`` runs the same segmented schedule without
    touching disk — useful in its own right because each segment is one
    bounded device execution (a monolithic multi-thousand-tick while_loop
    is a minutes-long single execution, which remote-device transports
    may kill).

    A config fingerprint stored alongside the state refuses to resume a
    checkpoint produced by different arguments.  The reference has no
    analog: its runs are one-shot to event exhaustion
    (``alibaba/runner.py:44``), and its process state (generator frames)
    could not be serialized anyway.
    """
    import os

    workload.check_group_demands()
    forms = _resolve_forms(forms)

    fp = _fingerprint(
        key, n_replicas, tick, max_ticks, perturb, workload, topo, avail0,
        storage_zones, fault_cfg=(n_faults, fault_horizon, mttr),
        policy=policy, congestion=congestion,
        realtime_scoring=realtime_scoring, tick_order=tick_order,
        forms=forms,
    )

    ticks_done = 0
    state = None
    if checkpoint_path and resume and os.path.exists(checkpoint_path):
        with np.load(checkpoint_path, allow_pickle=False) as ckpt:
            fields = set(RolloutState._fields)
            if str(ckpt["fingerprint"]) == fp and fields <= set(ckpt.files):
                # A checkpoint missing state fields (written by an older
                # layout) is ignored rather than resumed partial — resume
                # must be bit-identical or not happen at all.
                state = RolloutState(
                    **{f: jnp.asarray(ckpt[f]) for f in RolloutState._fields}
                )
                ticks_done = int(ckpt["ticks_done"])
    if state is None:
        Z = topo.cost.shape[0]
        state = jax.vmap(
            lambda _: _init_state(avail0, workload.n_tasks, Z,
                                  congestion=congestion)
        )(jnp.arange(n_replicas))

    # Monte-Carlo draws are a pure function of ``key`` and constant for the
    # whole run: generated once here (and regenerated once on resume), not
    # per segment.
    rt, arr, root_anchor = _perturbations(
        key, workload, storage_zones, n_replicas, perturb, avail0.dtype
    )
    faults = None
    if n_faults:
        faults = _make_fault_schedule(
            key, n_replicas, n_faults, avail0, tick, max_ticks,
            fault_horizon, mttr,
        )
    task_u = _opportunistic_uniforms(
        key, n_replicas, workload.n_tasks, avail0.dtype
    ) if policy == "opportunistic" else None

    # Late-bound through the package so a test (or tool) that patches
    # ``pivot_tpu.parallel.ensemble._segment_step`` — the historical
    # monolith attribute — still intercepts the segment calls.  Imported
    # lazily: the package ``__init__`` imports this module, so a
    # module-level import the other way would be circular.
    from pivot_tpu.parallel import ensemble as _pkg

    if not checkpoint_path:
        # Pure segmented execution (no disk): the donated-carry,
        # double-buffered pipeline — state never round-trips to host,
        # each boundary costs one scalar flag fetch, and segment k+1 is
        # enqueued while k's flag is in flight.  The disk-checkpoint loop
        # below stays synchronous on purpose: it must materialize the
        # full state tree to host after every segment anyway.
        def step(s, seg):
            return _pkg._segment_step_carry(
                s, rt, arr, root_anchor, workload, topo, tick=tick,
                segment_ticks=seg, faults=faults, totals=avail0,
                policy=policy, task_u=task_u, congestion=congestion,
                realtime_scoring=realtime_scoring, forms=forms,
                tick_order=tick_order,
            )

        if max_ticks > 0 and bool(jnp.any(state.stage != _DONE)):
            # Copy once: the fresh state's buffers may alias avail0,
            # which also rides every call as ``totals`` — a donated
            # buffer must not double as a regular argument.
            state = _run_segments_pipelined(
                step, jax.tree_util.tree_map(jnp.copy, state),
                max_ticks, segment_ticks,
            )
        return _finalize_batch(state, workload, topo)

    while ticks_done < max_ticks and bool(jnp.any(state.stage != _DONE)):
        seg = min(segment_ticks, max_ticks - ticks_done)
        state = _pkg._segment_step(
            state,
            rt,
            arr,
            root_anchor,
            workload,
            topo,
            tick=tick,
            segment_ticks=jnp.asarray(seg, jnp.int32),
            faults=faults,
            totals=avail0,
            policy=policy,
            task_u=task_u,
            congestion=congestion,
            realtime_scoring=realtime_scoring,
            forms=forms,
            tick_order=tick_order,
        )
        jax.block_until_ready(state)
        ticks_done += seg
        if checkpoint_path:
            tmp = checkpoint_path + ".tmp.npz"  # np.savez keeps an .npz suffix
            np.savez(
                tmp,
                fingerprint=fp,
                ticks_done=ticks_done,
                **{f: np.asarray(v) for f, v in zip(RolloutState._fields, state)},
            )
            os.replace(tmp, checkpoint_path)

    return _finalize_batch(state, workload, topo)


def rollout_chunked(
    key,
    avail0,
    workload: EnsembleWorkload,
    topo: DeviceTopology,
    storage_zones,
    checkpoint_path: Optional[str],
    replica_chunk: int,
    n_replicas: int = 64,
    segment_ticks: int = 256,
    resume: bool = True,
    **kw,
) -> RolloutResult:
    """Ensemble rollout in replica chunks of ``replica_chunk``.

    Why chunk: bound the per-call working set and duration.  When the
    tick body still carried vmapped scatters, R=1024 went superlinear
    (scalar-memory scatter operands spilled; chunking at 512 measured
    1.65×).  After the segment-op purge removed those scatters the
    R-axis scales near-linearly (R=1024 ≈ 4.5× the R=256 wall) and
    chunking is ~neutral at bench scale (2,520 vs 2,475 rollouts/s) —
    it remains the pressure valve for replica counts beyond what HBM
    comfortably holds, and keeps each device call short on remote
    transports that kill long executions (RESULTS.md, round-2 scaling
    tables before/after the purge).

    Execution shape per chunk: WITHOUT a ``checkpoint_path``, each chunk
    is one monolithic :func:`rollout` call (routing chunks through the
    segmented executor pays per-segment host round-trips).  WITH a
    ``checkpoint_path``, each chunk runs segmented via
    :func:`rollout_checkpointed`, checkpointing (and resuming) at
    ``<root>.c<c><ext>``; finished chunks resume straight to finalize.

    Sample-set semantics: chunk 0 uses ``key`` verbatim — it is
    bit-identical to ``rollout(key, n_replicas=replica_chunk)``, so the
    replica-0 ⇔ DES anchor pairing (``_perturbations``) survives
    chunking.  Chunk ``c > 0`` draws from ``fold_in(key, c)``.  The
    combined set is therefore a *different* (equally i.i.d.) Monte-Carlo
    sample than one monolithic ``n_replicas`` draw — threefry counters
    pair by array halves, so a bitwise-prefix chunking cannot exist —
    which is why the CLI keeps chunking opt-in (``--replica-chunk``):
    existing seeded results stay bit-stable unless the caller asks.

    Deterministic: same ``key``/config/chunking → same results.
    ``replica_chunk <= 0`` (or ``>= n_replicas``) delegates to the
    unchunked segmented path unchanged.
    """
    import os

    if replica_chunk <= 0 or n_replicas <= replica_chunk:
        return rollout_checkpointed(
            key, avail0, workload, topo, storage_zones, checkpoint_path,
            n_replicas=n_replicas, segment_ticks=segment_ticks,
            resume=resume, **kw,
        )
    root, ext = os.path.splitext(checkpoint_path) if checkpoint_path else ("", "")
    parts = []
    done = 0
    while done < n_replicas:
        c = len(parts)
        n = min(replica_chunk, n_replicas - done)
        ck = key if c == 0 else jax.random.fold_in(key, c)
        if checkpoint_path:
            parts.append(
                rollout_checkpointed(
                    ck, avail0, workload, topo, storage_zones,
                    f"{root}.c{c}{ext}", n_replicas=n,
                    segment_ticks=segment_ticks, resume=resume, **kw,
                )
            )
        else:
            # Lazy: ``rollout`` lives in the package ``__init__``, which
            # imports this module (see the ``_segment_step`` note above).
            from pivot_tpu.parallel import ensemble as _pkg

            parts.append(
                _pkg.rollout(
                    ck, avail0, workload, topo, storage_zones,
                    n_replicas=n, **kw,
                )
            )
        done += n
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *parts)
