"""obs-boundary lint: the observability plane's hot-path/determinism pins.

Round 14's tentpole (``pivot_tpu/obs``) makes two structural promises
that are trivially easy to erode one convenient line at a time:

  * **no instrumentation inside the device layer** — trace events are
    emitted at dispatch *boundaries* only.  A tracer hook inside a
    jitted/Pallas body would either trace once and record nothing (the
    call happens at trace time, not run time) or force a host sync per
    iteration — both silent lies.  Enforced two ways: the device-layer
    files (``pivot_tpu/ops/``) may not import ``pivot_tpu.obs`` (or
    the ``utils.trace`` shim) at all, and the host-sync pass's
    auto-discovered hot bodies (:data:`pivot_tpu.analysis.hostsync
    .DISCOVER` — the registration the obs hooks share) may not call a
    tracer recording method (``tracer.emit`` / ``.stage`` / ``.span``
    / ``.wall_span`` / ``.record_span`` / ``.mark``);
  * **wall capture lives inside ``obs/``** — hooks in the
    determinism-scoped modules (:data:`pivot_tpu.analysis.determinism
    .SCOPE`) pass sim-time payloads and let the tracer stamp the wall
    side.  The determinism pass already bans literal ``time.*`` reads
    there; this pass closes the obs-shaped loophole — constructing an
    :class:`~pivot_tpu.obs.clock.ObsClock` or calling ``clock.now()``
    / ``clock.elapsed()`` in scope is the same wall read wearing a
    new name.

Calling a *tracer* from a determinism-scoped module is fine (that is
the designed boundary: ``sched/batch.py`` wraps its flush in
``tracer.wall_span``); owning a *clock* there is not.
"""

from __future__ import annotations

import ast
import os
from typing import List, Tuple

from pivot_tpu.analysis import Finding, SourceFile
from pivot_tpu.analysis import determinism as _determinism
from pivot_tpu.analysis import hostsync as _hostsync

RULE = "obs-boundary"

#: Tracer recording methods banned inside discovered hot bodies.
_TRACER_METHODS = {
    "emit", "stage", "span", "wall_span", "record_span", "mark",
}

#: Wall-clock methods banned on a ``clock``-named base in determinism
#: scope (the ObsClock surface).
_CLOCK_METHODS = {"now", "elapsed"}


def _is_obs_import(node: ast.AST) -> Tuple[bool, str]:
    """Any spelling that brings the obs package (or its ``utils.trace``
    shim) into scope — dotted imports, aliased imports, and the
    ``from pivot_tpu import obs`` / ``from pivot_tpu.utils import
    trace`` package-member forms (the bypasses a prefix-only check
    missed, review round 14)."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name.startswith("pivot_tpu.obs") or (
                alias.name == "pivot_tpu.utils.trace"
            ):
                return True, alias.name
    elif isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        names = {alias.name for alias in node.names}
        if mod.startswith("pivot_tpu.obs") or mod == "pivot_tpu.utils.trace":
            return True, mod
        if mod == "pivot_tpu" and "obs" in names:
            return True, "pivot_tpu.obs"
        if mod == "pivot_tpu.utils" and "trace" in names:
            return True, "pivot_tpu.utils.trace"
    return False, ""


def _base_is(node: ast.AST, name: str) -> bool:
    """True when an attribute chain ends in ``<...>.name`` or ``name``."""
    if isinstance(node, ast.Name):
        return node.id == name
    if isinstance(node, ast.Attribute):
        return node.attr == name
    return False


def _scan_ops_file(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        hit, mod = _is_obs_import(node)
        if hit:
            out.append(Finding(
                RULE, src.path, node.lineno,
                f"device-layer module imports {mod} — instrumentation "
                "belongs at dispatch boundaries (sched/serve), never "
                "inside the jitted/Pallas layer",
            ))
    return out


def _scan_hot_bodies(src: SourceFile, names: List[str]) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in names
        ):
            continue
        for sub in ast.walk(node):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _TRACER_METHODS
                and _base_is(sub.func.value, "tracer")
            ):
                continue
            out.append(Finding(
                RULE, src.path, sub.lineno,
                f"tracer hook .{sub.func.attr}() inside hot-path body "
                f"{node.name}() — events are emitted at dispatch "
                "boundaries only (a hook here traces once and lies, "
                "or host-syncs per iteration)",
            ))
    return out


def _scan_determinism_file(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            # `import pivot_tpu.obs.clock [as oc]` — the aliased form
            # would make every later `oc.ObsClock()` invisible to the
            # call checks below, so the import itself is the finding
            # (the determinism pass hardened against exactly this
            # evasion class in round 12).
            for alias in node.names:
                if alias.name.startswith("pivot_tpu.obs.clock"):
                    out.append(Finding(
                        RULE, src.path, node.lineno,
                        f"`import {alias.name}` in a determinism-"
                        "scoped module — the obs wall clock may not "
                        "live here (hooks pass sim-time payloads; the "
                        "tracer stamps the wall side)",
                    ))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            names = {alias.name for alias in node.names}
            if (
                mod == "pivot_tpu.obs.clock"
                or (mod.startswith("pivot_tpu.obs") and "ObsClock" in names)
                or (mod == "pivot_tpu.obs" and "clock" in names)
            ):
                out.append(Finding(
                    RULE, src.path, node.lineno,
                    "ObsClock import in a determinism-scoped module — "
                    "wall capture lives inside pivot_tpu/obs; hooks "
                    "here pass sim-time payloads only",
                ))
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Name) and f.id == "ObsClock"
            ) or (
                isinstance(f, ast.Attribute) and f.attr == "ObsClock"
            ):
                out.append(Finding(
                    RULE, src.path, node.lineno,
                    "ObsClock() constructed in a determinism-scoped "
                    "module — the obs wall clock may not live here",
                ))
            elif (
                isinstance(f, ast.Attribute)
                and f.attr in _CLOCK_METHODS
                and _base_is(f.value, "clock")
            ):
                out.append(Finding(
                    RULE, src.path, node.lineno,
                    f"wall read clock.{f.attr}() in a determinism-"
                    "scoped module — the obs clock is a wall clock "
                    "wearing a new name; emit sim-time payloads and "
                    "let the tracer stamp the wall side",
                ))
    return out


def collect(cache) -> Tuple[List[Finding], List[str]]:
    out: List[Finding] = []
    scanned: List[str] = []

    # 1) Device layer: no obs imports anywhere under pivot_tpu/ops/.
    ops_dir = os.path.join(cache.root, "pivot_tpu/ops")
    if os.path.isdir(ops_dir):
        for name in sorted(os.listdir(ops_dir)):
            if not name.endswith(".py"):
                continue
            rel = f"pivot_tpu/ops/{name}"
            src = cache.get(rel)
            if src is None:
                continue
            scanned.append(rel)
            out.extend(_scan_ops_file(src))

    # 2) Hot bodies: reuse the host-sync pass's discovery so the obs
    # hooks are registered with the SAME body set — a new hot body is
    # covered by both passes the moment hostsync discovers it.
    for rel, patterns in _hostsync.DISCOVER.items():
        src = cache.get(rel)
        if src is None:
            continue  # hostsync itself reports the missing file
        if rel not in scanned:
            scanned.append(rel)
        names = _hostsync.discover_targets(src, patterns)
        out.extend(_scan_hot_bodies(src, names))

    # 3) Determinism scope: no obs wall clock (sim-time payloads only).
    for rel in _determinism._scope_files(cache.root):
        src = cache.get(rel)
        if src is None:
            continue
        if rel not in scanned:
            scanned.append(rel)
        out.extend(_scan_determinism_file(src))

    return out, scanned
