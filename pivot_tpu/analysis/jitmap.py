"""Shared jit-site discovery for the jitcheck passes.

The ``retrace`` and ``donation`` passes both reason about *jitted entry
points*: the ~30 places a Python function crosses into XLA (``jax.jit``
as a decorator, a module-level ``name = jax.jit(impl, ...)`` wrapper, or
a ``return jax.jit(...)`` inside a cached factory).  This module is the
one resolver both passes share, so "what counts as a jitted entry
point" — and which file hosts one — can never drift between them.

Recognized wrapping shapes (everything the repo actually uses):

  * ``@jax.jit`` / ``@functools.partial(jax.jit, static_argnames=...,
    donate_argnums=...)`` on a ``def``;
  * ``name = jax.jit(impl, static_argnames=..., donate_argnums=...)``
    at module level, with ``impl`` a module-level ``def`` or ``lambda``;
  * ``jax.jit(X, ...)`` inside a factory function (the lru-cached
    shard_map wrappers), where ``X`` unwraps through ``jax.vmap(f)``,
    ``_shard_map(f, ...)``, or ``functools.partial(f, **bound)`` to a
    local or module-level ``def``.  ``functools.partial`` keyword names
    count as *static* (they are bound at trace time, exactly like
    ``static_argnames``).

:data:`JIT_FILES` is the registry of files allowed to contain jitted
entry points.  Discovery sweeps the whole package for ``jax.jit``
occurrences, so a NEW file acquiring a jit wrapper is a finding ("add
it to the registry") instead of a silent coverage gap — the same
register-or-flag discipline as the parity manifest and the hostsync
DISCOVER map.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Tuple

from pivot_tpu.analysis import Finding, SourceFile

#: Files registered to contain jitted entry points.  Adding a jit
#: wrapper to any other pivot_tpu file fails the sweep below until the
#: file is registered here (and thereby scanned by retrace/donation).
JIT_FILES: Tuple[str, ...] = (
    "pivot_tpu/ops/kernels.py",
    "pivot_tpu/ops/tickloop.py",
    "pivot_tpu/ops/shard.py",
    "pivot_tpu/ops/pallas_kernels.py",
    "pivot_tpu/sched/tpu.py",
    "pivot_tpu/sched/batch.py",
    "pivot_tpu/obs/profiler.py",
    "pivot_tpu/parallel/ensemble/__init__.py",
    "pivot_tpu/parallel/ensemble/checkpoint.py",
    "pivot_tpu/parallel/ensemble/sweeps.py",
    "pivot_tpu/parallel/ensemble/bill.py",
    "pivot_tpu/search/fitness.py",
)

#: Package subtree swept for unregistered ``jax.jit`` usage.
_SWEEP_ROOT = "pivot_tpu"


class JitSite(NamedTuple):
    """One jitted entry point, resolved as far as the AST allows."""

    path: str                      # repo-relative file
    name: str                      # public handle (wrapper/factory name)
    lineno: int                    # line of the jax.jit call
    fn: Optional[ast.AST]          # wrapped FunctionDef/Lambda (or None)
    static_names: Tuple[str, ...]  # trace-time-constant parameter names
    donate_params: Tuple[str, ...]  # donated parameter names (resolved)
    donate_nums: Tuple[int, ...]   # raw donate_argnums
    stale_statics: Tuple[str, ...]  # static names matching no parameter


def _is_jax_jit(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


def _is_partial(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "partial" and isinstance(
            node.value, ast.Name
        ) and node.value.id == "functools"
    return isinstance(node, ast.Name) and node.id == "partial"


def _const_strings(node: Optional[ast.AST]) -> Tuple[str, ...]:
    """String constants of a name-tuple keyword (``static_argnames``)."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _const_ints(node: Optional[ast.AST]) -> Tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def positional_params(fn: ast.AST) -> List[str]:
    """Positional parameter names of a ``def``/``lambda`` (no varargs)."""
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def all_params(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


class _Resolver:
    """Name → function-def resolution: module level plus the locals of
    the factory function enclosing the jit call."""

    def __init__(self, tree: ast.Module):
        self.module: Dict[str, ast.AST] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module[node.name] = node
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Lambda
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module[tgt.id] = node.value

    def resolve(self, name: str, scope: Optional[ast.AST]) -> Optional[ast.AST]:
        if scope is not None:
            for node in ast.walk(scope):
                if (
                    isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == name
                ):
                    return node
        return self.module.get(name)


def _unwrap(node: ast.AST, resolver: _Resolver, scope) -> Tuple[
    Optional[ast.AST], Tuple[str, ...]
]:
    """Resolve a jit operand to the wrapped function, collecting the
    static names ``functools.partial`` binds along the way."""
    bound: Tuple[str, ...] = ()
    if isinstance(node, ast.Lambda):
        return node, bound
    if isinstance(node, ast.Name):
        return resolver.resolve(node.id, scope), bound
    if isinstance(node, ast.Call):
        f = node.func
        if _is_partial(f):
            bound = tuple(
                kw.arg for kw in node.keywords if kw.arg is not None
            )
            if node.args:
                inner, more = _unwrap(node.args[0], resolver, scope)
                return inner, bound + more
            return None, bound
        # jax.vmap(f) / _shard_map(f, ...) / any wrapper(f, ...): the
        # first positional argument is the wrapped callable.
        if node.args:
            return _unwrap(node.args[0], resolver, scope)
    return None, bound


def _site_name(call: ast.Call, parents: Dict[int, ast.AST]) -> Tuple[str, int]:
    """Public handle for a jit call: the assignment target, the
    decorated def, or the enclosing factory function."""
    node: ast.AST = call
    while id(node) in parents:
        parent = parents[id(node)]
        if isinstance(parent, ast.Assign):
            for tgt in parent.targets:
                if isinstance(tgt, ast.Name):
                    return tgt.id, call.lineno
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return parent.name, call.lineno
        node = parent
    return "<module>", call.lineno


def _build_site(
    path: str,
    name: str,
    lineno: int,
    fn: Optional[ast.AST],
    keywords: List[ast.keyword],
    partial_bound: Tuple[str, ...],
) -> JitSite:
    kw = {k.arg: k.value for k in keywords if k.arg is not None}
    static = _const_strings(kw.get("static_argnames")) + partial_bound
    static_nums = _const_ints(kw.get("static_argnums"))
    donate_nums = _const_ints(kw.get("donate_argnums"))
    donate_names = _const_strings(kw.get("donate_argnames"))
    stale: Tuple[str, ...] = ()
    donate_params = donate_names
    if fn is not None:
        pos = positional_params(fn)
        names = set(all_params(fn))
        stale = tuple(
            s for s in _const_strings(kw.get("static_argnames"))
            if s not in names
        ) + tuple(
            # An out-of-range static_argnums index is the same rot as a
            # stale static name: the knob it used to pin is gone and
            # something else is silently traced.
            f"static_argnums[{i}]" for i in static_nums
            if not 0 <= i < len(pos)
        )
        static = static + tuple(
            pos[i] for i in static_nums if 0 <= i < len(pos)
        )
        donate_params = donate_params + tuple(
            pos[i] for i in donate_nums if 0 <= i < len(pos)
        )
    return JitSite(
        path, name, lineno, fn, tuple(dict.fromkeys(static)),
        tuple(dict.fromkeys(donate_params)), donate_nums, stale,
    )


def sites_in(src: SourceFile) -> List[JitSite]:
    """Every jitted entry point of one parsed file."""
    resolver = _Resolver(src.tree)
    parents: Dict[int, ast.AST] = {}
    enclosing: Dict[int, Optional[ast.AST]] = {}

    def index(node: ast.AST, scope: Optional[ast.AST]):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
            enclosing[id(child)] = scope
            index(
                child,
                child if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) else scope,
            )

    index(src.tree, None)
    out: List[JitSite] = []
    seen_calls: set = set()

    # Decorated defs first: the decorator list owns the jit call there.
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if _is_jax_jit(dec):
                out.append(_build_site(
                    src.path, node.name, node.lineno, node, [], ()
                ))
            elif isinstance(dec, ast.Call) and _is_partial(dec.func):
                if dec.args and _is_jax_jit(dec.args[0]):
                    seen_calls.add(id(dec))
                    out.append(_build_site(
                        src.path, node.name, node.lineno, node,
                        dec.keywords, (),
                    ))

    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call) and _is_jax_jit(node.func)):
            continue
        if id(node) in seen_calls or not node.args:
            continue
        scope = enclosing.get(id(node))
        fn, partial_bound = _unwrap(node.args[0], resolver, scope)
        name, lineno = _site_name(node, parents)
        out.append(_build_site(
            src.path, name, lineno, fn, node.keywords, partial_bound
        ))
    return out


def _sweep_unregistered(cache) -> Tuple[List[Finding], List[str]]:
    """Package files with ``jax.jit`` usage outside :data:`JIT_FILES`."""
    out: List[Finding] = []
    swept: List[str] = []
    root = os.path.join(cache.root, _SWEEP_ROOT)
    if not os.path.isdir(root):
        return out, swept
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fname), cache.root)
            if rel in JIT_FILES or rel.startswith("pivot_tpu/analysis"):
                continue
            src = cache.get(rel)
            if src is None or "jax.jit" not in src.text:
                continue
            swept.append(rel)
            if sites_in(src):
                out.append(Finding(
                    "retrace", rel, 1,
                    "jitted entry point in a file the jitcheck passes do "
                    f"not cover — add {rel} to pivot_tpu/analysis/"
                    "jitmap.py JIT_FILES so retrace/donation scan it",
                ))
    return out, swept


def collect_sites(cache) -> Tuple[
    Dict[str, List[JitSite]], List[Finding], List[str]
]:
    """All jit sites per registered file, plus registry findings
    (missing registered file, unregistered file hosting a jit site) and
    the scanned-file list for suppression processing."""
    findings: List[Finding] = []
    scanned: List[str] = []
    sites: Dict[str, List[JitSite]] = {}
    for rel in JIT_FILES:
        src = cache.get(rel)
        if src is None:
            findings.append(Finding(
                "retrace", rel, 0,
                f"registered jit file {rel} is missing — renamed/deleted? "
                "update pivot_tpu/analysis/jitmap.py JIT_FILES (its entry "
                "points lost all jitcheck coverage)",
            ))
            continue
        scanned.append(rel)
        sites[rel] = sites_in(src)
    sweep_findings, swept = _sweep_unregistered(cache)
    findings.extend(sweep_findings)
    scanned.extend(swept)
    return sites, findings, scanned
