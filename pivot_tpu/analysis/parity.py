"""Backend feature-parity matrix: every knob reaches every kernel form.

Each placement policy is one *family* served by several backend forms —
the reference scan oracle (``*_kernel_ref``), the two-phase form
(``*_impl``), the host-sharded twin (``*_kernel_sharded``), and for
cost-aware the Pallas kernels — plus the span-driver family
(``fused_tick_run`` / ``reference_tick_run`` / ``sharded_fused_tick_run``)
and the ``sched/tpu.py`` routing layer that forwards the knobs.  A
scheduling knob that reaches some forms but not others is a silent
parity break: the affected form keeps compiling and keeps passing every
test that doesn't exercise that knob on that form.  PR 9 threaded
``risk``/``cost_stack`` through seven forms by hand; this pass turns
the eighth such exercise into a static failure.

Four checks:

1. **Signature matrix** — per family, the knob set (parameter names
   intersected with :data:`KNOBS` / :data:`SPAN_KNOBS`) must be equal
   across forms, modulo each form's *declared* exemptions in
   :data:`MANIFEST` (e.g. the Pallas kernel has no ``totals``/``phase2``
   — it has no speculation to steer — and the scan oracles ARE the scan
   mode, so ``phase2`` would be dead weight).  An exemption is a
   documented decision; an undeclared gap is a finding.
2. **Auto-discovery** — form names are *discovered* from naming
   conventions (``<stem>_kernel_ref`` / ``<stem>_impl`` /
   ``<stem>_kernel_sharded`` / ``<stem>_pallas[_batched]`` /
   ``*tick_run``) in the declared files, so a NEW backend form shows up
   as "unregistered form: add it to the manifest" instead of silently
   escaping the matrix.  A manifest entry whose function vanished is
   flagged too (renames cannot drop coverage).
3. **Routing** — ``sched/tpu.py``'s ``_device_place`` methods must
   forward every routing-layer knob (:data:`ROUTING_KNOBS` ∩ the
   family's knob union) of the kernels they reference: explicit keyword
   arguments and dict-key staging (``kw["live"] = …`` then ``**kw``)
   both count.  The span route (``place_span`` + the ``_span_kw`` /
   ``_span_market_kw`` builders) must stage :data:`SPAN_ROUTING_KNOBS`.
4. **Ragged axis coverage** (round 18) — the ragged repack's axis
   tables (``tickloop.RAGGED_AXES`` ∪ ``RAGGED_INVARIANT``) must
   partition *exactly* the span family's array knobs (the keyword-only
   ``fused_tick_run`` parameters defaulting to None).  An array knob
   added to the span driver but absent from both tables would be
   silently dropped from the coalescing key AND left unpadded by
   ``ragged_span_pad`` — a shape error at best, a wrong-merge at
   worst; an overlap would pad an operand twice.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from pivot_tpu.analysis import Finding, SourceFile

RULE = "backend-parity"

#: Knobs tracked for the per-tick kernel families (parameter names).
KNOBS = frozenset({
    "live", "risk", "totals", "phase2", "strict", "uniforms",
    "bin_pack", "sort_hosts", "host_decay", "rt_bw_rows", "rt_bw_idx",
    "score_exp",
})

#: Knobs tracked for the span-driver family.
SPAN_KNOBS = frozenset({
    "uniforms", "sort_norm", "anchor_zone", "bucket_id", "totals",
    "live", "risk_rows", "cost_stack", "cost_seg", "strict",
    "decreasing", "bin_pack", "sort_tasks", "sort_hosts", "host_decay",
    "phase2", "score_exp",
})

_KERNELS = "pivot_tpu/ops/kernels.py"
_PALLAS = "pivot_tpu/ops/pallas_kernels.py"
_SHARD = "pivot_tpu/ops/shard.py"
_TICKLOOP = "pivot_tpu/ops/tickloop.py"
_ROUTING_FILE = "pivot_tpu/sched/tpu.py"

#: The scan oracles have no two-phase machinery: ``phase2``/``totals``
#: would be dead parameters on the reference form.
_REF_EXEMPT = frozenset({"phase2", "totals"})
#: The Pallas kernels keep the whole tick in VMEM — no speculation
#: (``totals``/``phase2``) and no live-bandwidth rows (per-tick host
#: state a persistent kernel cannot hold).  Learned score exponents are
#: also out (the tile algebra hard-codes the reference shape);
#: ``sched/tpu.py`` rejects ``use_pallas`` with non-default exponents.
_PALLAS_EXEMPT = frozenset({
    "phase2", "totals", "rt_bw_rows", "rt_bw_idx", "score_exp",
})
#: The sharded twins have not been threaded for learned exponents —
#: ``enable_sharding`` rejects non-default ``score_exponents()`` at the
#: policy layer (sched/tpu.py), so the gap is a declared decision, not
#: a silent parity break.
_SHARD_EXEMPT = frozenset({"score_exp"})

#: family stem → {form name: (repo-relative file, exempt knobs)}.
#: Registering a form here is a statement that its knob set matches the
#: family union minus the listed, justified exemptions.
MANIFEST: Dict[str, Dict[str, Tuple[str, FrozenSet[str]]]] = {
    "opportunistic": {
        "opportunistic_kernel_ref": (_KERNELS, _REF_EXEMPT),
        "opportunistic_impl": (_KERNELS, frozenset()),
        "opportunistic_kernel_sharded": (_SHARD, frozenset()),
        "opportunistic_kernel_sharded_batched": (_SHARD, frozenset()),
    },
    "first_fit": {
        "first_fit_kernel_ref": (_KERNELS, _REF_EXEMPT),
        "first_fit_impl": (_KERNELS, frozenset()),
        "first_fit_kernel_sharded": (_SHARD, frozenset()),
        "first_fit_kernel_sharded_batched": (_SHARD, frozenset()),
    },
    "best_fit": {
        "best_fit_kernel_ref": (_KERNELS, _REF_EXEMPT),
        "best_fit_impl": (_KERNELS, frozenset()),
        "best_fit_kernel_sharded": (_SHARD, frozenset()),
        "best_fit_kernel_sharded_batched": (_SHARD, frozenset()),
    },
    "cost_aware": {
        "cost_aware_kernel_ref": (_KERNELS, _REF_EXEMPT),
        "cost_aware_impl": (_KERNELS, frozenset()),
        "cost_aware_kernel_sharded": (_SHARD, _SHARD_EXEMPT),
        "cost_aware_kernel_sharded_batched": (_SHARD, _SHARD_EXEMPT),
        "cost_aware_pallas": (_PALLAS, _PALLAS_EXEMPT),
        "cost_aware_pallas_batched": (_PALLAS, _PALLAS_EXEMPT),
    },
}

#: Span-driver family: one knob contract across the fused driver, the
#: sequential referee, the host-sharded twin, and the round-17
#: [G]-batched 2-D form.
#: The resident span forms (round 20) carry ``live`` INSIDE the donated
#: carry (edited via sparse ``edit_live`` rows, never re-staged) and
#: replace the host-rendered ``risk_rows`` [K, H] with a once-staged
#: ``risk_table`` [P, H] gathered by a per-span ``risk_seg`` [K] row —
#: the knobs are absent because their STATE moved device-side, not
#: because the feature is unreachable (tests/test_resident.py pins
#: live/risk parity against the re-staged driver).
_RESIDENT_EXEMPT = frozenset({"live", "risk_rows"})

#: Elastic mesh serving (round 22) registers NO new span forms here:
#: shrink/regrow re-instantiates the ``sharded_*`` entries below on a
#: smaller/larger mesh from the divisor ladder
#: (``ops.shard.mesh_shape_ladder``), so every rung is covered by the
#: existing rows — the one-knob contract holds per rung for free.  The
#: ``elastic_*`` re-layout helpers are host-side numpy (reshard
#: boundary, not a device program) and are intentionally invisible to
#: the discovery patterns.
SPAN_MANIFEST: Dict[str, Tuple[str, FrozenSet[str]]] = {
    "fused_tick_run": (_TICKLOOP, frozenset()),
    "reference_tick_run": (_TICKLOOP, frozenset()),
    "sharded_fused_tick_run": (_SHARD, _SHARD_EXEMPT),
    "sharded_batched_tick_run": (_SHARD, _SHARD_EXEMPT),
    "resident_span_run": (_TICKLOOP, _RESIDENT_EXEMPT),
    "sharded_resident_span_run": (
        _SHARD, _RESIDENT_EXEMPT | _SHARD_EXEMPT,
    ),
}

#: Knobs the routing layer must forward per family (∩ the family's
#: actual knob union — a family without ``totals`` isn't required to
#: route it).
ROUTING_KNOBS = frozenset({"live", "risk", "totals", "phase2", "score_exp"})
#: Market/quarantine operands ``place_span``/``_span_kw``/
#: ``_span_market_kw`` must stage for the span drivers.
SPAN_ROUTING_KNOBS = frozenset({
    "live", "risk_rows", "cost_stack", "cost_seg", "score_exp",
})
_SPAN_ROUTING_FUNCS = ("place_span", "_span_kw", "_span_market_kw")

#: Jitted wrappers the routing layer references for each family.
_FORM_ALIASES: Dict[str, str] = {
    "opportunistic_kernel": "opportunistic",
    "first_fit_kernel": "first_fit",
    "best_fit_kernel": "best_fit",
    "cost_aware_kernel": "cost_aware",
}

#: Discovery patterns: (regex with a ``stem`` group, form label).  Any
#: public top-level function matching one of these in a manifest file
#: is a backend form and must be registered.
_DISCOVER = (
    (re.compile(r"^(?P<stem>[a-z]\w*)_kernel_ref$"), "kernel_ref"),
    (re.compile(r"^(?P<stem>[a-z]\w*)_impl$"), "impl"),
    (re.compile(r"^(?P<stem>[a-z]\w*)_kernel_sharded$"), "kernel_sharded"),
    (re.compile(r"^(?P<stem>[a-z]\w*)_kernel_sharded_batched$"),
     "kernel_sharded_batched"),
    (re.compile(r"^(?P<stem>[a-z]\w*)_pallas(_batched)?$"), "pallas"),
)
_DISCOVER_SPAN = re.compile(r"^[a-z]\w*tick_run$")


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    a = fn.args
    return {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}


def _top_level_functions(src: SourceFile) -> Dict[str, ast.FunctionDef]:
    return {
        node.name: node
        for node in src.tree.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _matrix_findings(
    family: str,
    forms: Dict[str, Tuple[str, FrozenSet[str]]],
    knob_universe: FrozenSet[str],
    funcs_by_file: Dict[str, Dict[str, ast.FunctionDef]],
) -> Tuple[List[Finding], Dict[str, Set[str]]]:
    """Signature-matrix check for one family.  Returns findings plus
    each found form's knob set (the routing check reuses the union)."""
    out: List[Finding] = []
    knob_sets: Dict[str, Set[str]] = {}
    lines: Dict[str, Tuple[str, int]] = {}
    for name, (rel, _exempt) in forms.items():
        funcs = funcs_by_file.get(rel)
        if funcs is None:
            continue  # file absent from this tree: nothing to check
        fn = funcs.get(name)
        if fn is None:
            out.append(Finding(
                RULE, rel, 1,
                f"registered backend form {name}() of family "
                f"{family!r} not found — update the parity manifest "
                "after renames",
            ))
            continue
        knob_sets[name] = _param_names(fn) & knob_universe
        lines[name] = (rel, fn.lineno)
    if not knob_sets:
        return out, knob_sets
    union: Set[str] = set().union(*knob_sets.values())
    for name, knobs in knob_sets.items():
        rel, lineno = lines[name]
        missing = union - knobs - forms[name][1]
        if missing:
            out.append(Finding(
                RULE, rel, lineno,
                f"{name}() is missing family {family!r} knob(s) "
                f"{sorted(missing)} — every backend form must accept "
                "every family knob (or declare an exemption in the "
                "manifest with a justification)",
            ))
    return out, knob_sets


def _discovery_findings(
    funcs_by_file: Dict[str, Dict[str, ast.FunctionDef]],
) -> List[Finding]:
    registered = {
        name for forms in MANIFEST.values() for name in forms
    } | set(SPAN_MANIFEST)
    out: List[Finding] = []
    for rel, funcs in funcs_by_file.items():
        for name, fn in funcs.items():
            if name.startswith("_") or name in registered:
                continue
            hit = any(pat.match(name) for pat, _ in _DISCOVER)
            if not hit:
                hit = bool(_DISCOVER_SPAN.match(name))
            if hit:
                out.append(Finding(
                    RULE, rel, fn.lineno,
                    f"unregistered backend form {name}() — a new kernel/"
                    "span form must join the parity manifest "
                    "(pivot_tpu/analysis/parity.py) so the knob matrix "
                    "covers it",
                ))
    return out


# ---------------------------------------------------------------------------
# Routing-layer check
# ---------------------------------------------------------------------------

def _forwarded_names(fn: ast.AST) -> Set[str]:
    """Every keyword-ish name a function can forward to a kernel call:
    explicit call keywords, dict-literal string keys, ``dict(...)``
    keywords, and ``kw["name"] = ...`` subscript staging."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg is not None:
                    out.add(kw.arg)
        elif isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    out.add(key.value)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)
                ):
                    out.add(tgt.slice.value)
    return out


def _referenced_families(fn: ast.AST) -> Set[str]:
    members = dict(_FORM_ALIASES)
    for family, forms in MANIFEST.items():
        for name in forms:
            members[name] = family
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in members:
            out.add(members[node.id])
    return out


def _routing_findings(
    src: SourceFile, family_unions: Dict[str, Set[str]]
) -> List[Finding]:
    out: List[Finding] = []
    span_vocab: Set[str] = set()
    span_seen = False
    references_span = False
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if (
                    isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and item.name == "_device_place"
                ):
                    vocab = _forwarded_names(item)
                    for family in sorted(_referenced_families(item)):
                        required = ROUTING_KNOBS & family_unions.get(
                            family, set()
                        )
                        missing = required - vocab
                        if missing:
                            out.append(Finding(
                                RULE, src.path, item.lineno,
                                f"{node.name}._device_place does not "
                                f"forward knob(s) {sorted(missing)} to "
                                f"the {family!r} kernels — the routing "
                                "layer must thread every routing knob",
                            ))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in _SPAN_ROUTING_FUNCS:
                span_seen = True
                span_vocab |= _forwarded_names(node)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id in SPAN_MANIFEST:
                        references_span = True
    if span_seen and references_span:
        missing = SPAN_ROUTING_KNOBS - span_vocab
        if missing:
            out.append(Finding(
                RULE, src.path, 1,
                f"the span route ({'/'.join(_SPAN_ROUTING_FUNCS)}) never "
                f"stages span knob(s) {sorted(missing)} for the fused "
                "tick drivers",
            ))
    return out


# ---------------------------------------------------------------------------
# Pass entry point
# ---------------------------------------------------------------------------

#: Directory swept for backend forms living in files the manifest does
#: not know yet — every recent backend PR introduced its forms in a NEW
#: file (tickloop.py, pallas_kernels.py, shard.py), so discovery must
#: not be limited to already-registered files.
_OPS_DIR = "pivot_tpu/ops"


def _set_literal_names(node: ast.AST) -> Optional[Set[str]]:
    """String elements of a ``{...}`` / ``frozenset({...})`` literal, or
    None when the node is not one (the check then reports it rather than
    guessing)."""
    if isinstance(node, ast.Call) and len(node.args) == 1:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "frozenset":
            node = node.args[0]
    if isinstance(node, ast.Set):
        elts = node.elts
    elif isinstance(node, ast.Dict):
        elts = [k for k in node.keys if k is not None]
    else:
        return None
    out: Set[str] = set()
    for e in elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.add(e.value)
    return out


def _ragged_findings(
    funcs_by_file: Dict[str, Dict[str, ast.FunctionDef]],
    tickloop_src: Optional[SourceFile],
) -> List[Finding]:
    """Check 4: RAGGED_AXES ∪ RAGGED_INVARIANT partitions the span
    family's array knobs (kwonly ``fused_tick_run`` params defaulting
    to None) — every operand the ragged repack may see is classified
    exactly once as padded-per-axis or shape-invariant."""
    if tickloop_src is None:
        return []  # the missing-file finding already fired
    fn = funcs_by_file.get(_TICKLOOP, {}).get("fused_tick_run")
    if fn is None:
        return []  # span-manifest check already reports the vanish
    array_knobs = {
        p.arg
        for p, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults)
        if isinstance(d, ast.Constant) and d.value is None
    }
    tables: Dict[str, Optional[Set[str]]] = {
        "RAGGED_AXES": None, "RAGGED_INVARIANT": None,
    }
    lines: Dict[str, int] = {}
    for node in tickloop_src.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name) and tgt.id in tables:
            tables[tgt.id] = _set_literal_names(node.value)
            lines[tgt.id] = node.lineno
    out: List[Finding] = []
    for name, names in tables.items():
        if names is None:
            out.append(Finding(
                RULE, _TICKLOOP, lines.get(name, 0),
                f"{name} is missing or not a string-keyed literal — the "
                "ragged axis-coverage check cannot read it statically",
            ))
    axes, invariant = tables["RAGGED_AXES"], tables["RAGGED_INVARIANT"]
    if axes is None or invariant is None:
        return out
    overlap = axes & invariant
    if overlap:
        out.append(Finding(
            RULE, _TICKLOOP, lines["RAGGED_AXES"],
            "ragged tables overlap (operand classified twice): "
            f"{sorted(overlap)}",
        ))
    uncovered = array_knobs - axes - invariant
    if uncovered:
        out.append(Finding(
            RULE, _TICKLOOP, lines["RAGGED_AXES"],
            "span array knob(s) missing from both ragged tables — the "
            "repack would drop them from the coalescing key and leave "
            f"them unpadded: {sorted(uncovered)} (add to RAGGED_AXES "
            "with (K, B) axis positions, or to RAGGED_INVARIANT if the "
            "operand has neither axis)",
        ))
    stale = (axes | invariant) - array_knobs
    if stale:
        out.append(Finding(
            RULE, _TICKLOOP, lines["RAGGED_AXES"],
            "ragged table entries with no matching fused_tick_run "
            f"array knob (renamed/removed?): {sorted(stale)}",
        ))
    return out


def _ops_files(root: str) -> List[str]:
    import os

    abspath = os.path.join(root, _OPS_DIR)
    if not os.path.isdir(abspath):
        return []
    return [
        f"{_OPS_DIR}/{name}"
        for name in sorted(os.listdir(abspath))
        if name.endswith(".py")
    ]


def collect(cache) -> Tuple[List[Finding], List[str]]:
    registered = sorted(
        {rel for forms in MANIFEST.values() for rel, _ in forms.values()}
        | {rel for rel, _ in SPAN_MANIFEST.values()}
        | {_ROUTING_FILE}
    )
    files = sorted(set(registered) | set(_ops_files(cache.root)))
    funcs_by_file: Dict[str, Dict[str, ast.FunctionDef]] = {}
    scanned: List[str] = []
    missing: List[Finding] = []
    for rel in files:
        src = cache.get(rel)
        if src is None:
            # A registered file that vanished takes ALL of its forms'
            # coverage with it — loud failure, not a silent skip (the
            # old lint raised FileNotFoundError here; review finding,
            # round 12).
            missing.append(Finding(
                RULE, rel, 0,
                f"registered file {rel} is missing — renamed/deleted? "
                "update the parity manifest (its forms lost all static "
                "coverage)",
            ))
            continue
        scanned.append(rel)
        if rel != _ROUTING_FILE:
            funcs_by_file[rel] = _top_level_functions(src)

    out: List[Finding] = list(missing)
    family_unions: Dict[str, Set[str]] = {}
    for family, forms in MANIFEST.items():
        findings, knob_sets = _matrix_findings(
            family, forms, KNOBS, funcs_by_file
        )
        out.extend(findings)
        if knob_sets:
            family_unions[family] = set().union(*knob_sets.values())
    span_findings, _span_sets = _matrix_findings(
        "span", SPAN_MANIFEST, SPAN_KNOBS, funcs_by_file
    )
    out.extend(span_findings)
    out.extend(_ragged_findings(funcs_by_file, cache.get(_TICKLOOP)))
    out.extend(_discovery_findings(funcs_by_file))

    routing = cache.get(_ROUTING_FILE)
    if routing is not None:
        out.extend(_routing_findings(routing, family_unions))
    return out, scanned
