"""Thread-guard discipline: declared guarded-by maps, checked lexically.

The threaded serve/batch layer (session threads, the producer, the
flush coordinator, the watchdog, the autoscaler) serializes its shared
state behind per-object condition variables — ``ServeDriver._cv`` and
``DispatchBatcher._cond``.  The discipline is documented in docstrings
("cv held") and enforced by nothing; a new code path reading
``self._inflight`` without the lock compiles, passes the determinism
suites (races are timing-dependent by definition), and corrupts a
ledger once a quarter.

This pass makes the guarded-by relation *declared data*
(:data:`GUARDS`) and checks it lexically: every load/store of a
declared guarded field must sit inside a ``with self.<lock>:`` block
(or inside a ``lambda`` under one — ``Condition.wait_for`` predicates
run with the lock held), or in a method declared ``held`` (documented
lock-held helpers: the "(cv held)" docstring convention, now
machine-checked against the map) or ``exempt`` (single-threaded
lifecycle phases: constructors before any thread exists, ``run``'s
setup/teardown around its join barrier).  Accesses of guarded fields
through a *foreign* object (``driver._stop`` from the autoscaler
thread) are checked the same way against the owning class's lock.

Lexical scope is the deliberate precision limit: a nested ``def``
body is treated as UNguarded even under a ``with`` (closures execute
later, the lock may be long released), while ``lambda`` keeps the
enclosing guard state (the wait-predicate idiom).  What the pass
cannot prove, code must either restructure or suppress with a written
justification — the suppression inventory IS the audit of benign
racy reads (monotonic stop flags, snapshot iteration).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pivot_tpu.analysis import Finding, SourceFile

RULE = "thread-guard"

#: repo-relative file → {class name: guard spec}.  ``fields`` are the
#: attributes the class's lock guards; ``held`` methods are documented
#: to run with the lock already held (their call sites are inside
#: ``with`` blocks — the "(cv held)" docstring convention); ``exempt``
#: methods are single-threaded by lifecycle (no concurrent thread can
#: exist while they run).
GUARDS: Dict[str, Dict[str, dict]] = {
    "pivot_tpu/sched/batch.py": {
        "DispatchBatcher": {
            "lock": "_cond",
            "fields": (
                "_pending", "_open", "_idle", "_clients", "_n_slots",
                "stats",
            ),
            # _quiescent is the coordinator's wait_for predicate —
            # Condition.wait_for evaluates it with the lock held.
            "held": ("_quiescent",),
            "exempt": ("__init__",),
        },
    },
    "pivot_tpu/serve/driver.py": {
        "ServeDriver": {
            "lock": "_cv",
            "fields": (
                "_released", "_stop", "_draining", "_errors", "_rr",
                "_inflight", "_admit_seq", "_waiting_tier",
                "_preempt_outstanding", "_restarts", "_n_grown",
                "sessions", "_threads", "_abandoned", "_retired",
            ),
            # The "(cv held)" helpers: called only under the cv by
            # their docstring contract.
            "held": (
                "_release_to", "_recover_inflight", "_requeue",
                "_wire_and_start", "_try_preempt", "_reoffer_spilled",
                "_register_inflight", "_route", "_preempt_for",
                "_release_one", "_batching_compatible",
            ),
            # Single-threaded lifecycle phases: __init__ precedes every
            # thread; report/audit run on the drained service.
            # publish_metrics is NOT exempt since round 15 — the
            # --metrics-port scrape endpoint calls it mid-run, so its
            # pool-state reads must (and do) snapshot under the cv.
            # run() is NOT exempt — its setup section is pre-thread
            # (per-line suppressions say so), but its join loop runs
            # concurrently with supervisor restarts and stays checked
            # (that is where this pass caught the _threads iteration
            # race).
            "exempt": ("__init__", "report", "audit"),
        },
    },
    "pivot_tpu/mpc/controller.py": {
        # Same shape as the autoscaler below: the MPC controller owns
        # no guarded state (its forecaster/tuner/rollout lock or
        # thread-confine internally; every pool mutation goes through
        # ServeDriver methods).  The entry puts the file in scope so
        # foreign reads of driver fields (``driver._stop``) are
        # checked and its suppressions staleness-tracked.
        "MpcController": {
            "lock": None,
            "fields": (),
            "held": (),
            "exempt": ("__init__",),
        },
    },
    "pivot_tpu/serve/autoscale.py": {
        # The autoscaler owns no guarded state of its own: every pool
        # mutation goes through ServeDriver methods (which take the
        # driver's cv), its event log is autoscaler-thread-confined
        # until ``stop()`` joins the thread, and its stop flag is a
        # threading.Event.  The entry exists so the file is in scope:
        # foreign reads of ServeDriver fields (``driver._stop``) are
        # checked here, and suppressions in it are staleness-tracked.
        "SloAutoscaler": {
            "lock": None,
            "fields": (),
            "held": (),
            "exempt": ("__init__",),
        },
    },
}


def _lock_items(node: ast.With, lock: str) -> bool:
    for item in node.items:
        expr = item.context_expr
        if (
            isinstance(expr, ast.Attribute)
            and expr.attr == lock
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return True
    return False


class _GuardVisitor(ast.NodeVisitor):
    """Walk one method body tracking lexical ``with <base>.<lock>``
    nesting; record unguarded accesses of guarded fields."""

    def __init__(self, src: SourceFile, lock: Optional[str],
                 fields: Set[str], method: str,
                 foreign_owners: Dict[str, Tuple[str, str]]):
        self.src = src
        self.lock = lock
        self.fields = fields
        self.method = method
        #: guarded-field name → (owning class, its lock) for foreign
        #: (non-self) accesses.
        self.foreign_owners = foreign_owners
        self.depth_self = 0
        #: (foreign base name, lock attr) → with-nesting depth
        self.depth_foreign: Dict[Tuple[str, str], int] = {}
        self.findings: List[Finding] = []

    # -- scope rules ------------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef):
        # Nested def: executes later; the enclosing lock may be
        # released.  Reset guard state for its body.
        saved_self, saved_foreign = self.depth_self, self.depth_foreign
        self.depth_self, self.depth_foreign = 0, {}
        self.generic_visit(node)
        self.depth_self, self.depth_foreign = saved_self, saved_foreign

    visit_AsyncFunctionDef = visit_FunctionDef

    # Lambdas keep the enclosing guard state: the dominant use is the
    # ``cv.wait_for(lambda: ...)`` predicate, which runs lock-held.

    def visit_With(self, node: ast.With):
        held_self = self.lock is not None and _lock_items(node, self.lock)
        all_locks = {lock for _cls, lock in self.foreign_owners.values()}
        held_foreign: List[Tuple[str, str]] = []
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id != "self"
                and expr.attr in all_locks
            ):
                held_foreign.append((expr.value.id, expr.attr))
        if held_self:
            self.depth_self += 1
        for key in held_foreign:
            self.depth_foreign[key] = self.depth_foreign.get(key, 0) + 1
        self.generic_visit(node)
        if held_self:
            self.depth_self -= 1
        for key in held_foreign:
            self.depth_foreign[key] -= 1

    visit_AsyncWith = visit_With

    # -- the accesses -----------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "self" and node.attr in self.fields:
                if self.depth_self == 0:
                    self.findings.append(Finding(
                        RULE, self.src.path, node.lineno,
                        f"self.{node.attr} accessed outside `with "
                        f"self.{self.lock}:` in {self.method}() — the "
                        "guarded-by map declares it lock-protected",
                    ))
            elif base != "self" and node.attr in self.foreign_owners:
                cls, lock = self.foreign_owners[node.attr]
                if self.depth_foreign.get((base, lock), 0) == 0:
                    self.findings.append(Finding(
                        RULE, self.src.path, node.lineno,
                        f"{base}.{node.attr} ({cls}-guarded field) "
                        f"accessed outside `with {base}.{lock}:` in "
                        f"{self.method}()",
                    ))
        self.generic_visit(node)


def _foreign_owner_map(
    exclude_fields: Set[str],
) -> Dict[str, Tuple[str, str]]:
    """guarded-field name → (owning class, lock), across every mapped
    class — how ``driver._stop`` in another file gets checked.  Fields
    guarded by the class under inspection are excluded (those are the
    ``self`` path)."""
    owners: Dict[str, Tuple[str, str]] = {}
    for classes in GUARDS.values():
        for cls, spec in classes.items():
            for field in spec["fields"]:
                if field not in exclude_fields:
                    owners.setdefault(field, (cls, spec["lock"]))
    return owners


def check_source(
    src: SourceFile, class_guards: Dict[str, dict]
) -> List[Finding]:
    """Check one file against its class guard specs (exposed separately
    so the seeded-violation tests can drive synthetic files)."""
    out: List[Finding] = []
    found: Set[str] = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        spec = class_guards.get(node.name)
        if spec is None:
            continue
        found.add(node.name)
        fields = set(spec["fields"])
        skip = set(spec.get("held", ())) | set(spec.get("exempt", ()))
        foreign = _foreign_owner_map(exclude_fields=fields)
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in skip:
                continue
            visitor = _GuardVisitor(
                src, spec["lock"], fields, item.name, foreign
            )
            # Visit the body directly (not the def node) so the
            # method's own def doesn't reset the guard state.
            for stmt in item.body:
                visitor.visit(stmt)
            out.extend(visitor.findings)
    # Module-level and unmapped-class code in a mapped file still gets
    # the foreign-field check (closed_loop_source reads driver._stop).
    foreign_all = _foreign_owner_map(exclude_fields=set())
    mapped_classes = set(class_guards)

    class _Module(ast.NodeVisitor):
        def __init__(self):
            self.findings: List[Finding] = []

        def visit_ClassDef(self, node: ast.ClassDef):
            if node.name in mapped_classes:
                return  # handled above with the class's own spec
            self._scan(node)

        def _scan(self, node):
            visitor = _GuardVisitor(
                src, None, set(), "<module>", foreign_all
            )
            for stmt in (
                node.body if hasattr(node, "body") else [node]
            ):
                visitor.visit(stmt)
            self.findings.extend(visitor.findings)

        def visit_FunctionDef(self, node):
            self._scan(node)

        visit_AsyncFunctionDef = visit_FunctionDef

    mod = _Module()
    for stmt in src.tree.body:
        mod.visit(stmt)
    out.extend(mod.findings)
    for cls in set(class_guards) - found:
        out.append(Finding(
            RULE, src.path, 1,
            f"guarded class {cls} not found — update the guarded-by "
            "map (pivot_tpu/analysis/threadguard.py) after renames",
        ))
    return out


def collect(cache) -> Tuple[List[Finding], List[str]]:
    out: List[Finding] = []
    scanned: List[str] = []
    for rel, class_guards in GUARDS.items():
        src = cache.get(rel)
        if src is None:
            out.append(Finding(
                RULE, rel, 0,
                f"guard-mapped file {rel} is missing — renamed/deleted? "
                "update the guarded-by map (its classes lost all "
                "static coverage)",
            ))
            continue
        scanned.append(rel)
        out.extend(check_source(src, class_guards))
    return out, scanned
