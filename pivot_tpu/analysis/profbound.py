"""profiler-boundary lint: where the dispatch profiler may hook in.

The sampled dispatch profiler (``pivot_tpu/obs/profiler.py``, round 15)
is safe precisely because it brackets dispatches at the three
registered host↔device boundaries and nowhere else.  Every erosion mode
is one convenient line away:

  * a ``profiler.profile(...)`` call inside a jitted/Pallas body would
    trace once and lie (or force a host sync per iteration) — the same
    failure class the ``obs-boundary`` pass pins for tracer hooks;
  * a profiler hook at a NEW, unregistered call site would silently
    time something that is not a device dispatch (a lock wait, a
    batcher park) and poison the per-family census the regression
    tooling trusts;
  * the boundary bodies themselves could be renamed away, leaving the
    registry pointing at nothing while dispatches go unprofiled.

This pass enforces the register-or-flag discipline (the jitmap/parity
convention):

  * :data:`BOUNDARIES` is the registry of (file, function) bodies
    allowed to invoke the profiler's recording surface
    (``.profile(...)``).  Any ``*.profile(...)`` call in the package —
    outside ``pivot_tpu/obs`` (the profiler's home) and
    ``pivot_tpu/analysis`` (this suite) — that is not lexically inside
    a registered body is a finding;
  * every registered boundary body must still EXIST (rename
    protection — a silently renamed boundary drops out of coverage);
  * the device layer (``pivot_tpu/ops/``) may not import
    ``pivot_tpu.obs.profiler`` at all (explicit here even though the
    broader ``obs-boundary`` import pin also covers it: the finding
    message should name the profiler contract, not a generic one).

The wall-capture side needs no new rule: the profiler owns every
``time.*`` read (the ``determinism`` pass bans them in scope), and
``ObsClock`` ownership is already pinned by ``obs-boundary``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Tuple

from pivot_tpu.analysis import Finding, SourceFile

RULE = "profiler-boundary"

#: (repo-relative file) → function bodies allowed to call
#: ``profiler.profile(...)``.  ``_call_kernel`` is the per-policy
#: direct-dispatch rung (``place_span`` and the per-tick kernels both
#: route through it); ``_execute`` is the batcher flush's per-group
#: device call (``DispatchBatcher._flush`` delegates to it so the
#: profiled span nests inside the flush span).
#: ``_resident_dispatch`` is the resident tier's dispatch rung (round
#: 20): it cannot route through ``_call_kernel`` because the donated
#: carry must be threaded positionally and the returned carry captured
#: — but it brackets exactly one device call, same as the others.
BOUNDARIES: Dict[str, Tuple[str, ...]] = {
    "pivot_tpu/sched/tpu.py": ("_call_kernel", "_resident_dispatch"),
    "pivot_tpu/sched/batch.py": ("_execute",),
}

#: Package subtrees excluded from the call sweep: the profiler's home
#: (it calls itself) and this analysis suite (pattern strings in
#: checks/tests).
_EXEMPT_PREFIXES = ("pivot_tpu/obs", "pivot_tpu/analysis")

_SWEEP_ROOT = "pivot_tpu"


def _profile_calls(src: SourceFile) -> List[Tuple[int, str]]:
    """(lineno, innermost enclosing function name) of every
    ``X.profile(...)`` call in the file ('<module>' at top level)."""
    out: List[Tuple[int, str]] = []

    def walk(node: ast.AST, func: str) -> None:
        for child in ast.iter_child_nodes(node):
            scope = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = child.name
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr == "profile"
            ):
                out.append((child.lineno, func))
            walk(child, scope)

    walk(src.tree, "<module>")
    return out


def _has_function(src: SourceFile, name: str) -> bool:
    return any(
        isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name == name
        for node in ast.walk(src.tree)
    )


def _scan_ops_imports(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(
                alias.name.startswith("pivot_tpu.obs.profiler")
                for alias in node.names
            )
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            names = {alias.name for alias in node.names}
            hit = mod.startswith("pivot_tpu.obs.profiler") or (
                mod == "pivot_tpu.obs"
                and ("profiler" in names or "DispatchProfiler" in names)
            )
        if hit:
            out.append(Finding(
                RULE, src.path, node.lineno,
                "device-layer module imports the dispatch profiler — "
                "profiling brackets dispatches at the registered host "
                "boundaries (sched/tpu._call_kernel, sched/batch."
                "_execute), never inside the jitted/Pallas layer",
            ))
    return out


def collect(cache) -> Tuple[List[Finding], List[str]]:
    out: List[Finding] = []
    scanned: List[str] = []

    # 1) Boundary registry: allowed call sites + rename protection.
    for rel, funcs in sorted(BOUNDARIES.items()):
        src = cache.get(rel)
        if src is None:
            out.append(Finding(
                RULE, rel, 0,
                f"registered profiler boundary file {rel} is missing — "
                "renamed/deleted? update pivot_tpu/analysis/profbound.py "
                "BOUNDARIES",
            ))
            continue
        scanned.append(rel)
        for fn in funcs:
            if not _has_function(src, fn):
                out.append(Finding(
                    RULE, rel, 1,
                    f"registered profiler boundary {fn}() no longer "
                    f"exists in {rel} — renamed? update BOUNDARIES (its "
                    "dispatches lost profiler coverage)",
                ))

    # 2) Package sweep: .profile(...) calls outside registered bodies.
    root = os.path.join(cache.root, _SWEEP_ROOT)
    if os.path.isdir(root):
        for dirpath, _dirs, files in sorted(os.walk(root)):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                rel = os.path.relpath(
                    os.path.join(dirpath, fname), cache.root
                )
                if any(rel.startswith(p) for p in _EXEMPT_PREFIXES):
                    continue
                src = cache.get(rel)
                if src is None or ".profile(" not in src.text:
                    continue
                if rel not in scanned:
                    scanned.append(rel)
                allowed = BOUNDARIES.get(rel, ())
                for lineno, func in _profile_calls(src):
                    if func in allowed:
                        continue
                    out.append(Finding(
                        RULE, rel, lineno,
                        f"profiler recording call .profile() in "
                        f"{func}() — not a registered dispatch "
                        "boundary; register (file, function) in "
                        "pivot_tpu/analysis/profbound.py BOUNDARIES "
                        "if this genuinely brackets a device dispatch",
                    ))

    # 3) Device layer: no profiler imports under pivot_tpu/ops/.
    ops_dir = os.path.join(cache.root, "pivot_tpu/ops")
    if os.path.isdir(ops_dir):
        for name in sorted(os.listdir(ops_dir)):
            if not name.endswith(".py"):
                continue
            rel = f"pivot_tpu/ops/{name}"
            src = cache.get(rel)
            if src is None:
                continue
            if rel not in scanned:
                scanned.append(rel)
            out.extend(_scan_ops_imports(src))

    return out, scanned
