"""graftcheck: the repo-wide static-analysis suite.

The repo's three load-bearing invariants are enforced at runtime by the
parity/replay/soak test suites — but each of them has a *static* shadow
that can be proven before any test runs, and history says the runtime
net has holes exactly where a PR threads a new knob or a new thread:

  * **backend feature-parity** (``rules/backend-parity``) — every
    scheduling knob (live mask, risk vector, cost tensor, totals,
    phase-2 selector, …) must reach every declared form of its kernel
    family: the scan oracle, the two-phase ``*_impl``, the Pallas
    kernel, the sharded twin, the fused span drivers, and the
    ``sched/tpu.py`` routing layer.  PR 9 threaded ``risk``/
    ``cost_tensor`` through seven forms by hand; this pass makes the
    eighth time a compile-time error instead of a reviewer's diff hunt.
  * **determinism** (``rules/determinism``) — seeded replay is
    bit-identical only while the sim/replay-critical modules (``des/``,
    ``infra/faults.py``, ``infra/market.py``, ``sched/``, ``ops/``)
    never read a wall clock, never touch global RNG state, and never
    iterate a hash-ordered set.  One ``time.time()`` breaks the
    replay contract that ``chaos_replay``/``market_replay`` audit.
  * **thread-guard** (``rules/thread-guard``) — the threaded serve/
    batch layer serializes its shared state behind declared condition
    variables; this pass checks every access of a declared guarded
    field lexically sits under ``with self.<lock>:``.
  * **host-sync** (``rules/host-sync``) — the PR-6 hot-path lint,
    migrated into the framework with naming-convention auto-discovery
    replacing the hand-maintained target dict.

jitcheck (round 13) extends the suite to the layer where TPU
performance is won or lost — JAX/XLA compilation semantics — with four
more passes behind the same walker/registry/suppression framework:

  * **retrace** (``rules/retrace``) — recompilation hazards in the
    ~30 jitted entry points (traced-value branching, host coercions,
    stale static declarations, closure-captured numpy constants);
  * **donation** (``rules/donation``) — the declared-carry manifest,
    enforced both ways: declared-donated carries must keep their
    ``donate_argnums``, declared-undonated carries (host-numpy-staged
    operands: CPU zero-copy hazard) must stay undonated, no donated
    buffer may be read after the call, and an unrecorded returned
    carry is a finding;
  * **dtype** (``rules/dtype``) — no float64 typing arrays that cross
    the device boundary, no weak-type scalar forks in the kernel cores;
  * **pallas-budget** (``rules/pallas-budget``) — every Pallas kernel's
    VMEM footprint recomputed from its BlockSpec shapes and checked
    against the v5e budget constants in ``infra/roofline.py``.

Every retrace rule corresponds to a runtime observable: the
compile-counter harness (``pivot_tpu/utils/compile_counter.py``,
``--compile-check``, and the tier-1 ``tests/test_jitcheck.py``) asserts
the steady-state hypothesis — zero recompiles after warmup — on the
fused-span and serve dispatch paths.

The observability plane (round 14) adds a ninth pass:

  * **obs-boundary** (``rules/obs-boundary``) — the structural pins of
    ``pivot_tpu/obs``: the device layer (``pivot_tpu/ops/``) never
    imports the obs package, the hostsync-discovered hot bodies never
    call a tracer recording method (events belong at dispatch
    boundaries), and the determinism-scoped modules never own an
    ``ObsClock`` (hooks pass sim-time payloads; the wall side is
    stamped inside ``obs/``).

The performance-observability layer (round 15) adds a tenth:

  * **profiler-boundary** (``rules/profiler-boundary``) — the sampled
    dispatch profiler's structural pins: ``profiler.profile(...)``
    may be invoked only inside the registered boundary bodies
    (``sched/tpu._call_kernel``, ``sched/batch._execute``), those
    bodies must keep existing (rename protection), and the device
    layer never imports the profiler.

Framework pieces shared by every pass: :class:`Finding`, the rule
registry (:data:`REGISTRY`), ``# graftcheck: ignore[rule] -- reason``
suppressions (reason REQUIRED; a suppression that matches no finding is
itself a finding — stale suppressions rot into lies), and the
:func:`run` driver behind both CLIs (``tools/graftcheck.py`` and
``python -m pivot_tpu.analysis``).

Suppression contract: the comment suppresses findings of the named
rule(s) on its own line, on the line directly below (the
comment-above form), or — when it trails a later line of a multi-line
*simple* statement — at that statement's first line, where findings
anchor::

    t0 = time.perf_counter()  # graftcheck: ignore[determinism] -- why

    # graftcheck: ignore[thread-guard] -- snapshot read; see docstring
    for s in list(self.sessions):

The meta-rule ``suppression`` (bad or stale suppression comments) is
not itself suppressible.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "SourceFile",
    "REGISTRY",
    "repo_root",
    "run",
    "main",
]


class Finding(NamedTuple):
    """One static-analysis violation, repo-relative."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """A parsed source file: text, lines, AST — parsed once per run and
    shared by every pass through the run cache (one parse per file, not
    one per pass — the round-13 wall-clock budget depends on it)."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath
        with open(abspath) as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=abspath)
        self._stmt_spans: Optional[List[Tuple[int, int]]] = None

    @property
    def stmt_spans(self) -> List[Tuple[int, int]]:
        """(lineno, end_lineno) of every SIMPLE statement — computed
        once per file and shared by all suppression-scope lookups
        (previously one full AST walk per suppression comment)."""
        if self._stmt_spans is None:
            self._stmt_spans = [
                (node.lineno, node.end_lineno or node.lineno)
                for node in ast.walk(self.tree)
                if isinstance(node, ast.stmt)
                and not isinstance(node, _COMPOUND_STMTS)
            ]
        return self._stmt_spans


class _Cache:
    """Per-run SourceFile cache so passes sharing files parse once."""

    def __init__(self, root: str):
        self.root = root
        self._files: Dict[str, SourceFile] = {}

    def get(self, rel: str) -> Optional[SourceFile]:
        if rel not in self._files:
            abspath = os.path.join(self.root, rel)
            if not os.path.isfile(abspath):
                self._files[rel] = None
            else:
                self._files[rel] = SourceFile(abspath, rel)
        return self._files[rel]


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

#: ``# graftcheck: ignore[rule1,rule2] -- reason`` (reason mandatory).
_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[(?P<rules>[^\]]*)\]"
    r"(?:\s*--\s*(?P<reason>\S.*))?"
)


class Suppression(NamedTuple):
    path: str
    line: int
    rules: Tuple[str, ...]
    reason: Optional[str]


def find_suppressions(src: SourceFile) -> List[Suppression]:
    """Suppression comments in ``src`` — matched against actual COMMENT
    tokens, not raw lines, so suppression syntax *quoted* inside a
    docstring or string literal (e.g. documentation of the idiom) is
    never parsed as a live suppression."""
    import io
    import tokenize

    out: List[Suppression] = []
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(src.text).readline)
        )
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        # The file ast-parsed, so this should be unreachable; fail
        # open (no suppressions) rather than crash the run.
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m is None:
            continue
        rules = tuple(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        out.append(
            Suppression(src.path, tok.start[0], rules, m.group("reason"))
        )
    return out


_COMPOUND_STMTS = (
    ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.If,
    ast.For, ast.AsyncFor, ast.While, ast.With, ast.AsyncWith, ast.Try,
)


def _suppression_scope(
    sup: Suppression, src: Optional[SourceFile]
) -> Set[int]:
    """Line numbers a suppression covers: its own line, the next line,
    and the FULL span of the closest SIMPLE statement it attaches to —
    either the one its line sits inside (a trailing comment on any line
    of a multi-line call) or the one starting directly below it (the
    comment-above form over a multi-line statement, whose findings can
    anchor on inner lines).  Compound statements are excluded so a
    comment inside a function body cannot blanket the whole def."""
    cover = {sup.line, sup.line + 1}
    if src is not None:
        best = None  # innermost simple statement containing sup.line
        for lineno, end in src.stmt_spans:
            if lineno <= sup.line <= end:
                if best is None or lineno > best[0]:
                    best = (lineno, end)
            elif lineno == sup.line + 1:
                # Comment-above form: cover the whole statement below.
                cover.update(range(lineno, end + 1))
        if best is not None:
            cover.update(range(best[0], best[1] + 1))
    return cover


# ---------------------------------------------------------------------------
# Registry + runner
# ---------------------------------------------------------------------------

def _registry():
    # Imported lazily so ``import pivot_tpu.analysis`` stays cheap and
    # the pass modules can import framework types from here.
    from pivot_tpu.analysis import (
        determinism,
        donation,
        dtype,
        hostsync,
        obsbound,
        pallas_budget,
        parity,
        profbound,
        retrace,
        threadguard,
    )

    return {
        parity.RULE: parity,
        determinism.RULE: determinism,
        threadguard.RULE: threadguard,
        hostsync.RULE: hostsync,
        # jitcheck (round 13): the compile-semantics passes.
        retrace.RULE: retrace,
        donation.RULE: donation,
        dtype.RULE: dtype,
        pallas_budget.RULE: pallas_budget,
        # The observability plane's boundary pins (round 14): no
        # instrumentation inside the device layer / hot bodies, no obs
        # wall clock inside the determinism scope.
        obsbound.RULE: obsbound,
        # The dispatch profiler's boundary pins (round 15): profiler
        # recording calls only at the registered dispatch boundaries.
        profbound.RULE: profbound,
    }


#: Rule name → pass module (each exposes ``RULE`` and
#: ``collect(cache) -> (findings, scanned_relpaths)``).
REGISTRY = _registry


def run(
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the requested passes (default: all) over the tree at ``root``
    (default: this repo), apply suppressions, flag bad/stale
    suppressions, and return the surviving findings sorted by location.
    """
    root = root or repo_root()
    registry = REGISTRY()
    selected = list(registry) if rules is None else list(rules)
    unknown = [r for r in selected if r not in registry]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; known: {sorted(registry)}"
        )
    cache = _Cache(root)

    findings: List[Finding] = []
    scanned_by_rule: Dict[str, Set[str]] = {}
    for rule in selected:
        pass_findings, scanned = registry[rule].collect(cache)
        findings.extend(pass_findings)
        scanned_by_rule[rule] = set(scanned)

    # Suppression processing over every file any pass scanned.
    all_scanned = sorted(set().union(*scanned_by_rule.values(), set()))
    suppressions: List[Suppression] = []
    for rel in all_scanned:
        src = cache.get(rel)
        if src is not None:
            suppressions.extend(find_suppressions(src))

    known_rules = set(registry)
    scopes = [
        _suppression_scope(sup, cache.get(sup.path))
        for sup in suppressions
    ]
    used: Set[Tuple[int, str]] = set()  # (index into suppressions, rule)
    kept: List[Finding] = []
    for f in findings:
        suppressed = False
        for idx, sup in enumerate(suppressions):
            if (
                sup.path == f.path
                and f.rule in sup.rules
                and sup.reason
                and f.line in scopes[idx]
            ):
                used.add((idx, f.rule))
                suppressed = True
        if not suppressed:
            kept.append(f)

    # Bad / stale suppressions are findings of the (unsuppressible)
    # meta-rule ``suppression``.
    for idx, sup in enumerate(suppressions):
        if not sup.reason:
            kept.append(Finding(
                "suppression", sup.path, sup.line,
                "suppression without a justification — write "
                "`# graftcheck: ignore[rule] -- reason`",
            ))
            continue
        for rule in sup.rules:
            if rule not in known_rules:
                kept.append(Finding(
                    "suppression", sup.path, sup.line,
                    f"suppression names unknown rule {rule!r} "
                    f"(known: {sorted(known_rules)})",
                ))
            elif (
                rule in scanned_by_rule
                and sup.path in scanned_by_rule[rule]
                and (idx, rule) not in used
            ):
                kept.append(Finding(
                    "suppression", sup.path, sup.line,
                    f"stale suppression: no [{rule}] finding in its "
                    "scope (this line, the line below, or the span of "
                    "the simple statement it attaches to) — the "
                    "violation it excused is gone; delete the comment",
                ))

    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept


def _compile_check(quick: bool) -> int:
    """The falsifying runtime twin of the ``retrace`` pass: run the
    fused span driver cold (warmup), then steady-state, and fail if the
    steady phase compiled ANYTHING — the "zero recompiles after warmup"
    hypothesis, observed instead of assumed.  ``quick`` keeps shapes
    tiny (CI smoke lane); the tier-1 suite covers the serve path too
    (``tests/test_jitcheck.py``)."""
    import numpy as np  # deferred: the static passes must not need jax

    import jax.numpy as jnp

    from pivot_tpu.ops.tickloop import fused_tick_run, span_bucket
    from pivot_tpu.utils.compile_counter import count_compiles

    H, B, K = (8, 8, 4) if quick else (64, 32, 8)
    rng = np.random.default_rng(0)
    avail = rng.uniform(1, 6, (H, 4))
    dem = rng.uniform(0.3, 2.0, (B, 4))
    arrive = np.zeros(B, np.int32)

    def span(k_dyn, seed):
        r = np.random.default_rng(seed)
        return fused_tick_run(
            jnp.asarray(avail * r.uniform(0.9, 1.1, avail.shape)),
            jnp.asarray(dem), jnp.asarray(arrive),
            jnp.asarray(k_dyn, jnp.int32),
            policy="first-fit", n_ticks=span_bucket(K),
        )

    np.asarray(span(K, 0).placements)  # warmup: compile the program
    with count_compiles() as counter:
        for seed in range(3):
            np.asarray(span(K - 1 - seed % 2, seed).placements)
    if counter.compiles or counter.traces:
        print(
            f"compile-check: FAILED — {counter.compiles} backend "
            f"compile(s), {counter.traces} retrace(s) after warmup on "
            "the fused-span path (steady state must be zero)",
            file=sys.stderr,
        )
        return 1
    print("compile-check: zero recompiles after warmup (fused-span path)")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: exit 1 on findings.  ``--rules a,b`` filters passes (unknown
    names exit 2 listing the valid set); ``--json`` prints the findings
    machine-readably; ``--root`` points at another tree (tests use
    this); ``--compile-check`` runs the runtime recompile harness."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="graftcheck",
        description="repo-wide static analysis: backend knob parity, "
        "replay determinism, thread-guard discipline, host-sync lint, "
        "the jitcheck compile-hazard passes (retrace, donation, "
        "dtype, pallas-budget), and the observability boundary pins "
        "(obs-boundary)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule subset (default: all); unknown "
        "names error listing the valid rule set",
    )
    parser.add_argument("--root", help="tree to analyze (default: repo)")
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings on stdout: {rule, path, line, "
        "message} per finding (CI annotates per file:line from this)",
    )
    parser.add_argument(
        "--compile-check", nargs="?", const="quick", default=None,
        choices=("quick", "full"),
        help="run the runtime compile-counter harness (imports jax): "
        "warm the fused span driver, then assert ZERO recompiles in "
        "steady state",
    )
    args = parser.parse_args(argv)
    registry = REGISTRY()
    if args.list_rules:
        if args.json:
            import json

            print(json.dumps({
                rule: (mod.__doc__ or "").strip().splitlines()[0]
                for rule, mod in registry.items()
            }, indent=2))
            return 0
        for rule, mod in registry.items():
            doc = (mod.__doc__ or "").strip().splitlines()
            print(f"{rule}: {doc[0] if doc else ''}")
        return 0
    if args.compile_check is not None:
        return _compile_check(quick=args.compile_check == "quick")
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    try:
        findings = run(root=args.root, rules=rules)
    except ValueError as exc:
        print(f"graftcheck: {exc}", file=sys.stderr)
        return 2
    if args.json:
        import json

        print(json.dumps(
            {
                "clean": not findings,
                "rules": rules or sorted(registry),
                "findings": [f._asdict() for f in findings],
            },
            indent=2,
        ))
        return 1 if findings else 0
    for f in findings:
        print(f, file=sys.stderr)
    if findings:
        print(
            f"graftcheck: {len(findings)} finding(s)", file=sys.stderr
        )
        return 1
    n = len(rules) if rules else len(registry)
    print(f"graftcheck: clean ({n} pass(es))")
    return 0
