"""Donation lint: the declared-carry manifest, checked two ways.

The repo's device-resident carries — the tickloop span availability,
its host-sharded twin, and the ensemble segment states — are the
dominant live allocations of their dispatch paths.  ``donate_argnums``
is what lets XLA alias a carry's input buffer with its output instead
of holding two copies per call; it is also a contract the *caller*
must honor (a donated buffer is deleted — reading it after the call is
a runtime error the CPU tests may never hit if the code path is
device-only).  Both sides rot silently, so both are checked:

  1. **Manifest coverage** — every carry in :data:`MANIFEST` is a
     recorded decision, positive or negative.  ``donated=True``
     entries (the ensemble segment/sweep carries, whose inputs are
     always previous jit OUTPUTS — device-owned buffers) must be
     wrapped by a jit that donates the declared position; a wrapper
     that vanished or dropped its ``donate_argnums`` is a finding.
     ``donated=False`` entries (the RE-STAGED span availability
     carries) must stay UNdonated: their operands are staged from host
     numpy at the call boundary, and on the CPU backend
     ``jnp.asarray(host_array)`` is **zero-copy for large aligned
     arrays** — donating such a buffer lets XLA reuse memory the
     caller still owns (measured in round 13: silent, allocation-
     order-dependent corruption of the DES availability snapshot).
     Flipping either direction without flipping the manifest is a
     finding.

     Round 20 AMENDS that hazard writeup rather than repealing it: the
     resident span tier (``resident-span-carry`` /
     ``sharded-resident-span-carry``) donates the very state the
     re-staged entries refuse to, and both decisions are correct —
     what changed is buffer OWNERSHIP, not the rule.  The resident
     carry is always a previous jit OUTPUT (``resident_carry_init``
     materializes an explicit device copy before the first donation;
     every later span's carry is the prior ``resident_span_run``
     output), so caller-owned host memory can never sit behind the
     donated position.  ``fused_tick_run``'s re-staged form keeps its
     negative entry because ITS operands still arrive from host numpy
     every call.
  2. **Use-after-donate** — a call passing a plain variable at a
     donated position kills that variable: any later read of it in the
     same function (without an intervening rebind — the
     ``state, pending = step(state, ...)`` loop idiom rebinds at the
     call itself) is a finding.  Round 21 extends the tracked argument
     shapes from plain ``Name``\\ s to **dotted attribute paths**
     (``rs.carry``): the resident-state object hangs its donated carry
     off an attribute, and the crash-safe snapshot hook made host
     reads of that attribute (``np.asarray(rs.carry.avail)``) an easy
     mistake — reading any path AT or BELOW a donated path after the
     donating call, without an intervening rebind of the path or a
     prefix of it (``rs.carry = new`` or ``rs = ...``), is a finding.
     Precision limit: only Name/Attribute chains are tracked (a
     ``*args`` spread or a fresh ``jnp.asarray(...)`` at the call site
     has no path to misuse).
  3. **Missed donations** — discovery: a jitted entry point whose
     wrapped function *returns* a carry-named parameter
     (:data:`_CARRY_HINTS` — the structurally-unchanged-shape carry
     signature) without donating it is flagged, unless the carry is
     covered by a manifest entry or the gap is a declared, justified
     exemption in :data:`EXEMPT`.  An exemption is a documented
     decision; an undeclared gap is a finding — the same discipline
     as the parity matrix.
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from pivot_tpu.analysis import Finding
from pivot_tpu.analysis import jitmap

RULE = "donation"


class Carry(NamedTuple):
    """One declared carry decision."""

    file: str       # repo-relative file of the jit site
    site: str       # jit-site name (wrapper/factory, jitmap naming)
    arg: int        # positional index of the carry in the WRAPPED fn
    param: str      # its parameter name
    donated: bool   # the declared decision, enforced both ways
    why: str        # rationale (negative entries especially)


#: carry label → declared decision.
MANIFEST: Dict[str, Carry] = {
    "span-avail-carry": Carry(
        "pivot_tpu/ops/tickloop.py", "_fused_tick_run", 0, "avail",
        donated=False,
        why="span operands are staged from host numpy per call; "
            "CPU-backend jnp.asarray is zero-copy for large aligned "
            "arrays, so donation would scribble on caller-owned memory",
    ),
    "sharded-span-avail-carry": Carry(
        "pivot_tpu/ops/shard.py", "_sharded_span_fn", 0, "avail",
        donated=False,
        why="sharded twin of span-avail-carry — same zero-copy hazard",
    ),
    "resident-span-carry": Carry(
        "pivot_tpu/ops/tickloop.py", "_resident_span_run", 0, "carry",
        donated=True,
        why="the carry is always a previous jit OUTPUT — "
            "resident_carry_init materializes an explicit device copy "
            "before the first donation, so the round-13 zero-copy "
            "hazard (caller-owned host memory behind a donated "
            "position) is structurally unreachable",
    ),
    "sharded-resident-span-carry": Carry(
        "pivot_tpu/ops/shard.py", "_sharded_resident_span_fn", 0,
        "carry", donated=True,
        why="sharded twin of resident-span-carry — same output-fed "
            "ownership contract, the carry shard-resident between "
            "spans",
    ),
    "ensemble-segment-carry": Carry(
        "pivot_tpu/parallel/ensemble/checkpoint.py",
        "_segment_step_carry", 0, "state", donated=True,
        why="the carry is always a previous segment's OUTPUT (device-"
            "owned; the executor defensively copies the first state)",
    ),
    "sweep-row-carry": Carry(
        "pivot_tpu/parallel/ensemble/sweeps.py",
        "_row_segment_step_carry", 0, "states", donated=True,
        why="same output-fed contract as ensemble-segment-carry",
    ),
}

#: Donating callables tracked for use-after-donate, by PUBLIC call name
#: → donated positional index at that call site (only the POSITIVE
#: manifest entries — an undonated carry cannot be used-after-donate).
DONATING_CALLS: Dict[str, int] = {
    "_segment_step_carry": 0,
    "_row_segment_step_carry": 0,
    # Resident span tier (round 20): the public wrappers, their jitted
    # forms, and the policy-layer dispatch helper all CONSUME the carry
    # at the listed position — reading it afterwards is the classic
    # span-of-death (works on GPU until the allocator reuses the page,
    # raises on CPU).
    "resident_span_run": 0,
    "_resident_span_run": 0,
    "sharded_resident_span_run": 1,  # (mesh, carry, ...)
    "_resident_dispatch": 0,
}

#: Parameter names that mark a carry-shaped argument in the
#: missed-donation discovery.
_CARRY_HINTS = frozenset({"avail", "avail_r", "state", "states", "carry"})

#: (file, site name, param) → justification.  Declared decisions NOT to
#: donate a returned carry-shaped argument.
EXEMPT: Dict[Tuple[str, str, str], str] = {
    ("pivot_tpu/ops/kernels.py", "opportunistic_kernel", "avail"):
        "per-tick twin: parity suites re-dispatch one staged snapshot "
        "to several forms; the [H, 4] buffer is not a cross-call carry",
    ("pivot_tpu/ops/kernels.py", "first_fit_kernel", "avail"):
        "per-tick twin — same snapshot-sharing contract as above",
    ("pivot_tpu/ops/kernels.py", "best_fit_kernel", "avail"):
        "per-tick twin — same snapshot-sharing contract as above",
    ("pivot_tpu/ops/kernels.py", "cost_aware_kernel", "avail"):
        "per-tick twin — same snapshot-sharing contract as above",
    ("pivot_tpu/ops/pallas_kernels.py", "cost_aware_pallas_batched",
     "avail_r"):
        "bench and placement_sensitivity re-score the same [R, H, 4] "
        "replica ensemble across repeats; VMEM, not HBM aliasing, is "
        "the binding constraint for the Pallas form",
    ("pivot_tpu/ops/tickloop.py", "_resident_carry_init", "avail"):
        "init materializes the explicit device-owned copy that SEEDS "
        "the resident donation chain; donating its input — possibly a "
        "zero-copy view of caller host memory — is exactly the "
        "round-13 hazard the copy exists to rule out",
    ("pivot_tpu/ops/tickloop.py", "_resident_carry_clone", "carry"):
        "the splice checkpoint clone must leave its SOURCE intact (the "
        "span re-runs from it on a mid-span arrival); donation would "
        "defeat the clone's purpose",
    ("pivot_tpu/parallel/ensemble/checkpoint.py", "_segment_step",
     "state"):
        "the deliberately NON-donating twin behind the segmented "
        "executor's defensive first copy (see _segment_step_carry)",
    ("pivot_tpu/parallel/ensemble/sweeps.py", "_row_segment_step",
     "states"):
        "non-donating twin of _row_segment_step_carry, by design",
    ("pivot_tpu/parallel/ensemble/bill.py", "_finalize_batch", "states"):
        "finalize derives metrics from every state leaf; callers "
        "legitimately inspect final states after finalizing, and the "
        "int32 stage/qpos leaves share no shape with any output",
}


def _manifest_findings(
    sites: Dict[str, List[jitmap.JitSite]]
) -> List[Finding]:
    out: List[Finding] = []
    for label, carry in sorted(MANIFEST.items()):
        if carry.file not in sites:
            continue  # registry finding already emitted by retrace
        match = [s for s in sites[carry.file] if s.name == carry.site]
        if not match:
            out.append(Finding(
                RULE, carry.file, 0,
                f"manifest carry {label!r}: jit site {carry.site} not "
                "found — renamed? update pivot_tpu/analysis/donation.py "
                "MANIFEST (the carry lost its declared-decision check)",
            ))
            continue
        for site in match:
            donated = (
                carry.arg in site.donate_nums
                or carry.param in site.donate_params
            )
            if carry.donated and not donated:
                out.append(Finding(
                    RULE, carry.file, site.lineno,
                    f"manifest carry {label!r}: {carry.site} does not "
                    f"donate argument {carry.arg} ({carry.param!r}) — "
                    "the carry holds two live copies per dispatch; add "
                    f"donate_argnums=({carry.arg},)",
                ))
            elif not carry.donated and donated:
                out.append(Finding(
                    RULE, carry.file, site.lineno,
                    f"manifest carry {label!r}: {carry.site} DONATES "
                    f"argument {carry.arg} ({carry.param!r}) against "
                    f"the declared decision ({carry.why}) — remove "
                    "donate_argnums or flip the manifest entry with a "
                    "new safety argument",
                ))
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    """Resolve a Name/Attribute chain to its dotted path (``rs.carry``
    → ``"rs.carry"``), or None when any link is something else (a
    subscript, a call result — no stable path to track)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _assigned_names(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        for tgt in targets:
            for node in ast.walk(tgt):
                if isinstance(node, ast.Name):
                    out.add(node.id)
                elif isinstance(node, ast.Attribute):
                    path = _dotted(node)
                    if path is not None:
                        out.add(path)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        for node in ast.walk(stmt.target):
            if isinstance(node, ast.Name):
                out.add(node.id)
    return out


def _own_nodes(fn: ast.AST) -> List[ast.AST]:
    """Every AST node belonging to ``fn`` itself — nested ``def``
    bodies are excluded, so a donation in one function can never be
    conflated with a read of a same-named variable in another scope
    (lambdas stay included: they close over the enclosing frame)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _use_after_donate(src, fn: ast.AST) -> List[Finding]:
    """Flag reads of a variable — or dotted attribute path — after it
    was passed at a donated position, with no rebind in between
    (line-ordered approximation over ONE function scope; a rebind at
    the donating call's own statement counts, and rebinding any dotted
    PREFIX of a donated path — ``rs.carry = new``, ``rs = fresh()`` —
    clears the path it carries)."""
    out: List[Finding] = []
    # (path, call lineno, call end lineno) — the call's own span is
    # excluded from the read scan (the donated argument itself may sit
    # on a later physical line of a multi-line call).
    donations: List[Tuple[str, int, int]] = []
    rebinds: List[Tuple[str, int]] = []
    nodes = _own_nodes(fn)

    for node in nodes:
        if isinstance(node, ast.stmt):
            for name in _assigned_names(node):
                rebinds.append((name, node.lineno))
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        callee = (
            f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None
        )
        if callee not in DONATING_CALLS:
            continue
        idx = DONATING_CALLS[callee]
        if idx < len(node.args):
            path = _dotted(node.args[idx])
            if path is not None:
                donations.append((
                    path, node.lineno, node.end_lineno or node.lineno,
                ))

    if not donations:
        return out
    for var, call_line, call_end in donations:
        for node in nodes:
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            if node.lineno <= call_end:
                continue
            # Exact-path matching suffices for deeper reads too: a load
            # of ``rs.carry.avail`` CONTAINS the ``rs.carry`` Attribute
            # node as a Load child, which matches here — one finding at
            # the donated path, not one per trailing attribute.
            if _dotted(node) != var:
                continue
            rebound = any(
                (name == var or var.startswith(name + "."))
                and call_line <= line <= node.lineno
                for name, line in rebinds
            )
            if not rebound:
                out.append(Finding(
                    RULE, src.path, node.lineno,
                    f"use-after-donate: {var!r} was donated at line "
                    f"{call_line} (its buffer is deleted by the "
                    "call) and is read here without a rebind — "
                    "re-stage the operand or restructure",
                ))
    return out


def _returned_names(fn: ast.AST) -> Set[str]:
    """Names appearing in any return expression (lambda body included)."""
    out: Set[str] = set()
    if isinstance(fn, ast.Lambda):
        for node in ast.walk(fn.body):
            if isinstance(node, ast.Name):
                out.add(node.id)
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


def _missed_donations(
    sites: Dict[str, List[jitmap.JitSite]]
) -> List[Finding]:
    out: List[Finding] = []
    covered = {
        (c.file, c.site, c.param) for c in MANIFEST.values()
    }
    for rel in sorted(sites):
        for site in sites[rel]:
            if site.fn is None:
                continue
            returned = _returned_names(site.fn)
            for param in jitmap.positional_params(site.fn):
                if param not in _CARRY_HINTS or param not in returned:
                    continue
                if param in site.donate_params:
                    continue
                key = (rel, site.name, param)
                if key in covered or key in EXEMPT:
                    continue
                out.append(Finding(
                    RULE, rel, site.lineno,
                    f"missed donation: jitted {site.name} returns its "
                    f"carry-shaped argument {param!r} without donating "
                    "it — two live copies per call; record the decision "
                    "in the MANIFEST (donated or justified-undonated) "
                    "or declare an exemption in "
                    "pivot_tpu/analysis/donation.py",
                ))
    return out


def collect(cache) -> Tuple[List[Finding], List[str]]:
    sites, _registry_findings, scanned = jitmap.collect_sites(cache)
    out: List[Finding] = []
    # A manifest carry whose registered file vanished must fail THIS
    # pass loudly, not rely on retrace also running — `--rules
    # donation` alone would otherwise print clean while the carry's
    # declared-decision check silently disappears.
    for label, carry in sorted(MANIFEST.items()):
        if carry.file not in sites:
            out.append(Finding(
                RULE, carry.file, 0,
                f"manifest carry {label!r}: registered file "
                f"{carry.file} is missing — renamed/deleted? update "
                "pivot_tpu/analysis/donation.py MANIFEST (and "
                "jitmap.JIT_FILES); the carry lost its donation check",
            ))
    out.extend(_manifest_findings(sites))
    out.extend(_missed_donations(sites))
    for rel in sorted(sites):
        src = cache.get(rel)
        for node in ast.walk(src.tree):
            # Per innermost function: _own_nodes keeps each scope's
            # donations and reads from leaking into sibling scopes.
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_use_after_donate(src, node))
    return out, scanned
