"""``python -m pivot_tpu.analysis`` — the graftcheck CLI."""

import sys

from pivot_tpu.analysis import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
