"""Host-sync lint for the fused device hot paths (the migrated PR-6 lint).

The dispatch floor this repo spent three perf rounds killing creeps
back in through ONE line of code: a host synchronization inside a
device loop body — ``np.asarray`` on a tracer, ``.item()``, a
``float(...)`` coercion, a stray ``block_until_ready``.  Each forces a
device→host round trip per loop iteration and silently turns an
O(1)-dispatch program back into an O(K)-dispatch one.

What changed in the graftcheck migration: the hand-maintained
``DEFAULT_TARGETS`` dict of ``tools/hotpath_lint.py`` is replaced by
**naming-convention auto-discovery** (:data:`DISCOVER`) — every
top-level function matching a hot-path pattern (``*_impl``, the scan
cores, the span algebra, the sharded passes/reduces, the rollout
body) in the registered files is a lint target the moment it is
written, so a NEW kernel form cannot be forgotten.  :data:`REQUIRED`
keeps the rename protection: anchor functions that must exist (a
registered hot path silently renamed away would otherwise drop out of
coverage).  ``tools/hotpath_lint.py`` remains as a thin shim over
this module with its CLI contract and ``lint_paths``/``lint_file``
API unchanged.

Banned constructs (in a discovered body, nested closures included):
``.block_until_ready()``/``.item()``/``.tolist()``, numpy host
materialization (``np.asarray``/``np.array``/…), ``jax.device_get``,
``float``/``int``/``bool`` on a non-literal, and ``print``.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, NamedTuple, Sequence, Tuple

from pivot_tpu.analysis import Finding, SourceFile

RULE = "host-sync"

#: repo-relative file → fnmatch patterns of top-level hot-path bodies.
DISCOVER: Dict[str, Tuple[str, ...]] = {
    "pivot_tpu/ops/kernels.py": (
        "*_impl", "_*_scan", "_slim_drive", "_chunk_drive",
        "_speculate_commit", "_ca_*",
    ),
    "pivot_tpu/ops/tickloop.py": (
        "_fused_tick_run_impl", "_span_*",
        # Round-20 resident tier: the donated-carry span driver and the
        # carry init/clone impls (a host sync inside any of them would
        # fetch the device-persistent state every span — the exact
        # round-trip residency exists to eliminate).
        "_resident_*",
    ),
    "pivot_tpu/ops/shard.py": (
        "*_sharded_pass", "*_sharded_chunk*", "_sharded_chunk_drive",
        "_sharded_span_body", "_two_stage_argmin*", "_first_index_of*",
        "_opportunistic_pick*", "_place_local", "_bump_local",
        "_risk_restrict_sharded*",
        # Round-17 shared per-shard body factories: the closures the 1-D
        # AND [G]-batched 2-D jit factories both wrap (a host sync here
        # would poison every sharded program at once).
        "*_sharded_body", "_span_fn_body",
        # Round-20: the shard-resident donated-carry span body factory.
        "_resident_span_fn_body",
        # Round-22 (elastic mesh serving) adds NO new device bodies:
        # every ladder rung reuses the sharded programs above on a
        # smaller mesh.  The ``elastic_*`` / ``mesh_shape_ladder``
        # re-layout helpers are deliberately HOST-side (numpy at the
        # reshard boundary — folding the carry off a dying mesh IS a
        # host materialization) and must stay out of these patterns:
        # registering them would flag their np.asarray fetches, which
        # are the feature, not a leak.
    ),
    "pivot_tpu/parallel/ensemble/tick.py": ("_rollout_segment",),
    "pivot_tpu/search/fitness.py": ("_fitness_rows_impl", "_draw_rows_impl"),
}

#: Anchor bodies that MUST be discovered per file — a rename that
#: dodges the patterns is flagged instead of silently dropping out.
REQUIRED: Dict[str, Tuple[str, ...]] = {
    "pivot_tpu/ops/kernels.py": (
        "opportunistic_impl", "first_fit_impl", "best_fit_impl",
        "cost_aware_impl", "_speculate_commit",
    ),
    "pivot_tpu/ops/tickloop.py": (
        "_fused_tick_run_impl", "_resident_span_run_impl",
    ),
    "pivot_tpu/ops/shard.py": (
        "_sharded_span_body", "_two_stage_argmin",
        "_cost_aware_sharded_body", "_span_fn_body",
        "_resident_span_fn_body",
    ),
    "pivot_tpu/parallel/ensemble/tick.py": ("_rollout_segment",),
    "pivot_tpu/search/fitness.py": ("_fitness_rows_impl",),
}

_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_NUMPY_HOST_FNS = {"asarray", "array", "copyto", "savetxt"}
_COERCIONS = {"float", "int", "bool"}


class Violation(NamedTuple):
    """The legacy hotpath_lint violation shape (API-stable for the
    ``tools/hotpath_lint.py`` shim and ``tests/test_meta.py``)."""

    path: str
    func: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: in {self.func}(): {self.message}"


def _is_literal(node: ast.AST) -> bool:
    """Constant-ish argument — coercing it cannot touch a device value.
    Covers signed numeric literals (``-1`` parses as UnaryOp(USub,
    Constant))."""
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_literal(node.operand)
    return isinstance(node, ast.Constant)


def _check_call(node: ast.Call, path: str, func: str) -> List[Violation]:
    out: List[Violation] = []
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in _SYNC_ATTRS:
            out.append(Violation(
                path, func, node.lineno,
                f"host-sync call .{f.attr}() inside a fused hot path",
            ))
        elif (
            isinstance(f.value, ast.Name)
            and f.value.id in _NUMPY_ALIASES
            and f.attr in _NUMPY_HOST_FNS
        ):
            out.append(Violation(
                path, func, node.lineno,
                f"host materialization {f.value.id}.{f.attr}(...) inside "
                "a fused hot path",
            ))
        elif (
            isinstance(f.value, ast.Name)
            and f.value.id == "jax"
            and f.attr == "device_get"
        ):
            out.append(Violation(
                path, func, node.lineno,
                "jax.device_get(...) inside a fused hot path",
            ))
    elif isinstance(f, ast.Name):
        if f.id in _COERCIONS and node.args and not all(
            _is_literal(a) for a in node.args
        ):
            out.append(Violation(
                path, func, node.lineno,
                f"scalar coercion {f.id}(...) on a non-literal inside a "
                "fused hot path (blocks on the traced value)",
            ))
        elif f.id == "print":
            out.append(Violation(
                path, func, node.lineno,
                "print(...) inside a fused hot path (stringification "
                "fetches)",
            ))
    return out


def lint_tree(
    tree: ast.AST, path: str, func_names: Sequence[str]
) -> List[Violation]:
    """Scan the named function bodies (nested closures included) of a
    parsed module.  A registered name that does not exist is itself a
    violation — a silently renamed hot path would otherwise drop out of
    coverage without anyone noticing."""
    found: set = set()
    out: List[Violation] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in func_names
        ):
            found.add(node.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    out.extend(_check_call(sub, path, node.name))
    for missing in sorted(set(func_names) - found):
        out.append(Violation(
            path, missing, 0,
            "registered hot-path function not found — update the "
            "hot-path registration after renames",
        ))
    return out


def lint_functions(path: str, func_names: Sequence[str]) -> List[Violation]:
    """File-path entry point (the shim's ``lint_file``)."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    return lint_tree(tree, path, func_names)


def discover_targets(src: SourceFile, patterns: Sequence[str]) -> List[str]:
    """Top-level function names matching the hot-path patterns, in
    definition order."""
    return [
        node.name
        for node in src.tree.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and any(fnmatch.fnmatchcase(node.name, p) for p in patterns)
    ]


#: Union of every per-file pattern — used to sweep ops files the
#: DISCOVER dict does not know yet: a hot-path-shaped body in a NEW
#: file must be flagged for registration, not silently skipped (every
#: recent backend PR introduced its bodies in a new file).
_ALL_PATTERNS = tuple(
    sorted({p for pats in DISCOVER.values() for p in pats})
)


def collect(cache) -> Tuple[List[Finding], List[str]]:
    import os

    out: List[Finding] = []
    scanned: List[str] = []
    ops_dir = os.path.join(cache.root, "pivot_tpu/ops")
    if os.path.isdir(ops_dir):
        for name in sorted(os.listdir(ops_dir)):
            rel = f"pivot_tpu/ops/{name}"
            if not name.endswith(".py") or rel in DISCOVER:
                continue
            src = cache.get(rel)
            if src is None:
                continue
            scanned.append(rel)
            for fn in discover_targets(src, _ALL_PATTERNS):
                out.append(Finding(
                    RULE, rel, 1,
                    f"hot-path-shaped body {fn}() in a file the lint "
                    f"does not cover — add {rel} to "
                    "pivot_tpu/analysis/hostsync.py DISCOVER",
                ))
    for rel, patterns in DISCOVER.items():
        src = cache.get(rel)
        if src is None:
            out.append(Finding(
                RULE, rel, 0,
                f"registered hot-path file {rel} is missing — renamed/"
                "deleted? update hostsync DISCOVER/REQUIRED (its bodies "
                "lost all lint coverage)",
            ))
            continue
        scanned.append(rel)
        names = discover_targets(src, patterns)
        if not names:
            out.append(Finding(
                RULE, rel, 1,
                "no hot-path bodies discovered — the naming patterns "
                "match nothing; update pivot_tpu/analysis/hostsync.py",
            ))
        missing = [
            name for name in REQUIRED.get(rel, ()) if name not in names
        ]
        for name in missing:
            out.append(Finding(
                RULE, rel, 1,
                f"required hot-path body {name}() not discovered — "
                "renamed away from the conventions? update REQUIRED/"
                "DISCOVER in pivot_tpu/analysis/hostsync.py",
            ))
        for v in lint_tree(src.tree, rel, names):
            out.append(Finding(RULE, v.path, v.line, v.message))
    return out, scanned
