"""Dtype lint: no float64 across the device boundary, no weak-type forks.

The device layer is a **float32 world** (``_DevicePolicyBase.dtype``;
the f64 parity runs opt in explicitly by overriding the policy dtype).
Under ``jax_enable_x64`` — the tests' configuration, and any user's one
config flag away — an implicitly-typed staging buffer silently becomes
float64 on the device: memory doubles, and the compile cache forks into
per-dtype program families (the retrace pass's problem wearing a dtype
mask).  PR 11's fix moved every such buffer to **cast-at-source** (built
in the policy dtype, f64 math rounding once on assignment — bit-identical
to the old cast-at-staging); this pass keeps it that way:

  * **float64 on the boundary** — any ``np.float64`` / ``jnp.float64``
    reference, ``"float64"`` dtype string, or ``.astype(np.float64)``
    in the device-boundary modules (:data:`SCOPE`) is a finding.  Host
    f64 math is fine everywhere else (the DES and the numpy twins ARE
    f64 by contract); what is banned is f64 *typing the arrays that get
    staged*.  A justified exception carries a
    ``# graftcheck: ignore[dtype] -- reason`` suppression.
  * **weak-type mixing in kernel cores** — inside the hot-path bodies
    (the host-sync pass's DISCOVER map, shared so the two passes cover
    the same cores), a ``jnp.asarray``/``jnp.array``/``jnp.full`` whose
    payload is a float literal and that omits an explicit dtype creates
    a weak-typed scalar whose concrete dtype follows the x64 flag —
    one innocuous constant forks the kernel's compile cache per config.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from pivot_tpu.analysis import Finding, SourceFile
from pivot_tpu.analysis import hostsync

RULE = "dtype"

#: Device-boundary modules: files whose numpy arrays get staged onto
#: the accelerator.  The CPU twins (``sched/policies.py``), the DES,
#: and the converters stay out of scope — f64 is their contract.
SCOPE = (
    "pivot_tpu/ops",
    "pivot_tpu/sched/tpu.py",
    "pivot_tpu/sched/batch.py",
    "pivot_tpu/parallel",
)

_ARRAY_MODS = {"np", "numpy", "onp", "jnp"}


def _is_float64_ref(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "float64"
        and isinstance(node.value, ast.Name)
        and node.value.id in _ARRAY_MODS
    )


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_float_literal(node.operand)
    return isinstance(node, ast.Constant) and isinstance(node.value, float)


def scan_boundary(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        if _is_float64_ref(node):
            out.append(Finding(
                RULE, src.path, node.lineno,
                "float64 on a device-boundary path — under x64 this "
                "stages a double-width buffer and forks the compile "
                "cache per dtype; build in the policy dtype at source "
                "(np.dtype(self.dtype)) so f64 math rounds once on "
                "assignment",
            ))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "float64"
        ):
            out.append(Finding(
                RULE, src.path, node.lineno,
                'astype("float64") on a device-boundary path — see the '
                "cast-at-source rule",
            ))
        elif isinstance(node, ast.keyword) and node.arg == "dtype" and (
            isinstance(node.value, ast.Constant)
            and node.value.value == "float64"
        ):
            out.append(Finding(
                RULE, src.path, node.lineno,
                'dtype="float64" on a device-boundary path — see the '
                "cast-at-source rule",
            ))
    return out


def _weak_ctor_findings(src: SourceFile, fn_names) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in fn_names
        ):
            continue
        for sub in ast.walk(node):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == "jnp"
                and sub.func.attr in {"asarray", "array", "full"}
            ):
                continue
            payload_idx = 1 if sub.func.attr == "full" else 0
            if len(sub.args) <= payload_idx or not _is_float_literal(
                sub.args[payload_idx]
            ):
                continue
            has_dtype = len(sub.args) > payload_idx + 1 or any(
                kw.arg == "dtype" for kw in sub.keywords
            )
            if not has_dtype:
                out.append(Finding(
                    RULE, src.path, sub.lineno,
                    f"weak-typed jnp.{sub.func.attr}(<float literal>) "
                    f"without an explicit dtype inside hot body "
                    f"{node.name}() — its concrete dtype follows the "
                    "x64 flag and forks the kernel's compile cache; "
                    "pass the carry dtype explicitly",
                ))
    return out


def _scope_files(root: str) -> List[str]:
    import os

    rels: List[str] = []
    for entry in SCOPE:
        abspath = os.path.join(root, entry)
        if os.path.isfile(abspath):
            rels.append(entry)
        elif os.path.isdir(abspath):
            for dirpath, _dirs, files in sorted(os.walk(abspath)):
                for name in sorted(files):
                    if name.endswith(".py"):
                        rels.append(os.path.relpath(
                            os.path.join(dirpath, name), root
                        ))
    return rels


def collect(cache) -> Tuple[List[Finding], List[str]]:
    import os

    out: List[Finding] = []
    scanned: List[str] = []
    for entry in SCOPE:
        if not os.path.exists(os.path.join(cache.root, entry)):
            out.append(Finding(
                RULE, entry, 0,
                f"device-boundary scope entry {entry} is missing — "
                "renamed/deleted? update dtype SCOPE (it lost all lint "
                "coverage)",
            ))
    for rel in _scope_files(cache.root):
        src = cache.get(rel)
        if src is None:
            continue
        scanned.append(rel)
        out.extend(scan_boundary(src))
        targets = hostsync.DISCOVER.get(rel)
        if targets:
            names = hostsync.discover_targets(src, targets)
            out.extend(_weak_ctor_findings(src, set(names)))
    return out, scanned
