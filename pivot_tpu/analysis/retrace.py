"""Retrace lint: recompilation hazards in the jitted entry points.

A jitted program is compiled once per (shapes, dtypes, static values)
key; everything that silently widens that key — or blocks the trace on
a device value — passes every CPU test and only surfaces as wall-clock
collapse on hardware (ROADMAP item 1's recapture is exactly where this
bites).  The steady-state hypothesis this pass protects is the one the
tier-1 compile-counter harness (``tests/test_jitcheck.py``) asserts at
runtime: **zero recompiles after warmup** on the fused-span and serve
dispatch paths.  Every rule below is the static shadow of a way that
hypothesis dies:

  * **traced-value branching** — an ``if``/``while``/ternary testing a
    non-static parameter of a jitted function raises a tracer-bool
    error at best and, when the value happens to be concrete (weak
    scalars, shapes smuggled as values), forks one compile cache entry
    per value at worst.  ``x is None`` / ``x is not None`` tests are the
    sanctioned trace-structure dispatch (an operand that is absent vs
    present IS a static program distinction) and stay allowed.
  * **host coercion of traced values** — ``float()``/``int()``/
    ``bool()`` over an expression containing a traced parameter,
    ``.item()``/``.tolist()`` on one, ``np.asarray``/``np.array`` of
    one, and ``jax.device_get`` force a device→host sync per call
    (the host-sync pass covers the hot *bodies*; this covers every
    jitted entry point, including the ensemble and batcher wrappers).
  * **stale static declarations** — a ``static_argnames`` entry naming
    no parameter of the wrapped function: after a parameter rename the
    knob silently becomes *traced*, and every distinct value retraces
    the program.  ``static_argnums`` out of positional range is the
    same rot.
  * **unhashable static defaults** — a static parameter defaulting to a
    list/dict/set literal fails hashing at the first call that relies
    on the default.
  * **closure-captured numpy constants** — a module-level
    ``np.array(...)``-family constant referenced inside a jitted body
    constant-folds into the HLO: the array is baked into the program
    (bloating it and re-baking on every content change) instead of
    riding the argument path as a device operand.
  * **Python loops over traced extents** — ``for ... in range(x)`` with
    ``x`` traced unrolls (or errors); bounded device loops belong in
    ``lax.fori_loop``/``lax.while_loop``.

Scope: the jitted entry points discovered by
:mod:`pivot_tpu.analysis.jitmap` (plus its registry findings — a new
file growing a jit wrapper must register there).  Only the wrapped
function's own body (nested defs/lambdas included) is scanned; helpers
it calls are covered when they are themselves registered hot bodies
(host-sync pass) or entry points.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from pivot_tpu.analysis import Finding, SourceFile
from pivot_tpu.analysis import jitmap

RULE = "retrace"

_NUMPY_ALIASES = {"np", "numpy", "onp"}
_NUMPY_CTORS = {
    "array", "asarray", "zeros", "ones", "full", "arange", "linspace",
    "eye", "empty",
}
_COERCIONS = {"float", "int", "bool"}
_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}


def _names_in(node: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _is_none_guard(test: ast.AST, traced: Set[str]) -> bool:
    """True when every traced-parameter reference in ``test`` sits
    inside an ``is None`` / ``is not None`` comparison (possibly under
    boolean operators) — the sanctioned operand-presence dispatch."""
    if isinstance(test, ast.BoolOp):
        return all(_is_none_guard(v, traced) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_guard(test.operand, traced)
    if isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return True
    return not (_names_in(test) & traced)


def _module_np_constants(src: SourceFile) -> Dict[str, int]:
    """Module-level names bound to numpy-constructor calls."""
    out: Dict[str, int] = {}
    for node in src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Attribute)
            and isinstance(v.func.value, ast.Name)
            and v.func.value.id in _NUMPY_ALIASES
            and v.func.attr in _NUMPY_CTORS
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.lineno
    return out


def check_site(
    site: jitmap.JitSite,
    np_constants: Dict[str, int],
) -> List[Finding]:
    out: List[Finding] = []
    for stale in site.stale_statics:
        out.append(Finding(
            RULE, site.path, site.lineno,
            f"static declaration {stale!r} of {site.name} matches no "
            "parameter of the wrapped function — after a rename the knob "
            "silently becomes TRACED and every distinct value recompiles "
            "the program; update the static declaration",
        ))
    fn = site.fn
    if fn is None:
        return out
    pos = jitmap.positional_params(fn)
    statics = set(site.static_names)
    traced = {p for p in jitmap.all_params(fn) if p not in statics}

    # Unhashable static defaults.
    args = fn.args
    named = (*args.posonlyargs, *args.args)
    defaults = args.defaults
    for param, default in zip(named[len(named) - len(defaults):], defaults):
        if param.arg in statics and isinstance(
            default, (ast.List, ast.Dict, ast.Set)
        ):
            out.append(Finding(
                RULE, site.path, default.lineno,
                f"static parameter {param.arg!r} of {site.name} defaults "
                "to an unhashable literal — the first call relying on the "
                "default fails the static-argument hash",
            ))

    body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
    # Names the function shadows — its own parameters (nested defs'
    # included) and everything it assigns: a module-level numpy
    # constant hidden behind a same-named local never constant-folds.
    shadowed: Set[str] = set(jitmap.all_params(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            shadowed.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            shadowed.update(jitmap.all_params(node))
    for stmt in body:
        for node in ast.walk(stmt):
            test = None
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                test = node.test
            if test is not None:
                hits = _names_in(test) & traced
                if hits and not _is_none_guard(test, traced):
                    out.append(Finding(
                        RULE, site.path, node.lineno,
                        "Python-level branch on traced parameter(s) "
                        f"{sorted(hits)} inside jitted {site.name} — "
                        "declare the knob static, dispatch on `is None`, "
                        "or move the branch into lax.cond/lax.select",
                    ))
            if isinstance(node, ast.For):
                if (
                    isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and _names_in(node.iter) & traced
                ):
                    out.append(Finding(
                        RULE, site.path, node.lineno,
                        "Python for-loop over a traced extent inside "
                        f"jitted {site.name} — unrolls per value; use "
                        "lax.fori_loop / lax.while_loop",
                    ))
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id in _COERCIONS:
                    hits = set().union(
                        *(_names_in(a) for a in node.args), set()
                    ) & traced
                    if hits:
                        out.append(Finding(
                            RULE, site.path, node.lineno,
                            f"host coercion {f.id}(...) of traced "
                            f"parameter(s) {sorted(hits)} inside jitted "
                            f"{site.name} — blocks on the device value "
                            "every call",
                        ))
                elif isinstance(f, ast.Attribute):
                    if f.attr in _SYNC_ATTRS and _names_in(f.value) & traced:
                        out.append(Finding(
                            RULE, site.path, node.lineno,
                            f"host sync .{f.attr}() on a traced parameter "
                            f"inside jitted {site.name}",
                        ))
                    elif (
                        isinstance(f.value, ast.Name)
                        and (
                            (f.value.id in _NUMPY_ALIASES
                             and f.attr in {"asarray", "array"})
                            or (f.value.id == "jax"
                                and f.attr == "device_get")
                        )
                        and node.args
                        and _names_in(node.args[0]) & traced
                    ):
                        out.append(Finding(
                            RULE, site.path, node.lineno,
                            f"host materialization {f.value.id}.{f.attr}"
                            f"(...) of a traced parameter inside jitted "
                            f"{site.name}",
                        ))
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id in np_constants and node.id not in shadowed:
                out.append(Finding(
                    RULE, site.path, node.lineno,
                    f"jitted {site.name} closes over module-level numpy "
                    f"constant {node.id!r} (defined line "
                    f"{np_constants[node.id]}) — it constant-folds into "
                    "the HLO; pass it as an operand instead",
                ))
    return out


def collect(cache) -> Tuple[List[Finding], List[str]]:
    sites, findings, scanned = jitmap.collect_sites(cache)
    out = list(findings)
    for rel in sorted(sites):
        src = cache.get(rel)
        np_constants = _module_np_constants(src)
        for site in sites[rel]:
            out.extend(check_site(site, np_constants))
    return out, scanned
