"""Pallas VMEM-budget pass: recompute every kernel's footprint statically.

The replica-batched greedy kernel (``ops/pallas_kernels.py``) sizes its
blocks against byte formulas (``rb_bytes``/``tile_bytes``) that were
derived BY HAND from the BlockSpec tile set and validated on hardware
(RB=512 at Hp=512 compiles; RB=1024 fails Mosaic).  Those formulas are
load-bearing — the auto-sizer trusts them — and nothing stopped a tile
edit from silently de-syncing them until a real chip OOMed.  This pass
closes the loop without a chip:

  1. **Recompute** the footprint from the ``pl.pallas_call`` spec set
     itself: every VMEM ``BlockSpec``/scratch shape is symbolically
     evaluated over the size variables (``RB``, ``Hp``, ``chunk``),
     with the accounting convention the hardware validated — blocks
     whose index_map varies along the **innermost grid axis** are
     double-buffered by the Mosaic pipeline (×2); grid-outer and
     invariant blocks are single (×1); SMEM streams are not VMEM.
  2. **Drift check**: the spec-derived replica-scaled and streamed-tile
     byte functions must equal the in-source ``rb_bytes``/``tile_bytes``
     formulas at every probe point.  Editing the specs without the
     formulas (or vice versa) fails here, at lint time.
  3. **Budget check**: against the v5e constants in
     ``infra/roofline.py`` (``PALLAS_VMEM_BUDGET_BYTES`` <
     ``V5E_SCOPED_VMEM_BYTES``), inside the hardware-proven host-lane
     envelope (``PALLAS_PROVEN_HP``): the auto-sizer's block must fit
     the budget, and even the minimum (one-sublane) block must fit the
     scoped limit — if it cannot, no fallback exists and the kernel is
     a guaranteed Mosaic compile failure at that shape.
  4. **Constant hygiene**: the kernel file must import the budget
     constants from roofline (a re-hardcoded literal is drift waiting
     to happen), and no Pallas operand may be 8-byte-typed (the dtype
     pass's rule, enforced where it doubles VMEM).
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Tuple

from pivot_tpu.analysis import Finding, SourceFile

RULE = "pallas-budget"

_PALLAS_FILE = "pivot_tpu/ops/pallas_kernels.py"
_ROOFLINE_FILE = "pivot_tpu/infra/roofline.py"
_BUDGET_CONSTS = (
    "V5E_SCOPED_VMEM_BYTES", "PALLAS_VMEM_BUDGET_BYTES", "PALLAS_PROVEN_HP",
)

#: dtype name (as written in source) → bytes per element.
_DTYPE_BYTES = {
    "f32": 4, "float32": 4, "int32": 4, "i32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int8": 1, "uint8": 1, "bool_": 1,
    "float64": 8, "int64": 8,
}

#: Probe points for the drift check: (Hp, chunk) pairs inside the
#: proven envelope plus RB values spanning the block range.
_RB_PROBES = (8, 64, 512)


class _Block(NamedTuple):
    shape: Tuple[ast.AST, ...]   # element expressions (unevaluated)
    dtype_bytes: int
    inner_varying: bool          # index_map reads the innermost grid axis
    memory_space: str            # "vmem" | "smem" | "?"
    lineno: int


def _safe_eval(node: ast.AST, env: Dict[str, float]):
    """Tiny arithmetic evaluator: constants, env names, + - * / // **."""
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise KeyError(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        v = _safe_eval(node.operand, env)
        return -v if isinstance(node.op, ast.USub) else v
    if isinstance(node, ast.BinOp):
        left = _safe_eval(node.left, env)
        right = _safe_eval(node.right, env)
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Div):
            return left / right
        if isinstance(node.op, ast.FloorDiv):
            return left // right
        if isinstance(node.op, ast.Pow):
            return left ** right
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in {"int", "max", "min"}:
            vals = [_safe_eval(a, env) for a in node.args]
            if node.func.id == "int":
                return int(vals[0])
            return max(vals) if node.func.id == "max" else min(vals)
    raise ValueError(
        f"unevaluable expression at line {getattr(node, 'lineno', '?')}"
    )


def _dtype_bytes_of(node: Optional[ast.AST], aliases: Dict[str, str]) -> int:
    """Bytes/element of a dtype expression (Name alias or jnp.attr)."""
    name = None
    if isinstance(node, ast.Name):
        name = aliases.get(node.id, node.id)
    elif isinstance(node, ast.Attribute):
        name = node.attr
    if name in _DTYPE_BYTES:
        return _DTYPE_BYTES[name]
    return -1  # unknown


def _lambda_inner_varying(lam: ast.AST) -> bool:
    """Does a BlockSpec index_map read its LAST (innermost-grid) param?"""
    if not isinstance(lam, ast.Lambda) or not lam.args.args:
        return False
    inner = lam.args.args[-1].arg
    return any(
        isinstance(n, ast.Name) and n.id == inner
        for n in ast.walk(lam.body)
    )


def _collect_spec_exprs(node: ast.AST) -> List[ast.Call]:
    """Spec-instance Call nodes of an in/out_specs expression: lists and
    tuples contribute their elements, ``+`` both sides, ternaries BOTH
    branches (worst case — the optional risk row counts)."""
    if isinstance(node, (ast.List, ast.Tuple)):
        out: List[ast.Call] = []
        for e in node.elts:
            out.extend(_collect_spec_exprs(e))
        return out
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _collect_spec_exprs(node.left) + _collect_spec_exprs(
            node.right
        )
    if isinstance(node, ast.IfExp):
        return _collect_spec_exprs(node.body) + _collect_spec_exprs(
            node.orelse
        )
    if isinstance(node, ast.Call):
        return [node]
    return []


class _KernelModel:
    """The statically-extracted model of one pallas_call's tile set."""

    def __init__(self):
        self.blocks: List[_Block] = []
        self.problems: List[Tuple[int, str]] = []  # (lineno, message)


def _resolve_helper(call: ast.Call, helpers: Dict[str, ast.Lambda]):
    """Expand ``smem_chunk(4)`` / ``whole((1, Hp))`` through its local
    lambda to the underlying BlockSpec call plus a substitution env."""
    name = call.func.id if isinstance(call.func, ast.Name) else None
    lam = helpers.get(name)
    if lam is None:
        return None, None
    subst: Dict[str, ast.AST] = {}
    for param, arg in zip(lam.args.args, call.args):
        subst[param.arg] = arg
    body = lam.body
    if isinstance(body, ast.Call):
        return body, subst
    return None, None


def _shape_elts(node: ast.AST, subst: Dict[str, ast.AST]) -> Optional[
    Tuple[ast.AST, ...]
]:
    if isinstance(node, ast.Name) and node.id in subst:
        node = subst[node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            subst.get(e.id, e) if isinstance(e, ast.Name) else e
            for e in node.elts
        )
    return None


def _classify_blockspec(
    call: ast.Call, subst: Dict[str, ast.AST], model: _KernelModel
) -> None:
    shape = _shape_elts(call.args[0], subst) if call.args else None
    index_map = call.args[1] if len(call.args) > 1 else None
    space = "vmem"
    for kw in call.keywords:
        if kw.arg == "index_map":
            index_map = kw.value
        elif kw.arg == "memory_space":
            if isinstance(kw.value, ast.Attribute):
                space = kw.value.attr.lower()
    if shape is None:
        model.problems.append((
            call.lineno,
            "BlockSpec with an unresolvable block shape — the budget "
            "pass cannot account for it; use a literal shape tuple",
        ))
        return
    model.blocks.append(_Block(
        shape, 4, _lambda_inner_varying(index_map), space, call.lineno
    ))


def extract_models(src: SourceFile) -> Tuple[
    List[Tuple[ast.FunctionDef, _KernelModel]], Dict[str, float]
]:
    """(function, tile model) per pallas_call, plus module constants."""
    consts: Dict[str, float] = {}
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.targets[0], ast.Name
        ):
            try:
                consts[node.targets[0].id] = _safe_eval(node.value, {})
            except (ValueError, KeyError):
                pass
    models: List[Tuple[ast.FunctionDef, _KernelModel]] = []
    for fn in src.tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        helpers: Dict[str, ast.Lambda] = {}
        aliases: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Name
            ):
                if isinstance(node.value, ast.Lambda):
                    helpers[node.targets[0].id] = node.value
                elif isinstance(node.value, ast.Attribute):
                    aliases[node.targets[0].id] = node.value.attr
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "pallas_call"
            ):
                continue
            model = _KernelModel()
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs"):
                    for spec in _collect_spec_exprs(kw.value):
                        f = spec.func
                        if isinstance(f, ast.Attribute) and (
                            f.attr == "BlockSpec"
                        ):
                            _classify_blockspec(spec, {}, model)
                        elif isinstance(f, ast.Name):
                            body, subst = _resolve_helper(spec, helpers)
                            if body is not None:
                                _classify_blockspec(body, subst, model)
                            else:
                                model.problems.append((
                                    spec.lineno,
                                    f"unresolvable spec helper "
                                    f"{f.id}(...) — the budget pass "
                                    "cannot account for this block",
                                ))
                elif kw.arg == "scratch_shapes":
                    for spec in _collect_spec_exprs(kw.value):
                        f = spec.func
                        if isinstance(f, ast.Attribute) and f.attr in (
                            "VMEM", "SMEM"
                        ):
                            shape = _shape_elts(spec.args[0], {})
                            nbytes = _dtype_bytes_of(
                                spec.args[1] if len(spec.args) > 1
                                else None,
                                aliases,
                            )
                            if shape is None or nbytes < 0:
                                model.problems.append((
                                    spec.lineno,
                                    "scratch shape/dtype the budget "
                                    "pass cannot evaluate",
                                ))
                            else:
                                model.blocks.append(_Block(
                                    shape, nbytes, False,
                                    f.attr.lower(), spec.lineno,
                                ))
            models.append((fn, model))
    return models, consts


def _footprint(
    model: _KernelModel, env: Dict[str, float]
) -> Tuple[float, float, float, List[str]]:
    """(replica-scaled bytes per replica, streamed fixed bytes,
    invariant fixed bytes, unevaluable-shape problems) under the
    validated accounting convention.  A shape the evaluator cannot
    price (a renamed size variable, a new free name) is reported as a
    problem string, never a crash — the pass must degrade to findings."""
    rb = env["RB"]
    per_replica = 0.0
    streamed = 0.0
    invariant = 0.0
    problems: List[str] = []
    for blk in model.blocks:
        if blk.memory_space != "vmem":
            continue
        n = blk.dtype_bytes
        uses_rb = False
        try:
            for e in blk.shape:
                names = {
                    x.id for x in ast.walk(e) if isinstance(x, ast.Name)
                }
                if "RB" in names:
                    uses_rb = True
                n *= _safe_eval(e, env)
        except (ValueError, KeyError) as exc:
            problems.append(
                f"line {blk.lineno}: block shape is not evaluable over "
                f"the size variables {sorted(env)} ({exc!r}) — rename "
                "back to the RB/Hp/chunk convention or teach "
                "pivot_tpu/analysis/pallas_budget.py the new variable"
            )
            continue
        mult = 2.0 if blk.inner_varying else 1.0
        if uses_rb:
            per_replica += mult * n / rb
        elif blk.inner_varying:
            streamed += mult * n
        else:
            invariant += n
    return per_replica, streamed, invariant, problems


def _source_formula(fn: ast.FunctionDef, name: str) -> Optional[ast.AST]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name
            for t in node.targets
        ):
            return node.value
    return None


def _chunk_cap(fn: ast.FunctionDef) -> Optional[int]:
    """The literal cap of ``chunk = min(<cap>, ...)``."""
    expr = _source_formula(fn, "chunk")
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id == "min"
        and expr.args
        and isinstance(expr.args[0], ast.Constant)
    ):
        return int(expr.args[0].value)
    return None


def _roofline_consts(cache) -> Tuple[Dict[str, int], List[Finding]]:
    out: Dict[str, int] = {}
    findings: List[Finding] = []
    src = cache.get(_ROOFLINE_FILE)
    if src is None:
        findings.append(Finding(
            RULE, _ROOFLINE_FILE, 0,
            "infra/roofline.py is missing — the v5e VMEM budget "
            "constants have no home; the pallas-budget pass cannot run",
        ))
        return out, findings
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.targets[0], ast.Name
        ) and node.targets[0].id in _BUDGET_CONSTS:
            try:
                out[node.targets[0].id] = int(
                    _safe_eval(node.value, {})
                )
            except (ValueError, KeyError):
                findings.append(Finding(
                    RULE, _ROOFLINE_FILE, node.lineno,
                    f"budget constant {node.targets[0].id} is not a "
                    "literal integer expression — the static pass "
                    "cannot evaluate it",
                ))
    for name in _BUDGET_CONSTS:
        if name not in out and not findings:
            findings.append(Finding(
                RULE, _ROOFLINE_FILE, 0,
                f"v5e budget constant {name} not found in "
                "infra/roofline.py — the pallas-budget pass has no "
                "reference to check against",
            ))
    return out, findings


def collect(cache) -> Tuple[List[Finding], List[str]]:
    out: List[Finding] = []
    scanned: List[str] = []
    consts, const_findings = _roofline_consts(cache)
    out.extend(const_findings)
    if cache.get(_ROOFLINE_FILE) is not None:
        scanned.append(_ROOFLINE_FILE)
    src = cache.get(_PALLAS_FILE)
    if src is None:
        out.append(Finding(
            RULE, _PALLAS_FILE, 0,
            "ops/pallas_kernels.py is missing — renamed? update "
            "pivot_tpu/analysis/pallas_budget.py",
        ))
        return out, scanned
    scanned.append(_PALLAS_FILE)

    # Constant hygiene: budget literals must come from roofline.
    imports_budget = any(
        isinstance(node, ast.ImportFrom)
        and node.module == "pivot_tpu.infra.roofline"
        and {a.name for a in node.names} & set(_BUDGET_CONSTS)
        for node in src.tree.body
    )
    if not imports_budget:
        out.append(Finding(
            RULE, _PALLAS_FILE, 1,
            "pallas kernels do not import the v5e budget constants from "
            "infra/roofline.py — a re-hardcoded byte budget drifts from "
            "the checked one",
        ))

    if not all(c in consts for c in _BUDGET_CONSTS):
        return out, scanned
    scoped = consts["V5E_SCOPED_VMEM_BYTES"]
    budget = consts["PALLAS_VMEM_BUDGET_BYTES"]
    proven_hp = consts["PALLAS_PROVEN_HP"]
    if budget >= scoped:
        out.append(Finding(
            RULE, _ROOFLINE_FILE, 0,
            f"PALLAS_VMEM_BUDGET_BYTES ({budget}) must leave headroom "
            f"under V5E_SCOPED_VMEM_BYTES ({scoped}) for Mosaic's own "
            "buffers",
        ))

    models, module_consts = extract_models(src)
    rb_cap = int(module_consts.get("_MAX_BLOCK_REPLICAS", 512))
    checked_any = False
    for fn, model in models:
        if not model.blocks:
            continue
        checked_any = True
        for lineno, message in model.problems:
            out.append(Finding(RULE, _PALLAS_FILE, lineno, message))
        chunk_cap = _chunk_cap(fn) or 256
        rb_expr = _source_formula(fn, "rb_bytes")
        tile_expr = _source_formula(fn, "tile_bytes")
        if rb_expr is None or tile_expr is None:
            out.append(Finding(
                RULE, _PALLAS_FILE, fn.lineno,
                f"{fn.name}: rb_bytes/tile_bytes byte formulas not "
                "found — the auto-sizer has nothing to size against "
                "and the drift check nothing to check",
            ))
            continue
        hp_probes = sorted({128, 256, proven_hp})
        for hp in hp_probes:
            for rb in _RB_PROBES:
                env = {"Hp": float(hp), "chunk": float(chunk_cap),
                       "RB": float(rb), **module_consts}
                per_replica, streamed, invariant, shape_problems = (
                    _footprint(model, env)
                )
                if shape_problems:
                    for msg in shape_problems:
                        out.append(Finding(
                            RULE, _PALLAS_FILE, fn.lineno,
                            f"{fn.name}: {msg}",
                        ))
                    break
                try:
                    src_rb = _safe_eval(rb_expr, env)
                    src_tile = _safe_eval(tile_expr, env)
                except (ValueError, KeyError) as exc:
                    out.append(Finding(
                        RULE, _PALLAS_FILE, rb_expr.lineno,
                        f"{fn.name}: byte formula is not statically "
                        f"evaluable ({exc}) — keep it arithmetic over "
                        "the size variables",
                    ))
                    break
                if abs(src_rb - per_replica) > 0.5:
                    out.append(Finding(
                        RULE, _PALLAS_FILE, rb_expr.lineno,
                        f"{fn.name}: rb_bytes drifted from the BlockSpec "
                        f"tile set at (Hp={hp}, chunk={chunk_cap}, "
                        f"RB={rb}): formula says {src_rb:.0f} B/replica, "
                        f"the specs say {per_replica:.0f} — update the "
                        "formula (or the specs) so the auto-sizer sizes "
                        "against reality",
                    ))
                    break
                if abs(src_tile - streamed) > 0.5:
                    out.append(Finding(
                        RULE, _PALLAS_FILE, tile_expr.lineno,
                        f"{fn.name}: tile_bytes drifted from the "
                        f"streamed-tile specs at (Hp={hp}, "
                        f"chunk={chunk_cap}): formula {src_tile:.0f} B "
                        f"vs specs {streamed:.0f} B",
                    ))
                    break
            else:
                continue
            break
        # Budget checks at the proven envelope (worst in-envelope shape).
        env = {"Hp": float(proven_hp), "chunk": float(chunk_cap),
               "RB": 8.0, **module_consts}
        per_replica, streamed, invariant, shape_problems = _footprint(
            model, env
        )
        if shape_problems or per_replica <= 0:
            # Unevaluable (already reported above) or no replica-scaled
            # blocks at all — the auto-sizer math below has no meaning.
            if per_replica <= 0 and not shape_problems:
                out.append(Finding(
                    RULE, _PALLAS_FILE, fn.lineno,
                    f"{fn.name}: no replica-scaled (RB-shaped) VMEM "
                    "block found — the replica auto-sizer has nothing "
                    "to size; update the budget pass's convention if "
                    "the block layout changed",
                ))
            continue
        floor_total = 8 * per_replica + streamed + invariant
        if floor_total > scoped:
            out.append(Finding(
                RULE, _PALLAS_FILE, fn.lineno,
                f"{fn.name}: even the minimum one-sublane block needs "
                f"{floor_total / 1e6:.1f} MB of scoped VMEM at "
                f"Hp={proven_hp} (limit {scoped / 1e6:.1f} MB) — a "
                "guaranteed Mosaic compile failure with no fallback",
            ))
        auto_rb = max(
            8,
            min(rb_cap,
                int(max(budget - streamed, per_replica * 8)
                    // per_replica) // 8 * 8),
        )
        auto_total = auto_rb * per_replica + streamed + invariant
        if auto_total > scoped:
            out.append(Finding(
                RULE, _PALLAS_FILE, fn.lineno,
                f"{fn.name}: the auto-sized block (RB={auto_rb}) needs "
                f"{auto_total / 1e6:.1f} MB at Hp={proven_hp} — over "
                f"the {scoped / 1e6:.1f} MB scoped-VMEM limit; shrink "
                "the budget constant or the tile set",
            ))
    if not checked_any:
        out.append(Finding(
            RULE, _PALLAS_FILE, 1,
            "no pallas_call tile set found — the Pallas kernels moved? "
            "update pivot_tpu/analysis/pallas_budget.py",
        ))
    return out, scanned
