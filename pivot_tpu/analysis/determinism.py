"""Determinism lint: the replay=bit-identical contract, statically.

``chaos_replay``/``market_replay`` referee the whole evaluation
methodology on one promise: replaying a seeded schedule on a seeded
world reproduces the fault log, the meter, and every placement bit for
bit.  That promise dies the moment sim/replay-critical code reads a
wall clock, consumes process-global RNG state, or iterates a
hash-ordered container.  This pass bans those constructs in the
replay-critical modules (:data:`SCOPE` — the DES core, the fault/market
engines, the scheduling layer, and the device kernels; the *serve*
layer is deliberately out of scope, wall-clock pacing and stall
watchdogs are its job):

  * **wall-clock reads** — ``time.time()``/``monotonic()``/
    ``perf_counter()``/… and ``datetime.now()``-family calls.  (Pure
    *measurement* uses — meter bookkeeping, the adaptive router's
    latency EMA, whose routing choice is placement-neutral by the
    twin-parity contract — carry explicit ``ignore[determinism]``
    suppressions with that justification.)
  * **global / unseeded RNG** — any ``random.*`` call (module state;
    ``random.Random(seed)`` construction is allowed) and ``np.random.*``
    module-state calls (``np.random.rand`` etc.); the seeded
    constructors (``default_rng``, ``RandomState``, ``Philox``,
    ``Generator``, …) are the sanctioned idiom and stay allowed.
  * **hash-ordered iteration** — ``for x in {…}`` / ``set(…)`` /
    comprehensions over set expressions, list/tuple/iter/enumerate/
    reversed of a set expression, and ``os.environ`` iteration.  Set
    *membership* and ``sorted(set(…))`` stay fine — only order leaks
    break replay.  (Dict iteration is insertion-ordered in Python 3.7+
    and therefore deterministic; it is not flagged.)
"""

from __future__ import annotations

import ast
import os
from typing import List, Tuple

from pivot_tpu.analysis import Finding, SourceFile

RULE = "determinism"

#: Replay-critical modules (repo-relative files or directories).
SCOPE = (
    "pivot_tpu/des",
    "pivot_tpu/infra/faults.py",
    "pivot_tpu/infra/market.py",
    "pivot_tpu/sched",
    "pivot_tpu/ops",
    # The policy-search subsystem (round 16): search runs must replay —
    # same seed + same env ⇒ identical winning vector and fitness trace
    # — so its optimizers and fitness plumbing live under the same lint
    # as the DES core (seeded generators only, no wall-clock reads).
    "pivot_tpu/search",
    # Model-predictive serving (round 19): the SCORING half of the MPC
    # loop — the forecaster's fit and the planner's fused action
    # dispatch — must replay bit-for-bit (every actuation is auditable
    # from its recorded inputs).  The controller/tuner/rollout threads
    # do wall-clock pacing and stay outside, like serve/.
    "pivot_tpu/mpc/forecast.py",
    "pivot_tpu/mpc/planner.py",
)

_WALL_FNS = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "process_time",
    "process_time_ns",
}
_DATETIME_FNS = {"now", "utcnow", "today"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
#: Seeded-generator constructors: the sanctioned numpy RNG idiom.
_SEEDED_OK = {
    "default_rng", "Generator", "RandomState", "SeedSequence",
    "Philox", "PCG64", "PCG64DXSM", "MT19937", "SFC64", "BitGenerator",
}
#: Consuming one of these around a set expression leaks hash order.
_ORDER_SENSITIVE_CALLS = {"list", "tuple", "iter", "enumerate", "reversed"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in {"set", "frozenset"}
    )


def _is_os_environ(node: ast.AST) -> bool:
    if (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    ):
        return True
    # os.environ.keys()/values()/items()
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in {"keys", "values", "items"}
        and _is_os_environ(node.func.value)
    )


def _check_call(node: ast.Call, path: str) -> List[Finding]:
    out: List[Finding] = []
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        base, attr = f.value.id, f.attr
        if base == "time" and attr in _WALL_FNS:
            out.append(Finding(
                RULE, path, node.lineno,
                f"wall-clock read time.{attr}() in a replay-critical "
                "module — replay must be a pure function of "
                "(seed, schedule)",
            ))
        elif base == "datetime" and attr in _DATETIME_FNS:
            out.append(Finding(
                RULE, path, node.lineno,
                f"wall-clock read datetime.{attr}() in a "
                "replay-critical module",
            ))
        elif base == "random" and attr != "Random":
            out.append(Finding(
                RULE, path, node.lineno,
                f"global-state RNG random.{attr}() — use a seeded "
                "np.random generator (or random.Random(seed))",
            ))
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Attribute)
        and isinstance(f.value.value, ast.Name)
    ):
        # np.random.<fn>(...) / datetime.datetime.now(...)
        root, mid, attr = f.value.value.id, f.value.attr, f.attr
        if (
            root in _NUMPY_ALIASES
            and mid == "random"
            and attr not in _SEEDED_OK
        ):
            out.append(Finding(
                RULE, path, node.lineno,
                f"module-state RNG {root}.random.{attr}() — seed a "
                f"generator ({root}.random.default_rng(seed)) instead",
            ))
        elif root == "datetime" and mid in {
            "datetime", "date"
        } and attr in _DATETIME_FNS:
            out.append(Finding(
                RULE, path, node.lineno,
                f"wall-clock read datetime.{mid}.{attr}() in a "
                "replay-critical module",
            ))
    return out


def _check_import(node: ast.AST, path: str) -> List[Finding]:
    """The call checks above key on literal base names (``time.X``,
    ``random.X``, ``np.random.X``); an aliased or from-import would
    bypass them silently, so the import statements themselves are
    banned in scope — import the module unaliased and call through it
    (review finding, round 12)."""
    out: List[Finding] = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name in {"time", "random"} and alias.asname:
                out.append(Finding(
                    RULE, path, node.lineno,
                    f"aliased import `import {alias.name} as "
                    f"{alias.asname}` defeats the determinism lint — "
                    "import it unaliased so the call checks see it",
                ))
            elif alias.name == "numpy.random" or (
                alias.name == "numpy"
                and alias.asname not in (None, *_NUMPY_ALIASES)
            ):
                shown = alias.name + (
                    f" as {alias.asname}" if alias.asname else ""
                )
                out.append(Finding(
                    RULE, path, node.lineno,
                    f"`import {shown}` defeats the determinism lint — "
                    "use `import numpy as np` and the np.random.* "
                    "seeded constructors",
                ))
    elif isinstance(node, ast.ImportFrom):
        names = {alias.name for alias in node.names}
        if node.module == "time" and names & _WALL_FNS:
            out.append(Finding(
                RULE, path, node.lineno,
                f"`from time import {', '.join(sorted(names & _WALL_FNS))}`"
                " defeats the determinism lint — import the module and "
                "call through it (so bans and suppressions attach to "
                "the call sites)",
            ))
        elif node.module == "random" and names - {"Random"}:
            out.append(Finding(
                RULE, path, node.lineno,
                "`from random import ...` pulls module-state RNG into "
                "scope — use a seeded generator",
            ))
        elif node.module == "numpy.random" and names - _SEEDED_OK:
            out.append(Finding(
                RULE, path, node.lineno,
                "`from numpy.random import "
                f"{', '.join(sorted(names - _SEEDED_OK))}` pulls "
                "module-state RNG into scope — seed a generator instead",
            ))
        elif node.module == "numpy" and "random" in names:
            out.append(Finding(
                RULE, path, node.lineno,
                "`from numpy import random` defeats the determinism "
                "lint — use `import numpy as np`",
            ))
        elif node.module == "datetime" and names & {"datetime", "date"}:
            # ``datetime.now()`` on the from-imported class matches the
            # two-level attribute check, so only note the import when
            # it renames.
            for alias in node.names:
                if alias.name in {"datetime", "date"} and alias.asname:
                    out.append(Finding(
                        RULE, path, node.lineno,
                        f"aliased `from datetime import {alias.name} as "
                        f"{alias.asname}` defeats the determinism lint",
                    ))
    return out


def _iter_message(path: str, lineno: int, what: str) -> Finding:
    return Finding(
        RULE, path, lineno,
        f"iteration over {what} is hash-ordered (env-dependent) — "
        "sort it (sorted(...)) or use an order-preserving container",
    )


def scan_source(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            out.extend(_check_import(node, src.path))
        elif isinstance(node, ast.Call):
            out.extend(_check_call(node, src.path))
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _ORDER_SENSITIVE_CALLS
                and node.args
                and _is_set_expr(node.args[0])
            ):
                out.append(_iter_message(
                    src.path, node.lineno,
                    f"a set expression via {node.func.id}(...)",
                ))
        elif isinstance(node, ast.For):
            if _is_set_expr(node.iter):
                out.append(_iter_message(
                    src.path, node.lineno, "a set expression"
                ))
            elif _is_os_environ(node.iter):
                out.append(_iter_message(
                    src.path, node.lineno, "os.environ"
                ))
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if _is_set_expr(gen.iter):
                    out.append(_iter_message(
                        src.path, node.lineno,
                        "a set expression (comprehension)",
                    ))
                elif _is_os_environ(gen.iter):
                    out.append(_iter_message(
                        src.path, node.lineno, "os.environ"
                    ))
    return out


def _scope_files(root: str) -> List[str]:
    rels: List[str] = []
    for entry in SCOPE:
        abspath = os.path.join(root, entry)
        if os.path.isfile(abspath):
            rels.append(entry)
        elif os.path.isdir(abspath):
            for dirpath, _dirs, files in sorted(os.walk(abspath)):
                for name in sorted(files):
                    if name.endswith(".py"):
                        rels.append(os.path.relpath(
                            os.path.join(dirpath, name), root
                        ))
    return rels


def collect(cache) -> Tuple[List[Finding], List[str]]:
    import os as _os

    out: List[Finding] = []
    scanned: List[str] = []
    for entry in SCOPE:
        if not _os.path.exists(_os.path.join(cache.root, entry)):
            out.append(Finding(
                RULE, entry, 0,
                f"replay-critical scope entry {entry} is missing — "
                "renamed/deleted? update determinism SCOPE (it lost "
                "all lint coverage)",
            ))
    for rel in _scope_files(cache.root):
        src = cache.get(rel)
        if src is None:
            continue
        scanned.append(rel)
        out.extend(scan_source(src))
    return out, scanned
