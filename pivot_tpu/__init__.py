"""pivot_tpu — a TPU-native cost-aware DAG-scheduling simulation framework.

A brand-new framework with the capabilities of the PIVOT scheduling simulator
(dcvan24/pivot-scheduling): a discrete-event simulator for cost-aware placement
of DAG-structured, data-intensive container workloads on simulated cross-cloud
infrastructure, driven by Alibaba 2018 cluster-trace jobs.

Architecture (TPU-first, see SURVEY.md §7; modules land incrementally —
check the tree for what has shipped so far):
  - ``des``        : a minimal deterministic discrete-event kernel (CPU).
  - ``workload``   : Application / TaskGroup / Task DAG model + generators +
                     the Alibaba trace loader.
  - ``infra``      : simulated cross-cloud fabric — hosts, zone-local storage,
                     chunked fair-share network routes, and the zone×zone
                     bandwidth / egress-cost matrices kept as dense arrays.
  - ``sched``      : two-level scheduler runtime and placement policies, each
                     available in ``naive`` (reference-faithful Python
                     baseline), ``numpy`` (vectorized) and ``tpu`` (fused JAX
                     kernel) modes.
  - ``ops``        : the fused fit/score/argmin placement kernels (jit/vmap/
                     lax.scan, optional Pallas).
  - ``parallel``   : device meshes, sharded ensemble scheduling, Monte-Carlo
                     rollouts.
  - ``experiments``: experiment drivers, CLI, plots, trace sampler.
"""

__version__ = "0.1.0"
