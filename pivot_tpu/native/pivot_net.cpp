// Native network co-simulator: per-route chunked fair-share packet service.
//
// Behavioral parity with the Python `pivot_tpu.infra.network.Route` (itself
// a redesign of the reference's `NetworkRoute`/`Packet`,
// /root/reference/resources/network.py:10-103): a transfer is served one
// CHUNK_MB-sized chunk at a time at chunk/bw sim-seconds per chunk; an
// unfinished transfer re-enters the tail of the route's queue after each
// chunk, so concurrent transfers share the route round-robin and congestion
// emerges from queueing.
//
// Why native: chunk service is the simulator's dominant event source — a
// 50 GB transfer is 50 chunk events, and a full Alibaba trace run generates
// millions.  This engine keeps the entire chunk-service loop (heap, queues,
// stats) in C++; the Python event kernel sees ONE wake callback per distinct
// completion instant instead of one event per chunk.
//
// The engine is a co-simulator: it never sees wall-clock or sim-clock except
// through `now` values passed in.  Arithmetic is double-precision with the
// same operation order as the Python implementation (start + chunk/bw), so
// completion times are bit-identical.
//
// API (extern "C", ctypes-friendly): create/destroy, add_route, send, peek,
// advance/collect_done, queued_mb, route_stats, total_chunks.

#include <cstdint>
#include <cmath>
#include <deque>
#include <queue>
#include <vector>

namespace {

constexpr double kChunkMb = 1000.0;  // ref Packet.PACKET_SIZE, network.py:12

struct Transfer {
  double remaining;
  double last_end = -1.0;  // end time of this transfer's previous chunk
  int32_t route;
  bool started = false;    // counted in the route's n_transfers yet?
  bool cancelled = false;  // dropped after its in-service chunk completes
};

struct HeapEntry {
  double time;
  int64_t seq;
  int32_t route;
  bool operator>(const HeapEntry& o) const {
    if (time != o.time) return time > o.time;
    return seq > o.seq;
  }
};

struct RouteState {
  double bw;
  std::deque<int64_t> queue;  // waiting transfer ids (excludes in-service)
  bool busy = false;
  int64_t current = -1;       // transfer in service
  double cur_chunk = 0.0;
  // Stats mirroring the Python Meter's per-slot logs (meter.py:121-125):
  double served_mb = 0.0;   // chunk MB counted at slot END (len==3 slots)
  int64_t n_transfers = 0;  // transfers with >=1 slot start (check-in)
  double gap_sum = 0.0;     // sum of slots[i].start - slots[i-1].end
};

struct Engine {
  std::vector<RouteState> routes;
  std::vector<Transfer> transfers;
  std::priority_queue<HeapEntry, std::vector<HeapEntry>, std::greater<HeapEntry>>
      heap;
  int64_t seq = 0;
  int64_t total_chunks = 0;
  // Completions accumulated by advance(), drained by collect_done().
  std::vector<int64_t> done_ids;
  std::vector<double> done_times;
  size_t done_cursor = 0;
  // Transfer slots released at collect time, reused by send — a slot is
  // only recycled after the caller has consumed its completion, so an id
  // is never live twice concurrently.
  std::vector<int64_t> free_ids;

  void serve_next(int32_t ri, double now) {
    RouteState& r = routes[ri];
    if (r.queue.empty()) {
      r.busy = false;
      r.current = -1;
      return;
    }
    r.busy = true;
    int64_t id = r.queue.front();
    r.queue.pop_front();
    Transfer& t = transfers[id];
    double chunk = t.remaining < kChunkMb ? t.remaining : kChunkMb;
    if (!t.started) {
      t.started = true;
      r.n_transfers += 1;
    } else if (t.last_end >= 0.0) {
      r.gap_sum += now - t.last_end;
    }
    r.current = id;
    r.cur_chunk = chunk;
    double service = r.bw > 0.0 ? chunk / r.bw : 0.0;
    heap.push(HeapEntry{now + service, seq++, ri});
  }

  void complete_chunk(int32_t ri, double tc) {
    RouteState& r = routes[ri];
    int64_t id = r.current;
    Transfer& t = transfers[id];
    t.remaining -= r.cur_chunk;
    r.served_mb += r.cur_chunk;
    t.last_end = tc;
    total_chunks += 1;
    // Cancelled wins over completed (Route._finish_chunk order,
    // network.py:118-127): even a fully transferred cancelled transfer
    // never reports done.
    if (t.cancelled) {
      free_ids.push_back(id);  // dropped: no completion, no re-enqueue
    } else if (t.remaining <= 0.0) {
      done_ids.push_back(id);
      done_times.push_back(tc);
    } else {
      r.queue.push_back(id);  // round-robin fairness
    }
    serve_next(ri, tc);
  }
};

}  // namespace

extern "C" {

void* net_create() { return new Engine(); }

void net_destroy(void* h) { delete static_cast<Engine*>(h); }

int32_t net_add_route(void* h, double bw) {
  Engine* e = static_cast<Engine*>(h);
  e->routes.push_back(RouteState{bw});
  return static_cast<int32_t>(e->routes.size() - 1);
}

int64_t net_send(void* h, int32_t route, double size_mb, double now) {
  Engine* e = static_cast<Engine*>(h);
  RouteState& r = e->routes[route];
  int64_t id;
  if (!e->free_ids.empty()) {  // recycle a collected transfer slot
    id = e->free_ids.back();
    e->free_ids.pop_back();
    e->transfers[id] = Transfer{size_mb, -1.0, route, false};
  } else {
    id = static_cast<int64_t>(e->transfers.size());
    e->transfers.push_back(Transfer{size_mb, -1.0, route, false});
  }
  r.queue.push_back(id);
  if (!r.busy) e->serve_next(route, now);
  return id;
}

double net_peek(void* h) {
  Engine* e = static_cast<Engine*>(h);
  return e->heap.empty() ? HUGE_VAL : e->heap.top().time;
}

// Process every chunk completion with time <= until; returns the number of
// finished transfers now waiting in the done buffer.
int64_t net_advance(void* h, double until) {
  Engine* e = static_cast<Engine*>(h);
  while (!e->heap.empty() && e->heap.top().time <= until) {
    HeapEntry top = e->heap.top();
    e->heap.pop();
    e->complete_chunk(top.route, top.time);
  }
  return static_cast<int64_t>(e->done_ids.size() - e->done_cursor);
}

// Drain up to cap finished transfers into (ids, times); returns count.
int64_t net_collect_done(void* h, int64_t* ids, double* times, int64_t cap) {
  Engine* e = static_cast<Engine*>(h);
  int64_t n = 0;
  while (e->done_cursor < e->done_ids.size() && n < cap) {
    ids[n] = e->done_ids[e->done_cursor];
    times[n] = e->done_times[e->done_cursor];
    e->free_ids.push_back(ids[n]);
    ++e->done_cursor;
    ++n;
  }
  if (e->done_cursor == e->done_ids.size()) {
    e->done_ids.clear();
    e->done_times.clear();
    e->done_cursor = 0;
  }
  return n;
}

// Cancel a live transfer (parity with Route.cancel, network.py:81-100):
// a waiting transfer is removed from its route's queue eagerly, so
// queued_mb / realtime_bw stay exact immediately; the in-service transfer
// has its current chunk (data already on the wire) finish normally and is
// then dropped by complete_chunk.  An id that is neither queued nor in
// service already completed — no-op, matching the Python fabric's scan
// finding nothing.
void net_cancel(void* h, int64_t id) {
  Engine* e = static_cast<Engine*>(h);
  Transfer& t = e->transfers[id];
  RouteState& r = e->routes[t.route];
  if (r.current == id) {
    t.cancelled = true;
    return;
  }
  for (auto it = r.queue.begin(); it != r.queue.end(); ++it) {
    if (*it == id) {
      r.queue.erase(it);
      e->free_ids.push_back(id);
      return;
    }
  }
}

// Exact FIFO-order sum over waiting transfers (excludes the in-service
// chunk) — summed fresh like the Python property, so parity is bitwise
// rather than accumulator-drift-prone.
double net_queued_mb(void* h, int32_t route) {
  Engine* e = static_cast<Engine*>(h);
  const RouteState& r = e->routes[route];
  double total = 0.0;
  for (int64_t id : r.queue) total += e->transfers[id].remaining;
  return total;
}

// out[0]=served_mb, out[1]=n_transfers, out[2]=gap_sum
void net_route_stats(void* h, int32_t route, double* out) {
  const RouteState& r = static_cast<Engine*>(h)->routes[route];
  out[0] = r.served_mb;
  out[1] = static_cast<double>(r.n_transfers);
  out[2] = r.gap_sum;
}

int64_t net_total_chunks(void* h) {
  return static_cast<Engine*>(h)->total_chunks;
}

}  // extern "C"
