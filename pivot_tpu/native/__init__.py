"""Native (C++) runtime components, bound via ctypes.

The only native component the domain demands is the network co-simulator
(``pivot_net.cpp``) — chunk service is the simulator's dominant event
source (SURVEY.md §3.4 hot loop 2: the reference runs one SimPy process
per route, ~16k at 100 hosts).  The shared library is compiled on first
use with the in-image ``g++`` into ``pivot_tpu/native/_build/`` and
cached by source hash.  Construction fails fast with :class:`BuildError`
when no toolchain is present; callers that want graceful degradation
(e.g. the experiment CLI) should check :func:`available` up front and
select the pure-Python fabric instead.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from math import inf
from typing import Dict, List, Optional, Tuple

__all__ = ["available", "load_library", "NativeNetworkEngine", "BuildError"]

_SRC = os.path.join(os.path.dirname(__file__), "pivot_net.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")

_lib = None
_lib_error: Optional[str] = None


class BuildError(RuntimeError):
    pass


def _source_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def load_library() -> ctypes.CDLL:
    """Compile (if needed) and load the native library; caches the handle."""
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        raise BuildError(_lib_error)
    so_path = os.path.join(_BUILD_DIR, f"libpivotnet-{_source_hash()}.so")
    if not os.path.exists(so_path):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # Compile to a private temp path, then rename atomically — concurrent
        # worker processes may race to build the same library, and a CDLL of
        # a half-written .so is a crash.
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp_path]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp_path, so_path)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", str(e))
            _lib_error = f"native build failed: {detail}"
            raise BuildError(_lib_error) from e
        finally:
            if os.path.exists(tmp_path):
                try:
                    os.remove(tmp_path)
                except OSError:
                    pass
    lib = ctypes.CDLL(so_path)
    lib.net_create.restype = ctypes.c_void_p
    lib.net_destroy.argtypes = [ctypes.c_void_p]
    lib.net_add_route.restype = ctypes.c_int32
    lib.net_add_route.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.net_send.restype = ctypes.c_int64
    lib.net_send.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_double,
        ctypes.c_double,
    ]
    lib.net_peek.restype = ctypes.c_double
    lib.net_peek.argtypes = [ctypes.c_void_p]
    lib.net_advance.restype = ctypes.c_int64
    lib.net_advance.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.net_collect_done.restype = ctypes.c_int64
    lib.net_collect_done.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
    ]
    lib.net_cancel.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.net_queued_mb.restype = ctypes.c_double
    lib.net_queued_mb.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.net_route_stats.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.net_total_chunks.restype = ctypes.c_int64
    lib.net_total_chunks.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    """True if the native library can be built/loaded on this machine."""
    try:
        load_library()
        return True
    except BuildError:
        return False


class NativeNetworkEngine:
    """ctypes wrapper + event-kernel bridge for the C++ co-simulator.

    The bridge keeps exactly one *live* wake armed at the engine's next
    chunk-completion instant.  Each armed callback carries an arm-sequence
    tag; re-arming bumps the sequence, so a superseded callback dies inert
    on arrival (one no-op, never a duplicate chain).  The pump advances the
    engine to ``now``, succeeds the done-events of finished transfers, and
    re-arms.  ``send`` first drains completions due at ``now`` so the new
    transfer queues behind engine state that is current — at an exact
    same-instant tie this deterministically orders completions before the
    send.  (The pure-Python fabric breaks such ties by event-heap seq
    interleaving instead, so tie order can differ between fabrics; totals
    and meter metrics are unaffected, and full-sim parity holds on the
    canonical experiments.)
    """

    _COLLECT_CAP = 4096

    def __init__(self, env):
        self._h = None
        self._lib = load_library()
        self._h = ctypes.c_void_p(self._lib.net_create())
        self.env = env
        self._done_events: Dict[int, object] = {}
        self._tid_by_event: Dict[object, int] = {}  # reverse map for cancel
        self._routes: List[object] = []  # route facade per native index
        self._armed_time: float = inf  # completion instant of the live wake
        self._arm_seq = 0  # tag of the live wake; older tags are inert
        self._ids_buf = (ctypes.c_int64 * self._COLLECT_CAP)()
        self._times_buf = (ctypes.c_double * self._COLLECT_CAP)()

    def __del__(self):
        h = getattr(self, "_h", None)
        self._h = None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.net_destroy(h)

    # -- route registration ----------------------------------------------
    def add_route(self, bw: float, facade) -> int:
        idx = self._lib.net_add_route(self._h, float(bw))
        self._routes.append(facade)
        return idx

    # -- data plane -------------------------------------------------------
    def send(self, route_idx: int, size_mb: float, done_event) -> int:
        # Bring engine state up to `now` first: a chunk completing at this
        # exact instant must vacate the route before the new transfer
        # queues, or completion order diverges from the Python fabric.
        self._drain()
        tid = self._lib.net_send(
            self._h, route_idx, float(size_mb), float(self.env.now)
        )
        self._done_events[tid] = done_event
        self._tid_by_event[done_event] = tid
        self._sync_wake()
        return tid

    def cancel(self, done_event) -> None:
        """Cancel the live transfer whose completion event is ``done_event``.

        Drains first, so a completion due at exactly ``now`` fires rather
        than being cancelled — the same completions-before-caller tie
        policy ``send`` documents.  A transfer that already completed is a
        no-op, matching the Python fabric's cancel scan finding nothing.
        """
        self._drain()
        tid = self._tid_by_event.pop(done_event, None)
        if tid is None:
            return
        self._done_events.pop(tid, None)
        self._lib.net_cancel(self._h, tid)

    def queued_mb(self, route_idx: int) -> float:
        return self._lib.net_queued_mb(self._h, route_idx)

    @property
    def total_chunks(self) -> int:
        return int(self._lib.net_total_chunks(self._h))

    # -- pump -------------------------------------------------------------
    def _drain(self) -> None:
        """Process completions due at or before ``env.now``."""
        n = self._lib.net_advance(self._h, self.env.now)
        while n > 0:
            got = self._lib.net_collect_done(
                self._h, self._ids_buf, self._times_buf, self._COLLECT_CAP
            )
            for i in range(got):
                evt = self._done_events.pop(self._ids_buf[i])
                self._tid_by_event.pop(evt, None)
                evt.succeed()
            n -= got

    def _sync_wake(self) -> None:
        """Ensure the one live wake matches the engine's next completion."""
        t = self._lib.net_peek(self._h)
        if t == self._armed_time:
            return
        self._arm_seq += 1
        self._armed_time = t
        if t != inf:
            seq = self._arm_seq
            self.env.schedule_callback_at(t, lambda: self._pump(seq))

    def _pump(self, arm_seq: int) -> None:
        if arm_seq != self._arm_seq:
            return  # superseded wake — die inert, the live chain re-arms
        self._drain()
        self._armed_time = inf  # consumed; recompute from the engine
        self._sync_wake()

    # -- meter integration -------------------------------------------------
    def metered_route_stats(self) -> List[Tuple[object, float, int, float]]:
        """(route_facade, served_mb, n_transfers, gap_sum) for metered routes."""
        out = []
        buf = (ctypes.c_double * 3)()
        for idx, facade in enumerate(self._routes):
            if getattr(facade, "meter", None) is None:
                continue
            self._lib.net_route_stats(self._h, idx, buf)
            out.append((facade, buf[0], int(buf[1]), buf[2]))
        return out
