"""Background weight tuning with a regret gate.

The policy-search subsystem (``search/``) tunes
:class:`~pivot_tpu.search.weights.PolicyWeights` offline; this module
runs the SAME machinery as a background worker inside the serving
process.  The controller submits each freshly rendered forecast
environment; the worker re-fits a small CEM search against it
(``search/cem.py`` — replayed recent traffic, seeded scenario draws)
and publishes the best vector as a *challenger*.

A challenger is only eligible for the planner's WEIGHTS slot after the
**regret gate**: the candidate's greedy placement on a small oracle
instance derived from the same environment must sit within
``max_regret`` dollars of the branch-and-bound optimum
(``search/oracle.py``).  The gate bounds distance-from-optimal *before*
any live traffic sees the vector — a CEM run that wandered into a
pathological corner of weight space is rejected here, not by the canary
rollback.

The worker thread does wall-clock pacing and therefore lives OUTSIDE
the determinism manifest (like ``serve/``); each ``tune_once`` call is
itself deterministic in its arguments.
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Optional

import numpy as np

from pivot_tpu.search.weights import DEFAULT_WEIGHTS, PolicyWeights
from pivot_tpu.utils import LogMixin

__all__ = ["TunerResult", "MpcTuner", "tune_once", "gate_regret"]


class TunerResult(NamedTuple):
    """One finished tuning round."""

    weights: PolicyWeights
    score: float           # CEM best fitness (cost/completed task)
    init_score: float      # incumbent's fitness, same scenarios
    regret: float          # oracle-gate regret ($), inf if gate failed
    eligible: bool         # beat the incumbent AND passed the gate
    seed: int


def gate_regret(
    env,
    weights: PolicyWeights,
    *,
    n_tasks: int = 5,
    max_nodes: int = 200_000,
) -> float:
    """Regret ($) of ``weights``'s greedy placement against the exact
    optimum on the root wave of ``env``'s workload.

    The instance is the first ``n_tasks`` tasks placed against the
    environment's initial availability — small enough for
    branch-and-bound to prove the optimum, derived from the same
    operands the rollouts scored.  The oracle raising (node budget,
    degenerate instance) gates the candidate OUT (``inf``): an
    unverifiable candidate is treated like a bad one.
    """
    from pivot_tpu.search.oracle import (
        greedy_placement,
        instance_from_wave,
        regret,
        solve_instance,
    )

    T = env.n_tasks
    mask = np.zeros(T, dtype=bool)
    mask[: min(n_tasks, T)] = True
    hazard = None
    if env.hazard is not None:
        # Price eviction exposure at the horizon's FIRST hazard segment
        # — the wave the gate scores is the first wave placed.
        hazard = np.asarray(env.hazard[1])[0]
    try:
        inst = instance_from_wave(
            env.workload,
            env.topo,
            np.asarray(env.avail0, dtype=np.float64),
            np.full(T, -1, dtype=np.int64),
            mask,
            hazard=hazard,
            weights=weights,
        )
        _, optimum, _ = solve_instance(inst, max_nodes=max_nodes)
        return float(
            regret(inst, greedy_placement(inst, weights), optimum)
        )
    except (ValueError, RuntimeError):
        return float("inf")


def tune_once(
    env,
    *,
    incumbent: Optional[PolicyWeights] = None,
    seed: int = 0,
    generations: int = 2,
    popsize: int = 6,
    max_regret: float = 1.0,
    backend: str = "rollout",
) -> TunerResult:
    """One deterministic tuning round: CEM over ``env`` anchored at the
    incumbent, then the regret gate.  Eligibility requires BOTH a
    strictly better fitness than the incumbent under the same scenarios
    and a gate regret within ``max_regret``."""
    from pivot_tpu.search.cem import cem_search

    incumbent = (incumbent or DEFAULT_WEIGHTS).validate()
    result = cem_search(
        env, generations=generations, popsize=popsize, seed=seed,
        init=incumbent, backend=backend,
    )
    best = result.best.validate()
    improved = result.best_score < result.init_score
    reg = gate_regret(env, best, max_nodes=200_000) if improved else float(
        "inf"
    )
    return TunerResult(
        weights=best,
        score=float(result.best_score),
        init_score=float(result.init_score),
        regret=reg,
        eligible=bool(improved and reg <= max_regret),
        seed=seed,
    )


class MpcTuner(LogMixin):
    """The background worker.  The controller hands it rendered
    environments (:meth:`submit`); the worker re-fits on the newest one
    and publishes the latest :class:`TunerResult`; the controller takes
    an eligible challenger (:meth:`take_challenger`) when building the
    planner menu — taking clears it, so one tuning round backs at most
    one promotion attempt."""

    def __init__(
        self,
        *,
        seed: int = 0,
        generations: int = 2,
        popsize: int = 6,
        max_regret: float = 1.0,
        interval_s: float = 0.2,
        backend: str = "rollout",
    ):
        self.seed = int(seed)
        self.generations = int(generations)
        self.popsize = int(popsize)
        self.max_regret = float(max_regret)
        self.interval_s = float(interval_s)
        self.backend = backend
        self.rounds = 0
        self.results: list = []      # TunerResult log, newest last
        self._pending = None          # (env, incumbent) slot
        self._challenger: Optional[TunerResult] = None
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- controller-facing surface ----------------------------------------
    def submit(self, env, incumbent: PolicyWeights) -> None:
        """Queue the newest environment for the next tuning round
        (newest-wins: stale forecasts are not worth fitting)."""
        with self._lock:
            self._pending = (env, incumbent)

    def take_challenger(self) -> Optional[PolicyWeights]:
        """Pop the eligible challenger, if one is published."""
        with self._lock:
            res, self._challenger = self._challenger, None
        return res.weights if res is not None else None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="mpc-tuner", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join()

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            with self._lock:
                work, self._pending = self._pending, None
            if work is None:
                continue
            env, incumbent = work
            # Each round re-seeds deterministically: round k of a
            # seed-s tuner always fits with seed s + k.
            res = tune_once(
                env,
                incumbent=incumbent,
                seed=self.seed + self.rounds,
                generations=self.generations,
                popsize=self.popsize,
                max_regret=self.max_regret,
                backend=self.backend,
            )
            self.rounds += 1
            with self._lock:
                self.results.append(res)
                if res.eligible:
                    self._challenger = res
