"""The model-predictive planner: one fused dispatch scores every action.

Each decision window the controller enumerates a small, FIXED-SIZE menu
of candidate actions — hold, grow the pool, drain a session, shed the
lowest forecast tier, promote the tuner's challenger weights — and
scores *all of them at once* as one ``evaluate_candidates`` dispatch
over K seeded shadow rollouts of the forecast horizon
(``search/fitness.py``).  The actions ride the two per-candidate
channels added for this subsystem:

* ``cap_rows[b]``   — capacity scale: ``(pool + Δ_b) / pool`` prices a
  grow/drain as proportionally more/less availability in the rollout;
* ``active_rows[b]`` — admit mask: a shed action deactivates every
  task of the shed tier's apps, so the score trades the lost
  throughput against the saved cost *inside the same number*.

The menu size never changes (infeasible slots are scored as clones of
HOLD and excluded from the argmin), so after the first call every plan
is served by the one warm compiled program — the acceptance soak
asserts zero recompiles on this path.  Scoring is deterministic end to
end (seeded draws, one fixed reduction order); :func:`referee_check`
replays a plan's dispatch and demands bitwise equality — the per-tick
referee that guards the controller against nondeterministic scoring
ever reaching an actuator.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from pivot_tpu.search.weights import PolicyWeights

__all__ = [
    "CandidateAction",
    "PlanResult",
    "enumerate_actions",
    "plan",
    "referee_check",
]

#: Action kinds, in menu order — the argmin tie-break is this order, so
#: HOLD (slot 0) wins every tie: the planner never moves on a wash.
HOLD, GROW, DRAIN, SHED, WEIGHTS = "hold", "grow", "drain", "shed", "weights"


class CandidateAction(NamedTuple):
    """One slot of the planner menu."""

    kind: str
    pool_delta: int                  # +1 grow / −1 drain / 0 otherwise
    shed_tier: Optional[int]         # tasks of tiers >= this are masked
    weights: PolicyWeights           # scoring vector this slot rolls with
    feasible: bool                   # infeasible slots pad the menu only


def enumerate_actions(
    pool: int,
    *,
    g_min: int,
    g_max: int,
    incumbent: PolicyWeights,
    shed_tier: Optional[int] = None,
    challenger: Optional[PolicyWeights] = None,
) -> List[CandidateAction]:
    """The fixed five-slot menu for one decision window.

    Slots: ``[hold, grow, drain, shed, weights]`` — always five, in
    that order, so the scoring dispatch keeps one compiled shape.
    Infeasible slots (at ``g_max``, at ``g_min``, nothing sheddable, no
    eligible challenger) are emitted as HOLD clones with
    ``feasible=False``.  ``shed_tier`` must be >= 1: tier 0 is the
    lossless tier and is never sheddable (the acceptance criterion).
    """
    if pool < 1:
        raise ValueError(f"pool must be >= 1, got {pool}")
    if shed_tier is not None and shed_tier < 1:
        raise ValueError(
            f"tier 0 is lossless — shed_tier must be >= 1, got {shed_tier}"
        )
    incumbent = incumbent.validate()
    hold = CandidateAction(HOLD, 0, None, incumbent, True)
    grow = (
        CandidateAction(GROW, 1, None, incumbent, True)
        if pool < g_max else hold._replace(kind=GROW, feasible=False)
    )
    drain = (
        CandidateAction(DRAIN, -1, None, incumbent, True)
        if pool > g_min else hold._replace(kind=DRAIN, feasible=False)
    )
    shed = (
        CandidateAction(SHED, 0, int(shed_tier), incumbent, True)
        if shed_tier is not None
        else hold._replace(kind=SHED, feasible=False)
    )
    wts = (
        CandidateAction(WEIGHTS, 0, None, challenger.validate(), True)
        if challenger is not None
        else hold._replace(kind=WEIGHTS, feasible=False)
    )
    return [hold, grow, drain, shed, wts]


class PlanResult(NamedTuple):
    """One scored decision window."""

    chosen: CandidateAction
    index: int                 # menu slot of the winner
    objectives: np.ndarray     # [B] combined objective (inf = infeasible)
    scores: np.ndarray         # [B] cost per completed task
    details: dict              # evaluate_rows detail block


def _action_channels(actions, task_tiers, pool):
    """(W [B,5], cap_rows [B], active_rows [B,T]) for one menu.  Both
    channels are ALWAYS materialized — a None would trace the other
    compiled program and recompile on the first real grow/shed."""
    tiers = np.asarray(task_tiers)
    B, T = len(actions), tiers.shape[0]
    W = PolicyWeights.stack([a.weights for a in actions])
    cap_rows = np.asarray(
        [(pool + a.pool_delta) / pool for a in actions], dtype=np.float64
    )
    active_rows = np.ones((B, T), dtype=bool)
    for b, a in enumerate(actions):
        if a.feasible and a.shed_tier is not None:
            active_rows[b] = tiers < a.shed_tier
            if not active_rows[b].any():
                # A mask that sheds EVERYTHING scores 0/0; keep the
                # slot shaped but force it infeasible via the caller.
                active_rows[b] = True
    return W, cap_rows, active_rows


def plan(
    actions: List[CandidateAction],
    env,
    task_tiers,
    pool: int,
    *,
    latency_weight: float = 0.0,
    key=None,
    backend: str = "rollout",
    tick_order: str = "fifo",
) -> PlanResult:
    """Score the menu with ONE fused dispatch and pick the winner.

    The objective is ``cost_per_completed + latency_weight × makespan``
    — dollars per task with a configurable latency term, both produced
    by the same rollout.  Infeasible slots score ``inf``; ties break to
    the lowest slot index (HOLD first), so an indifferent model holds.
    """
    from pivot_tpu.search.fitness import evaluate_rows

    if not actions:
        raise ValueError("planner needs a non-empty action menu")
    W, cap_rows, active_rows = _action_channels(actions, task_tiers, pool)
    scores, details = evaluate_rows(
        W, env, key=key, backend=backend, tick_order=tick_order,
        cap_rows=cap_rows, active_rows=active_rows,
    )
    objectives = np.asarray(scores, dtype=np.float64) + (
        float(latency_weight) * np.asarray(details["makespan"], np.float64)
    )
    feasible = np.asarray([a.feasible for a in actions], dtype=bool)
    masked = np.where(feasible, objectives, np.inf)
    if not np.isfinite(masked).any():
        index = 0  # every slot infeasible or diverged: hold
    else:
        index = int(np.argmin(masked))  # first minimum = menu order
    return PlanResult(
        chosen=actions[index],
        index=index,
        objectives=objectives,
        scores=np.asarray(scores, dtype=np.float64),
        details=details,
    )


def referee_check(
    actions: List[CandidateAction],
    env,
    task_tiers,
    pool: int,
    *,
    latency_weight: float = 0.0,
    key=None,
    backend: str = "rollout",
    tick_order: str = "fifo",
) -> bool:
    """Deterministic-scoring referee: replay the plan dispatch and
    demand bitwise-identical objectives AND the same winning slot.
    The controller runs this every ``referee_every`` windows; a failure
    means the scoring path picked up nondeterminism (exactly what must
    never drive an actuator) and disables the controller's actions."""
    a = plan(
        actions, env, task_tiers, pool, latency_weight=latency_weight,
        key=key, backend=backend, tick_order=tick_order,
    )
    b = plan(
        actions, env, task_tiers, pool, latency_weight=latency_weight,
        key=key, backend=backend, tick_order=tick_order,
    )
    return bool(
        np.array_equal(a.objectives, b.objectives) and a.index == b.index
    )
