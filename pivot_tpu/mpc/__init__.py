"""Model-predictive serving: the simulator runs inside the server.

Everything before this package used the fitness estimator
(``search/fitness.py``) *offline* — tune weights, sweep scenarios,
report regret.  ``pivot_tpu.mpc`` closes the loop: a serving driver
built with an :class:`MpcConfig` runs a control thread that forecasts
the arrival stream it is serving (``forecast``), scores a menu of
candidate actions with seeded shadow rollouts of the predicted next
horizon — ONE fused device dispatch per decision window (``planner``)
— executes the predicted-best action through the driver's existing
pool machinery, re-fits :class:`~pivot_tpu.search.weights.PolicyWeights`
in a background CEM worker gated by the exact-oracle regret bound
(``tuner``), and promotes winners through a shadow → canary → fleet
rollout with automatic SLO rollback (``rollout``).

The default is OFF and bit-identical: ``ServeDriver(mpc=None)`` never
imports this package, and weight promotions ride the traced-operand
path (``Policy.apply_weights`` + the ``[3]`` exponent operand), so a
promotion changes VALUES with zero recompiles.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = [
    "MpcConfig",
    "MpcController",
    "MpcTuner",
    "TierForecaster",
    "WeightRollout",
]


@dataclasses.dataclass(frozen=True)
class MpcConfig:
    """Knobs for the model-predictive control loop.

    The config deliberately mirrors ``AutoscaleConfig``'s shape (pool
    bounds, governed tier, check interval) plus the model side: the
    forecast/rollout horizon, the rendered environment's pinned size
    (``env_apps`` — fixed so every window reuses one compiled
    program), the tuner budget, and the staged-rollout thresholds.
    ``dry_run=True`` scores and records every window but never touches
    an actuator — the observe-only mode A/B soaks compare against.
    """

    # -- control loop ------------------------------------------------------
    check_interval_s: float = 0.05
    #: Shadow-rollout horizon (sim seconds) each window predicts over.
    horizon: float = 300.0
    tick: float = 5.0
    #: Seeded rollouts per candidate action (the K in K-shadow-rollouts).
    n_replicas: int = 4
    #: Apps in the rendered environment — FIXED so operand shapes pin.
    env_apps: int = 6
    seed: int = 0
    #: Minimum forecaster observations before the first plan.
    min_observations: int = 4
    #: Wall seconds between actuations (not charged for hold/observe).
    cooldown_s: float = 0.2
    #: $-per-sim-second weight on predicted makespan in the objective.
    latency_weight: float = 0.01
    #: Per-replica eviction-plan redraws in the rendered env.
    redraw_faults: bool = True
    #: Replay the plan dispatch bitwise every Nth window (0 = off).
    referee_every: int = 8
    #: Score + record only; never actuate.
    dry_run: bool = False
    backend: str = "rollout"

    # -- pool bounds + governed tier ---------------------------------------
    g_min: int = 1
    g_max: int = 8
    tier: int = 0
    n_tiers: int = 3

    # -- forecaster --------------------------------------------------------
    bucket_s: float = 20.0
    alpha: float = 0.5

    # -- background tuner --------------------------------------------------
    tune: bool = True
    tune_interval_s: float = 0.2
    tune_generations: int = 2
    tune_popsize: int = 6
    #: Oracle-gate bound ($ from the proven optimum) on challengers.
    max_regret: float = 1.0

    # -- staged rollout ----------------------------------------------------
    canary_checks: int = 2
    watch_checks: int = 2
    regression_factor: float = 1.5

    # -- template world (optional injection) -------------------------------
    #: Render template cluster/market; None builds a synthetic cluster
    #: sized like the pool's and generates a market from its meta.
    cluster: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    market: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __post_init__(self):
        if not self.check_interval_s > 0:
            raise ValueError("check_interval_s must be positive")
        if not self.horizon > 0 or not self.tick > 0:
            raise ValueError("horizon and tick must be positive")
        if self.n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {self.n_replicas}")
        if self.env_apps < 1:
            raise ValueError(f"env_apps must be >= 1, got {self.env_apps}")
        if self.g_min < 1:
            raise ValueError(f"g_min must be >= 1, got {self.g_min}")
        if self.g_max < self.g_min:
            raise ValueError(
                f"g_max ({self.g_max}) must be >= g_min ({self.g_min})"
            )
        if not 0 <= self.tier < self.n_tiers:
            raise ValueError(
                f"tier must be in [0, {self.n_tiers}), got {self.tier}"
            )
        if self.min_observations < 1:
            raise ValueError("min_observations must be >= 1")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if self.latency_weight < 0:
            raise ValueError("latency_weight must be >= 0")
        if self.referee_every < 0:
            raise ValueError("referee_every must be >= 0")
        if self.tune_generations < 1 or self.tune_popsize < 2:
            raise ValueError(
                "tune_generations must be >= 1 and tune_popsize >= 2"
            )
        if self.max_regret < 0:
            raise ValueError("max_regret must be >= 0")
        if self.canary_checks < 1 or self.watch_checks < 1:
            raise ValueError("canary_checks/watch_checks must be >= 1")
        if self.regression_factor <= 1.0:
            raise ValueError("regression_factor must be > 1")


def __getattr__(name):
    # Lazy re-exports: importing MpcConfig (the driver's type check)
    # must not drag the jax-importing planner/controller stack along.
    if name == "MpcController":
        from pivot_tpu.mpc.controller import MpcController

        return MpcController
    if name == "MpcTuner":
        from pivot_tpu.mpc.tuner import MpcTuner

        return MpcTuner
    if name == "TierForecaster":
        from pivot_tpu.mpc.forecast import TierForecaster

        return TierForecaster
    if name == "WeightRollout":
        from pivot_tpu.mpc.rollout import WeightRollout

        return WeightRollout
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
