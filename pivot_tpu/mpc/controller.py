"""The model-predictive control loop: forecast → plan → act.

The :class:`MpcController` is the serving pool's *proactive* twin of
``serve/autoscale.py``'s reactive supervisor, with the same ownership
shape — a daemon thread started/stopped by ``ServeDriver.run``, every
pool mutation routed through the driver's thread-safe surface
(``grow_pool`` / ``begin_retire`` / ``shed_pressure``), every action on
the shared trace timeline — but a different decision rule: instead of
reacting to a p99 already breached, each window it

1. fits the arrival forecaster from the stream the driver has admitted
   so far (``mpc/forecast.py``),
2. renders the predicted next horizon into the fitness estimator's
   operands with the live market's hazard segments — one FIXED
   environment shape (pinned ``env_apps`` / seed / fault plan), so
   every window's dispatch reuses the one warm compiled program and
   only the operands (arrival spacing, tier masks, scenario key)
   change,
3. scores the full action menu (hold / grow / drain / shed-tier /
   challenger weights) as ONE fused ``evaluate_candidates`` dispatch
   (``mpc/planner.py``), and
4. executes the predicted-best action — including handing a winning
   challenger to the staged rollout machine (``mpc/rollout.py``).

The proactive-drain trigger this replaces was a flat ``risk_weight``
bias: hazard now enters as the rendered environment's per-replica
eviction plans, so "drain before the spot market turns" wins exactly
when the shadow rollouts price it cheaper — a model decision, not a
hand-tuned constant.

Determinism boundary: the *scoring* path (``forecast``/``planner``)
is in the determinism manifest; this module — like the autoscaler —
does wall-clock pacing and is not.  The planner's :func:`referee_check`
runs every ``referee_every`` windows; a referee failure permanently
disables actuation (observe-only) and is recorded, so nondeterministic
scoring can never keep driving the pool.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from pivot_tpu.mpc.forecast import TierForecaster, render_env
from pivot_tpu.mpc.planner import (
    WEIGHTS,
    enumerate_actions,
    plan,
    referee_check,
)
from pivot_tpu.mpc.rollout import WeightRollout
from pivot_tpu.mpc.tuner import MpcTuner
from pivot_tpu.search.weights import DEFAULT_WEIGHTS
from pivot_tpu.utils import LogMixin

__all__ = ["MpcController"]


class MpcController(LogMixin):
    """One model-predictive supervisor per driver.  Owned and started
    by ``ServeDriver.run`` when the driver is built with an
    :class:`~pivot_tpu.mpc.MpcConfig`; owns the forecaster, the
    background tuner, and the rollout state machine."""

    def __init__(self, driver, config):
        self.driver = driver
        self.config = config
        self.forecaster = TierForecaster(
            n_tiers=config.n_tiers,
            bucket_s=config.bucket_s,
            alpha=config.alpha,
        )
        self.tuner = (
            MpcTuner(
                seed=config.seed,
                generations=config.tune_generations,
                popsize=config.tune_popsize,
                max_regret=config.max_regret,
                interval_s=config.tune_interval_s,
                backend=config.backend,
            )
            if config.tune else None
        )
        self.rollout = WeightRollout(
            driver,
            tier=config.tier,
            canary_checks=config.canary_checks,
            watch_checks=config.watch_checks,
            regression_factor=config.regression_factor,
        )
        #: Planner decision log: one dict per executed window.
        self.events: List[dict] = []
        self.rounds = 0          # windows with a scored plan
        self.plans = 0           # fused planner dispatches issued
        self.disabled = False    # referee tripped: observe-only forever
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._template_cluster = None
        self._template_market = None
        self._key = None

    # -- template world -----------------------------------------------------
    def _ensure_template(self) -> None:
        """Build the render template once: the config's injected
        cluster/market, else a fresh synthetic cluster the size of the
        serving pool's (WITHOUT ``reset_ids`` — fresh ids must not
        collide with the sessions' live apps) and a market generated
        from its meta.  One template for the controller's lifetime is
        what pins the compiled shadow-rollout shape."""
        if self._template_cluster is not None:
            return
        cfg = self.config
        if cfg.cluster is not None:
            self._template_cluster = cfg.cluster
        else:
            from pivot_tpu.utils.config import ClusterConfig, build_cluster

            n_hosts = len(self.driver.sessions[0].cluster.hosts)
            self._template_cluster = build_cluster(
                ClusterConfig(n_hosts=n_hosts, seed=cfg.seed)
            )
        if cfg.market is not None:
            self._template_market = cfg.market
        else:
            from pivot_tpu.infra.market import MarketSchedule

            self._template_market = MarketSchedule.generate(
                self._template_cluster.meta,
                seed=cfg.seed,
                horizon=cfg.horizon,
            )

    # -- observability ------------------------------------------------------
    def record(self, action: str, objective: float, pool: int,
               detail: str = "") -> None:
        self.events.append(
            {
                "wall_s": round(self.driver.slo.wall_clock, 4),
                "action": action,
                "objective": round(float(objective), 6),
                "pool": pool,
                "detail": detail,
            }
        )
        self.driver.tracer.mark(
            "mpc", action, objective=round(float(objective), 6),
            pool=pool, detail=detail,
        )

    def action_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for evt in list(self.events):
            counts[evt["action"]] = counts.get(evt["action"], 0) + 1
        return counts

    def summary(self) -> dict:
        fc = self.forecaster.snapshot()
        return {
            "rounds": self.rounds,
            "plans": self.plans,
            "disabled": self.disabled,
            "dry_run": self.config.dry_run,
            "forecast": {
                "rates": [round(r, 6) for r in fc.rates],
                "mix": [round(m, 4) for m in fc.mix],
                "n_observed": fc.n_observed,
            },
            "events": list(self.events),
            "tuner": (
                {
                    "rounds": self.tuner.rounds,
                    "eligible": sum(
                        1 for r in list(self.tuner.results) if r.eligible
                    ),
                }
                if self.tuner is not None else None
            ),
            "rollout": {
                "promotions": self.rollout.promotions,
                "rollbacks": self.rollout.rollbacks,
                "stage": self.rollout.stage,
                "events": list(self.rollout.events),
            },
        }

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self.tuner is not None:
            self.tuner.start()
        self._thread = threading.Thread(
            target=self._loop, name="serve-mpc", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join()
        if self.tuner is not None:
            self.tuner.stop()

    # -- the control loop ---------------------------------------------------
    def _incumbent(self):
        pool = self.driver.policy_pool()
        if pool:
            w = getattr(pool[0][1], "weights", None)
            if w is not None:
                return w
        return DEFAULT_WEIGHTS

    def _loop(self) -> None:
        cfg = self.config
        driver = self.driver
        baseline = driver.slo.tier_decision_baseline(cfg.tier)
        last_event = -float("inf")
        while not self._stop_evt.wait(cfg.check_interval_s):
            # graftcheck: ignore[thread-guard] -- monotonic stop flag; a stale read costs one control window, and every pool mutation below re-validates under the driver's cv
            if driver._stop:
                break
            driver.finish_drained_retires()
            p99 = driver.slo.tier_decision_p99_since(cfg.tier, baseline)
            baseline = driver.slo.tier_decision_baseline(cfg.tier)
            # Staged rollout verdicts come first: a regression rolls
            # back in the same window it is measured.
            self.rollout.check(p99)
            forecast = self.forecaster.snapshot()
            if forecast.n_observed < cfg.min_observations:
                continue
            try:
                result, actions = self._plan_window(forecast)
            except Exception as e:  # pragma: no cover - defensive
                self.log.warning("mpc planning failed: %s", e)
                self.record("error", float("inf"), driver.pool_size(),
                            detail=str(e))
                continue
            if result is None:
                continue
            self.rounds += 1
            now = time.perf_counter()
            if (
                cfg.dry_run
                or self.disabled
                or now - last_event < cfg.cooldown_s
            ):
                self.record(
                    "observe", result.objectives[result.index],
                    driver.pool_size(), detail=result.chosen.kind,
                )
                continue
            if self._execute(result, p99):
                last_event = now

    def _plan_window(self, forecast):
        """Render the forecast and score the menu (one dispatch)."""
        import jax

        cfg = self.config
        driver = self.driver
        self._ensure_template()
        env, _, task_tiers = render_env(
            forecast,
            cluster=self._template_cluster,
            market=self._template_market,
            horizon=cfg.horizon,
            seed=cfg.seed,
            n_replicas=cfg.n_replicas,
            tick=cfg.tick,
            max_apps=cfg.env_apps,
            n_apps=cfg.env_apps,
            redraw_faults=cfg.redraw_faults,
        )
        incumbent = self._incumbent()
        if self.tuner is not None:
            self.tuner.submit(env, incumbent)
        challenger = (
            self.tuner.take_challenger()
            if self.tuner is not None and self.rollout.stage == "idle"
            else None
        )
        # The highest tier with forecast traffic is the sheddable one;
        # tier 0 is lossless and never enters the menu.
        shed_tier = None
        for t in range(cfg.n_tiers - 1, 0, -1):
            if forecast.rates[t] > 0:
                shed_tier = t
                break
        pool = driver.pool_size()
        actions = enumerate_actions(
            pool,
            g_min=cfg.g_min,
            g_max=cfg.g_max,
            incumbent=incumbent,
            shed_tier=shed_tier,
            challenger=challenger,
        )
        # Scenario draws refresh per window but replay per (seed,
        # round): the window index folds into the env-seed key.
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed), self.plans
        )
        self.plans += 1
        kw = dict(
            latency_weight=cfg.latency_weight, key=key,
            backend=cfg.backend,
        )
        if cfg.referee_every > 0 and self.plans % cfg.referee_every == 1:
            if not referee_check(actions, env, task_tiers, pool, **kw):
                self.disabled = True
                self.driver.slo.count("mpc_referee_failures")
                self.record(
                    "referee_failed", float("inf"), pool,
                    detail="bitwise replay mismatch; actuation disabled",
                )
                return None, actions
        return plan(actions, env, task_tiers, pool, **kw), actions

    def _execute(self, result, p99: float) -> bool:
        """Drive the chosen action through the driver's thread-safe
        surface.  Returns True when an actuator actually moved (the
        cooldown only charges real actions)."""
        driver = self.driver
        chosen = result.chosen
        obj = float(result.objectives[result.index])
        pool = driver.pool_size()
        plane = getattr(driver, "_recovery", None)
        if plane is not None:
            # Write-ahead: the actuation intent hits the journal before
            # the actuator moves — a crash between record and effect is
            # a journaled intent a replay can reconcile, never a silent
            # pool mutation.
            plane.journal_mpc(chosen.kind, pool)
        if chosen.kind == "grow":
            if driver.grow_pool(reason=f"mpc predicted obj {obj:.4f}"):
                driver.slo.count("mpc_grows")
                self.record("grow", obj, pool + 1)
                return True
        elif chosen.kind == "drain":
            victim = driver.begin_retire()
            if victim is not None:
                driver.slo.count("mpc_drains")
                self.record(
                    "drain", obj, pool - 1,
                    detail=f"draining {victim.label}",
                )
                return True
        elif chosen.kind == "shed":
            # shed_pressure victims are tiers STRICTLY below its
            # argument, so shedding tier t passes t − 1.
            if driver.shed_pressure(chosen.shed_tier - 1):
                driver.slo.count("mpc_sheds")
                self.record(
                    "shed", obj, pool, detail=f"tier {chosen.shed_tier}",
                )
                return True
        elif chosen.kind == WEIGHTS:
            if self.rollout.propose(chosen.weights, p99):
                self.record("canary", obj, pool)
                return True
        else:
            self.record("hold", obj, pool)
            return False
        # The actuator declined (no spare session, no victim, rollout
        # busy): recorded so a soak report shows the planner's intent
        # even when the pool could not follow it.
        self.record(f"{chosen.kind}_noop", obj, pool)
        return False
