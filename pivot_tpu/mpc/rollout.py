"""Staged weight rollout: shadow → canary → fleet, with auto-rollback.

A tuner challenger that wins the planner's WEIGHTS slot has only been
validated in *shadow* — seeded rollouts of the forecast horizon.  This
module is the path from shadow to the fleet, one stage at a time:

  ``SHADOW``   the planner scored the challenger against the incumbent
               in the same fused dispatch (``mpc/planner.py``) — a win
               there is what calls :meth:`WeightRollout.propose`;
  ``CANARY``   the vector is applied to ONE live session's policy
               (``Policy.apply_weights`` — attribute swap, zero
               recompiles) and the governed tier's windowed p99 is
               watched for ``canary_checks`` decision windows;
  ``FLEET``    promotion applies the vector to every pool policy, then
               keeps watching for ``watch_checks`` windows before the
               vector becomes the new incumbent.

A p99 regression beyond ``regression_factor`` × the pre-rollout
reference at ANY watched stage rolls every touched policy back to the
saved incumbent in the same control-loop tick — automatic, logged, and
counted.  Every transition lands in :attr:`events`, on the service
trace timeline (``tracer.mark("mpc", ...)``), and in the SLO meter's
counters, so a soak report shows each promotion and why it survived or
died.
"""

from __future__ import annotations

from typing import List, Optional

from pivot_tpu.search.weights import PolicyWeights
from pivot_tpu.utils import LogMixin

__all__ = ["WeightRollout"]

IDLE, CANARY, FLEET = "idle", "canary", "fleet"


class WeightRollout(LogMixin):
    """The promotion state machine.  Single-threaded by construction:
    every method is called from the controller loop only (the one
    thread that also runs the planner), so stage transitions need no
    lock of their own; driver interactions go through the driver's
    thread-safe surface (``policy_pool``, the SLO meter, the tracer).
    """

    def __init__(
        self,
        driver,
        *,
        tier: int = 0,
        canary_checks: int = 2,
        watch_checks: int = 2,
        regression_factor: float = 1.5,
        min_p99_s: float = 1e-4,
    ):
        if canary_checks < 1 or watch_checks < 1:
            raise ValueError("canary_checks/watch_checks must be >= 1")
        if regression_factor <= 1.0:
            raise ValueError(
                f"regression_factor must be > 1, got {regression_factor}"
            )
        self.driver = driver
        self.tier = int(tier)
        self.canary_checks = int(canary_checks)
        self.watch_checks = int(watch_checks)
        self.regression_factor = float(regression_factor)
        #: Floor on the regression reference: a canary started in an
        #: idle window (p99 ≈ 0) must not treat the first real latency
        #: sample as an infinite-ratio regression.
        self.min_p99_s = float(min_p99_s)
        self.stage = IDLE
        self.incumbent: Optional[PolicyWeights] = None
        self.events: List[dict] = []
        self.promotions = 0
        self.rollbacks = 0
        self._candidate: Optional[PolicyWeights] = None
        self._saved: List = []       # (label, policy, saved_weights)
        self._reference_p99 = 0.0
        self._checks = 0

    # -- observability ------------------------------------------------------
    def record(self, stage: str, detail: str = "", **extra) -> None:
        evt = {
            "wall_s": round(self.driver.slo.wall_clock, 4),
            "stage": stage,
            "detail": detail,
            **extra,
        }
        self.events.append(evt)
        self.driver.tracer.mark("mpc", stage, detail=detail, **extra)

    # -- stage transitions --------------------------------------------------
    def propose(self, weights: PolicyWeights, reference_p99: float) -> bool:
        """Shadow winner → canary: apply ``weights`` to one session.

        ``reference_p99`` is the governed tier's p99 over the windows
        *before* the rollout — the yardstick every later regression
        check compares against.  Returns False (and records why) when a
        rollout is already staging or the pool rejects the vector.
        """
        if self.stage != IDLE:
            return False
        pool = self.driver.policy_pool()
        if not pool:
            return False
        label, policy = pool[0]
        saved = policy.weights
        try:
            policy.apply_weights(weights)
        except ValueError as e:
            # A gated configuration (Pallas / sharded / realtime-bw)
            # rejects learned exponents — the rollout records and
            # drops the candidate instead of crashing the controller.
            self.record(IDLE, detail=f"canary rejected: {e}")
            return False
        self.stage = CANARY
        self._candidate = weights
        self._saved = [(label, policy, saved)]
        self._reference_p99 = max(float(reference_p99), self.min_p99_s)
        self._checks = 0
        self.driver.slo.count("mpc_canaries")
        self.record(
            CANARY, detail=f"canary on {label}",
            weights=[round(float(x), 4) for x in weights],
        )
        return True

    def check(self, p99: float) -> Optional[str]:
        """One decision window's verdict for the staging rollout.

        Returns the transition taken (``"promote"``, ``"rollback"``,
        ``"adopt"``) or None when nothing moved.  Called every
        controller window with the governed tier's windowed p99.
        """
        if self.stage == IDLE:
            return None
        if float(p99) > self.regression_factor * self._reference_p99:
            self._rollback(p99)
            return "rollback"
        self._checks += 1
        if self.stage == CANARY and self._checks >= self.canary_checks:
            return self._promote_fleet(p99)
        if self.stage == FLEET and self._checks >= self.watch_checks:
            self._adopt(p99)
            return "adopt"
        return None

    def _promote_fleet(self, p99: float) -> str:
        """Canary survived its windows: roll the vector to every pool
        policy (the canary's is already applied).  Any rejection mid-
        fleet rolls the whole attempt back — a split-brain pool scoring
        with two vectors is worse than either vector."""
        applied = {label for label, _, _ in self._saved}
        for label, policy in self.driver.policy_pool():
            if label in applied:
                continue
            try:
                saved = policy.weights
                policy.apply_weights(self._candidate)
                self._saved.append((label, policy, saved))
            except ValueError as e:
                self.record(FLEET, detail=f"fleet apply failed on {label}: {e}")
                self._rollback(p99)
                return "rollback"
        self.stage = FLEET
        self._checks = 0
        self.driver.slo.count("mpc_fleet_promotions")
        self.record(FLEET, detail=f"fleet of {len(self._saved)}")
        return "promote"

    def _adopt(self, p99: float) -> None:
        """Fleet watch clean: the candidate is the new incumbent."""
        self.promotions += 1
        self.incumbent = self._candidate
        self.record(
            IDLE, detail="adopted", p99_s=round(float(p99), 6),
        )
        self.stage = IDLE
        self._candidate = None
        self._saved = []

    def _rollback(self, p99: float) -> None:
        """SLO regression: restore every touched policy's saved vector
        (reverse order — the canary last, matching apply order)."""
        for label, policy, saved in reversed(self._saved):
            try:
                policy.apply_weights(saved)
            except ValueError:  # pragma: no cover - saved vectors re-apply
                self.log.warning("rollback re-apply failed on %s", label)
        self.rollbacks += 1
        self.driver.slo.count("mpc_rollbacks")
        self.record(
            IDLE,
            detail=f"rollback from {self.stage}",
            p99_s=round(float(p99), 6),
            reference_s=round(self._reference_p99, 6),
        )
        self.stage = IDLE
        self._candidate = None
        self._saved = []
