"""Arrival forecasting for model-predictive serving.

The reactive serving stack (``serve/autoscale.py``) only ever looks
backward: it scales on the p99 *already measured*, so every response is
one breach window late by construction.  Model-predictive serving
closes that gap by running the simulator's own fitness estimator
(``search/fitness.py``) *inside* the server — and the forecaster here
is the bridge: it fits a small, seeded, replayable model of the recent
arrival stream and renders it into the exact ensemble operands
(:class:`~pivot_tpu.search.fitness.SearchEnv`) the estimator scores,
so the planner's shadow rollouts predict the next horizon instead of
re-measuring the last one.

Two deliberate properties:

* **Deterministic.**  A :class:`TierForecast` is a pure function of the
  observed ``(sim_ts, tier)`` pairs — per-tier exponentially-weighted
  bucket rates over the observation window, no wall clocks, no
  unseeded randomness — and :func:`render_env` is a pure function of
  ``(forecast, cluster, market, seed)``.  The same observations always
  render the same environment bit for bit (``tests/test_mpc.py`` pins
  the replay), which is what makes every planner decision auditable
  after the fact.

* **Live-world injection.**  ``render_env`` hands the controller's
  *template* cluster and the live :class:`MarketSchedule` straight to
  ``make_search_env(cluster=..., market=...)`` — the injection path
  added for this module — so the shadow rollouts price placements with
  the SAME hazard segments and price multipliers the serving sessions
  are experiencing, not a synthetic market drawn from a different seed.
"""

from __future__ import annotations

import math
import threading
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

__all__ = ["TierForecast", "TierForecaster", "render_env"]


class TierForecast(NamedTuple):
    """Fitted per-tier arrival rates (jobs / sim-second) plus the
    window they were fitted over.  ``mix`` is the normalized tier
    distribution (sums to 1 when any traffic was seen)."""

    rates: Tuple[float, ...]   # per-tier jobs/sim-s
    mix: Tuple[float, ...]     # per-tier fraction of traffic
    n_observed: int            # observations in the fit window
    window: float              # sim-seconds the fit covered

    @property
    def total_rate(self) -> float:
        return float(sum(self.rates))


class TierForecaster:
    """Per-tier arrival-rate estimator over a sliding stream window.

    ``observe`` is called from the driver's admission path (producer
    thread) with the arrival's *sim* timestamp and tier; ``snapshot``
    fits from the controller thread.  The fit is an exponentially-
    weighted mean of per-bucket counts — newer buckets dominate, so a
    burst shows up within one bucket width — computed over at most
    ``max_obs`` retained arrivals.  Everything is sim-time: the
    forecaster never reads a wall clock (``analysis/determinism.py``
    holds this file to that).
    """

    def __init__(
        self,
        n_tiers: int = 3,
        bucket_s: float = 20.0,
        alpha: float = 0.5,
        max_obs: int = 4096,
    ):
        if n_tiers < 1:
            raise ValueError(f"n_tiers must be >= 1, got {n_tiers}")
        if not bucket_s > 0:
            raise ValueError(f"bucket_s must be positive, got {bucket_s}")
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.n_tiers = n_tiers
        self.bucket_s = float(bucket_s)
        self.alpha = float(alpha)
        self.max_obs = int(max_obs)
        self._lock = threading.Lock()
        self._obs: List[Tuple[float, int]] = []

    def observe(self, ts: float, tier: int) -> None:
        """Record one arrival (sim timestamp, tier).  Thread-safe;
        out-of-range tiers clamp into the forecast's last bucket rather
        than dropping traffic silently."""
        t = min(max(int(tier), 0), self.n_tiers - 1)
        with self._lock:
            self._obs.append((float(ts), t))
            if len(self._obs) > self.max_obs:
                # Keep the newest window; admission order is
                # timestamp order, so a slice is the window.
                del self._obs[: len(self._obs) - self.max_obs]

    def snapshot(self) -> TierForecast:
        """Fit the current window.  Empty stream ⇒ zero rates."""
        with self._lock:
            obs = list(self._obs)
        if not obs:
            z = (0.0,) * self.n_tiers
            return TierForecast(rates=z, mix=z, n_observed=0, window=0.0)
        t0 = obs[0][0]
        t1 = obs[-1][0]
        # At least one full bucket so a single arrival yields a finite
        # rate instead of a division by zero.
        span = max(t1 - t0, self.bucket_s)
        n_buckets = int(math.ceil(span / self.bucket_s))
        counts = np.zeros((n_buckets, self.n_tiers), dtype=np.float64)
        for ts, tier in obs:
            b = min(int((ts - t0) / self.bucket_s), n_buckets - 1)
            counts[b, tier] += 1.0
        # EWMA over buckets, oldest → newest: rate_k = α·x_k + (1−α)·
        # rate_{k−1}, seeded with the first bucket.
        rate = counts[0] / self.bucket_s
        for k in range(1, n_buckets):
            rate = self.alpha * (counts[k] / self.bucket_s) + (
                1.0 - self.alpha
            ) * rate
        total = float(rate.sum())
        mix = (
            tuple(float(r) / total for r in rate)
            if total > 0 else (0.0,) * self.n_tiers
        )
        return TierForecast(
            rates=tuple(float(r) for r in rate),
            mix=mix,
            n_observed=len(obs),
            window=float(span),
        )


def render_env(
    forecast: TierForecast,
    *,
    cluster,
    market,
    horizon: float,
    seed: int,
    n_replicas: int = 4,
    tick: float = 5.0,
    max_apps: int = 12,
    n_apps: Optional[int] = None,
    redraw_faults: bool = True,
    perturb: float = 0.1,
):
    """Render a forecast into scoring operands: ``(SearchEnv,
    app_tiers [A], task_tiers [T])``.

    The predicted horizon carries ``ceil(total_rate × horizon)`` apps
    (clamped to ``[1, max_apps]`` — the environment is a *model*, and
    its cost is one fused dispatch over B×R rollouts, so it must stay
    small), evenly spaced at the predicted inter-arrival gap.  Passing
    ``n_apps`` pins the app count instead — the controller does, every
    window, so the rendered operand SHAPES never change and one warm
    compiled program serves every plan (the predicted rate then enters
    through the arrival spacing, which is data, not shape).  Each app
    is assigned a tier by largest-remainder apportionment of the
    forecast mix — deterministic, and exact in expectation — and every
    task inherits its app's tier (``workload.app_of``), so the
    planner's shed masks drop whole DAGs: masking a mid-graph task
    would strand its active successors as permanently unfinished and
    corrupt the score.
    """
    from pivot_tpu.search.fitness import make_search_env

    lam = forecast.total_rate
    if n_apps is None:
        n_apps = int(min(max(math.ceil(lam * horizon), 1), max_apps))
    n_apps = int(n_apps)
    if n_apps < 1:
        raise ValueError(f"n_apps must be >= 1, got {n_apps}")
    # Predicted inter-arrival gap, clamped into the horizon so a lull
    # cannot push the whole rendered stream past the scoring window.
    spacing = (
        min(max(1.0 / lam, 0.0), horizon / n_apps) if lam > 0 else 0.0
    )
    env = make_search_env(
        n_hosts=len(cluster.hosts),
        seed=seed,
        n_apps=n_apps,
        horizon=horizon,
        tick=tick,
        n_replicas=n_replicas,
        perturb=perturb,
        arrival_spacing=spacing,
        redraw_faults=redraw_faults,
        cluster=cluster,
        market=market,
    )
    app_tiers = _apportion_tiers(forecast.mix, n_apps)
    app_of = np.asarray(env.workload.app_of)
    task_tiers = app_tiers[app_of]
    return env, app_tiers, task_tiers


def _apportion_tiers(mix: Tuple[float, ...], n_apps: int) -> np.ndarray:
    """[A] i32 tier per app by largest-remainder apportionment of
    ``mix`` (ties to the lower tier — deterministic).  A zero mix
    (no traffic observed) assigns everything tier 0."""
    m = np.asarray(mix, dtype=np.float64)
    if m.sum() <= 0:
        return np.zeros(n_apps, dtype=np.int32)
    m = m / m.sum()
    quota = m * n_apps
    base = np.floor(quota).astype(np.int64)
    short = n_apps - int(base.sum())
    if short > 0:
        remainder = quota - base
        # Stable argsort descending remainder; ties favor lower tiers.
        order = np.argsort(-remainder, kind="stable")
        for k in range(short):
            base[order[k]] += 1
    tiers = np.repeat(np.arange(len(m), dtype=np.int32), base)
    return tiers[:n_apps].astype(np.int32)
