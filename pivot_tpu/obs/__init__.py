"""pivot_tpu.obs — the first-class observability plane (round 14).

Three pillars (ISSUE 12):

  * **causal task tracing** (:mod:`pivot_tpu.obs.tracer`) — every serve
    job carries a trace id from arrival through admission/queue/spill →
    routing → batcher slot/device dispatch → placement/retry/preemption
    /dead-letter → completion, as parent-linked stages on dual clocks
    (sim + wall); DES ticks, batcher flushes, autoscaler actions, and
    chaos/market events land on the same timeline; exported as
    Perfetto/Chrome ``trace_event`` JSON and JSONL, rendered by
    ``tools/obs_report.py``;
  * **unified metrics registry** (:mod:`pivot_tpu.obs.registry`) — one
    thread-safe, label-aware counter/gauge/summary store that
    ``Meter``, ``SloMeter``, the dispatch batcher, the autoscaler, and
    the compile counter publish into, exported as Prometheus text
    exposition and JSON;
  * **hot-path safety** — zero-cost when disabled, bounded when
    enabled, wall capture confined to this package (the graftcheck
    ``obs-boundary`` pass pins the determinism boundary; the
    ``obs_overhead`` bench row gates the enabled cost).

Round 15 adds the *performance* observability layer on top:

  * **sampled dispatch profiling** (:mod:`pivot_tpu.obs.profiler`) —
    :class:`DispatchProfiler` times a deterministic 1-in-N sample of
    kernel dispatches to completion at the ``_call_kernel`` /
    ``place_span`` / batcher-flush boundaries, publishing per-family
    latency summaries into the registry and ``device``-lane Perfetto
    spans carrying shape + analytic roofline predictions (the
    ``profiler-boundary`` graftcheck pass pins the call sites; the
    ``profiler_overhead`` bench row gates the enabled cost);
  * **XLA cost attribution** (:mod:`pivot_tpu.obs.costattr`) — every
    jitmap-registered entry point gets FLOPs/bytes from
    ``lowered.compile().cost_analysis()`` or an explicit flag
    (register-or-flag, the jitcheck convention), joined against the
    analytic ``infra/roofline.py`` model;
  * **live scrape** (:mod:`pivot_tpu.obs.metrics_http`) — the
    registry's Prometheus exposition served over a stdlib HTTP
    endpoint (``serve --metrics-port``).

See docs/ARCHITECTURE.md "The observability plane" and "Performance
observability".
"""

from __future__ import annotations

from typing import Callable, Optional

from pivot_tpu.obs.clock import ObsClock
from pivot_tpu.obs.metrics_http import MetricsHTTPServer
from pivot_tpu.obs.profiler import DispatchProfiler
from pivot_tpu.obs.registry import MetricsRegistry
from pivot_tpu.obs.tracer import (
    NULL_TRACER,
    TERMINAL_STAGES,
    Tracer,
    device_profile,
)

__all__ = [
    "DispatchProfiler",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "NULL_TRACER",
    "ObsClock",
    "TERMINAL_STAGES",
    "Tracer",
    "attach_compile_observer",
    "device_profile",
]


def attach_compile_observer(
    registry: Optional[MetricsRegistry] = None,
    tracer: Optional[Tracer] = None,
    sim_time: Optional[Callable[[], float]] = None,
) -> Callable[[], None]:
    """Make JAX recompiles *visible*: publish every backend compile /
    jaxpr trace into the registry
    (``pivot_jax_compile_events_total{kind=...}``) and stamp an instant
    event on the trace timeline — a recompile after warmup becomes a
    mark a human sees in Perfetto, not just a test assertion
    (``tests/test_jitcheck.py``).

    ``sim_time`` (optional, e.g. ``lambda: env.now``) anchors the
    instant on the sim timeline as well; without it the event is
    wall-only.  Returns a detach callable — call it when the observed
    window ends (the underlying ``jax.monitoring`` listener is
    process-permanent, but the observer fan-out list is not).
    """
    from pivot_tpu.utils import compile_counter

    if registry is not None:
        registry.counter(
            "pivot_jax_compile_events_total",
            "XLA backend compiles and jaxpr traces observed by the "
            "compile counter (zero after warmup is the steady-state "
            "hypothesis)",
            labelnames=("kind",),
        )

    def _observe(kind: str) -> None:
        if registry is not None:
            registry.inc("pivot_jax_compile_events_total", kind=kind)
        if tracer is not None and tracer.enabled:
            sim = sim_time() if sim_time is not None else None
            if sim is not None:
                tracer.emit("compile", kind, sim)
            else:
                tracer.mark("compile", kind)

    compile_counter.add_observer(_observe)

    def detach() -> None:
        compile_counter.remove_observer(_observe)

    return detach
