"""Unified metrics registry: one snapshot shape instead of five.

Before round 14 the system's metrics were a scatter of per-subsystem
JSON dumps — ``SloMeter.snapshot()``, the batcher's ``stats_out`` dict,
the autoscaler's event list, ``Meter.summary()``, and the compile
counter — each with its own schema, none correlatable without writing
a bespoke joiner.  Detecting metastable feedback (retry storms feeding
backpressure feeding autoscaling — Bronson et al., PAPERS.md) needs the
signals in ONE place with ONE shape.

:class:`MetricsRegistry` is that place: a thread-safe, label-aware
store of **counters** (monotone), **gauges** (point-in-time), and
**summaries** (count/sum/quantiles — the export shape of
:class:`~pivot_tpu.infra.meter.StreamingHistogram` snapshots), exported
two ways:

  * :meth:`to_prometheus` — Prometheus text exposition (format 0.0.4):
    ``# HELP``/``# TYPE`` headers, label-escaped sample lines, summary
    quantile series plus ``_count``/``_sum`` — scrape-ready;
  * :meth:`to_json` — the same families as one JSON document (the
    snapshot shape tests pin).

Publishers do not push continuously; sources *publish* their current
state into the registry at snapshot points (``SloMeter
.publish_metrics``, ``Meter.publish_metrics``, ``ServeDriver.report``,
the compile-counter observer).  Publishing is idempotent — ``set`` on
a counter family records the source's monotone value, so republishing
a snapshot never double-counts.

Metric and label names are validated against the Prometheus grammar at
family creation, so a typo fails at declaration, not at scrape time.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["MetricsRegistry", "declare_recovery_metrics"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_KINDS = ("counter", "gauge", "summary")


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


class _Family:
    """One metric family: a kind, a help string, fixed label names, and
    samples keyed by label-value tuples."""

    __slots__ = ("name", "kind", "help", "labelnames", "samples")

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Tuple[str, ...]):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        # label values tuple -> float (counter/gauge) or summary dict
        self.samples: Dict[Tuple[str, ...], Any] = {}


class MetricsRegistry:
    """Thread-safe counters/gauges/summaries with labels."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # -- declaration -----------------------------------------------------
    def _declare(self, name: str, kind: str, help: str,
                 labelnames: Sequence[str]) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind or fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name} re-declared as {kind}"
                    f"{tuple(labelnames)} (was {fam.kind}"
                    f"{fam.labelnames})"
                )
            if help and not fam.help:
                fam.help = help
            return fam
        fam = _Family(name, kind, help, tuple(labelnames))
        self._families[name] = fam
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> "MetricsRegistry":
        with self._lock:
            self._declare(name, "counter", help, labelnames)
        return self

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> "MetricsRegistry":
        with self._lock:
            self._declare(name, "gauge", help, labelnames)
        return self

    def summary(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> "MetricsRegistry":
        with self._lock:
            self._declare(name, "summary", help, labelnames)
        return self

    # -- recording -------------------------------------------------------
    def _key(self, fam: _Family, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(fam.labelnames):
            raise ValueError(
                f"{fam.name} wants labels {fam.labelnames}, got "
                f"{tuple(sorted(labels))}"
            )
        return tuple(str(labels[ln]) for ln in fam.labelnames)

    def _recording_family(self, name: str, kinds: Tuple[str, ...],
                          labels: Dict[str, Any]) -> _Family:
        """Family for a recording call (auto-declared as ``kinds[0]``
        on first use), kind-checked at RECORDING time — "a typo fails
        at declaration, not scrape time" must also hold for the write
        path, or a ``set()`` on a summary family stores a raw float
        that only explodes later inside ``to_prometheus()``."""
        fam = self._families.get(name)
        if fam is None:
            return self._declare(name, kinds[0], "", tuple(sorted(labels)))
        if fam.kind not in kinds:
            raise ValueError(
                f"{name} is a {fam.kind}; this recording method "
                f"serves {kinds}"
            )
        return fam

    def inc(self, name: str, value: float = 1.0, **labels: Any) -> None:
        """Increment a counter (auto-declared on first use)."""
        if value < 0:
            raise ValueError(f"counter {name} increment must be >= 0")
        with self._lock:
            fam = self._recording_family(name, ("counter",), labels)
            key = self._key(fam, labels)
            fam.samples[key] = fam.samples.get(key, 0.0) + float(value)

    def set(self, name: str, value: float, **labels: Any) -> None:
        """Record a value: point-in-time for gauges, the source's
        current monotone total for counters (publish-style — idempotent
        on republish, never double-counting)."""
        with self._lock:
            fam = self._recording_family(name, ("gauge", "counter"), labels)
            fam.samples[self._key(fam, labels)] = float(value)

    def observe_summary(self, name: str, count: int, total: float,
                        quantiles: Dict[float, float],
                        **labels: Any) -> None:
        """Publish a pre-aggregated distribution (the shape a
        ``StreamingHistogram.snapshot()`` reduces to): exact count and
        sum plus quantile estimates keyed by q in (0, 1]."""
        with self._lock:
            fam = self._recording_family(name, ("summary",), labels)
            fam.samples[self._key(fam, labels)] = {
                "count": int(count),
                "sum": float(total),
                "quantiles": {
                    float(q): float(v) for q, v in quantiles.items()
                },
            }

    # -- export ----------------------------------------------------------
    def to_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4), families sorted by name."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                if fam.help:
                    lines.append(f"# HELP {name} {_escape(fam.help)}")
                lines.append(f"# TYPE {name} {fam.kind}")
                for key in sorted(fam.samples):
                    label_str = ",".join(
                        f'{ln}="{_escape(v)}"'
                        for ln, v in zip(fam.labelnames, key)
                    )
                    if fam.kind == "summary":
                        s = fam.samples[key]
                        for q in sorted(s["quantiles"]):
                            qlabels = label_str + ("," if label_str else "")
                            lines.append(
                                f'{name}{{{qlabels}quantile="{q:g}"}} '
                                f"{s['quantiles'][q]:.9g}"
                            )
                        suffix = f"{{{label_str}}}" if label_str else ""
                        lines.append(
                            f"{name}_count{suffix} {s['count']}"
                        )
                        lines.append(f"{name}_sum{suffix} {s['sum']:.9g}")
                    else:
                        suffix = f"{{{label_str}}}" if label_str else ""
                        lines.append(
                            f"{name}{suffix} {fam.samples[key]:.9g}"
                        )
        return "\n".join(lines) + "\n"

    def to_json(self) -> dict:
        """The same families as one JSON document — the unified
        snapshot shape (``{"metrics": {name: {kind, help, samples:
        [{labels, value}]}}}``)."""
        with self._lock:
            metrics: Dict[str, Any] = {}
            for name in sorted(self._families):
                fam = self._families[name]
                metrics[name] = {
                    "kind": fam.kind,
                    "help": fam.help,
                    "samples": [
                        {
                            "labels": dict(zip(fam.labelnames, key)),
                            "value": fam.samples[key],
                        }
                        for key in sorted(fam.samples)
                    ],
                }
            return {"metrics": metrics}

    def save_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    def save_prometheus(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_prometheus())

    # -- convenience -----------------------------------------------------
    def families(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    def get(self, name: str, **labels: Any) -> Optional[Any]:
        """Current value of one sample (None when absent) — test hook."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            try:
                return fam.samples.get(self._key(fam, labels))
            except ValueError:
                return None


def declare_recovery_metrics(registry: MetricsRegistry) -> None:
    """Declare the ``pivot_recover_*`` family (idempotent — declare is
    chainable and re-declaration with identical schema is a no-op).
    Published by ``pivot_tpu.recover.RecoveryPlane.publish`` whenever a
    serve driver runs with a recovery plane attached:

      * ``pivot_recover_snapshot_age_s`` — seconds since the last
        resident-carry snapshot landed on disk (the recovery-point age).
      * ``pivot_recover_journal_lag`` — journaled records not yet
        fsynced (the write-ahead journal's durability lag).
      * ``pivot_recover_retries_total`` — watchdog dispatch retries.
      * ``pivot_recover_quarantined_rows`` — rows in the per-tenant
        penalty box, labelled by tenant.
    """
    registry.gauge(
        "pivot_recover_snapshot_age_s",
        "seconds since the last resident-carry snapshot was written",
    )
    registry.gauge(
        "pivot_recover_journal_lag",
        "journal records appended but not yet fsynced",
    )
    registry.counter(
        "pivot_recover_retries_total",
        "watchdog dispatch retries issued",
    )
    registry.gauge(
        "pivot_recover_quarantined_rows",
        "rows quarantined in the penalty box, per tenant",
        labelnames=("tenant",),
    )
