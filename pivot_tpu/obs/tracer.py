"""Causal task tracing on dual clocks — the observability plane's spine.

Grown from the round-1 seed tracer (``pivot_tpu/utils/trace.py``, now a
compatibility shim over this module).  Three event families share one
append-only log:

  * **instants** (:meth:`Tracer.emit`) — a named point on the sim
    timeline (task finished, host quarantined, price-segment change);
  * **spans** (:meth:`Tracer.span` / :meth:`Tracer.wall_span`) — a
    wall-clock duration (one policy invocation, one batcher flush);
    ``span`` anchors on a sim instant, ``wall_span`` is sim-less (for
    dispatch-boundary work with no single sim time, e.g. a coalesced
    flush serving several sessions' ticks at once);
  * **causal stages** (:meth:`Tracer.stage`) — parent-linked events of
    one *trace* (a serve job's life): every stage records the previous
    stage of its trace as ``parent``, so the full
    arrival → admission/queue/spill → routing → injection → placement
    → completion chain is reconstructable by walking parent links
    (``tools/obs_report.py`` and ``tests/test_obs.py`` do exactly
    that).

Every event carries BOTH clocks where both exist: ``sim`` (discrete-
event virtual seconds — *what the simulated system did*) and ``wall``
(host seconds since tracer creation — *what the framework paid to
compute it*).

**Hot-path contract** (the tentpole's third pillar):

  * zero-cost when disabled — every recording method short-circuits on
    ``self.enabled`` before touching a clock, a lock, or a dict;
  * the wall capture lives HERE, inside ``pivot_tpu/obs`` — hooks in
    the determinism-scoped modules (``des/``, ``sched/``, ``ops/``,
    the fault/market engines) pass sim-time payloads only, and the
    graftcheck ``obs-boundary`` pass pins that they never read a wall
    clock or import this package's clock;
  * no instrumentation inside jitted/Pallas bodies — events are emitted
    at dispatch *boundaries* only; the ``obs-boundary`` pass reuses the
    host-sync discovery to flag a tracer hook inside a fused hot body.

Thread safety: the serve layer records from the driver, session, and
autoscaler threads concurrently; the log append + id allocation run
under one lock.  Recording never blocks on I/O — serialization
(:meth:`save_jsonl` / :meth:`save_chrome` / :meth:`save_perfetto`) is
explicit and post-hoc.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, List, Optional

from pivot_tpu.obs.clock import ObsClock

__all__ = ["Tracer", "NULL_TRACER", "TERMINAL_STAGES", "device_profile"]

#: Stage names that end a job's causal chain — used by the Perfetto
#: exporter (async-span close) and the report/check walkers.  Exactly
#: one of these must terminate every admitted job's trace.
TERMINAL_STAGES = frozenset(
    {"completed", "failed", "shed", "dead_letter"}
)


class _Span:
    """Hand-rolled span context manager — the per-tick hot hook.

    A ``@contextlib.contextmanager`` generator costs ~2× this class per
    entry (generator frame + throw/close protocol); the tick loop opens
    one span per scheduler tick, so the entry cost IS the tracer-on
    overhead the ``obs_overhead`` bench row gates.  ``sim=None`` makes
    it the sim-less ``wall_span`` variant.
    """

    __slots__ = ("_tracer", "_cat", "_name", "_sim", "_args", "_t0")

    def __init__(self, tracer: "Tracer", cat: str, name: str,
                 sim: Optional[float], args: Dict[str, Any]):
        self._tracer = tracer
        self._cat = cat
        self._name = name
        self._sim = sim
        self._args = args
        self._t0: Optional[float] = None

    def __enter__(self) -> Dict[str, Any]:
        if self._tracer.enabled:
            self._t0 = time.perf_counter()
        return self._args

    def __exit__(self, exc_type, exc, tb) -> bool:
        tr = self._tracer
        t0 = self._t0
        if not tr.enabled or t0 is None:
            return False
        evt: Dict[str, Any] = {
            "cat": self._cat,
            "name": self._name,
            "wall": t0 - tr._wall0,
            "dur": time.perf_counter() - t0,
        }
        if self._sim is not None:
            evt["sim"] = self._sim
        if self._args:
            evt["args"] = self._args
        with tr._lock:
            tr.events.append(evt)
        return False


class Tracer:
    """Append-only structured event log with sim + wall timestamps."""

    __slots__ = (
        "enabled", "events", "clock", "_wall0", "_lock", "_seq",
        "_trace_seq", "_trace_tail",
    )

    def __init__(self, enabled: bool = True,
                 clock: Optional[ObsClock] = None):
        self.enabled = enabled
        self.events: List[Dict[str, Any]] = []
        #: The injected obs clock — the EPOCH the meters share; inside
        #: this module the hot paths read ``time.perf_counter()``
        #: directly (``ObsClock.now`` is a passthrough; the indirection
        #: costs ~1µs/event, which the obs_overhead gate charges).
        self.clock = clock or ObsClock()
        self._wall0 = time.perf_counter()
        self._lock = threading.Lock()
        self._seq = 0  # stage event ids (parent-link targets)
        self._trace_seq = 0  # trace ids (admission order)
        #: trace id -> event id of its most recent stage (parent links).
        self._trace_tail: Dict[int, int] = {}

    # -- recording -------------------------------------------------------
    # Only causal *stages* carry event ids (they are what parent links
    # point at); instants and spans append id-free — per-event id
    # bookkeeping on the tick hot path would be pure overhead.

    def emit(self, cat: str, name: str, sim: float, **args: Any) -> None:
        """Record an instant event at sim time ``sim``."""
        if not self.enabled:
            return
        evt: Dict[str, Any] = {
            "cat": cat,
            "name": name,
            "sim": sim,
            "wall": time.perf_counter() - self._wall0,
        }
        if args:
            evt["args"] = args
        with self._lock:
            self.events.append(evt)

    def span(self, cat: str, name: str, sim: float, **args: Any) -> _Span:
        """Record a wall-clock duration span (e.g. one policy invocation).

        The span's ``dur`` is *wall* seconds — sim time does not advance
        inside a synchronous block.  Mutations to ``args`` made inside the
        block (e.g. recording the number of placed tasks once known) are
        captured because the dict is attached at exit.
        """
        return _Span(self, cat, name, sim, args)

    def record_span(self, cat: str, name: str, dur: float,
                    sim: Optional[float] = None, **args: Any) -> None:
        """Record an already-measured wall duration (the caller timed
        the work itself, e.g. the serve decision tap) as a span ending
        now — so dispatch latencies land on the timeline without the
        tracer owning the measurement."""
        if not self.enabled:
            return
        end = time.perf_counter() - self._wall0
        evt: Dict[str, Any] = {
            "cat": cat,
            "name": name,
            "wall": max(end - dur, 0.0),
            "dur": dur,
        }
        if sim is not None:
            evt["sim"] = sim
        if args:
            evt["args"] = args
        with self._lock:
            self.events.append(evt)

    def mark(self, cat: str, name: str, **args: Any) -> None:
        """A wall-only instant — framework events with no sim anchor
        (a recompile observed mid-dispatch, a watchdog action)."""
        if not self.enabled:
            return
        evt: Dict[str, Any] = {
            "cat": cat,
            "name": name,
            "wall": time.perf_counter() - self._wall0,
        }
        if args:
            evt["args"] = args
        with self._lock:
            self.events.append(evt)

    def wall_span(self, cat: str, name: str, **args: Any) -> _Span:
        """A sim-less measurement span for dispatch boundaries.

        A coalesced batcher flush serves several sessions' ticks — it
        has no single sim instant, only a wall duration.  Call sites in
        determinism-scoped modules use THIS instead of reading
        ``time.perf_counter()`` themselves: the wall capture stays
        inside ``obs/`` (the determinism boundary the ``obs-boundary``
        pass pins)."""
        return _Span(self, cat, name, None, args)

    # -- causal task tracing ---------------------------------------------
    def new_trace(self) -> int:
        """Allocate a trace id (one per serve job, in admission order —
        deterministic under the driver's serialized admission)."""
        with self._lock:
            tid = self._trace_seq
            self._trace_seq += 1
            return tid

    def stage(self, trace: int, name: str, sim: Optional[float] = None,
              cat: str = "job", **args: Any) -> Optional[int]:
        """One parent-linked stage of a job's causal chain.

        The event records the trace's previous stage as ``parent``;
        walking parents from a terminal stage reconstructs the full
        arrival→completion chain.  ``sim`` is optional — wall-domain
        stages (routing decisions made between sim instants) carry the
        wall clock only.  Returns the event id (None when disabled).
        """
        if not self.enabled:
            return None
        evt: Dict[str, Any] = {
            "cat": cat,
            "name": name,
            "wall": time.perf_counter() - self._wall0,
            "trace": trace,
        }
        if sim is not None:
            evt["sim"] = sim
        if args:
            evt["args"] = args
        with self._lock:
            eid = self._seq
            self._seq += 1
            evt["id"] = eid
            parent = self._trace_tail.get(trace)
            if parent is not None:
                evt["parent"] = parent
            self._trace_tail[trace] = eid
            self.events.append(evt)
            return eid

    # -- serialization ---------------------------------------------------
    def save_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for evt in self.events:
                f.write(json.dumps(evt) + "\n")

    def _ts(self, evt: Dict[str, Any], timeline: str) -> float:
        """Event's position on the chosen timeline, in µs.  Wall-only
        events (sim-less spans/stages) fall back to the wall clock on
        the sim timeline — they are framework work, not sim events, but
        dropping them would hide dispatch costs from the default view."""
        if timeline == "sim" and "sim" in evt:
            return evt["sim"] * 1e6
        return evt["wall"] * 1e6

    def _record(self, evt: Dict[str, Any], timeline: str,
                rich: bool) -> Dict[str, Any]:
        """One ``trace_event`` record for an event — the single record
        shape both exporters share (two hand-maintained copies would
        drift).  ``rich`` hoists the causal fields (``id``/``trace``/
        ``parent``) and the sim anchor into args for the Perfetto
        artifact obs_report walks."""
        rec: Dict[str, Any] = {
            "name": evt["name"],
            "cat": evt["cat"],
            "pid": 0,
            "tid": evt["cat"],
            "ts": self._ts(evt, timeline),
        }
        if "dur" in evt:
            rec["ph"] = "X"
            rec["dur"] = max(evt["dur"] * 1e6, 1.0)
        else:
            rec["ph"] = "i"
            rec["s"] = "t"
        if rich:
            args = dict(evt.get("args", ()))
            for key in ("id", "trace", "parent"):
                if key in evt:
                    args[key] = evt[key]
            if "sim" in evt:
                args["sim"] = evt["sim"]
            if args:
                rec["args"] = args
        elif "args" in evt:
            rec["args"] = evt["args"]
        return rec

    def save_chrome(self, path: str, timeline: str = "sim") -> None:
        """Write a Chrome/Perfetto trace (``chrome://tracing`` loadable).

        ``timeline='sim'`` places events at their simulated time (µs = sim
        seconds × 1e6, so 1 simulated second reads as 1 s in the viewer);
        ``timeline='wall'`` places them at host time — use this to inspect
        where the framework itself spends wall clock (policy spans carry
        real durations on either timeline).
        """
        assert timeline in ("sim", "wall")
        out = [self._record(evt, timeline, rich=False)
               for evt in self.events]
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)

    def save_perfetto(self, path: str, timeline: str = "sim") -> None:
        """Write the full observability timeline as Perfetto/Chrome
        ``trace_event`` JSON: one lane (``tid``) per category, causal
        stages carrying ``trace``/``parent``/``id`` in their args, and
        one *async span* per job trace (``ph: b``/``e`` keyed by trace
        id) stretching from its first stage to its last — so a job's
        whole life reads as one bar with its stages nested under it.
        Events are sorted by timestamp (``tools/obs_report.py --check``
        verifies monotonicity per lane).
        """
        assert timeline in ("sim", "wall")
        out: List[Dict[str, Any]] = []
        first_last: Dict[int, List[Dict[str, Any]]] = {}
        for evt in self.events:
            rec = self._record(evt, timeline, rich=True)
            out.append(rec)
            trace = evt.get("trace")
            if trace is not None:
                span = first_last.setdefault(trace, [rec, rec])
                span[1] = rec
        for trace, (first, last) in sorted(first_last.items()):
            base = {
                "cat": "job",
                "pid": 0,
                "tid": "jobs",
                "id": str(trace),
                "name": f"job-{trace}",
            }
            out.append(dict(base, ph="b", ts=first["ts"]))
            out.append(dict(base, ph="e", ts=max(last["ts"], first["ts"])))
        out.sort(key=lambda r: r["ts"])
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)

    # -- analysis helpers ------------------------------------------------
    def by_category(self, cat: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e["cat"] == cat]

    def by_trace(self, trace: int) -> List[Dict[str, Any]]:
        """The causal chain of one trace, in append (stage) order."""
        return [e for e in self.events if e.get("trace") == trace]

    def traces(self) -> List[int]:
        """Every trace id that recorded at least one stage, sorted."""
        return sorted({
            e["trace"] for e in self.events if "trace" in e
        })

    def total_dur(self, cat: str, name: Optional[str] = None) -> float:
        """Σ wall-clock duration of matching spans (e.g. total policy time)."""
        return sum(
            e.get("dur", 0.0)
            for e in self.events
            if e["cat"] == cat and (name is None or e["name"] == name)
        )


NULL_TRACER = Tracer(enabled=False)


@contextlib.contextmanager
def device_profile(logdir: Optional[str]):
    """Capture a ``jax.profiler`` device trace around the enclosed block.

    The resulting TensorBoard-loadable trace shows XLA/Pallas kernel
    timings on the accelerator — the microscope for the decision-kernel
    hot path.  No-op when ``logdir`` is falsy (so call sites can thread an
    optional CLI flag straight through).
    """
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
