"""Stdlib HTTP scrape endpoint for the unified metrics registry.

``serve --metrics-port N`` (round 15 satellite): a Prometheus scraper
pointed at ``http://127.0.0.1:N/metrics`` sees the live service's text
exposition mid-soak, instead of waiting for the post-run
``--metrics-out`` file.  Pure stdlib (``http.server``) — the serve
layer must not grow a web-framework dependency for one GET route.

Thread safety: the handler calls the injected ``render`` callable on
the HTTP server's worker thread while the serve driver's session /
producer / autoscaler threads are live.  The contract is that
``render`` returns a *snapshot* string assembled under the owners'
locks (``ServeDriver.publish_metrics`` snapshots the pool under its
cv; ``MetricsRegistry.to_prometheus`` runs under the registry lock) —
the scrape-during-soak test in ``tests/test_profiler.py`` hammers the
endpoint mid-run to pin this.

A render failure answers 500 with the error text instead of killing
the worker thread: a scrape must never be able to take the service
down.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

__all__ = ["MetricsHTTPServer"]


class MetricsHTTPServer:
    """Background ``/metrics`` (Prometheus text, version 0.0.4) and
    ``/metrics.json`` (unified JSON snapshot) endpoint.

    ``port=0`` binds an ephemeral port (tests); read :attr:`port` after
    :meth:`start`.  ``render`` returns the exposition text;
    ``render_json`` (optional) the JSON document — omitted, the JSON
    route answers 404.
    """

    def __init__(
        self,
        render: Callable[[], str],
        render_json: Optional[Callable[[], dict]] = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self._render = render
        self._render_json = render_json
        self._host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def start(self) -> int:
        render, render_json = self._render, self._render_json

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *_args):  # quiet: no per-scrape stderr
                pass

            def _answer(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?")[0]
                try:
                    if path in ("/metrics", "/"):
                        body = render().encode()
                        self._answer(
                            200, body,
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/metrics.json" and render_json:
                        body = json.dumps(render_json()).encode()
                        self._answer(200, body, "application/json")
                    else:
                        self._answer(404, b"not found\n", "text/plain")
                except Exception as exc:  # noqa: BLE001 — scrape-safe
                    self._answer(
                        500, f"render failed: {exc}\n".encode(),
                        "text/plain",
                    )

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="metrics-http", daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
