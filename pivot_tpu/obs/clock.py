"""The one injected wall-clock source of the observability plane.

Before round 14, every telemetry object kept a *private* wall epoch —
``Meter._wall_start`` and ``SloMeter._wall_start`` each called
``time.perf_counter()`` at construction, so two snapshots taken from
the same run at the same instant reported *different* elapsed wall
times (they disagreed by however long the constructors were apart).
Worse, wall reads were scattered across modules, which is exactly how
a wall read eventually creeps into a determinism-scoped module (the
graftcheck ``determinism`` pass bans ``time.*`` in ``des/``, ``sched/``,
``ops/``, the fault/market engines).

:class:`ObsClock` fixes both: it owns ONE epoch, and every consumer —
meters, tracers, report renderers — is handed the clock instead of
calling ``time`` itself.  Snapshots from objects sharing a clock agree
exactly on elapsed wall time, and the wall capture has one auditable
home inside ``pivot_tpu/obs`` (the ``obs-boundary`` pass pins that the
determinism-scoped modules never import this module — hooks there emit
sim-time payloads and the obs layer stamps the wall side).
"""

from __future__ import annotations

import time

__all__ = ["ObsClock"]


class ObsClock:
    """A monotonic wall clock with a fixed epoch.

    ``elapsed()`` is seconds since the clock's construction — hand the
    same instance to a run's :class:`~pivot_tpu.infra.meter.Meter` and
    :class:`~pivot_tpu.infra.meter.SloMeter` and their ``wall_clock``
    snapshots agree to the read instant.  ``now()`` is the raw
    monotonic reading (for interval measurement where the epoch is
    irrelevant, e.g. span durations).
    """

    __slots__ = ("_epoch",)

    def __init__(self):
        self._epoch = time.perf_counter()

    def now(self) -> float:
        """Raw monotonic seconds (epoch-free; subtract two reads)."""
        return time.perf_counter()

    def elapsed(self) -> float:
        """Seconds since this clock's construction."""
        return time.perf_counter() - self._epoch
