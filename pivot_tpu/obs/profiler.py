"""Sampled device-dispatch profiler (round 15, ISSUE 13 pillar 1).

PR 12 gave the system a causal timeline and a metrics registry; this
module answers the question neither could: *how long do the device
dispatches actually take, and is that what the analytic device model
predicts?*  :class:`DispatchProfiler` brackets kernel dispatches at the
three boundaries where host control crosses into XLA —
``sched/tpu.py`` ``_call_kernel`` (per-tick kernels), ``place_span``
(fused spans, through the same ``_call_kernel`` rung), and
``DispatchBatcher._flush`` (coalesced serve/grid dispatches) — and
times a deterministic 1-in-N sample of them to completion with
``jax.block_until_ready``.

Design pins (the ``profiler-boundary`` graftcheck pass enforces the
structural ones):

  * **wall capture lives HERE** — the boundary hooks hand the profiler
    a thunk; the profiler owns every ``time.perf_counter`` read, so the
    determinism-scoped modules (``sched/``, ``ops/``) stay clock-free
    exactly as the ``obs-boundary``/``determinism`` passes require;
  * **outside the jitted bodies** — the profiler wraps the *dispatch*,
    never instruments inside a jitted/Pallas body (a hook there would
    trace once and lie); the hostsync-discovered hot bodies may not
    call it;
  * **zero-cost off, bounded on, placements bit-identical either way**
    — ``profile()`` short-circuits on ``enabled`` before touching a
    clock or lock; sampling only *times* the thunk (forcing completion
    of a result the caller was about to fetch anyway) and never touches
    operands, so the ``profiler_overhead`` bench row can hold the
    traced run to the same bit-parity bar as ``obs_overhead``;
  * **deterministic cadence** — whether call #k of a family is sampled
    is a pure function of (seed, family, k): a per-family phase derived
    from ``crc32(seed:family)`` offsets a call counter, so two profiled
    replays of a seeded run sample the identical dispatches
    (``tests/test_profiler.py`` pins replayability).

Each sampled dispatch publishes into three sinks:

  * per-family streaming stats (count/sum/min/max + a bounded duration
    ring for quantiles), exported to the unified
    :class:`~pivot_tpu.obs.registry.MetricsRegistry` via
    :meth:`publish_metrics` (``pivot_dispatch_*`` families);
  * a ``device``-lane Perfetto span on the attached tracer whose args
    carry the dispatch shape (tasks/hosts/span-K/group), the backend,
    and the analytic prediction — ``tools/obs_report.py``'s perf
    section joins these without importing jax;
  * a measured-vs-predicted roofline ratio against the analytic
    ``infra/roofline.py`` model (dispatch floor + max(flops/peak,
    bytes/bw)) — the per-family median ratio is the "device model is
    lying" drift signal that stalled the ROADMAP-1 hardware recapture.
"""

from __future__ import annotations

import threading
import time
import zlib
from statistics import median as _median
from typing import Any, Callable, Dict, List, Optional

__all__ = ["DispatchProfiler", "family_of", "predicted_seconds"]

#: Kernel-family → analytic work-model kind (``roofline.placement_cost``).
#: ``auto``-phase2 two-phase kernels resolve to the slim early-exit pass
#: on the CPU backend and the scan form elsewhere (``ops/kernels.py``),
#: which is exactly how ``bench.py`` annotates its rows.
_TWO_PHASE = {
    "opportunistic", "first_fit", "best_fit", "cost_aware",
}
_SCAN_ONLY = {
    "opportunistic_ref", "first_fit_ref", "best_fit_ref",
    "cost_aware_ref", "fused_tick_run", "resident_span_run",
}
_PALLAS = {"cost_aware_pallas", "cost_aware_pallas_batched"}


def family_of(kernel: Any) -> str:
    """Stable family name for a dispatched kernel callable: the wrapped
    implementation's ``__name__`` with the ``_impl``/``_kernel``
    plumbing suffixes stripped (``first_fit_impl`` → ``first_fit``,
    ``cost_aware_kernel_ref`` → ``cost_aware_ref``)."""
    name = getattr(kernel, "__name__", None) or type(kernel).__name__
    for suffix, repl in (
        ("_kernel_ref", "_ref"), ("_impl", ""), ("_kernel", ""),
    ):
        if name.endswith(suffix):
            return name[: -len(suffix)] + repl
    return name


def _model_kind(family: str, backend: str) -> Optional[str]:
    if family in _PALLAS:
        return "pallas_rb"
    if family in _SCAN_ONLY:
        return "scan"
    if family in _TWO_PHASE:
        return "slim" if backend == "cpu" else "scan"
    return None


def predicted_seconds(
    family: str,
    shape: Dict[str, int],
    backend: str,
    floor_s: float,
    peaks: Optional[Dict[str, float]] = None,
) -> Optional[float]:
    """Analytic wall prediction for one dispatch: the probed per-call
    dispatch floor plus the roofline time bound of the estimated work
    (``max(flops/peak_flops, bytes/peak_bw)``).  A trend-level model —
    its job is the ×-level drift verdict, not microsecond accuracy.
    None when the family has no work model (the ratio is then omitted
    rather than fabricated)."""
    kind = _model_kind(family, backend)
    h = int(shape.get("h", 0))
    t = int(shape.get("t", shape.get("b", 0)))
    if kind is None or h <= 0 or t <= 0:
        return None
    from pivot_tpu.infra import roofline

    k = int(shape.get("k", 1)) or 1
    r = int(shape.get("g", 1)) or 1
    peaks = peaks or roofline.backend_peaks(backend)
    cost = roofline.placement_cost(kind, t * k, h, R=r, dtype_bytes=4)
    work_s = max(
        cost["flops"] / (peaks["flops_peak_gflops"] * 1e9),
        cost["bytes"] / (peaks["bw_gbps"] * 1e9),
    )
    return floor_s + work_s


class _FamilyStats:
    """Streaming per-family latency stats + a bounded duration ring."""

    __slots__ = ("calls", "sampled", "total_s", "min_s", "max_s",
                 "durs", "ratios", "h2d_bytes")

    _RING = 1024  # bounded memory for quantiles on long soaks

    def __init__(self):
        self.calls = 0
        self.sampled = 0
        self.total_s = 0.0
        self.min_s = float("inf")
        self.max_s = 0.0
        self.durs: List[float] = []
        self.ratios: List[float] = []
        # Host→device bytes freshly staged for the dispatch, as declared
        # by the boundary hook (round 20, the resident-carry ISSUE):
        # accumulated for EVERY call — transfer volume is an exact
        # caller-side count, not a sampled wall measurement — so the
        # resident-vs-re-staged comparison is census-grade.
        self.h2d_bytes = 0

    def record(self, dur: float, ratio: Optional[float]) -> None:
        self.sampled += 1
        self.total_s += dur
        self.min_s = min(self.min_s, dur)
        self.max_s = max(self.max_s, dur)
        if len(self.durs) < self._RING:
            self.durs.append(dur)
        else:
            self.durs[self.sampled % self._RING] = dur
        if ratio is not None:
            if len(self.ratios) < self._RING:
                self.ratios.append(ratio)
            else:
                self.ratios[self.sampled % self._RING] = ratio


#: Per-process dispatch-floor cache, keyed by backend name.  The floor
#: is a property of the process's backend link, not of any one profiler
#: instance — and re-probing per instance would pay a fresh XLA compile
#: for the probe lambda each time (a new function object defeats jax's
#: jit cache), which alone would blow the profiler_overhead gate.
_FLOOR_CACHE: Dict[str, float] = {}
_FLOOR_LOCK = threading.Lock()


def _probe_floor(backend: str) -> float:
    """Fixed per-call dispatch latency: trivial jit round trip, best of
    3 (the ``sched.tpu._probe_device_floor`` protocol), probed once per
    (process, backend)."""
    with _FLOOR_LOCK:
        cached = _FLOOR_CACHE.get(backend)
        if cached is not None:
            return cached
        import jax
        import numpy as np

        f = jax.jit(lambda x: x + 1.0)
        x = np.zeros((8,), np.float32)
        np.asarray(f(x))  # compile outside the timed reps
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(x))
            best = min(best, time.perf_counter() - t0)
        _FLOOR_CACHE[backend] = best
        return best


def _quantile(vals: List[float], q: float) -> float:
    s = sorted(vals)
    return s[min(int(q * len(s)), len(s) - 1)]


class DispatchProfiler:
    """Deterministically sampled, completion-forced dispatch timer.

    ``sample_every`` is the cadence N (1 = every dispatch; the default
    16 keeps the enabled cost inside the ``profiler_overhead`` bench
    gate); ``seed`` fixes the per-family sampling phase; ``tracer``
    (optional) receives one ``device``-lane span per sampled dispatch;
    ``registry`` (optional) is the default :meth:`publish_metrics`
    sink.  Thread-safe: serve sessions and the batcher coordinator
    share one profiler (counter advance + stats append run under one
    lock; the timed thunk itself does not).
    """

    def __init__(
        self,
        sample_every: int = 16,
        seed: int = 0,
        tracer=None,
        registry=None,
        enabled: bool = True,
    ):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.enabled = enabled
        self.sample_every = int(sample_every)
        self.seed = int(seed)
        self.tracer = tracer
        self.registry = registry
        self._lock = threading.Lock()
        self._stats: Dict[str, _FamilyStats] = {}
        self._phases: Dict[str, int] = {}
        self._backend: Optional[str] = None
        self._floor_s: Optional[float] = None
        self._peaks: Optional[Dict[str, float]] = None

    # -- deterministic cadence -------------------------------------------
    def _phase(self, family: str) -> int:
        phase = self._phases.get(family)
        if phase is None:
            phase = zlib.crc32(
                f"{self.seed}:{family}".encode()
            ) % self.sample_every
            self._phases[family] = phase
        return phase

    def _tick(self, family: str) -> bool:
        """Advance ``family``'s call counter; True iff this call is the
        deterministic 1-in-N sample (call under the lock)."""
        st = self._stats.get(family)
        if st is None:
            st = self._stats[family] = _FamilyStats()
        n = st.calls
        st.calls += 1
        return (n + self._phase(family)) % self.sample_every == 0

    def sampled_indices(self, family: str, n_calls: int) -> List[int]:
        """Which of ``n_calls`` consecutive calls WOULD be sampled — the
        pure cadence function, exposed so tests can pin replayability
        without driving real dispatches."""
        phase = zlib.crc32(
            f"{self.seed}:{family}".encode()
        ) % self.sample_every
        return [
            i for i in range(n_calls)
            if (i + phase) % self.sample_every == 0
        ]

    # -- the boundary hook ------------------------------------------------
    def _lazy_backend(self) -> str:
        if self._backend is None:
            import jax

            self._backend = jax.default_backend()
        return self._backend

    def _lazy_floor(self) -> float:
        """The fixed per-call dispatch latency — the intercept of the
        analytic prediction.  Lazy (building a profiler never touches
        the backend) and process-cached (:func:`_probe_floor`)."""
        if self._floor_s is None:
            self._floor_s = _probe_floor(self._lazy_backend())
        return self._floor_s

    def profile(
        self,
        family: str,
        fn: Callable[[], Any],
        shape: Optional[Dict[str, int]] = None,
        flush: bool = False,
        h2d_bytes: int = 0,
    ):
        """Run one dispatch thunk, timing it to completion when this
        call lands on the family's sampling cadence.

        Unsampled calls still advance the counter (the cadence is over
        *calls*, so it is replayable) but pay only a dict lookup and an
        increment.  Sampled calls force completion with
        ``jax.block_until_ready`` — legal at every registered boundary
        because the caller is about to fetch (or hand off) the result
        anyway — and record a ``device`` span whose args carry the
        shape, backend, and analytic prediction.  ``flush=True`` marks
        spans recorded inside a batcher flush (``in_flush``), which
        ``obs_report --check`` requires to nest inside their
        ``dispatch/flush`` parent span.  ``h2d_bytes`` is the caller's
        count of operand bytes freshly staged host→device for THIS
        dispatch (cached device buffers excluded) — accumulated on
        every call, sampled or not, so transfer totals stay exact.
        """
        if not self.enabled:
            return fn()
        with self._lock:
            sampled = self._tick(family)
            if h2d_bytes:
                self._stats[family].h2d_bytes += int(h2d_bytes)
        if not sampled:
            return fn()
        import jax

        backend = self._lazy_backend()
        floor_s = self._lazy_floor()
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        dur = time.perf_counter() - t0
        shape = shape or {}
        pred = predicted_seconds(
            family, shape, backend, floor_s, self._peaks
        )
        ratio = dur / pred if pred and pred > 0 else None
        with self._lock:
            st = self._stats[family]
            # The family's FIRST sample almost always carries XLA
            # compile time (the same poisoning the adaptive router's
            # warm-bucket guard exists for) — keep its duration in the
            # census but exclude it from the model-ratio stats, or the
            # drift verdict would fire on every fresh process.
            cold = st.sampled == 0
            st.record(dur, None if cold else ratio)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            args: Dict[str, Any] = {"backend": backend}
            args.update({k: int(v) for k, v in shape.items()})
            if pred is not None:
                args["pred_us"] = round(pred * 1e6, 3)
                if not cold:
                    args["model_ratio"] = round(ratio, 3)
            if cold:
                args["cold"] = True  # first sample: includes compile
            if flush:
                args["in_flush"] = True
            if h2d_bytes:
                args["h2d_bytes"] = int(h2d_bytes)
            tracer.record_span("device", family, dur, **args)
        return out

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        """Per-family latency census + model-ratio medians (the
        machine-readable view ``bench.py``'s ``profiler_overhead`` row
        and the serve report embed)."""
        with self._lock:
            fams = {}
            for family in sorted(self._stats):
                st = self._stats[family]
                row = {
                    "calls": st.calls,
                    "sampled": st.sampled,
                }
                if st.h2d_bytes:
                    row["h2d_bytes_total"] = st.h2d_bytes
                    row["h2d_bytes_per_call"] = round(
                        st.h2d_bytes / st.calls, 1
                    )
                if st.sampled:
                    row.update(
                        total_ms=round(st.total_s * 1e3, 3),
                        min_us=round(st.min_s * 1e6, 3),
                        max_us=round(st.max_s * 1e6, 3),
                        p50_us=round(_quantile(st.durs, 0.5) * 1e6, 3),
                        p95_us=round(_quantile(st.durs, 0.95) * 1e6, 3),
                    )
                if st.ratios:
                    row["model_ratio_p50"] = round(
                        _median(st.ratios), 3
                    )
                fams[family] = row
            return {
                "sample_every": self.sample_every,
                "seed": self.seed,
                "backend": self._backend,
                "dispatch_floor_us": (
                    round(self._floor_s * 1e6, 3)
                    if self._floor_s is not None else None
                ),
                "families": fams,
            }

    def publish_metrics(self, registry=None) -> None:
        """Publish the per-family census into the unified registry
        (publish-style: idempotent on republish).  Families:
        ``pivot_dispatch_calls_total``/``..._sampled_total`` counters,
        ``pivot_dispatch_latency_seconds`` summaries (p50/p95), and the
        ``pivot_dispatch_model_ratio`` gauge — the scrapeable form of
        the drift signal."""
        registry = registry or self.registry
        if registry is None:
            return
        backend = self._backend or "unknown"
        registry.counter(
            "pivot_dispatch_calls_total",
            "kernel dispatches crossing a profiled boundary",
            labelnames=("family", "backend"),
        )
        registry.counter(
            "pivot_dispatch_sampled_total",
            "dispatches timed to completion by the sampler",
            labelnames=("family", "backend"),
        )
        registry.summary(
            "pivot_dispatch_latency_seconds",
            "sampled dispatch wall latency (block_until_ready-forced)",
            labelnames=("family", "backend"),
        )
        registry.gauge(
            "pivot_dispatch_model_ratio",
            "median measured/predicted dispatch wall ratio vs the "
            "analytic roofline model (>2 or <0.5 = the device model "
            "is lying)",
            labelnames=("family", "backend"),
        )
        registry.counter(
            "pivot_dispatch_h2d_bytes_total",
            "operand bytes freshly staged host->device at profiled "
            "dispatch boundaries (cached device buffers excluded)",
            labelnames=("family", "backend"),
        )
        with self._lock:
            # Full snapshot under the lock: a --metrics-port scrape runs
            # concurrently with recording threads, and reading the
            # mutable stats fields (or sorting a ring being overwritten)
            # outside it would export torn count/total/quantile pairs.
            items = [
                (
                    family, st.calls, st.sampled, st.total_s,
                    list(st.durs), list(st.ratios), st.h2d_bytes,
                )
                for family, st in sorted(self._stats.items())
            ]
        for family, calls, sampled, total_s, durs, ratios, h2d in items:
            labels = dict(family=family, backend=backend)
            registry.set("pivot_dispatch_calls_total", calls, **labels)
            registry.set(
                "pivot_dispatch_sampled_total", sampled, **labels
            )
            if h2d:
                registry.set(
                    "pivot_dispatch_h2d_bytes_total", h2d, **labels
                )
            if sampled:
                registry.observe_summary(
                    "pivot_dispatch_latency_seconds",
                    count=sampled,
                    total=total_s,
                    quantiles={
                        0.5: _quantile(durs, 0.5),
                        0.95: _quantile(durs, 0.95),
                    },
                    **labels,
                )
            if ratios:
                registry.set(
                    "pivot_dispatch_model_ratio",
                    round(_median(ratios), 6), **labels,
                )
