"""XLA cost attribution over the jitmap entry-point registry (pillar 2).

``analysis/jitmap.py`` already knows every place a Python function
crosses into XLA.  This module closes the measurement loop: for each
registered entry point it either *measures* the compiled program —
``jitfn.lower(args).compile().cost_analysis()`` FLOPs / bytes-accessed
at a small canonical shape — or carries an explicit *flag* explaining
why that site has no standalone attribution (a sharded twin of a
measured kernel, a TPU-only Mosaic program, a latency probe, an
ensemble rollout attributed by its own bench row).

**Register-or-flag** (the jitcheck convention): :func:`coverage_problems`
diffs the live jitmap discovery against :data:`ENTRY_POINTS` — a NEW
jit site anywhere in the package fails the bench ``cost_attribution``
gate (and ``tests/test_profiler.py``) until it gets a manifest entry,
and a manifest entry whose site vanished is equally a finding.  No jit
program can silently have *no* cost story.

Measured rows are joined against the analytic ``infra/roofline.py``
work model at the same shape: ``flops_vs_model`` / ``bytes_vs_model``
are the measured/analytic ratios.  They are recorded, not gated — the
analytic model counts compares/selects as vector-issue-slot work while
XLA's cost analysis counts arithmetic only, so a constant-factor gap is
expected; what the ratio buys is *trend* comparability across forms of
the same kernel (and a drift alarm when a rewrite silently changes a
program's work class).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "ENTRY_POINTS",
    "coverage_problems",
    "cost_attribution",
]

#: Canonical measurement shape: small enough that the whole manifest
#: compiles in seconds on CPU, large enough that the [T, H] decision
#: space dominates the program.
_T, _H = 32, 16


def _operands(T: int = _T, H: int = _H):
    import numpy as np

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    avail = jnp.asarray(
        rng.uniform(2.0, 8.0, (H, 4)).astype(np.float32)
    )
    dem = jnp.asarray(rng.uniform(0.1, 1.0, (T, 4)).astype(np.float32))
    valid = jnp.ones(T, dtype=bool)
    u = jnp.asarray(rng.uniform(size=T).astype(np.float32))
    ng = jnp.asarray((np.arange(T) % 4 == 0))
    az = jnp.zeros(T, dtype=jnp.int32)
    cost = jnp.ones((2, 2), dtype=jnp.float32)
    bw = jnp.ones((2, 2), dtype=jnp.float32)
    hz = jnp.zeros(H, dtype=jnp.int32)
    counts = jnp.zeros(H, dtype=jnp.int32)
    totals = jnp.asarray(np.asarray(avail).sum(axis=0))
    return dict(
        avail=avail, dem=dem, valid=valid, u=u, ng=ng, az=az,
        cost=cost, bw=bw, hz=hz, counts=counts, totals=totals,
    )


def _b_opportunistic_ref(o):
    from pivot_tpu.ops.kernels import opportunistic_kernel_ref

    return opportunistic_kernel_ref, (
        o["avail"], o["dem"], o["valid"], o["u"],
    ), {}, "scan"


def _b_first_fit_ref(o):
    from pivot_tpu.ops.kernels import first_fit_kernel_ref

    return first_fit_kernel_ref, (
        o["avail"], o["dem"], o["valid"],
    ), dict(strict=False), "scan"


def _b_best_fit_ref(o):
    from pivot_tpu.ops.kernels import best_fit_kernel_ref

    return best_fit_kernel_ref, (
        o["avail"], o["dem"], o["valid"],
    ), {}, "scan"


def _b_cost_aware_ref(o):
    from pivot_tpu.ops.kernels import cost_aware_kernel_ref

    return cost_aware_kernel_ref, (
        o["avail"], o["dem"], o["valid"], o["ng"], o["az"],
        o["cost"], o["bw"], o["hz"], o["counts"],
    ), dict(bin_pack="first-fit", sort_hosts=True, host_decay=False), "scan"


def _two_phase_kind(backend: str) -> str:
    return "slim" if backend == "cpu" else "scan"


def _b_opportunistic(o):
    from pivot_tpu.ops.kernels import opportunistic_kernel

    return opportunistic_kernel, (
        o["avail"], o["dem"], o["valid"], o["u"],
    ), dict(phase2="auto"), None


def _b_first_fit(o):
    from pivot_tpu.ops.kernels import first_fit_kernel

    return first_fit_kernel, (
        o["avail"], o["dem"], o["valid"],
    ), dict(strict=False, totals=o["totals"], phase2="auto"), None


def _b_best_fit(o):
    from pivot_tpu.ops.kernels import best_fit_kernel

    return best_fit_kernel, (
        o["avail"], o["dem"], o["valid"],
    ), dict(totals=o["totals"], phase2="auto"), None


def _b_cost_aware(o):
    from pivot_tpu.ops.kernels import cost_aware_kernel

    return cost_aware_kernel, (
        o["avail"], o["dem"], o["valid"], o["ng"], o["az"],
        o["cost"], o["bw"], o["hz"], o["counts"],
    ), dict(
        bin_pack="first-fit", sort_hosts=True, host_decay=False,
        totals=o["totals"], phase2="auto",
    ), None


def _b_fused_tick_run(o):
    import jax.numpy as jnp
    import numpy as np

    from pivot_tpu.ops.tickloop import _fused_tick_run

    K = 4
    arrive = jnp.asarray(
        (np.arange(o["dem"].shape[0]) % K).astype(np.int32)
    )
    args = (
        o["avail"], o["dem"], arrive, jnp.int32(K),
        None, None, None, None, None, None, None, None, None,
        None, None, None, None, None,
    )
    return _fused_tick_run, args, dict(
        policy="first-fit", n_ticks=K, strict=False, decreasing=False,
        bin_pack="first-fit", sort_tasks=False, sort_hosts=True,
        host_decay=False, phase2="auto",
    ), "scan"


def _b_resident_span_run(o):
    import jax.numpy as jnp
    import numpy as np

    from pivot_tpu.ops.tickloop import ResidentCarry, _resident_span_run

    K = 4
    H = o["avail"].shape[0]
    carry = ResidentCarry(
        o["avail"],
        jnp.zeros((H,), jnp.int32),
        jnp.ones((H,), bool),
    )
    arrive = jnp.asarray(
        (np.arange(o["dem"].shape[0]) % K).astype(np.int32)
    )
    args = (
        carry, None, None, None, None, o["dem"], arrive, jnp.int32(K),
        None, None, None, None, None, None, None, None, None, None,
        None, None, None,
    )
    return _resident_span_run, args, dict(
        policy="first-fit", n_ticks=K, strict=False, decreasing=False,
        bin_pack="first-fit", sort_tasks=False, sort_hosts=True,
        host_decay=False, phase2="auto",
    ), "scan"


#: Builder registry: key → callable(operands) returning ``(jit entry
#: point, positional args, static kwargs, analytic kind-or-None)``
#: (``None`` = resolve the two-phase kind per backend).
_BUILDERS: Dict[str, Callable] = {
    "opportunistic_ref": _b_opportunistic_ref,
    "first_fit_ref": _b_first_fit_ref,
    "best_fit_ref": _b_best_fit_ref,
    "cost_aware_ref": _b_cost_aware_ref,
    "opportunistic": _b_opportunistic,
    "first_fit": _b_first_fit,
    "best_fit": _b_best_fit,
    "cost_aware": _b_cost_aware,
    "fused_tick_run": _b_fused_tick_run,
    "resident_span_run": _b_resident_span_run,
}


def measure(key: str) -> Tuple[str, str]:
    assert key in _BUILDERS, key
    return ("measure", key)


def flag(reason: str) -> Tuple[str, str]:
    return ("flag", reason)


#: The manifest: every jitmap-discovered (path, entry-point name) must
#: appear here — measured, or flagged with the reason it has no
#: standalone program to attribute.  ``coverage_problems`` enforces
#: both directions.
ENTRY_POINTS: Dict[Tuple[str, str], Tuple[str, str]] = {
    # -- the placement-kernel families: measured directly ----------------
    ("pivot_tpu/ops/kernels.py", "opportunistic_kernel_ref"):
        measure("opportunistic_ref"),
    ("pivot_tpu/ops/kernels.py", "first_fit_kernel_ref"):
        measure("first_fit_ref"),
    ("pivot_tpu/ops/kernels.py", "best_fit_kernel_ref"):
        measure("best_fit_ref"),
    ("pivot_tpu/ops/kernels.py", "cost_aware_kernel_ref"):
        measure("cost_aware_ref"),
    ("pivot_tpu/ops/kernels.py", "opportunistic_kernel"):
        measure("opportunistic"),
    ("pivot_tpu/ops/kernels.py", "first_fit_kernel"):
        measure("first_fit"),
    ("pivot_tpu/ops/kernels.py", "best_fit_kernel"):
        measure("best_fit"),
    ("pivot_tpu/ops/kernels.py", "cost_aware_kernel"):
        measure("cost_aware"),
    ("pivot_tpu/ops/tickloop.py", "_fused_tick_run"):
        measure("fused_tick_run"),
    # -- round-20 resident span tier (device-persistent donated carry) ---
    ("pivot_tpu/ops/tickloop.py", "_resident_span_run"):
        measure("resident_span_run"),
    ("pivot_tpu/ops/tickloop.py", "_resident_carry_init"): flag(
        "O(H) carry staging, one call per scheduler bind (or geometry "
        "change) — negligible next to the span driver it feeds"
    ),
    ("pivot_tpu/ops/tickloop.py", "_resident_carry_clone"): flag(
        "O(H) device-side checkpoint copy taken before each spliceable "
        "span — no host traffic; dwarfed by the span program it brackets"
    ),
    # -- sharded twins: same program family, host-sharded over a mesh ----
    ("pivot_tpu/ops/shard.py", "_opportunistic_sharded_fn"): flag(
        "host-sharded twin of opportunistic_kernel (bit-identical by "
        "tests/test_shard.py); per-shard work attributed by the "
        "single-device row, collectives by the shard_place bench row"
    ),
    ("pivot_tpu/ops/shard.py", "_first_fit_sharded_fn"): flag(
        "host-sharded twin of first_fit_kernel — see shard_place row"
    ),
    ("pivot_tpu/ops/shard.py", "_best_fit_sharded_fn"): flag(
        "host-sharded twin of best_fit_kernel — see shard_place row"
    ),
    ("pivot_tpu/ops/shard.py", "_cost_aware_sharded_fn"): flag(
        "host-sharded twin of cost_aware_kernel — see shard_place row"
    ),
    ("pivot_tpu/ops/shard.py", "_sharded_span_fn"): flag(
        "host-sharded twin of _fused_tick_run — see shard_place row"
    ),
    # -- round-17 [G]-batched 2-D forms (batching × sharding composed):
    # each row is the 1-D sharded program under vmap (bit-identical by
    # tests/test_shard.py's 2-D suite), so per-row work is attributed
    # by the single-device rows and the composed throughput by the
    # serve_sharded bench row's mesh_2d arm.
    ("pivot_tpu/ops/shard.py", "_opportunistic_sharded_batched_fn"): flag(
        "[G]-batched 2-D form of _opportunistic_sharded_fn — see the "
        "serve_sharded bench row"
    ),
    ("pivot_tpu/ops/shard.py", "_first_fit_sharded_batched_fn"): flag(
        "[G]-batched 2-D form of _first_fit_sharded_fn — see "
        "serve_sharded row"
    ),
    ("pivot_tpu/ops/shard.py", "_best_fit_sharded_batched_fn"): flag(
        "[G]-batched 2-D form of _best_fit_sharded_fn — see "
        "serve_sharded row"
    ),
    ("pivot_tpu/ops/shard.py", "_cost_aware_sharded_batched_fn"): flag(
        "[G]-batched 2-D form of _cost_aware_sharded_fn — see "
        "serve_sharded row"
    ),
    ("pivot_tpu/ops/shard.py", "_sharded_span_batched_fn"): flag(
        "[G]-batched 2-D form of _sharded_span_fn — see serve_sharded "
        "row"
    ),
    ("pivot_tpu/ops/shard.py", "_sharded_resident_span_fn"): flag(
        "host-sharded twin of _resident_span_run (bit-identical by "
        "tests/test_resident.py) — per-shard work attributed by the "
        "single-device resident row, throughput by serve_resident"
    ),
    ("pivot_tpu/ops/shard.py", "_sharded_resident_init_fn"): flag(
        "sharded carry staging, one call per bind — same story as "
        "_resident_carry_init (see the resident_span_run measured row)"
    ),
    # -- Pallas: Mosaic programs, only meaningful on the TPU backend -----
    ("pivot_tpu/ops/pallas_kernels.py", "cost_aware_pallas"): flag(
        "TPU-only Mosaic kernel; XLA cost_analysis does not see inside "
        "a pallas_call — VMEM work is accounted by the static "
        "pallas-budget pass and the hardware bench rows"
    ),
    ("pivot_tpu/ops/pallas_kernels.py", "cost_aware_pallas_batched"):
        flag(
            "TPU-only replica-batched Mosaic kernel — same accounting "
            "as cost_aware_pallas (pallas-budget pass + BENCH_TPU rows)"
        ),
    # -- routing / batching plumbing -------------------------------------
    ("pivot_tpu/sched/tpu.py", "f"): flag(
        "trivial x+1 latency probe (_probe_device_floor) — its cost IS "
        "the dispatch floor the profiler's model uses as intercept"
    ),
    ("pivot_tpu/obs/profiler.py", "f"): flag(
        "the profiler's own x+1 floor probe (DispatchProfiler."
        "_lazy_floor) — same trivial program as the sched.tpu probe"
    ),
    ("pivot_tpu/sched/batch.py", "_batched_fn"): flag(
        "factory: vmap of the wrapped placement kernel over the [G] "
        "run axis — work is G x the wrapped kernel's measured row "
        "(grid_batched bench row carries the measured amortization)"
    ),
    # -- ensemble rollout programs: attributed by their own bench rows ---
    ("pivot_tpu/parallel/ensemble/__init__.py", "_rollout_states"): flag(
        "full Monte-Carlo rollout program — attributed by bench.py's "
        "ensemble_roofline / ensemble_saturated rows at the real shape"
    ),
    ("pivot_tpu/parallel/ensemble/__init__.py", "_sharded_rollout_fn"):
        flag("host-sharded rollout twin — see ensemble rows"),
    ("pivot_tpu/parallel/ensemble/__init__.py", "shard_sweep"): flag(
        "sharded sweep driver over the rollout program — see ensemble "
        "rows"
    ),
    ("pivot_tpu/parallel/ensemble/checkpoint.py", "_segment_step"): flag(
        "segment-granular slice of the rollout program (double-buffer "
        "pipeline) — same program family as _rollout_states"
    ),
    ("pivot_tpu/parallel/ensemble/checkpoint.py", "_segment_step_carry"):
        flag("device-resident-carry variant of _segment_step"),
    ("pivot_tpu/parallel/ensemble/sweeps.py", "_row_segment_step"): flag(
        "per-row sweep variant of _segment_step (vmapped arm axis)"
    ),
    ("pivot_tpu/parallel/ensemble/sweeps.py", "_row_segment_step_carry"):
        flag("device-resident-carry variant of _row_segment_step"),
    ("pivot_tpu/parallel/ensemble/bill.py", "_finalize_batch"): flag(
        "O(R) billing reduction over rollout outputs — negligible next "
        "to the rollout program it post-processes"
    ),
    # -- policy-search fitness programs (round 16) -----------------------
    ("pivot_tpu/search/fitness.py", "_draw_rows"): flag(
        "tiny per-generation Monte-Carlo draw program ([B x R, T] "
        "tiled uniforms) — negligible next to the population rollout "
        "it feeds; kept unsharded by design (threefry lowering)"
    ),
    ("pivot_tpu/search/fitness.py", "_fitness_rows"): flag(
        "population fitness program: B x R rows of the rollout-segment "
        "family — same program family as _rollout_states/"
        "_row_segment_step; attributed at scale by bench.py's "
        "policy_search row (generations/s, rollouts/s)"
    ),
    ("pivot_tpu/search/fitness.py", "_sharded_fitness_fn"): flag(
        "row-sharded twin of _fitness_rows (NamedSharding over the "
        "replica mesh; bit-identical scores by tests/test_search.py) — "
        "see the policy_search bench row"
    ),
}


def coverage_problems() -> List[str]:
    """Register-or-flag diff of the live jitmap discovery against
    :data:`ENTRY_POINTS` (empty = every entry point has a cost story).
    Pure AST work — no jax import."""
    from pivot_tpu.analysis import _Cache, repo_root
    from pivot_tpu.analysis.jitmap import collect_sites

    cache = _Cache(repo_root())
    sites, findings, _scanned = collect_sites(cache)
    problems = [str(f) for f in findings]
    discovered = {
        (path, s.name) for path, ss in sites.items() for s in ss
    }
    for key in sorted(discovered - set(ENTRY_POINTS)):
        problems.append(
            f"jit entry point {key[1]} ({key[0]}) has no cost-"
            "attribution entry — add it to pivot_tpu/obs/costattr.py "
            "ENTRY_POINTS (measure or flag with a reason)"
        )
    for key in sorted(set(ENTRY_POINTS) - discovered):
        problems.append(
            f"stale cost-attribution entry {key[1]} ({key[0]}): no such "
            "jit site — renamed/deleted? update ENTRY_POINTS"
        )
    return problems


def _extract(cost) -> Dict[str, float]:
    """Normalize ``cost_analysis()`` output (dict, or list of dicts on
    this jax) to {flops, bytes}."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    cost = cost or {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes": float(cost.get("bytes accessed", 0.0)),
    }


def cost_attribution(
    T: int = _T, H: int = _H, include_flags: bool = True
) -> dict:
    """Measure every manifest "measure" entry at the canonical shape and
    join against the analytic roofline model.

    Returns ``{"t", "h", "backend", "complete", "coverage_problems",
    "rows": {name: row}}`` where a measured row carries
    ``{path, flops, bytes, analytic_flops, analytic_bytes,
    flops_vs_model, bytes_vs_model}`` and a flagged row
    ``{path, flagged: reason}``.  ``complete`` is the bench gate:
    every jitmap entry point has a row and no coverage problem exists.
    """
    import jax

    from pivot_tpu.infra import roofline

    backend = jax.default_backend()
    problems = coverage_problems()
    operands = _operands(T, H)
    rows: Dict[str, dict] = {}
    for (path, name), (kind, payload) in sorted(ENTRY_POINTS.items()):
        if kind == "flag":
            if include_flags:
                rows[name] = {"path": path, "flagged": payload}
            continue
        builder = _BUILDERS[payload]
        try:
            jitfn, args, static_kw, model_kind = builder(operands)
            lowered = jitfn.lower(*args, **static_kw)
            measured = _extract(lowered.compile().cost_analysis())
        except Exception as exc:  # noqa: BLE001 — row-level isolation
            rows[name] = {
                "path": path,
                "error": f"{type(exc).__name__}: {exc}"[:200],
            }
            problems.append(f"cost_analysis failed for {name}: {exc}")
            continue
        model_kind = model_kind or (
            "slim" if backend == "cpu" else "scan"
        )
        k = 4 if payload in ("fused_tick_run", "resident_span_run") else 1
        analytic = roofline.placement_cost(
            model_kind, T * k, H, dtype_bytes=4
        )
        row = {
            "path": path,
            "kind": model_kind,
            "flops": measured["flops"],
            "bytes": measured["bytes"],
            "analytic_flops": analytic["flops"],
            "analytic_bytes": analytic["bytes"],
        }
        if measured["flops"] and analytic["flops"]:
            row["flops_vs_model"] = round(
                measured["flops"] / analytic["flops"], 4
            )
        if measured["bytes"] and analytic["bytes"]:
            row["bytes_vs_model"] = round(
                measured["bytes"] / analytic["bytes"], 4
            )
        rows[name] = row
    return {
        "t": T,
        "h": H,
        "backend": backend,
        "entries": len(ENTRY_POINTS),
        "measured": sum(1 for r in rows.values() if "flops" in r),
        "flagged": sum(1 for r in rows.values() if "flagged" in r),
        "coverage_problems": problems,
        "complete": not problems,
        "rows": rows,
    }
